"""Per-stage device profile of the fused BLS verify pipeline — the
measured decomposition VERDICT r4 #2 asks for in the bench JSON.

The production path is ONE jit (a single host sync), so stage costs are
measured by queueing each kernel N× and syncing once (amortizing the
~100 ms axon tunnel roundtrip to <10 ms/row of noise).  Shapes default to
the 256-set C=2 bucket (comparable with the r5 baselines: final_exp
51.7 ms / HTC 44.3 ms / Miller 32.4 ms) and can be widened to the C=8
bucket the 1024-set row now dispatches as one program; inputs are
synthetic limb planes — the kernels' CORRECTNESS is pinned elsewhere
(host oracles + RFC anchors); this measures device time only.

Stages:

- the r5-comparable unfused rows (``miller`` / ``product_fold``), and
- ``miller_fold_fused`` — the fused Miller+fold program that replaced
  the two separate dispatches in the production pipeline.

Used by ``bench.py`` (the ``bls_stage_split`` row) and
``scripts/profile_bls.py`` (human-readable breakdown).
"""

from __future__ import annotations

import time
from typing import Dict

# Most recent :func:`profile_stages` output — the adapter-readable twin
# (``common.tracing.stage_split("bls_kernels")``) of the other LAST_*
# stage dicts, so bench.py's ``bls_stage_split`` row reads through the
# same surface as the tracer.
LAST_STAGE_PROFILE: Dict[str, float] = {}


def profile_stages(n: int = 10, C: int = 2) -> Dict[str, float]:
    """ms/call per pipeline stage at the C-chunk (C·128-lane) shape."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from . import htc_kernel as HK
    from . import pairing_kernel as PK

    S = PK.PREP_S
    rng = np.random.default_rng(0)
    pk = jnp.asarray(rng.integers(0, 2**16, (64, C * S)).astype(np.uint32))
    kmask = jnp.ones((1, C * S), jnp.int32)
    lo = jnp.ones((1, C * S), jnp.uint32)
    hi = jnp.zeros((1, C * S), jnp.uint32)
    g2 = jnp.asarray(rng.integers(0, 2**16, (128, C * S)).astype(np.uint32))
    lm = jnp.ones((1, C * S), jnp.int32)
    msgs = [(i // S, i % S, b"stage-msg-%03d" % (i % 64))
            for i in range(C * S)]
    ud = jnp.asarray(HK.u_planes_for_messages(msgs, C))

    g1_aff, _fl = PK.prepare_kernel_call(pk, kmask, lo, hi, K=1)
    f = PK.miller_kernel_call(g1_aff, g2)
    prod = PK.product_chunks_kernel_call(f, lm)
    fused = PK.miller_fold_kernel_call(g1_aff, g2, lm)
    ok = PK.finalize_kernel_call(prod)
    h = HK.hash_g2_kernel_call(ud)
    jax.block_until_ready((ok, h, fused))

    stages = {
        "hash_to_curve": lambda: HK.hash_g2_kernel_call(ud),
        "prepare_gather_rlc": lambda: PK.prepare_kernel_call(
            pk, kmask, lo, hi, K=1)[0],
        "miller": lambda: PK.miller_kernel_call(g1_aff, g2),
        "product_fold": lambda: PK.product_chunks_kernel_call(f, lm),
        "miller_fold_fused": lambda: PK.miller_fold_kernel_call(
            g1_aff, g2, lm),
        "final_exp": lambda: PK.finalize_kernel_call(prod),
    }
    out: Dict[str, float] = {}
    for name, fn in stages.items():
        t0 = time.perf_counter()
        outs = [fn() for _ in range(n)]
        jax.block_until_ready(outs)
        out[f"stage_{name}_ms"] = round(
            (time.perf_counter() - t0) * 1e3 / n, 2)
    out["stage_shape"] = f"C={C} ({C * S} lanes), K=1"
    LAST_STAGE_PROFILE.clear()
    LAST_STAGE_PROFILE.update(out)
    return out
