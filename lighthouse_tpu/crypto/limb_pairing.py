"""Batched optimal-ate pairing on the TPU — the heart of the ``tpu`` BLS
backend.

Device counterpart of the host oracle (:mod:`.pairing`) and of blst's
``verify_multiple_aggregate_signatures`` multi-pairing
(``/root/reference/crypto/bls/src/impls/blst.rs:36-119``).  Everything is
batched over a leading lane axis: one call runs B independent Miller loops
as wide vector ops, then a log2(B) product-reduction shares ONE final
exponentiation across the whole batch — the product-of-pairings trick.

TPU-shaped choices:

- **Projective Miller loop, affine base points.**  The running point T
  stays homogeneous projective (no per-step inversions — a field inversion
  is a 381-bit ladder, ruinous inside a 63-iteration loop), while the fixed
  points (G1 evaluation point, G2 base point Q) are affine, keeping the
  line formulas short.
- **Scanned, not unrolled.**  The 63 Miller iterations and the 64-bit
  x-power ladders run under ``lax.scan`` with the (static) bit pattern as
  scanned input — one compiled body instead of a 100k-op unrolled graph.
  Both branches (double-only vs double-and-add) are computed every
  iteration and lane-selected; |x| has Hamming weight 6, so this wastes
  ~45% device work in exchange for ~60× less XLA graph — the right trade
  until a Pallas rewrite.
- **Lines as sparse Fq12 with the w³ scaling.**  With the oracle's untwist
  convention (x/w², y/w³), a line through G2 points evaluated at a G1 point
  P=(xP,yP), scaled by w³·(any Fq2), is  A + B·v + C·v·w  with A,B,C ∈ Fq2:
  a "034"-sparse element.  w³ lies in the Fq4 subfield Fq2(v·w), so the
  easy part of the final exponentiation kills the scaling.
- **Final exponentiation via x-ladders, cubed.**  The hard part uses the
  Hayashida–Hayasaka–Teruya decomposition
      3·(p⁴−p²+1)/r = (u−1)²·(u+p)·(u²+p²−1) + 3
  (checked exactly in tests), i.e. the device computes the CUBE of the
  oracle's GT value — identical for the only consumer, the ``== 1`` check
  (GT has prime order r ≠ 3).  Five 64-bit x-ladders instead of a 2700-bit
  exponentiation.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from . import fields as F
from . import limb_field as LF
from . import limb_tower as T
from . import limb_curve as LC
from .fields import P as P_INT, BLS_X

X_ABS = -BLS_X  # 0xd201000000010000

# MSB-first bit arrays (static scan inputs).
X_BITS_FULL = np.array([int(b) for b in bin(X_ABS)[2:]], dtype=np.int32)
X_BITS_MILLER = X_BITS_FULL[1:]                      # implicit leading 1
P_MINUS_2_BITS = np.array([int(b) for b in bin(P_INT - 2)[2:]], dtype=np.int32)


# ---------------------------------------------------------------------------
# Batched field inversion (Fermat ladders) and Fq12 tower inversion
# ---------------------------------------------------------------------------

def fq_inv(a: jnp.ndarray) -> jnp.ndarray:
    """Batched a^(p-2) over (..., 26) Montgomery limbs; inv(0) = 0."""
    one = jnp.broadcast_to(jnp.asarray(LF.ONE_MONT), a.shape)

    def body(acc, bit):
        acc = LF.mont_mul(acc, acc)
        return LF.select(bit.astype(bool), LF.mont_mul(acc, a), acc), None

    # MSB-first square-and-multiply needs R·a at "multiply" steps because
    # mont_mul divides by R: track acc in the Montgomery domain throughout
    # (a already is), so acc stays a Montgomery residue of a^k. Start from
    # Montgomery one.
    acc, _ = jax.lax.scan(body, one, jnp.asarray(P_MINUS_2_BITS))
    return acc


def fq2_inv(a: jnp.ndarray) -> jnp.ndarray:
    """(a0 + a1·u)^-1 = conj(a) / (a0² + a1²), batched over (..., 2, 26)."""
    n = LF.add(LF.mont_mul(a[..., 0, :], a[..., 0, :]),
               LF.mont_mul(a[..., 1, :], a[..., 1, :]))
    ninv = fq_inv(n)
    return jnp.stack([LF.mont_mul(a[..., 0, :], ninv),
                      LF.mont_mul(LF.neg(a[..., 1, :]), ninv)], axis=-2)


def fq6_inv(a: jnp.ndarray) -> jnp.ndarray:
    """Standard Fq6 = Fq2[v]/(v³-ξ) inversion, batched (..., 3, 2, 26)."""
    a0, a1, a2 = (a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :])
    p = T.fq2_mul(
        jnp.stack([a0, a1, a2, a1, a0, a0], axis=-3),
        jnp.stack([a0, a2, a2, a1, a1, a2], axis=-3))
    a00, a12, a22, a11, a01, a02 = [p[..., i, :, :] for i in range(6)]
    c0 = T.sub(a00, T.fq2_mul_by_xi(a12))
    c1 = T.sub(T.fq2_mul_by_xi(a22), a01)
    c2 = T.sub(a11, a02)
    q = T.fq2_mul(
        jnp.stack([a0, a2, a1], axis=-3),
        jnp.stack([c0, c1, c2], axis=-3))
    n = T.add(q[..., 0, :, :],
              T.fq2_mul_by_xi(T.add(q[..., 1, :, :], q[..., 2, :, :])))
    ninv = fq2_inv(n)
    return T.fq2_mul(jnp.stack([c0, c1, c2], axis=-3), ninv[..., None, :, :])


def fq12_inv(a: jnp.ndarray) -> jnp.ndarray:
    """(a0 + a1·w)^-1 = (a0 - a1·w) / (a0² - v·a1²), batched."""
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    s = T.fq6_mul(jnp.stack([a0, a1], axis=-4), jnp.stack([a0, a1], axis=-4))
    n = T.sub(s[..., 0, :, :, :], T.fq6_mul_by_v(s[..., 1, :, :, :]))
    ninv = fq6_inv(n)
    return jnp.stack([T.fq6_mul(a0, ninv),
                      T.fq6_mul(T.neg(a1), ninv)], axis=-4)


# ---------------------------------------------------------------------------
# Frobenius: diagonal multipliers on the v^j·w^i basis
# ---------------------------------------------------------------------------

def _frobenius_tables():
    """γn[i][j] ∈ Fq2 with frob^n(Σ c_ij v^j w^i) = Σ conj^n(c_ij)·γn_ij v^j w^i.

    Derived by applying the host oracle's frobenius to basis elements and
    asserting diagonality — no transcribed constants to get wrong.
    """
    tables = {}
    for n in (1, 2, 3):
        gam = np.zeros((2, 3, 2, LF.LIMBS), dtype=np.uint32)
        for i in range(2):
            for j in range(3):
                c6 = [list(F.FQ6_ZERO) for _ in range(2)]
                c6[i][j] = F.FQ2_ONE
                basis = (tuple(c6[0]), tuple(c6[1]))
                out = F.fq12_frobenius(basis, n)
                for ii in range(2):
                    for jj in range(3):
                        if (ii, jj) != (i, j):
                            assert out[ii][jj] == F.FQ2_ZERO
                gam[i, j] = T.fq2_to_limbs(out[i][j])
        tables[n] = jnp.asarray(gam)
    return tables


_GAMMA = _frobenius_tables()


def fq12_frobenius(a: jnp.ndarray, n: int) -> jnp.ndarray:
    """frob^n for n ∈ {1,2,3}, batched (..., 2, 3, 2, 26)."""
    if n % 2:
        a = jnp.stack([a[..., 0, :], LF.neg(a[..., 1, :])], axis=-2)
    return T.fq2_mul(a, _GAMMA[n])


# ---------------------------------------------------------------------------
# Sparse line ↔ Fq12
# ---------------------------------------------------------------------------

def _line_to_fq12(A: jnp.ndarray, B: jnp.ndarray, C: jnp.ndarray) -> jnp.ndarray:
    """(A + B·v + C·v·w) with A,B,C ∈ Fq2 of shape (..., 2, 26)."""
    zero = jnp.zeros_like(A)
    c0 = jnp.stack([A, B, zero], axis=-3)
    c1 = jnp.stack([zero, C, zero], axis=-3)
    return jnp.stack([c0, c1], axis=-4)


def _fq2_mul_fq(a: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """Fq2 (..., 2, 26) × Fq scalar (..., 26) — coefficient-wise."""
    return LF.mont_mul(a, s[..., None, :])


# ---------------------------------------------------------------------------
# Miller loop (batched, scanned)
# ---------------------------------------------------------------------------

def _dbl_step(Tp: jnp.ndarray, xP: jnp.ndarray, yP: jnp.ndarray):
    """Line l_{T,T}(P)·w³·(2YZ²) and T' = 2T.  T homogeneous projective G2.

    With λ = 3x²/2y:  A = λx−y, B = −λ·xP, C = yP; scaled by 2YZ²:
        A' = 3X³ − 2Y²Z,  B' = −3X²Z·xP,  C' = 2YZ²·yP.
    """
    X, Y, Z = LC.G2_OPS.coords(Tp)
    r = T.fq2_mul(
        jnp.stack([X, Y, Z], axis=-3),
        jnp.stack([X, Y, Z], axis=-3))
    XX, YY, ZZ = r[..., 0, :, :], r[..., 1, :, :], r[..., 2, :, :]
    r2 = T.fq2_mul(
        jnp.stack([X, YY, XX, Y], axis=-3),
        jnp.stack([XX, Z, Z, ZZ], axis=-3))
    X3, Y2Z, X2Z, YZ2 = (r2[..., 0, :, :], r2[..., 1, :, :],
                         r2[..., 2, :, :], r2[..., 3, :, :])
    A = T.sub(LF.muls(X3, 3), LF.muls(Y2Z, 2))
    B = T.neg(_fq2_mul_fq(LF.muls(X2Z, 3), xP))
    C = _fq2_mul_fq(LF.muls(YZ2, 2), yP)
    return _line_to_fq12(A, B, C), LC.point_add(LC.G2_OPS, Tp, Tp)


def _add_step(Tp: jnp.ndarray, Q: jnp.ndarray, Qx: jnp.ndarray,
              Qy: jnp.ndarray, xP: jnp.ndarray, yP: jnp.ndarray):
    """Chord l_{T,Q}(P)·w³·D and T' = T + Q (Q affine, lifted in ``Q``).

    λ = N/D with N = y₂Z − Y, D = x₂Z − X:
        A' = N·x₂ − y₂·D,  B' = −N·xP,  C' = D·yP.
    """
    X, Y, Z = LC.G2_OPS.coords(Tp)
    r = T.fq2_mul(
        jnp.stack([Qy, Qx], axis=-3),
        jnp.stack([Z, Z], axis=-3))
    N = T.sub(r[..., 0, :, :], Y)
    D = T.sub(r[..., 1, :, :], X)
    r2 = T.fq2_mul(
        jnp.stack([N, Qy], axis=-3),
        jnp.stack([Qx, D], axis=-3))
    A = T.sub(r2[..., 0, :, :], r2[..., 1, :, :])
    B = T.neg(_fq2_mul_fq(N, xP))
    C = _fq2_mul_fq(D, yP)
    return _line_to_fq12(A, B, C), LC.point_add(LC.G2_OPS, Tp, Q)


def miller_loop(g1_affine: jnp.ndarray, g2_affine: jnp.ndarray) -> jnp.ndarray:
    """Batched f_{|x|,Q}(P), conjugated for x<0 — matches the oracle's
    :func:`..pairing.miller_loop` up to subfield scalings killed by the
    final exponentiation.

    ``g1_affine``: (..., 2, 26) Fq pairs (xP, yP); ``g2_affine``:
    (..., 2, 2, 26) Fq2 pairs (xQ, yQ).  Lanes must be non-infinity (mask
    garbage lanes downstream).  Returns (..., 2, 3, 2, 26) Fq12.
    """
    xP = g1_affine[..., 0, :]
    yP = g1_affine[..., 1, :]
    Qx = g2_affine[..., 0, :, :]
    Qy = g2_affine[..., 1, :, :]
    one2 = jnp.broadcast_to(
        jnp.stack([jnp.asarray(LF.ONE_MONT),
                   jnp.zeros(LF.LIMBS, jnp.uint32)]), Qx.shape)
    Q = LC.G2_OPS.point(Qx, Qy, one2)
    batch = xP.shape[:-1]
    f0 = jnp.broadcast_to(jnp.asarray(T.FQ12_ONE_LIMBS),
                          batch + (2, 3, 2, LF.LIMBS))
    Tp0 = Q

    def body(carry, bit):
        f, Tp = carry
        l_dbl, T2 = _dbl_step(Tp, xP, yP)
        f = T.fq12_mul(T.fq12_sqr(f), l_dbl)
        l_add, T3 = _add_step(T2, Q, Qx, Qy, xP, yP)
        take = bit.astype(bool)
        f = jnp.where(take, T.fq12_mul(f, l_add), f)
        Tp = jnp.where(take, T3, T2)
        return (f, Tp), None

    (f, _), _ = jax.lax.scan(body, (f0, Tp0), jnp.asarray(X_BITS_MILLER))
    return T.fq12_conj(f)  # x < 0


# ---------------------------------------------------------------------------
# Final exponentiation (cubed), x-power ladder
# ---------------------------------------------------------------------------

def _pow_x_abs(f: jnp.ndarray) -> jnp.ndarray:
    """f^|x| by scanned square-and-multiply (64 static bits)."""
    one = jnp.broadcast_to(jnp.asarray(T.FQ12_ONE_LIMBS), f.shape)

    def body(acc, bit):
        acc = T.fq12_sqr(acc)
        return jnp.where(bit.astype(bool), T.fq12_mul(acc, f), acc), None

    acc, _ = jax.lax.scan(body, one, jnp.asarray(X_BITS_FULL))
    return acc


def _pow_u(f: jnp.ndarray) -> jnp.ndarray:
    """f^u for the (negative) BLS parameter u — cyclotomic elements only
    (inverse = conjugate)."""
    return T.fq12_conj(_pow_x_abs(f))


def final_exponentiation_cubed(f: jnp.ndarray) -> jnp.ndarray:
    """f^(3·(q¹²−1)/r): easy part, then HHT hard part ×3 (docstring above)."""
    # Easy: f^(q⁶−1) then ^(q²+1).
    m = T.fq12_mul(T.fq12_conj(f), fq12_inv(f))
    m = T.fq12_mul(fq12_frobenius(m, 2), m)
    # Hard ×3: (u−1)²·(u+p)·(u²+p²−1) + 3.
    m1 = T.fq12_mul(_pow_u(m), T.fq12_conj(m))            # m^(u−1)
    k2 = T.fq12_mul(_pow_u(m1), T.fq12_conj(m1))          # ^(u−1)
    k3 = T.fq12_mul(_pow_u(k2), fq12_frobenius(k2, 1))    # ^(u+p)
    k4 = T.fq12_mul(T.fq12_mul(_pow_u(_pow_u(k3)), fq12_frobenius(k3, 2)),
                    T.fq12_conj(k3))                      # ^(u²+p²−1)
    return T.fq12_mul(k4, T.fq12_mul(T.fq12_sqr(m), m))


def fq12_is_one(f: jnp.ndarray) -> jnp.ndarray:
    """Batched f == 1 (lazy-representation aware)."""
    d = LF.sub(f, jnp.asarray(T.FQ12_ONE_LIMBS))
    z = LF.is_zero(d)  # (..., 2, 3, 2)
    return jnp.all(z, axis=(-3, -2, -1))


# ---------------------------------------------------------------------------
# Affine conversion + fused multi-pairing check
# ---------------------------------------------------------------------------

def g1_proj_to_affine(p: jnp.ndarray) -> jnp.ndarray:
    """(..., 3, 26) projective → (..., 2, 26) affine; identity → (0, 0)."""
    X, Y, Z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    zi = fq_inv(Z)
    return jnp.stack([LF.mont_mul(X, zi), LF.mont_mul(Y, zi)], axis=-2)


def g2_proj_to_affine(p: jnp.ndarray) -> jnp.ndarray:
    """(..., 3, 2, 26) projective → (..., 2, 2, 26) affine; identity → 0."""
    X, Y, Z = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    zi = fq2_inv(Z)
    return jnp.stack([T.fq2_mul(X, zi), T.fq2_mul(Y, zi)], axis=-3)


def _product_reduce(f: jnp.ndarray) -> jnp.ndarray:
    """Tree-product over the lane axis (len must be a power of two)."""
    n = f.shape[0]
    if n & (n - 1):
        raise ValueError("pad pairing lanes to a power of two")
    while n > 1:
        n //= 2
        f = T.fq12_mul(f[:n], f[n:2 * n])
    return f[0]


def multi_pairing_partial(g1_proj: jnp.ndarray, g2_proj: jnp.ndarray,
                          mask: jnp.ndarray) -> jnp.ndarray:
    """∏_{i: mask_i} f_{|x|}(P_i, Q_i) — the masked Miller-lane product
    WITHOUT the final exponentiation, as one (2, 3, 2, 26) Fq12.

    This is the per-chip half of the mesh-sharded batch verify: each
    chip folds its shard's lanes to a single Fq12 partial, the partials
    all-gather (5 KB/chip), and ONE replicated final exponentiation
    closes the product — the product-of-pairings trick stretched across
    the ICI.  Shapes as :func:`multi_pairing_is_one`; B a power of two;
    identity lanes and masked padding contribute 1.
    """
    g1_aff = g1_proj_to_affine(g1_proj)
    g2_aff = g2_proj_to_affine(g2_proj)
    f = miller_loop(g1_aff, g2_aff)
    live = (mask
            & ~LF.is_zero(g1_proj[..., 2, :])
            & ~T.fq2_is_zero(g2_proj[..., 2, :, :]))
    one = jnp.asarray(T.FQ12_ONE_LIMBS)
    f = jnp.where(live[:, None, None, None, None], f, one)
    return _product_reduce(f)


def multi_pairing_is_one(g1_proj: jnp.ndarray, g2_proj: jnp.ndarray,
                         mask: jnp.ndarray) -> jnp.ndarray:
    """∏_{i: mask_i} e(P_i, Q_i) == 1, fused on device.

    ``g1_proj``: (B, 3, 26); ``g2_proj``: (B, 3, 2, 26); ``mask``: (B,) bool.
    B must be a power of two.  Lanes where either point is the identity
    contribute 1 (e(O, ·) = e(·, O) = 1), as do masked padding lanes.
    """
    prod = multi_pairing_partial(g1_proj, g2_proj, mask)
    return fq12_is_one(final_exponentiation_cubed(prod))
