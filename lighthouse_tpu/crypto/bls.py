"""BLS signatures over BLS12-381 (min-pubkey-size: pk in G1, sig in G2).

The host-facing API of the framework's crypto layer, mirroring the
reference's backend-generic wrapper (``/root/reference/crypto/bls/src/``):

- ``SecretKey`` / ``PublicKey`` / ``Signature`` / ``AggregateSignature`` with
  compressed ZCash encodings (48/96 bytes).
- the consensus-critical validity rules: an all-zero (infinity) public key is
  INVALID (``generic_public_key.rs:14-15``); deserialization subgroup-checks
  points; the canonical infinity signature is representable and fails
  verification against any pubkey set.
- ``SignatureSet`` + ``verify_signature_sets`` — random-linear-combination
  batch verification with one multi-pairing, replicating
  ``impls/blst.rs:36-119``: per-set nonzero 64-bit random scalar, signature
  subgroup checks, per-set pubkey aggregation, empty-set/empty-keys => False.

Backends (the ``bls::impls::*`` seam):

- ``python``  — this module's pure-Python pairing (ground truth).
- ``fake``    — always-true verification for logic tests, like the
  reference's ``fake_crypto`` (``impls/fake_crypto.rs:29,105``).
- ``tpu``     — device-batched verification (lighthouse_tpu.ops), registered
  when the pairing kernels land.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from functools import lru_cache
from typing import Iterable, List, Optional, Sequence

from . import curve as C
from . import fields as F
from .hash_to_curve import DST_G2, hash_to_g2
from .pairing import multi_pairing_is_one

PUBLIC_KEY_BYTES_LEN = 48
SIGNATURE_BYTES_LEN = 96
SECRET_KEY_BYTES_LEN = 32
INFINITY_SIGNATURE = bytes([0xC0]) + b"\x00" * 95
RAND_BITS = 64


class BlsError(ValueError):
    pass


@lru_cache(maxsize=1 << 16)
def _g1_point_checked(data: bytes):
    """Decompress + subgroup-check a G1 pubkey encoding, memoized by bytes —
    the decompressed-pubkey cache role of ``validator_pubkey_cache.rs``
    pushed down to the codec (pure function of the encoding)."""
    try:
        point = C.g1_decompress(data)
    except ValueError as e:
        # Curve-codec rejections ("x not on curve", bad flags, x >= p)
        # are key-material failures: surface them as BlsError so callers
        # classifying signature-material errors (block import's
        # InvalidSignatures boundary) see one type.
        raise BlsError(str(e)) from e
    if point is None:
        raise BlsError("infinity public key is invalid")
    if not C.g1_subgroup_check(point):
        raise BlsError("public key not in the G1 subgroup")
    return point


def _g2_mul_fast(point, scalar: int):
    """[scalar]P via the native 256-bit ladder when built (signing and
    RLC hot path, ~7× python); falls back to the curve oracle."""
    from . import native

    if point is not None and 0 <= scalar < (1 << 256) and native.ready():
        return native.g2_mul(point, scalar)
    return C.g2_mul(point, scalar)


@lru_cache(maxsize=1 << 16)
def _g2_point_checked(data: bytes):
    try:
        point = C.g2_decompress(data)
    except ValueError as e:
        raise BlsError(str(e)) from e
    if point is not None and not C.g2_subgroup_check(point):
        raise BlsError("signature not in the G2 subgroup")
    return point


@dataclass(frozen=True)
class SecretKey:
    scalar: int

    @classmethod
    def random(cls) -> "SecretKey":
        # Rejection sampling: reducing mod R would bias ~9.5% of the range.
        while True:
            k = secrets.randbits(255)
            if 0 < k < F.R:
                return cls(k)

    @classmethod
    def deserialize(cls, data: bytes) -> "SecretKey":
        if len(data) != SECRET_KEY_BYTES_LEN:
            raise BlsError(f"secret key must be {SECRET_KEY_BYTES_LEN} bytes")
        k = int.from_bytes(data, "big")
        if k == 0 or k >= F.R:
            raise BlsError("secret key scalar out of range")
        return cls(k)

    def serialize(self) -> bytes:
        return self.scalar.to_bytes(32, "big")

    def public_key(self) -> "PublicKey":
        return PublicKey(C.g1_mul(C.G1_GEN, self.scalar))

    def sign(self, message: bytes) -> "Signature":
        return Signature(_g2_mul_fast(hash_to_g2(message), self.scalar))


@dataclass(frozen=True)
class PublicKey:
    point: tuple  # affine G1, never None (infinity pubkeys are invalid)

    def __post_init__(self):
        if self.point is None:
            raise BlsError("infinity public key is invalid")

    @classmethod
    def deserialize(cls, data: bytes) -> "PublicKey":
        if len(data) != PUBLIC_KEY_BYTES_LEN:
            raise BlsError(f"public key must be {PUBLIC_KEY_BYTES_LEN} bytes")
        return cls(_g1_point_checked(bytes(data)))

    def serialize(self) -> bytes:
        return C.g1_compress(self.point)


def aggregate_points(points):
    """G1 sum of pre-validated pubkey POINTS.

    Large sums route through the native jacobian accumulator when built
    (~5 µs/point vs ~500 µs python affine adds) — the sync-committee
    512-key aggregate drops from ~260 ms to ~3 ms."""
    from . import native
    if len(points) >= 16 and native.ready():
        return native.g1_aggregate(list(points))
    acc = None
    for p in points:
        acc = C.g1_add(acc, p)
    return acc


def aggregate_public_keys(keys: Sequence[PublicKey]):
    """G1 sum of pubkey points (keys pre-validated at deserialization)."""
    return aggregate_points([k.point for k in keys])


@dataclass(frozen=True)
class Signature:
    point: Optional[tuple]  # affine G2; None = infinity signature

    @classmethod
    def deserialize(cls, data: bytes) -> "Signature":
        if len(data) != SIGNATURE_BYTES_LEN:
            raise BlsError(f"signature must be {SIGNATURE_BYTES_LEN} bytes")
        return cls(_g2_point_checked(bytes(data)))

    def serialize(self) -> bytes:
        return C.g2_compress(self.point)

    def verify(self, pubkey: PublicKey, message: bytes) -> bool:
        return get_backend().verify(self, [pubkey], message)

    def fast_aggregate_verify(self, pubkeys: Sequence[PublicKey],
                              message: bytes) -> bool:
        """Aggregate-signature verify: one message, many signers."""
        if not pubkeys:
            return False
        return get_backend().verify(self, list(pubkeys), message)

    def aggregate_verify(self, pubkeys: Sequence[PublicKey],
                         messages: Sequence[bytes]) -> bool:
        """Distinct message per signer: e(g1, sig) == prod_i e(pk_i, H(m_i))."""
        if not pubkeys or len(pubkeys) != len(messages):
            return False
        return get_backend().aggregate_verify(self, list(pubkeys),
                                              list(messages))


def aggregate_signatures(sigs: Iterable[Signature]) -> Signature:
    """G2 sum; empty input yields the infinity signature (like the
    reference's ``AggregateSignature::infinity``)."""
    acc = None
    for s in sigs:
        if s.point is not None:
            acc = C.g2_add(acc, s.point)
    return Signature(acc)


@dataclass(frozen=True)
class SignatureSet:
    """{aggregate signature, signing keys, one message} —
    ``generic_signature_set.rs:62-73``."""
    signature: Optional[Signature]
    signing_keys: List[PublicKey]
    message: bytes


def signature_set_key(s: SignatureSet) -> tuple:
    """Exact-identity key of a set: (message, signature point, signing
    key points).  Two sets with equal keys verify identically under any
    backend."""
    return (bytes(s.message),
            None if s.signature is None else s.signature.point,
            tuple(k.point for k in s.signing_keys))


def dedup_signature_sets(sets: Sequence[SignatureSet]
                         ) -> tuple[List[SignatureSet], int]:
    """Drop exact-duplicate sets (same message, keys AND signature)
    before a batch dispatch; returns ``(unique_sets, dropped)``.

    Verdict-identical by construction: the RLC batch verifies iff every
    DISTINCT set verifies (duplicates contribute redundant random-linear
    terms), and the empty/invalid-set pre-checks see at least one copy
    of each distinct set.  A block's batch hits this when the proposer
    packs the same committee aggregate twice (allowed by spec) or an
    attester-slashing attestation repeats an included attestation."""
    seen: set = set()
    out: List[SignatureSet] = []
    for s in sets:
        key = signature_set_key(s)
        if key in seen:
            continue
        seen.add(key)
        out.append(s)
    return out, len(sets) - len(out)


# ---------------------------------------------------------------------------
# Backend seam
# ---------------------------------------------------------------------------

class PythonBackend:
    """Pure-Python pairing backend (ground truth, slow)."""

    name = "python"

    def verify(self, signature: Signature, pubkeys: Sequence[PublicKey],
               message: bytes) -> bool:
        if signature.point is None or not pubkeys:
            return False
        agg_pk = aggregate_public_keys(pubkeys)
        if agg_pk is None:
            return False
        h = hash_to_g2(message)
        # e(-g1, sig) * e(agg_pk, H(m)) == 1
        return multi_pairing_is_one([
            (C.g1_neg(C.G1_GEN), signature.point),
            (agg_pk, h),
        ])

    def aggregate_verify(self, signature: Signature,
                         pubkeys: Sequence[PublicKey],
                         messages: Sequence[bytes]) -> bool:
        if signature.point is None:
            return False
        pairs = [(pk.point, hash_to_g2(m)) for pk, m in zip(pubkeys, messages)]
        pairs.append((C.g1_neg(C.G1_GEN), signature.point))
        return multi_pairing_is_one(pairs)

    def verify_signature_sets(self, sets: Sequence[SignatureSet]) -> bool:
        """Random-linear-combination batch verify (``impls/blst.rs:36-119``).

        With per-set random nonzero 64-bit c_i:
            e(-g1, sum_i c_i * sig_i) * prod_i e(c_i * pk_agg_i, H(m_i)) == 1
        """
        if not sets:
            return False
        pairs = []
        sig_acc = None  # G2 accumulator of c_i * sig_i
        for s in sets:
            if s.signature is None or s.signature.point is None:
                return False  # empty signature => failure
            if not s.signing_keys:
                return False  # no signing keys => invalid
            c = 0
            while c == 0:
                c = secrets.randbits(RAND_BITS)
            agg_pk = aggregate_public_keys(s.signing_keys)
            if agg_pk is None:
                return False
            sig_acc = C.g2_add(sig_acc, _g2_mul_fast(s.signature.point, c))
            pairs.append((C.g1_mul(agg_pk, c), hash_to_g2(s.message)))
        if sig_acc is None:
            return False
        pairs.append((C.g1_neg(C.G1_GEN), sig_acc))
        return multi_pairing_is_one(pairs)


class FakeBackend:
    """Always-true verification for logic tests (``impls/fake_crypto.rs``).

    Deserialization validity rules still apply — only the pairing is skipped.
    """

    name = "fake"

    def verify(self, signature, pubkeys, message) -> bool:
        return signature.point is not None and bool(pubkeys)

    def aggregate_verify(self, signature, pubkeys, messages) -> bool:
        return signature.point is not None and bool(pubkeys)

    def verify_signature_sets(self, sets) -> bool:
        if not sets:
            return False
        return all(
            s.signature is not None and s.signature.point is not None
            and s.signing_keys
            for s in sets)


_BACKENDS = {"python": PythonBackend(), "fake": FakeBackend()}
_active = _BACKENDS["python"]


def register_backend(name: str, backend) -> None:
    _BACKENDS[name] = backend


def set_backend(name: str) -> None:
    global _active
    _active = _BACKENDS[name]


def get_backend():
    return _active


# Optional dispatch wrapper — the resilience seam.  When set (by
# `beacon_chain.verification_service.install_global_envelope`), every
# module-level `verify_signature_sets` call routes through
# `wrapper(active_backend, sets)`, which adds deadline/retry/circuit-
# breaker/host-fallback around the device dispatch.  Backends invoked
# DIRECTLY (`get_backend().verify_signature_sets`) bypass it — that is
# how the wrapper itself calls the device without recursing.
_dispatch_wrapper = None


def set_dispatch_wrapper(wrapper) -> None:
    """Install (or clear, with None) the global dispatch wrapper."""
    global _dispatch_wrapper
    _dispatch_wrapper = wrapper


def verify_signature_sets(sets: Sequence[SignatureSet]) -> bool:
    if _dispatch_wrapper is not None:
        return _dispatch_wrapper(_active, sets)
    return _active.verify_signature_sets(sets)
