"""Host crypto: BLS12-381 reference implementation + backend seam.

Ground-truth, pure-Python BLS12-381 (fields, curves, pairing, hash-to-curve,
signatures) mirroring the semantics of the reference's ``crypto/bls`` crate
(``/root/reference/crypto/bls``).  The device (JAX/Pallas) backend in
``lighthouse_tpu.ops`` is validated against this module, exactly as the
reference validates blst against milagro/fake_crypto
(``/root/reference/crypto/bls/src/lib.rs:8-21``).
"""
