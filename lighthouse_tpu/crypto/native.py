"""ctypes bindings for the native BLS12-381 runtime (``native/bls381.cpp``).

The native library is the HOST fast path: single-set / small-batch
verification where the TPU's fixed dispatch latency (~100 ms through the
axon tunnel) dominates, and the fast oracle for tests.  Large batches stay
on the TPU (`pairing_kernel.py`).  This is the tpu-native analogue of the
reference's blst host calls (``/root/reference/crypto/bls/src/impls/
blst.rs``) — portable C++ (no asm), built on demand with g++.

Build model: the checked-in source is compiled lazily to
``native/libbls381.so`` keyed on a source hash; rebuilds happen only when
``bls381.cpp`` / ``bls381_consts.h`` change.  If no compiler is available
the loader degrades to ``available() == False`` and callers fall back to
the pure-python pairing.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    os.pardir, os.pardir, "native")
_SRC = os.path.join(_DIR, "bls381.cpp")
_HDR = os.path.join(_DIR, "bls381_consts.h")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _source_tag() -> str:
    h = hashlib.sha256()
    for path in (_SRC, _HDR):
        with open(path, "rb") as f:
            h.update(f.read())
    # -march=native binaries are host-specific: fingerprint the CPU's
    # feature flags so a .so baked on one machine (e.g. into an image)
    # is rebuilt rather than SIGILL-ing on a lesser deploy host.
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    h.update(line.encode())
                    break
    except OSError:
        import platform
        h.update(platform.processor().encode())
    return h.hexdigest()[:16]


def _prune_stale(keep: str) -> None:
    """Delete completed build artifacts for OTHER source/CPU tags — the
    loader keys on the current tag, so they are dead weight that
    otherwise accumulates forever (and must never be committed:
    ``native/*.so`` is gitignored).  ``.tmp*`` files are deliberately
    NOT touched: one may be a CONCURRENT builder's in-progress output
    (deleting it would break its atomic ``os.replace``).  ``_build``
    unlinks its own tmp on failure; only a hard mid-build crash can
    orphan one."""
    import glob
    for path in glob.glob(os.path.join(_DIR, "libbls381-*.so")):
        if os.path.abspath(path) == os.path.abspath(keep):
            continue
        try:
            os.unlink(path)
        except OSError:
            pass  # concurrent process still loading it


def _build() -> Optional[str]:
    tag = _source_tag()
    so = os.path.join(_DIR, f"libbls381-{tag}.so")
    if os.path.exists(so):
        _prune_stale(so)
        return so
    tmp = so + ".tmp%d" % os.getpid()
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
           "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)  # partial compiler output
        except OSError:
            pass
        return None
    try:
        os.replace(tmp, so)  # atomic vs concurrent builders
    except OSError:
        # Our finished build can't land (e.g. unwritable dir entry).
        # Don't leak it as an orphan the prune pass never touches.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return so if os.path.exists(so) else None
    _prune_stale(so)
    return so


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        lib.bls381_multi_pairing_is_one.restype = ctypes.c_int
        lib.bls381_multi_pairing_is_one.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
        lib.bls381_multi_pairing_gt.restype = None
        lib.bls381_multi_pairing_gt.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.bls381_g1_aggregate.restype = ctypes.c_int
        lib.bls381_g1_aggregate.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.bls381_hash_to_g2_u.restype = ctypes.c_int
        lib.bls381_hash_to_g2_u.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.bls381_g2_mul.restype = ctypes.c_int
        lib.bls381_g2_mul.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        _lib = lib
        return _lib


def ready() -> bool:
    """The standard hot-path gate: honors LIGHTHOUSE_TPU_NO_NATIVE,
    kicks the async build, and answers WITHOUT blocking — callers fall
    back to pure python until the build lands."""
    from ..common.knobs import knob_bool
    # A typed read, not bare truthiness: NO_NATIVE=0 must keep the
    # native backend ENABLED (the bare-truthy read treated "0" as set).
    if knob_bool("LIGHTHOUSE_TPU_NO_NATIVE"):
        return False
    prebuild_async()
    return available(block=False)


def available(block: bool = True) -> bool:
    """Whether the native library is loadable.

    ``block=False`` never compiles or waits: it answers from the cached
    state only (hot paths use this — a fresh checkout answers False and
    batches stay on the device until :func:`prebuild_async` finishes)."""
    if _lib is not None:
        return True
    if not block:
        return False if not _tried else _lib is not None
    return _load() is not None


def prebuild_async() -> None:
    """Kick the g++ build/load on a daemon thread so the first verify
    never pays the compile synchronously (started at backend import)."""
    if _lib is not None or _tried:
        return
    threading.Thread(target=_load, name="bls381-native-build",
                     daemon=True).start()


def _limbs(x: int) -> Tuple[int, ...]:
    return tuple((x >> (64 * i)) & 0xFFFFFFFFFFFFFFFF for i in range(6))


def _pack(pairs: Sequence[Tuple[tuple, tuple]]):
    n = len(pairs)
    g1 = (ctypes.c_uint64 * (12 * n))()
    g2 = (ctypes.c_uint64 * (24 * n))()
    for i, (p, q) in enumerate(pairs):
        g1[i * 12:(i + 1) * 12] = _limbs(p[0]) + _limbs(p[1])
        g2[i * 24:(i + 1) * 24] = (_limbs(q[0][0]) + _limbs(q[0][1]) +
                                   _limbs(q[1][0]) + _limbs(q[1][1]))
    return g1, g2


def multi_pairing_is_one(pairs: Sequence[Tuple[tuple, tuple]]) -> bool:
    """prod_i e(P_i, Q_i) == 1 for AFFINE non-infinity pairs (validated
    upstream — the python seam filters identities before calling)."""
    lib = _load()
    assert lib is not None, "call available() first"
    g1, g2 = _pack(pairs)
    return bool(lib.bls381_multi_pairing_is_one(g1, g2, len(pairs)))


def g1_aggregate(points: Sequence[tuple]) -> Optional[tuple]:
    """Affine sum of non-infinity G1 points (None = identity sum) —
    the jacobian accumulation behind ``bls.aggregate_public_keys`` and
    the shared-keygroup dedup (~5 µs/point vs ~500 µs python)."""
    lib = _load()
    assert lib is not None, "call available() first"
    n = len(points)
    buf = (ctypes.c_uint64 * (12 * n))()
    for i, (x, y) in enumerate(points):
        buf[i * 12:(i + 1) * 12] = _limbs(x) + _limbs(y)
    out = (ctypes.c_uint64 * 12)()
    if not lib.bls381_g1_aggregate(buf, n, out):
        return None
    x = sum(int(out[j]) << (64 * j) for j in range(6))
    y = sum(int(out[6 + j]) << (64 * j) for j in range(6))
    return (x, y)


def _pack_g2_affine(point: tuple):
    buf = (ctypes.c_uint64 * 24)()
    buf[0:6] = _limbs(point[0][0])
    buf[6:12] = _limbs(point[0][1])
    buf[12:18] = _limbs(point[1][0])
    buf[18:24] = _limbs(point[1][1])
    return buf


def _unpack_g2_affine(out) -> tuple:
    v = [sum(int(out[o * 6 + j]) << (64 * j) for j in range(6))
         for o in range(4)]
    return ((v[0], v[1]), (v[2], v[3]))


def hash_to_g2_u(u0: tuple, u1: tuple) -> tuple:
    """SSWU → 3-isogeny → cofactor clearing for two Fq2 field elements
    (the curve half of RFC 9380 hash_to_curve; ~1.5 ms vs ~20 ms python).
    Returns the affine ((x0, x1), (y0, y1)) G2 point."""
    lib = _load()
    assert lib is not None, "call available() first"
    u = _pack_g2_affine((u0, u1))  # same 4×Fq layout as an affine point
    out = (ctypes.c_uint64 * 24)()
    if not lib.bls381_hash_to_g2_u(u, out):
        return None  # pathological infinity; callers treat like python's
    return _unpack_g2_affine(out)


def g2_mul(point: tuple, scalar: int) -> Optional[tuple]:
    """[scalar]P for affine G2 (256-bit ladder; ~1.5 ms vs ~10 ms
    python) — the sign/RLC hot path."""
    lib = _load()
    assert lib is not None, "call available() first"
    p = _pack_g2_affine(point)
    s = (ctypes.c_uint64 * 4)(
        *((scalar >> (64 * i)) & 0xFFFFFFFFFFFFFFFF for i in range(4)))
    out = (ctypes.c_uint64 * 24)()
    if not lib.bls381_g2_mul(p, s, out):
        return None
    return _unpack_g2_affine(out)


def multi_pairing_gt(pairs: Sequence[Tuple[tuple, tuple]]) -> tuple:
    """The CUBED GT value (matches ``pairing.final_exponentiation_cubed``
    of the Miller product) — oracle cross-checks in tests."""
    lib = _load()
    assert lib is not None, "call available() first"
    g1, g2 = _pack(pairs)
    out = (ctypes.c_uint64 * 144)()
    lib.bls381_multi_pairing_gt(g1, g2, len(pairs), out)
    f = [sum(int(out[i * 6 + j]) << (64 * j) for j in range(6))
         for i in range(12)]
    return (((f[0], f[1]), (f[2], f[3]), (f[4], f[5])),
            ((f[6], f[7]), (f[8], f[9]), (f[10], f[11])))
