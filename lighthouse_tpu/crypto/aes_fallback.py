"""Pure-python AES-128-CTR — dependency-free fallback for EIP-2335
keystores when the ``cryptography`` package is absent.

Keystore payloads are 32 bytes (two blocks) and the KDF (scrypt/pbkdf2)
dominates the cost by orders of magnitude, so a table-light python AES is
plenty; the S-box and round constants are DERIVED from the GF(2^8) field
structure at import rather than transcribed, and the implementation is
pinned to the FIPS-197 known-answer vector in tests.
"""

from __future__ import annotations


def _gmul(a: int, b: int) -> int:
    """GF(2^8) multiply, reduction polynomial x^8+x^4+x^3+x+1 (0x11B)."""
    r = 0
    for _ in range(8):
        if b & 1:
            r ^= a
        hi = a & 0x80
        a = (a << 1) & 0xFF
        if hi:
            a ^= 0x1B
        b >>= 1
    return r


def _ginv(a: int) -> int:
    """Multiplicative inverse via a^254 (square-and-multiply)."""
    if a == 0:
        return 0
    acc, base, e = 1, a, 254
    while e:
        if e & 1:
            acc = _gmul(acc, base)
        base = _gmul(base, base)
        e >>= 1
    return acc


def _build_sbox() -> list:
    sbox = []
    for i in range(256):
        c = _ginv(i)
        x = c
        for _ in range(4):
            c = ((c << 1) | (c >> 7)) & 0xFF
            x ^= c
        sbox.append(x ^ 0x63)
    return sbox


_SBOX = _build_sbox()


def _expand_key(key: bytes) -> list:
    """AES-128 key schedule → 11 round keys of 16 bytes."""
    words = [list(key[4 * i:4 * (i + 1)]) for i in range(4)]
    rcon = 1
    for i in range(4, 44):
        w = list(words[i - 1])
        if i % 4 == 0:
            w = w[1:] + w[:1]
            w = [_SBOX[b] for b in w]
            w[0] ^= rcon
            rcon = _gmul(rcon, 2)
        words.append([a ^ b for a, b in zip(words[i - 4], w)])
    return [sum((words[4 * r + c] for c in range(4)), [])
            for r in range(11)]


def _encrypt_block(block: bytes, round_keys: list) -> bytes:
    s = [b ^ k for b, k in zip(block, round_keys[0])]
    for rnd in range(1, 11):
        s = [_SBOX[b] for b in s]
        # ShiftRows on the column-major state: byte r + 4c moves left r.
        s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
        if rnd < 10:
            t = []
            for c in range(4):
                col = s[4 * c:4 * c + 4]
                t.extend([
                    _gmul(col[0], 2) ^ _gmul(col[1], 3) ^ col[2] ^ col[3],
                    col[0] ^ _gmul(col[1], 2) ^ _gmul(col[2], 3) ^ col[3],
                    col[0] ^ col[1] ^ _gmul(col[2], 2) ^ _gmul(col[3], 3),
                    _gmul(col[0], 3) ^ col[1] ^ col[2] ^ _gmul(col[3], 2),
                ])
            s = t
        s = [b ^ k for b, k in zip(s, round_keys[rnd])]
    return bytes(s)


def aes128_ctr(key16: bytes, iv: bytes, data: bytes) -> bytes:
    """CTR keystream XOR (the IV is the initial big-endian counter block,
    matching ``cryptography``'s ``modes.CTR`` semantics)."""
    if len(key16) != 16 or len(iv) != 16:
        raise ValueError("AES-128-CTR needs 16-byte key and IV")
    rks = _expand_key(key16)
    counter = int.from_bytes(iv, "big")
    out = bytearray()
    for off in range(0, len(data), 16):
        ks = _encrypt_block(
            counter.to_bytes(16, "big"), rks)
        counter = (counter + 1) % (1 << 128)
        chunk = data[off:off + 16]
        out.extend(b ^ k for b, k in zip(chunk, ks))
    return bytes(out)
