"""Hash-to-curve for BLS12-381 G2: BLS12381G2_XMD:SHA-256_SSWU_RO.

The message-hashing half of BLS verification (the H(m) of e(pk, H(m))),
as used by the reference via blst with DST
``BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_``
(``/root/reference/crypto/bls/src/impls/blst.rs:14``).

Pipeline per RFC 9380: expand_message_xmd(SHA-256) -> 2 Fq2 field elements
-> simplified SWU onto the 3-isogenous curve E' (A' = 240u, B' = 1012(1+u),
Z = -(2+u)) -> 3-isogeny to E -> point add -> cofactor clearing.

Validation status: externally anchored.  ``tests/test_external_vectors.py``
pins this pipeline to the published RFC 9380 known answers — Appendix
J.10.1 (`BLS12381G2_XMD:SHA-256_SSWU_RO_` u-values and output points) and
Appendix K.1 (`expand_message_xmd` SHA-256) — plus the eth2 interop
keypairs; all match exactly.  Structural checks (iso_map homomorphism onto
E(Fq2); h_eff an exact multiple of the true twist cofactor with r-coprime
quotient) remain in the suite as fast invariants.
"""

from __future__ import annotations

import hashlib

from . import fields as F
from .fields import P, R, BLS_X
from . import curve as C

DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# --- expand_message_xmd (SHA-256) ------------------------------------------

_B_IN_BYTES = 32   # SHA-256 output
_R_IN_BYTES = 64   # SHA-256 block


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255 or len_in_bytes > 65535:
        raise ValueError("requested output too long")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [b1]
    for i in range(2, ell + 1):
        prev = out[-1]
        mixed = bytes(a ^ b for a, b in zip(b0, prev))
        out.append(hashlib.sha256(mixed + bytes([i]) + dst_prime).digest())
    return b"".join(out)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes = DST_G2):
    """count Fq2 elements; L = 64 (ceil((381 + 128)/8))."""
    L = 64
    uniform = expand_message_xmd(msg, dst, count * 2 * L)
    els = []
    for i in range(count):
        coeffs = []
        for j in range(2):
            off = L * (j + i * 2)
            coeffs.append(int.from_bytes(uniform[off:off + L], "big") % P)
        els.append((coeffs[0], coeffs[1]))
    return els


# --- simplified SWU on E': y^2 = x^3 + A'x + B' ----------------------------

A_TWIST = (0, 240)          # 240u
B_TWIST = (1012, 1012)      # 1012(1+u)
Z_SSWU = (-2 % P, -1 % P)   # -(2+u)


def _gx_twist(x):
    return F.fq2_add(F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x),
                               F.fq2_mul(A_TWIST, x)), B_TWIST)


def map_to_curve_sswu(t) -> tuple:
    """RFC 9380 simplified SWU, non-constant-time (hashes public messages)."""
    tv1 = F.fq2_mul(Z_SSWU, F.fq2_sqr(t))                 # Z t^2
    tv2 = F.fq2_add(F.fq2_sqr(tv1), tv1)                  # Z^2 t^4 + Z t^2
    neg_b_over_a = F.fq2_mul(F.fq2_neg(B_TWIST), F.fq2_inv(A_TWIST))
    if F.fq2_is_zero(tv2):
        x1 = F.fq2_mul(B_TWIST, F.fq2_inv(F.fq2_mul(Z_SSWU, A_TWIST)))
    else:
        x1 = F.fq2_mul(neg_b_over_a, F.fq2_add(F.FQ2_ONE, F.fq2_inv(tv2)))
    gx1 = _gx_twist(x1)
    y1 = F.fq2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x = F.fq2_mul(tv1, x1)
        y = F.fq2_sqrt(_gx_twist(x))
        assert y is not None, "SSWU: neither candidate square — impossible"
    if F.fq2_sgn0(t) != F.fq2_sgn0(y):
        y = F.fq2_neg(y)
    return (x, y)


# --- 3-isogeny E' -> E (RFC 9380 Appendix E.3 coefficients) -----------------
# Each polynomial is listed low-degree-first in Fq2 pairs (c0, c1).

_ISO3_X_NUM = (
    (0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
     0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6),
    (0,
     0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
    (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
     0),
)
_ISO3_X_DEN = (
    (0,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    (0xC,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    (1, 0),  # monic x^2
)
_ISO3_Y_NUM = (
    (0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
     0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
    (0,
     0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
    (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
     0),
)
_ISO3_Y_DEN = (
    (0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
    (0,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
    (0x12,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
    (1, 0),  # monic x^3
)


def _poly_eval(coeffs, x):
    acc = F.FQ2_ZERO
    for c in reversed(coeffs):
        acc = F.fq2_add(F.fq2_mul(acc, x), c)
    return acc


def iso_map(p) -> tuple | None:
    """3-isogeny E'(Fq2) -> E(Fq2); None (infinity) if x_den vanishes."""
    if p is None:
        return None
    x, y = p
    x_den = _poly_eval(_ISO3_X_DEN, x)
    y_den = _poly_eval(_ISO3_Y_DEN, x)
    if F.fq2_is_zero(x_den) or F.fq2_is_zero(y_den):
        return None
    xo = F.fq2_mul(_poly_eval(_ISO3_X_NUM, x), F.fq2_inv(x_den))
    yo = F.fq2_mul(y, F.fq2_mul(_poly_eval(_ISO3_Y_NUM, x), F.fq2_inv(y_den)))
    return (xo, yo)


# --- cofactor --------------------------------------------------------------

def _compute_twist_cofactor() -> int:
    """h2 = #E'(Fq2)/r from the BLS12 family trace — derived, then sanity-
    checked in tests by killing random twist points."""
    x = BLS_X
    t = x + 1                      # trace of E/Fp
    t2 = t * t - 2 * P             # trace of E/Fp2
    # t2^2 - 4p^2 = -3f^2
    f2, rem = divmod(4 * P * P - t2 * t2, 3)
    assert rem == 0
    f = _isqrt(f2)
    assert f * f == f2
    candidates = [
        P * P + 1 - (t2 + 3 * f) // 2,
        P * P + 1 - (t2 - 3 * f) // 2,
        P * P + 1 + (t2 + 3 * f) // 2,
        P * P + 1 + (t2 - 3 * f) // 2,
    ]
    for n in candidates:
        if n % R == 0 and _order_kills_twist(n):
            return n // R
    raise AssertionError("no sextic-twist order divisible by r found")


def _isqrt(n: int) -> int:
    import math
    return math.isqrt(n)


def _order_kills_twist(n: int) -> bool:
    pt = _arbitrary_twist_point(5)
    return C.g2_mul_full(pt, n) is None


def _arbitrary_twist_point(seed: int):
    """Any point on E (the G2 curve equation) found by x-increment — NOT in
    the r-subgroup generally."""
    x = (seed, seed + 1)
    while True:
        y = F.fq2_sqrt(F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), (4, 4)))
        if y is not None:
            return (x, y)
        x = (x[0] + 1, x[1])


H2_TWIST_COFACTOR = _compute_twist_cofactor()

# RFC 9380 effective cofactor for G2 (what blst multiplies by).  Validated
# structurally in tests: it is an exact integer multiple of the derived
# H2_TWIST_COFACTOR (quotient coprime to r) and sends arbitrary curve points
# into the r-subgroup — properties a wrong constant fails with overwhelming
# probability.
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551


def clear_cofactor_slow(p):
    """Direct h_eff multiplication — the unambiguous oracle."""
    return C.g2_mul_full(p, H_EFF_G2)


# --- psi endomorphism (untwist-Frobenius-twist) -----------------------------
#
# ψ = twist ∘ π ∘ untwist on E(Fq2):  ψ(x, y) = (c_x·x̄, c_y·ȳ) with
# c_x = 1/ξ^((p−1)/3), c_y = 1/ξ^((p−1)/2) for the M-twist tower
# (ξ = 1 + u).  ψ satisfies ψ² − [t]ψ + [p] = 0 (t = trace) and acts as
# multiplication by x on G2 — both identities are asserted in tests, so a
# wrong constant cannot survive.

_PSI_CX = F.fq2_inv(F.fq2_pow(F.XI, (P - 1) // 3))
_PSI_CY = F.fq2_inv(F.fq2_pow(F.XI, (P - 1) // 2))


def psi(p):
    if p is None:
        return None
    x, y = p
    return (F.fq2_mul(_PSI_CX, F.fq2_conj(x)),
            F.fq2_mul(_PSI_CY, F.fq2_conj(y)))


def psi2(p):
    return psi(psi(p))


def clear_cofactor(p):
    """Budroni–Pintore fast cofactor clearing (what blst implements):

        h_eff·P = [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P)
                = ([x]t₁ − t₁ − P) + ψ(t₁ − P) + ψ²([2]P),  t₁ = [x]P

    — two |x|-bit ladders (HW 6) instead of a 636-bit h_eff ladder.
    Equality with :func:`clear_cofactor_slow` on random curve points is
    asserted in tests (two morphisms agreeing on random points are equal
    with overwhelming probability)."""
    if p is None:
        return None
    t1 = C.g2_mul_full(p, -BLS_X)
    t1 = C.g2_neg(t1)                                  # [x]P, x < 0
    t2 = C.g2_neg(C.g2_mul_full(t1, -BLS_X))           # [x²]P
    acc = C.g2_add(C.g2_add(t2, C.g2_neg(t1)), C.g2_neg(p))
    acc = C.g2_add(acc, psi(C.g2_add(t1, C.g2_neg(p))))
    return C.g2_add(acc, psi2(C.g2_add(p, p)))


def g2_subgroup_check_fast(p) -> bool:
    """P ∈ G2  ⟺  ψ(P) == [x]P (on-curve points) — the standard
    endomorphism subgroup check; equivalence with the [r]P == O oracle is
    asserted in tests over valid and invalid points."""
    if p is None:
        return True
    if not C.g2_on_curve(p):
        return False
    xp = C.g2_neg(C.g2_mul_full(p, -BLS_X))
    return psi(p) == xp


# --- branchless sqrt machinery (shared with the device kernel) --------------
#
# q = p² ≡ 9 (mod 16).  For α ≠ 0 let c = α^((q+7)/16); then ω := c²/α =
# α^((q−1)/8) is an 8th root of unity.  With e8 = sqrt(u) (a primitive 8th
# root, e8⁴ = −1) the candidates c·e8^(−k) (k < 4) square to α exactly when
# ω = e8^(2k) (the QR cases), and c·t_k with t_k = sqrt(Z/e8^(2k+1)) square
# to Z·α when ω = e8^(2k+1) (the non-residue cases, where Z/ω is a square
# because both are non-squares).  One 758-bit ladder + 8 cheap candidate
# tests, no branching on field values — the exact scheme the Pallas
# hash-to-curve kernel runs; validated here against :func:`..fields.fq2_sqrt`.

E16_EXP = (P * P + 7) // 16

E8 = F.fq2_sqrt((0, 1))
assert E8 is not None and F.fq2_sqr(E8) == (0, 1)

E8_INV_POWS = tuple(F.fq2_pow(F.fq2_inv(E8), k) for k in range(4))
T_KS = tuple(
    F.fq2_sqrt(F.fq2_mul(Z_SSWU, F.fq2_inv(F.fq2_pow(E8, 2 * k + 1))))
    for k in range(4))
assert all(t is not None for t in T_KS)


def sqrt_or_z_times(alpha):
    """(is_qr, root): root² = α if α is a QR else Z_SSWU·α.  Branchless
    8-candidate scheme (docstring above); host oracle for the kernel."""
    c = F.fq2_pow(alpha, E16_EXP)
    a = (alpha[0] % P, alpha[1] % P)
    for k in range(4):
        cand = F.fq2_mul(c, E8_INV_POWS[k])
        if F.fq2_sqr(cand) == a:
            return True, cand
    for k in range(4):
        cand = F.fq2_mul(c, T_KS[k])
        if F.fq2_sqr(cand) == F.fq2_mul(Z_SSWU, a):
            return False, cand
    raise AssertionError("unreachable: some 8th root of unity must match")


# --- full hash-to-curve ----------------------------------------------------

def hash_to_g2(msg: bytes, dst: bytes = DST_G2) -> tuple:
    """RFC 9380 hash_to_curve (random-oracle variant) onto G2.

    The field half (expand_message_xmd + hash_to_field) runs here; the
    curve half (SSWU → isogeny → cofactor) routes through the native C++
    library when built (~1.5 ms vs ~20 ms; both paths pinned to the RFC
    vectors in tests).  LIGHTHOUSE_TPU_NO_NATIVE=1 forces pure python."""
    from . import native

    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    if native.ready():
        return native.hash_to_g2_u(u0, u1)
    q0 = iso_map(map_to_curve_sswu(u0))
    q1 = iso_map(map_to_curve_sswu(u1))
    return clear_cofactor(C.g2_add(q0, q1))
