"""BLS12-381 G1/G2 group operations + ZCash-format serialization.

Host ground truth for the device curve kernels.  Mirrors the point/encoding
semantics of the reference's blst backend
(``/root/reference/crypto/bls/src/impls/blst.rs``): compressed encodings with
the three ZCash flag bits, infinity handling, subgroup checks, and the
"infinity pubkey is invalid" rule
(``/root/reference/crypto/bls/src/generic_public_key.rs:14-15``).

Points are affine tuples ``(x, y)`` with field elements per group (ints for
G1 over Fq, pairs for G2 over Fq2), and ``None`` for the point at infinity.
Internal arithmetic uses Jacobian coordinates generic over the field.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from . import fields as F
from .fields import P, R


@dataclass(frozen=True)
class _Fld:
    """Field vtable so the Jacobian formulas are written once for Fq/Fq2."""
    add: Callable
    sub: Callable
    mul: Callable
    sqr: Callable
    neg: Callable
    inv: Callable
    muls: Callable  # multiply by small int
    zero: Any
    one: Any
    b: Any          # curve constant: y^2 = x^3 + b


FQ = _Fld(
    add=lambda a, b: (a + b) % P, sub=lambda a, b: (a - b) % P,
    mul=lambda a, b: a * b % P, sqr=lambda a: a * a % P,
    neg=lambda a: -a % P, inv=F.fq_inv,
    muls=lambda a, s: a * s % P,
    zero=0, one=1, b=4,
)

FQ2 = _Fld(
    add=F.fq2_add, sub=F.fq2_sub, mul=F.fq2_mul, sqr=F.fq2_sqr,
    neg=F.fq2_neg, inv=F.fq2_inv, muls=F.fq2_muls,
    zero=F.FQ2_ZERO, one=F.FQ2_ONE, b=(4, 4),  # 4(u + 1)
)

# Standard generators (public constants).
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
     0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
     0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)


# ---------------------------------------------------------------------------
# Jacobian arithmetic, generic over the field
# ---------------------------------------------------------------------------
# Jacobian (X, Y, Z): affine x = X/Z^2, y = Y/Z^3.  Infinity: Z = 0.

def _jac_from_affine(f: _Fld, p):
    if p is None:
        return (f.one, f.one, f.zero)
    return (p[0], p[1], f.one)


def _jac_is_inf(f: _Fld, p) -> bool:
    return p[2] == f.zero


def _jac_double(f: _Fld, p):
    X, Y, Z = p
    if _jac_is_inf(f, p) or Y == f.zero:
        return (f.one, f.one, f.zero)
    A = f.sqr(X)
    B = f.sqr(Y)
    C = f.sqr(B)
    D = f.muls(f.sub(f.sub(f.sqr(f.add(X, B)), A), C), 2)
    E = f.muls(A, 3)
    X3 = f.sub(f.sqr(E), f.muls(D, 2))
    Y3 = f.sub(f.mul(E, f.sub(D, X3)), f.muls(C, 8))
    Z3 = f.muls(f.mul(Y, Z), 2)
    return (X3, Y3, Z3)


def _jac_add(f: _Fld, p, q):
    if _jac_is_inf(f, p):
        return q
    if _jac_is_inf(f, q):
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = f.sqr(Z1)
    Z2Z2 = f.sqr(Z2)
    U1 = f.mul(X1, Z2Z2)
    U2 = f.mul(X2, Z1Z1)
    S1 = f.mul(f.mul(Y1, Z2), Z2Z2)
    S2 = f.mul(f.mul(Y2, Z1), Z1Z1)
    if U1 == U2:
        if S1 == S2:
            return _jac_double(f, p)
        return (f.one, f.one, f.zero)
    H = f.sub(U2, U1)
    I = f.sqr(f.muls(H, 2))
    J = f.mul(H, I)
    rr = f.muls(f.sub(S2, S1), 2)
    V = f.mul(U1, I)
    X3 = f.sub(f.sub(f.sqr(rr), J), f.muls(V, 2))
    Y3 = f.sub(f.mul(rr, f.sub(V, X3)), f.muls(f.mul(S1, J), 2))
    Z3 = f.muls(f.mul(f.mul(Z1, Z2), H), 2)
    return (X3, Y3, Z3)


def _jac_to_affine(f: _Fld, p):
    if _jac_is_inf(f, p):
        return None
    zi = f.inv(p[2])
    zi2 = f.sqr(zi)
    return (f.mul(p[0], zi2), f.mul(p[1], f.mul(zi2, zi)))


def _affine_add(f: _Fld, p, q):
    return _jac_to_affine(
        f, _jac_add(f, _jac_from_affine(f, p), _jac_from_affine(f, q)))


def _affine_mul(f: _Fld, p, k: int):
    k %= R
    acc = (f.one, f.one, f.zero)
    base = _jac_from_affine(f, p)
    while k:
        if k & 1:
            acc = _jac_add(f, acc, base)
        base = _jac_double(f, base)
        k >>= 1
    return _jac_to_affine(f, acc)


def _affine_neg(f: _Fld, p):
    return None if p is None else (p[0], f.neg(p[1]))


def _on_curve(f: _Fld, p) -> bool:
    if p is None:
        return True
    return f.sqr(p[1]) == f.add(f.mul(f.sqr(p[0]), p[0]), f.b)


# Public, per-group API ------------------------------------------------------

def g1_add(p, q):
    return _affine_add(FQ, p, q)


def g1_mul(p, k: int):
    return _affine_mul(FQ, p, k)


def g1_neg(p):
    return _affine_neg(FQ, p)


def g1_on_curve(p) -> bool:
    return _on_curve(FQ, p)


def g1_subgroup_check(p) -> bool:
    return g1_on_curve(p) and g1_mul_full(p, R) is None


def g2_add(p, q):
    return _affine_add(FQ2, p, q)


def g2_mul(p, k: int):
    return _affine_mul(FQ2, p, k)


def g2_neg(p):
    return _affine_neg(FQ2, p)


def g2_on_curve(p) -> bool:
    return _on_curve(FQ2, p)


def g1_mul_full(p, k: int):
    """Scalar mul WITHOUT reduction mod R (for cofactor/order checks)."""
    acc = (FQ.one, FQ.one, FQ.zero)
    base = _jac_from_affine(FQ, p)
    while k:
        if k & 1:
            acc = _jac_add(FQ, acc, base)
        base = _jac_double(FQ, base)
        k >>= 1
    return _jac_to_affine(FQ, acc)


def g2_mul_full(p, k: int):
    acc = (FQ2.one, FQ2.one, FQ2.zero)
    base = _jac_from_affine(FQ2, p)
    while k:
        if k & 1:
            acc = _jac_add(FQ2, acc, base)
        base = _jac_double(FQ2, base)
        k >>= 1
    return _jac_to_affine(FQ2, acc)


def g2_subgroup_check(p) -> bool:
    return g2_on_curve(p) and g2_mul_full(p, R) is None


# ---------------------------------------------------------------------------
# ZCash serialization (48-byte G1 / 96-byte G2 compressed)
# ---------------------------------------------------------------------------
# Flag bits in the most significant byte: 0x80 = compressed, 0x40 = infinity,
# 0x20 = y is the lexicographically larger root.

def _fq_from_bytes(b: bytes) -> int:
    v = int.from_bytes(b, "big")
    if v >= P:
        raise ValueError("field element >= modulus")
    return v


def _y_is_larger_fq(y: int) -> bool:
    return y > P - y


def _y_is_larger_fq2(y) -> bool:
    # Lexicographic with the u-coefficient (c1) most significant.
    ny = F.fq2_neg(y)
    if y[1] != ny[1]:
        return y[1] > ny[1]
    return y[0] > ny[0]


def g1_compress(p: Optional[Tuple[int, int]]) -> bytes:
    if p is None:
        return bytes([0xC0]) + b"\x00" * 47
    out = bytearray(p[0].to_bytes(48, "big"))
    out[0] |= 0x80
    if _y_is_larger_fq(p[1]):
        out[0] |= 0x20
    return bytes(out)


def g1_decompress(b: bytes) -> Optional[Tuple[int, int]]:
    if len(b) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = b[0]
    if not flags & 0x80:
        raise ValueError("uncompressed encoding not accepted here")
    if flags & 0x40:
        if flags & 0x20 or any(b[1:]) or (flags & 0x1F):
            raise ValueError("malformed infinity encoding")
        return None
    x = _fq_from_bytes(bytes([flags & 0x1F]) + b[1:])
    y = F.fq_sqrt((x * x % P * x + 4) % P)
    if y is None:
        raise ValueError("x not on curve")
    if bool(flags & 0x20) != _y_is_larger_fq(y):
        y = P - y
    return (x, y)


def g2_compress(p) -> bytes:
    if p is None:
        return bytes([0xC0]) + b"\x00" * 95
    (x0, x1), y = p[0], p[1]
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= 0x80
    if _y_is_larger_fq2(y):
        out[0] |= 0x20
    return bytes(out)


def g2_decompress(b: bytes):
    if len(b) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = b[0]
    if not flags & 0x80:
        raise ValueError("uncompressed encoding not accepted here")
    if flags & 0x40:
        if flags & 0x20 or any(b[1:]) or (flags & 0x1F):
            raise ValueError("malformed infinity encoding")
        return None
    x1 = _fq_from_bytes(bytes([flags & 0x1F]) + b[1:48])
    x0 = _fq_from_bytes(b[48:])
    x = (x0, x1)
    y = F.fq2_sqrt(F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), FQ2.b))
    if y is None:
        raise ValueError("x not on curve")
    if bool(flags & 0x20) != _y_is_larger_fq2(y):
        y = F.fq2_neg(y)
    return (x, y)
