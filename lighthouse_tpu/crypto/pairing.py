"""Optimal ate pairing on BLS12-381 (host ground truth).

e: G1 x G2 -> GT = mu_r in Fq12.  Miller loop over |x| (the BLS parameter,
``fields.BLS_X``) with a conjugation at the end (x < 0), then the standard
BLS12 final exponentiation: easy part (q^6-1)(q^2+1), hard part via the
Karabina/Scott x-power ladder.

The device kernel batches the Miller loops and shares one final
exponentiation across a product of pairings — the same product-of-pairings
trick blst's ``verify_multiple_aggregate_signatures`` uses
(``/root/reference/crypto/bls/src/impls/blst.rs:110-119``); this module is
the semantics oracle for it.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

from . import fields as F
from .fields import P, BLS_X

_X_ABS = -BLS_X  # positive 0xd201000000010000
_X_BITS = bin(_X_ABS)[3:]  # MSB-first, top bit dropped (implicit leading 1)


# Line evaluations.  G2 points in affine (x, y) over Fq2; the G1 point (px,
# py) over Fq embeds via the twist: we evaluate the line at the G1 point and
# sparse-multiply into the Fq12 accumulator.
#
# With the M-twist layout (Fq12 = Fq6[w], v^3 = xi, w^2 = v) a line
# l(P) = y_p * c0 + (c1 * x_p) * w^2-part + c3 * w^3-part ... rather than
# tracking sparse positions symbolically, we lift G2 points to Fq12 via the
# untwist map and use plain (slow, obviously-correct) Fq12 arithmetic:
#
#   untwist(x, y) = (x / w^2, y / w^3)   with x, y in Fq2 ⊂ Fq12.
#
# Then the chord/tangent line through untwisted points evaluated at the
# (embedded) G1 point is an Fq12 element.  This is the py_ecc-style formulation:
# slow but a faithful oracle for the optimized device kernel.

def _fq12_from_fq2(a) -> tuple:
    """Embed c0 + c1*u in Fq2 into Fq12 (constant coefficient)."""
    return ((a, F.FQ2_ZERO, F.FQ2_ZERO), F.FQ6_ZERO)


def _fq12_from_int(a: int) -> tuple:
    return _fq12_from_fq2((a % P, 0))


# w^2 = v in Fq6 embedded in Fq12; w^-2 = v^-1 = v^2/xi.
_W2 = ((F.FQ2_ZERO, F.FQ2_ONE, F.FQ2_ZERO), F.FQ6_ZERO)          # v
_W3 = (F.FQ6_ZERO, (F.FQ2_ZERO, F.FQ2_ONE, F.FQ2_ZERO))          # v*w
_W2_INV = F.fq12_inv(_W2)
_W3_INV = F.fq12_inv(_W3)


def _untwist(q) -> Tuple[tuple, tuple]:
    """G2 affine (Fq2 pair) -> point on E(Fq12)."""
    x = F.fq12_mul(_fq12_from_fq2(q[0]), _W2_INV)
    y = F.fq12_mul(_fq12_from_fq2(q[1]), _W3_INV)
    return (x, y)


def _line(a, b, pt) -> tuple:
    """Evaluate the line through Fq12 points a, b at pt (all on E(Fq12))."""
    ax, ay = a
    bx, by = b
    px, py = pt
    if ax != bx:
        # chord
        m = F.fq12_mul(F.fq12_sub(by, ay), F.fq12_inv(F.fq12_sub(bx, ax)))
        return F.fq12_sub(F.fq12_sub(py, ay), F.fq12_mul(m, F.fq12_sub(px, ax)))
    if ay == by:
        # tangent
        m = F.fq12_mul(F.fq12_mul(_fq12_from_int(3), F.fq12_mul(ax, ax)),
                       F.fq12_inv(F.fq12_mul(_fq12_from_int(2), ay)))
        return F.fq12_sub(F.fq12_sub(py, ay), F.fq12_mul(m, F.fq12_sub(px, ax)))
    # vertical
    return F.fq12_sub(px, ax)


def _ell_add(a, b):
    """Affine addition on E(Fq12) (no exceptional doubling input)."""
    ax, ay = a
    bx, by = b
    if ax == bx and ay == by:
        m = F.fq12_mul(F.fq12_mul(_fq12_from_int(3), F.fq12_mul(ax, ax)),
                       F.fq12_inv(F.fq12_mul(_fq12_from_int(2), ay)))
    else:
        m = F.fq12_mul(F.fq12_sub(by, ay), F.fq12_inv(F.fq12_sub(bx, ax)))
    x3 = F.fq12_sub(F.fq12_sub(F.fq12_mul(m, m), ax), bx)
    y3 = F.fq12_sub(F.fq12_mul(m, F.fq12_sub(ax, x3)), ay)
    return (x3, y3)


def miller_loop(p, q) -> tuple:
    """f_{|x|,Q}(P) with the x<0 conjugation folded in.  p in G1, q in G2
    (affine, not infinity)."""
    pt = (_fq12_from_int(p[0]), _fq12_from_int(p[1]))
    Q = _untwist(q)
    T = Q
    f = F.FQ12_ONE
    for bit in _X_BITS:
        f = F.fq12_mul(F.fq12_sqr(f), _line(T, T, pt))
        T = _ell_add(T, T)
        if bit == "1":
            f = F.fq12_mul(f, _line(T, Q, pt))
            T = _ell_add(T, Q)
    # x < 0: f_{-|x|} = 1/f_{|x|} (up to final exp) = conjugate in the
    # cyclotomic subgroup — applied after the easy part; conjugating here on
    # the raw Miller value is equivalent post-final-exp.
    return F.fq12_conj(f)


def final_exponentiation(f: tuple) -> tuple:
    """f^((q^12-1)/r), easy part + BLS12 hard part (exact exponent)."""
    # Easy part: f^(q^6 - 1) then ^(q^2 + 1).
    f = F.fq12_mul(F.fq12_conj(f), F.fq12_inv(f))
    f = F.fq12_mul(F.fq12_frobenius(f, 2), f)
    # Hard part (exact integer exponent — slow, unambiguous oracle):
    # (q^4 - q^2 + 1)/r expanded in q with no polynomial tricks.
    e = (pow(P, 4) - pow(P, 2) + 1) // F.R
    return F.fq12_pow(f, e)


def _pow_u(g: tuple) -> tuple:
    """g^u for the (negative) BLS parameter u — cyclotomic g only."""
    return F.fq12_conj(F.fq12_pow(g, _X_ABS))


def final_exponentiation_cubed(f: tuple) -> tuple:
    """f^(3·(q¹²−1)/r) via the Hayashida–Hayasaka–Teruya x-ladder:

        3·(p⁴−p²+1)/r = (u−1)²·(u+p)·(u²+p²−1) + 3

    (identity asserted in tests).  ~400 Fq12 host multiplies instead of a
    2700-bit exponentiation — the fast shared tail for the device pairing
    kernels, whose ``== 1`` semantics are unchanged by the cube (GT has
    prime order r ≠ 3).  Matches the device
    :func:`..limb_pairing.final_exponentiation_cubed` exactly.
    """
    f1 = F.fq12_mul(F.fq12_conj(f), F.fq12_inv(f))
    m = F.fq12_mul(F.fq12_frobenius(f1, 2), f1)
    m1 = F.fq12_mul(_pow_u(m), F.fq12_conj(m))
    k2 = F.fq12_mul(_pow_u(m1), F.fq12_conj(m1))
    k3 = F.fq12_mul(_pow_u(k2), F.fq12_frobenius(k2, 1))
    k4 = F.fq12_mul(F.fq12_mul(_pow_u(_pow_u(k3)), F.fq12_frobenius(k3, 2)),
                    F.fq12_conj(k3))
    return F.fq12_mul(k4, F.fq12_mul(F.fq12_sqr(m), m))


def pairing(p, q) -> tuple:
    """Full pairing e(p, q); identities map to 1."""
    if p is None or q is None:
        return F.FQ12_ONE
    return final_exponentiation(miller_loop(p, q))


def multi_pairing(pairs: Iterable[Tuple[Optional[tuple], Optional[tuple]]]) -> tuple:
    """prod_i e(p_i, q_i) with ONE shared final exponentiation."""
    acc = F.FQ12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue
        acc = F.fq12_mul(acc, miller_loop(p, q))
    return final_exponentiation(acc)


def multi_pairing_is_one(
        pairs: Iterable[Tuple[Optional[tuple], Optional[tuple]]]) -> bool:
    """prod_i e(p_i, q_i) == 1 — the verification predicate.

    Routed through the native C++ pairing (``native/bls381.cpp``, ~8 ms
    for the 2-pairing verify vs ~430 ms pure-python) when it builds;
    identity pairs are dropped here (e(P, O) = 1).  Falls back to the
    python oracle otherwise.  Disable with LIGHTHOUSE_TPU_NO_NATIVE=1
    (tests use this to cross-check the two paths).
    """
    from . import native

    live = [(p, q) for p, q in pairs if p is not None and q is not None]
    if native.ready():
        if not live:
            return True
        return native.multi_pairing_is_one(live)
    return multi_pairing(live) == F.FQ12_ONE
