"""EIP-2335 BLS keystores — ``crypto/eth2_keystore``
(``/root/reference/crypto/eth2_keystore/src/``): scrypt or pbkdf2 key
derivation, AES-128-CTR encryption, SHA-256 checksum, JSON wire format,
NFKD password normalization with control-character stripping."""

from __future__ import annotations

import hashlib
import json
import secrets
import unicodedata
import uuid as uuid_mod
from dataclasses import dataclass
from typing import Optional

# The container may not ship `cryptography`; keystores then fall back to
# the vector-pinned pure-python AES (``aes_fallback``) — the KDF dominates
# keystore cost, so this is a correctness seam, not a performance one.
try:
    from cryptography.hazmat.primitives.ciphers import (
        Cipher, algorithms, modes)
except ModuleNotFoundError:  # pragma: no cover - env dependent
    Cipher = None


class KeystoreError(ValueError):
    pass


def normalize_password(password: str) -> bytes:
    """NFKD + strip C0/C1/DEL control chars (`eth2_keystore` password
    rules)."""
    norm = unicodedata.normalize("NFKD", password)
    return "".join(c for c in norm
                   if not unicodedata.category(c) == "Cc"
                   and c != "\x7f").encode("utf-8")


def _derive_key(password: bytes, kdf: dict) -> bytes:
    params = kdf["params"]
    salt = bytes.fromhex(params["salt"])
    if kdf["function"] == "scrypt":
        return hashlib.scrypt(password, salt=salt, n=params["n"],
                              r=params["r"], p=params["p"],
                              dklen=params["dklen"], maxmem=2**31 - 1)
    if kdf["function"] == "pbkdf2":
        if params.get("prf", "hmac-sha256") != "hmac-sha256":
            raise KeystoreError("unsupported prf")
        return hashlib.pbkdf2_hmac("sha256", password, salt, params["c"],
                                   dklen=params["dklen"])
    raise KeystoreError(f"unknown kdf {kdf['function']}")


def _aes128_ctr(key16: bytes, iv: bytes, data: bytes) -> bytes:
    if Cipher is None:
        from .aes_fallback import aes128_ctr
        return aes128_ctr(key16, iv, data)
    c = Cipher(algorithms.AES(key16), modes.CTR(iv)).encryptor()
    return c.update(data) + c.finalize()


@dataclass
class Keystore:
    """One encrypted secret key (JSON-roundtrippable)."""
    crypto: dict
    pubkey: str
    path: str
    uuid: str
    version: int = 4
    description: str = ""

    @classmethod
    def encrypt(cls, secret: bytes, password: str, *, pubkey: bytes,
                path: str = "", kdf: str = "scrypt",
                scrypt_n: int = 262144) -> "Keystore":
        """`Keystore::encrypt` — scrypt (default) or pbkdf2."""
        pw = normalize_password(password)
        salt = secrets.token_bytes(32)
        if kdf == "scrypt":
            kdf_module = {"function": "scrypt",
                          "params": {"dklen": 32, "n": scrypt_n, "p": 1,
                                     "r": 8, "salt": salt.hex()},
                          "message": ""}
        elif kdf == "pbkdf2":
            kdf_module = {"function": "pbkdf2",
                          "params": {"dklen": 32, "c": 262144,
                                     "prf": "hmac-sha256",
                                     "salt": salt.hex()},
                          "message": ""}
        else:
            raise KeystoreError(f"unknown kdf {kdf}")
        dk = _derive_key(pw, kdf_module)
        iv = secrets.token_bytes(16)
        ciphertext = _aes128_ctr(dk[:16], iv, secret)
        checksum = hashlib.sha256(dk[16:32] + ciphertext).digest()
        crypto = {
            "kdf": kdf_module,
            "checksum": {"function": "sha256", "params": {},
                         "message": checksum.hex()},
            "cipher": {"function": "aes-128-ctr",
                       "params": {"iv": iv.hex()},
                       "message": ciphertext.hex()},
        }
        return cls(crypto=crypto, pubkey=pubkey.hex(), path=path,
                   uuid=str(uuid_mod.uuid4()))

    def decrypt(self, password: str) -> bytes:
        """`Keystore::decrypt` — checksum-gated."""
        pw = normalize_password(password)
        dk = _derive_key(pw, self.crypto["kdf"])
        ciphertext = bytes.fromhex(self.crypto["cipher"]["message"])
        checksum = hashlib.sha256(dk[16:32] + ciphertext).digest()
        if checksum.hex() != self.crypto["checksum"]["message"]:
            raise KeystoreError("invalid password (checksum mismatch)")
        if self.crypto["cipher"]["function"] != "aes-128-ctr":
            raise KeystoreError("unsupported cipher")
        iv = bytes.fromhex(self.crypto["cipher"]["params"]["iv"])
        return _aes128_ctr(dk[:16], iv, ciphertext)

    def to_json(self) -> str:
        return json.dumps({
            "crypto": self.crypto, "description": self.description,
            "pubkey": self.pubkey, "path": self.path, "uuid": self.uuid,
            "version": self.version})

    @classmethod
    def from_json(cls, data: str) -> "Keystore":
        obj = json.loads(data)
        if obj.get("version") != 4:
            raise KeystoreError("only version-4 keystores supported")
        return cls(crypto=obj["crypto"], pubkey=obj.get("pubkey", ""),
                   path=obj.get("path", ""), uuid=obj.get("uuid", ""),
                   version=4, description=obj.get("description", ""))
