"""SSZ base machinery and basic types.

Mirrors the reference's ``Encode``/``Decode`` traits
(``/root/reference/consensus/ssz/src/{encode,decode}.rs``) and the basic-type
impls (``consensus/ssz/src/{encode,decode}/impls.rs``), plus the basic-kind
arm of the ``TreeHash`` trait (``consensus/tree_hash/src/lib.rs:106-121``).

Every SSZ type is a *class* (never instantiated for basic kinds); values are
plain Python objects: ``int``, ``bool``, ``bytes``.  Class-level API:

- ``is_fixed_size()`` / ``fixed_size()``
- ``serialize(value) -> bytes`` / ``deserialize(data) -> value``
- ``hash_tree_root(value) -> bytes`` (32 bytes)
- ``default()``
"""

from __future__ import annotations

import hashlib

from ..ops.merkle import merkleize_host, mix_in_length_host

BYTES_PER_CHUNK = 32
BYTES_PER_LENGTH_OFFSET = 4


class SszError(ValueError):
    """Invalid SSZ bytes or value (the ``DecodeError`` analogue,
    ``/root/reference/consensus/ssz/src/decode.rs:9-40``)."""


def _chunkify(data: bytes) -> list[bytes]:
    """Right-pad to a 32-byte multiple and split into chunks."""
    if not data:
        return []
    pad = (-len(data)) % BYTES_PER_CHUNK
    data = data + b"\x00" * pad
    return [data[i:i + BYTES_PER_CHUNK]
            for i in range(0, len(data), BYTES_PER_CHUNK)]


class SszType:
    """Root of the SSZ type-class hierarchy."""

    @classmethod
    def is_fixed_size(cls) -> bool:
        raise NotImplementedError

    @classmethod
    def fixed_size(cls) -> int:
        raise SszError(f"{cls.__name__} is variable-size")

    @classmethod
    def serialize(cls, value) -> bytes:
        raise NotImplementedError

    @classmethod
    def deserialize(cls, data: bytes):
        raise NotImplementedError

    @classmethod
    def hash_tree_root(cls, value) -> bytes:
        raise NotImplementedError

    @classmethod
    def default(cls):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Unsigned integers
# ---------------------------------------------------------------------------

class _Uint(SszType):
    BITS: int = 0

    @classmethod
    def is_fixed_size(cls) -> bool:
        return True

    @classmethod
    def fixed_size(cls) -> int:
        return cls.BITS // 8

    @classmethod
    def serialize(cls, value) -> bytes:
        try:
            value = value.__index__()  # ints & numpy ints; rejects floats
        except AttributeError:
            raise SszError(f"uint{cls.BITS} requires an integer, "
                           f"got {type(value).__name__}") from None
        if not 0 <= value < (1 << cls.BITS):
            raise SszError(f"{value} out of range for uint{cls.BITS}")
        return value.to_bytes(cls.BITS // 8, "little")

    @classmethod
    def deserialize(cls, data: bytes) -> int:
        if len(data) != cls.BITS // 8:
            raise SszError(
                f"uint{cls.BITS} expects {cls.BITS // 8} bytes, got {len(data)}")
        return int.from_bytes(data, "little")

    @classmethod
    def hash_tree_root(cls, value) -> bytes:
        return cls.serialize(value).ljust(BYTES_PER_CHUNK, b"\x00")

    @classmethod
    def default(cls) -> int:
        return 0


class uint8(_Uint):
    BITS = 8


class uint16(_Uint):
    BITS = 16


class uint32(_Uint):
    BITS = 32


class uint64(_Uint):
    BITS = 64


class uint128(_Uint):
    BITS = 128


class uint256(_Uint):
    BITS = 256


class boolean(SszType):
    @classmethod
    def is_fixed_size(cls) -> bool:
        return True

    @classmethod
    def fixed_size(cls) -> int:
        return 1

    @classmethod
    def serialize(cls, value) -> bytes:
        return b"\x01" if value else b"\x00"

    @classmethod
    def deserialize(cls, data: bytes) -> bool:
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise SszError(f"invalid boolean byte {data!r}")

    @classmethod
    def hash_tree_root(cls, value) -> bytes:
        return cls.serialize(value).ljust(BYTES_PER_CHUNK, b"\x00")

    @classmethod
    def default(cls) -> bool:
        return False


# ---------------------------------------------------------------------------
# Byte vectors / byte lists
# ---------------------------------------------------------------------------

_byte_vector_cache: dict[int, type] = {}
_byte_list_cache: dict[int, type] = {}


def ByteVector(length: int) -> type:
    """Fixed-length opaque bytes (``FixedVector<u8, N>`` fast path,
    ``/root/reference/consensus/ssz_types/src/fixed_vector.rs``)."""
    cls = _byte_vector_cache.get(length)
    if cls is not None:
        return cls

    class _ByteVector(SszType):
        LENGTH = length

        @classmethod
        def is_fixed_size(cls) -> bool:
            return True

        @classmethod
        def fixed_size(cls) -> int:
            return cls.LENGTH

        @classmethod
        def serialize(cls, value) -> bytes:
            value = bytes(value)
            if len(value) != cls.LENGTH:
                raise SszError(
                    f"ByteVector[{cls.LENGTH}] got {len(value)} bytes")
            return value

        @classmethod
        def deserialize(cls, data: bytes) -> bytes:
            return cls.serialize(data)

        @classmethod
        def hash_tree_root(cls, value) -> bytes:
            return merkleize_host(_chunkify(cls.serialize(value)))

        @classmethod
        def default(cls) -> bytes:
            return b"\x00" * cls.LENGTH

    _ByteVector.__name__ = f"ByteVector{length}"
    _byte_vector_cache[length] = _ByteVector
    return _ByteVector


def ByteList(limit: int) -> type:
    """Variable-length opaque bytes with a max length (e.g. transactions —
    ``/root/reference/consensus/types/src/execution_payload.rs`` ``Transaction``)."""
    cls = _byte_list_cache.get(limit)
    if cls is not None:
        return cls

    class _ByteList(SszType):
        LIMIT = limit

        @classmethod
        def is_fixed_size(cls) -> bool:
            return False

        @classmethod
        def serialize(cls, value) -> bytes:
            value = bytes(value)
            if len(value) > cls.LIMIT:
                raise SszError(f"ByteList[{cls.LIMIT}] got {len(value)} bytes")
            return value

        @classmethod
        def deserialize(cls, data: bytes) -> bytes:
            return cls.serialize(data)

        @classmethod
        def hash_tree_root(cls, value) -> bytes:
            value = cls.serialize(value)
            limit_chunks = (cls.LIMIT + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK
            root = merkleize_host(_chunkify(value), limit=max(limit_chunks, 1))
            return mix_in_length_host(root, len(value))

        @classmethod
        def default(cls) -> bytes:
            return b""

    _ByteList.__name__ = f"ByteList{limit}"
    _byte_list_cache[limit] = _ByteList
    return _ByteList


Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)


def hash_concat(a: bytes, b: bytes) -> bytes:
    """``hash32_concat`` (``/root/reference/crypto/eth2_hashing/src/lib.rs:31-37``)."""
    return hashlib.sha256(a + b).digest()
