"""SimpleSerialize (SSZ): encoding, decoding, and Merkleization.

The framework's counterpart of the reference's serialization layer —
``/root/reference/consensus/ssz`` (Encode/Decode), ``consensus/ssz_types``
(length-bounded containers), and ``consensus/tree_hash`` (hash_tree_root).
Where the reference expresses bounds in the type system via ``typenum``,
here each SSZ type is a Python class object carrying its bound; bounds are
still static per type, which is what makes worst-case batch shapes known to
XLA (``SURVEY.md §5.7``).

Host (de)serialization is numpy-accelerated for basic-element vectors/lists;
Merkleization defers to :mod:`lighthouse_tpu.ops.merkle` so that large trees
can run as batched device reductions.
"""

from .core import (
    SszError,
    SszType,
    BYTES_PER_CHUNK,
    BYTES_PER_LENGTH_OFFSET,
    boolean,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
    ByteVector,
    ByteList,
    Bytes4,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
)
from .composite import (
    Vector,
    List,
    Bitvector,
    Bitlist,
    Container,
)

__all__ = [
    "SszError", "SszType", "BYTES_PER_CHUNK", "BYTES_PER_LENGTH_OFFSET",
    "boolean", "uint8", "uint16", "uint32", "uint64", "uint128", "uint256",
    "ByteVector", "ByteList", "Bytes4", "Bytes20", "Bytes32", "Bytes48",
    "Bytes96", "Vector", "List", "Bitvector", "Bitlist", "Container",
]
