"""Spec-JSON encoding of SSZ values — the ``serde_utils`` role
(``/root/reference/consensus/serde_utils/src/``): byte fields as 0x-hex,
every uint as a decimal string, containers as objects, lists as arrays —
the Beacon-API wire convention."""

from __future__ import annotations

from typing import Any

import numpy as np

from .composite import Container


def to_json(value: Any) -> Any:
    """SSZ value → JSON-compatible structure (spec conventions)."""
    if isinstance(value, Container):
        return {name: to_json(getattr(value, name))
                for name in type(value).FIELDS}
    if isinstance(value, (bytes, bytearray)):
        return "0x" + bytes(value).hex()
    if isinstance(value, (bool, np.bool_)):
        return bool(value)
    if isinstance(value, (int, np.integer)):
        return str(int(value))
    if isinstance(value, np.ndarray):
        if value.dtype == np.uint8 and value.ndim == 2:
            return ["0x" + row.tobytes().hex() for row in value]
        return [to_json(v) for v in value.tolist()]
    if isinstance(value, (list, tuple)):
        return [to_json(v) for v in value]
    if hasattr(value, "__iter__"):
        return [to_json(v) for v in value]
    return value


def hex_bytes(data: str) -> bytes:
    if not data.startswith("0x"):
        raise ValueError("expected 0x-prefixed hex")
    return bytes.fromhex(data[2:])


def from_json(cls: type, obj: Any) -> Any:
    """Spec-JSON structure → SSZ value of type ``cls`` — the decode half of
    ``serde_utils`` (the Beacon-API request path: publish block, pool
    submissions).  Inverse of :func:`to_json`."""
    from . import boolean, core
    from ..types.validators import ValidatorRegistry

    name = cls.__name__
    if issubclass(cls, Container):
        kwargs = {}
        for fname, ftype in cls.FIELDS.items():
            if fname not in obj:
                raise core.SszError(f"{name}: missing field {fname}")
            kwargs[fname] = from_json(ftype, obj[fname])
        return cls(**kwargs)
    if cls is boolean:
        if not isinstance(obj, (bool, np.bool_)):
            raise core.SszError(f"{name}: expected a bool")
        return bool(obj)
    if issubclass(cls, core._Uint):
        return int(obj)
    elem = getattr(cls, "ELEM", None)
    if elem is not None:
        if elem.__name__ == "Validator" and hasattr(cls, "LIMIT"):
            vals = [from_json(elem, v) for v in obj]
            return ValidatorRegistry.from_validators(vals)
        if isinstance(obj, str):
            raise core.SszError(f"{name}: expected an array")
        out = [from_json(elem, v) for v in obj]
        if issubclass(elem, core._Uint):
            import numpy as _np
            dtype = {8: _np.uint8, 16: _np.uint16, 32: _np.uint32,
                     64: _np.uint64}.get(elem.BITS)
            if dtype is not None:
                return _np.asarray(out, dtype=dtype)
        return out
    if name.startswith(("Bitvector", "Bitlist")):
        if isinstance(obj, str):  # spec wire form: 0x-hex bitfield
            return cls.deserialize(hex_bytes(obj))
        return [bool(b) for b in obj]
    if isinstance(obj, str):  # ByteVector / ByteList / raw bytes fields
        return hex_bytes(obj)
    if isinstance(obj, list):
        if obj and isinstance(obj[0], str):
            if obj[0].startswith("0x"):
                # Columnar byte-row vectors (roots vectors etc.): rows of
                # equal-width 0x-hex → (n, w) u8 array.
                rows = [hex_bytes(r) for r in obj]
                return np.frombuffer(b"".join(rows), np.uint8).reshape(
                    len(rows), -1).copy()
            # Columnar uint lists (balances, inactivity scores): decimal
            # strings → u64 array.
            return np.asarray([int(v) for v in obj], dtype=np.uint64)
        if obj and isinstance(obj[0], (bool, np.bool_)):
            return np.asarray(obj, dtype=bool)
        if obj and isinstance(obj[0], (int, np.integer)):
            return np.asarray(obj, dtype=np.uint64)
        if not obj:
            return []
    raise core.SszError(f"cannot decode JSON into {name}")
