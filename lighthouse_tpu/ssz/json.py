"""Spec-JSON encoding of SSZ values — the ``serde_utils`` role
(``/root/reference/consensus/serde_utils/src/``): byte fields as 0x-hex,
every uint as a decimal string, containers as objects, lists as arrays —
the Beacon-API wire convention."""

from __future__ import annotations

from typing import Any

import numpy as np

from .composite import Container


def to_json(value: Any) -> Any:
    """SSZ value → JSON-compatible structure (spec conventions)."""
    if isinstance(value, Container):
        return {name: to_json(getattr(value, name))
                for name in type(value).FIELDS}
    if isinstance(value, (bytes, bytearray)):
        return "0x" + bytes(value).hex()
    if isinstance(value, (bool, np.bool_)):
        return bool(value)
    if isinstance(value, (int, np.integer)):
        return str(int(value))
    if isinstance(value, np.ndarray):
        if value.dtype == np.uint8 and value.ndim == 2:
            return ["0x" + row.tobytes().hex() for row in value]
        return [to_json(v) for v in value.tolist()]
    if isinstance(value, (list, tuple)):
        return [to_json(v) for v in value]
    if hasattr(value, "__iter__"):
        return [to_json(v) for v in value]
    return value


def hex_bytes(data: str) -> bytes:
    if not data.startswith("0x"):
        raise ValueError("expected 0x-prefixed hex")
    return bytes.fromhex(data[2:])
