"""SSZ composite types: Vector, List, Bitvector, Bitlist, Container.

Mirrors ``/root/reference/consensus/ssz_types/src/{fixed_vector,variable_list,
bitfield}.rs`` (length-typed bounds) and the container encode/decode scheme of
``consensus/ssz/src/{encode,decode}.rs`` (fixed parts + 4-byte offsets for
variable parts, with the strict offset checks of ``SszDecoderBuilder``).
The ``Container`` metaclass plays the role of ``ssz_derive`` +
``tree_hash_derive`` proc-macros: field layout is read from class annotations.

Basic-element vectors/lists accept and produce numpy arrays where that is the
natural value (hot state fields like ``balances``); serialization of those is
a single little-endian ``tobytes``.
"""

from __future__ import annotations

import numpy as np

from .core import (
    BYTES_PER_CHUNK,
    BYTES_PER_LENGTH_OFFSET,
    SszError,
    SszType,
    _Uint,
    _chunkify,
    boolean,
)
from ..ops.merkle import merkleize_host, mix_in_length_host

_UINT_DTYPES = {8: np.uint8, 16: np.uint16, 32: np.uint32, 64: np.uint64}


def _is_basic(t: type) -> bool:
    return (isinstance(t, type)
            and (issubclass(t, _Uint) or issubclass(t, boolean)))


def _serialize_basic_seq(elem_t: type, values) -> bytes:
    """Fast path: one numpy tobytes for uint sequences, per-element otherwise.

    Range-validated: signed/oversized inputs raise instead of wrapping — the
    consensus encoding must never silently produce wrong bytes.
    """
    if issubclass(elem_t, _Uint) and elem_t.BITS in _UINT_DTYPES:
        dtype = _UINT_DTYPES[elem_t.BITS]
        try:
            arr = np.asarray(values)
        except OverflowError as e:
            raise SszError(f"value out of range for uint{elem_t.BITS}") from e
        if arr.ndim != 1:
            raise SszError("basic sequence must be one-dimensional")
        if arr.size == 0:
            return b""
        if arr.dtype == dtype:
            pass  # already exact — the hot case (state SoA columns)
        elif arr.dtype.kind in "iu" or arr.dtype == bool:
            if arr.dtype.kind == "i" and arr.size and int(arr.min()) < 0:
                raise SszError(f"negative value in uint{elem_t.BITS} sequence")
            if (arr.dtype.itemsize * 8 > elem_t.BITS and arr.size
                    and int(arr.max()) >= (1 << elem_t.BITS)):
                raise SszError(f"value out of range for uint{elem_t.BITS}")
            arr = arr.astype(dtype)
        elif arr.dtype == object:
            # Python ints too big for int64 inference; go per-element.
            return b"".join(elem_t.serialize(v) for v in values)
        else:
            raise SszError(
                f"cannot serialize {arr.dtype} array as uint{elem_t.BITS}")
        return arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes()
    return b"".join(elem_t.serialize(v) for v in values)


def _deserialize_basic_seq(elem_t: type, data: bytes):
    if issubclass(elem_t, _Uint) and elem_t.BITS in _UINT_DTYPES:
        dtype = np.dtype(_UINT_DTYPES[elem_t.BITS]).newbyteorder("<")
        if len(data) % dtype.itemsize:
            raise SszError("byte length not a multiple of element size")
        return np.frombuffer(data, dtype=dtype).astype(
            _UINT_DTYPES[elem_t.BITS])
    size = elem_t.fixed_size()
    if len(data) % size:
        raise SszError("byte length not a multiple of element size")
    return [elem_t.deserialize(data[i:i + size])
            for i in range(0, len(data), size)]


def _seq_len(values) -> int:
    return int(values.shape[0]) if isinstance(values, np.ndarray) else len(values)


def _decode_fixed_seq(elem_t: type, data: bytes):
    """Fixed-size composite elements, concatenated."""
    size = elem_t.fixed_size()
    if len(data) % size:
        raise SszError("byte length not a multiple of element size")
    return [elem_t.deserialize(data[i:i + size])
            for i in range(0, len(data), size)]


def _decode_variable_seq(elem_t: type, data: bytes):
    """Variable-size elements: leading offset table, strictly validated
    (``/root/reference/consensus/ssz/src/decode/impls.rs`` Vec impl)."""
    if not data:
        return []
    if len(data) < BYTES_PER_LENGTH_OFFSET:
        raise SszError("truncated offset table")
    first = int.from_bytes(data[:BYTES_PER_LENGTH_OFFSET], "little")
    if first % BYTES_PER_LENGTH_OFFSET or first == 0:
        raise SszError("invalid first offset")
    count = first // BYTES_PER_LENGTH_OFFSET
    offsets = []
    for i in range(count):
        o = int.from_bytes(
            data[i * 4:(i + 1) * 4], "little")
        offsets.append(o)
    offsets.append(len(data))
    if offsets[0] != first or first > len(data):
        raise SszError("first offset out of bounds")
    out = []
    for i in range(count):
        if offsets[i] > offsets[i + 1]:
            raise SszError("offsets not monotonically increasing")
        out.append(elem_t.deserialize(data[offsets[i]:offsets[i + 1]]))
    return out


def _serialize_variable_seq(elem_t: type, values) -> bytes:
    parts = [elem_t.serialize(v) for v in values]
    fixed_len = BYTES_PER_LENGTH_OFFSET * len(parts)
    offsets = []
    pos = fixed_len
    for p in parts:
        offsets.append(pos.to_bytes(BYTES_PER_LENGTH_OFFSET, "little"))
        pos += len(p)
    return b"".join(offsets) + b"".join(parts)


def _htr_elements(elem_t: type, values, limit_chunks: int) -> bytes:
    """Merkle root of a sequence: packed chunks for basic elements, one
    32-byte root per element for composites
    (``/root/reference/consensus/tree_hash/src/lib.rs`` Vector/List kinds)."""
    if _is_basic(elem_t):
        chunks = _chunkify(_serialize_basic_seq(elem_t, values))
    else:
        chunks = [elem_t.hash_tree_root(v) for v in values]
    return merkleize_host(chunks, limit=max(limit_chunks, 1))


def _basic_chunk_count(elem_t: type, n: int) -> int:
    return (n * elem_t.fixed_size() + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK


_vector_cache: dict[tuple, type] = {}
_list_cache: dict[tuple, type] = {}
_bitvector_cache: dict[int, type] = {}
_bitlist_cache: dict[int, type] = {}


def Vector(elem_t: type, length: int) -> type:
    """``FixedVector<T, N>``: exactly ``length`` elements."""
    key = (elem_t, length)
    cls = _vector_cache.get(key)
    if cls is not None:
        return cls
    if length <= 0:
        raise SszError("Vector length must be positive")

    class _Vector(SszType):
        ELEM = elem_t
        LENGTH = length

        @classmethod
        def is_fixed_size(cls) -> bool:
            return cls.ELEM.is_fixed_size()

        @classmethod
        def fixed_size(cls) -> int:
            if not cls.is_fixed_size():
                return super().fixed_size()
            return cls.ELEM.fixed_size() * cls.LENGTH

        @classmethod
        def serialize(cls, values) -> bytes:
            if _seq_len(values) != cls.LENGTH:
                raise SszError(
                    f"Vector[{cls.ELEM.__name__},{cls.LENGTH}] got "
                    f"{_seq_len(values)} elements")
            if _is_basic(cls.ELEM):
                return _serialize_basic_seq(cls.ELEM, values)
            if cls.ELEM.is_fixed_size():
                return b"".join(cls.ELEM.serialize(v) for v in values)
            return _serialize_variable_seq(cls.ELEM, values)

        @classmethod
        def deserialize(cls, data: bytes):
            if _is_basic(cls.ELEM):
                out = _deserialize_basic_seq(cls.ELEM, data)
            elif cls.ELEM.is_fixed_size():
                out = _decode_fixed_seq(cls.ELEM, data)
            else:
                out = _decode_variable_seq(cls.ELEM, data)
            if _seq_len(out) != cls.LENGTH:
                raise SszError("vector length mismatch")
            return out

        @classmethod
        def hash_tree_root(cls, values) -> bytes:
            if _seq_len(values) != cls.LENGTH:
                raise SszError("vector length mismatch")
            if _is_basic(cls.ELEM):
                limit = _basic_chunk_count(cls.ELEM, cls.LENGTH)
            else:
                limit = cls.LENGTH
            return _htr_elements(cls.ELEM, values, limit)

        @classmethod
        def default(cls):
            if issubclass(cls.ELEM, _Uint) and cls.ELEM.BITS in _UINT_DTYPES:
                return np.zeros(cls.LENGTH, dtype=_UINT_DTYPES[cls.ELEM.BITS])
            return [cls.ELEM.default() for _ in range(cls.LENGTH)]

    _Vector.__name__ = f"Vector[{elem_t.__name__},{length}]"
    _vector_cache[key] = _Vector
    return _Vector


def List(elem_t: type, limit: int) -> type:
    """``VariableList<T, N>``: up to ``limit`` elements.  The bound is what
    makes worst-case device batch shapes static (``SURVEY.md §5.7``)."""
    key = (elem_t, limit)
    cls = _list_cache.get(key)
    if cls is not None:
        return cls

    class _List(SszType):
        ELEM = elem_t
        LIMIT = limit

        @classmethod
        def is_fixed_size(cls) -> bool:
            return False

        @classmethod
        def serialize(cls, values) -> bytes:
            if _seq_len(values) > cls.LIMIT:
                raise SszError(
                    f"List[{cls.ELEM.__name__},{cls.LIMIT}] got "
                    f"{_seq_len(values)} elements")
            if _is_basic(cls.ELEM):
                return _serialize_basic_seq(cls.ELEM, values)
            if cls.ELEM.is_fixed_size():
                return b"".join(cls.ELEM.serialize(v) for v in values)
            return _serialize_variable_seq(cls.ELEM, values)

        @classmethod
        def deserialize(cls, data: bytes):
            if _is_basic(cls.ELEM):
                out = _deserialize_basic_seq(cls.ELEM, data)
            elif cls.ELEM.is_fixed_size():
                out = _decode_fixed_seq(cls.ELEM, data)
            else:
                out = _decode_variable_seq(cls.ELEM, data)
            if _seq_len(out) > cls.LIMIT:
                raise SszError("list exceeds limit")
            return out

        @classmethod
        def hash_tree_root(cls, values) -> bytes:
            n = _seq_len(values)
            if n > cls.LIMIT:
                raise SszError("list exceeds limit")
            if _is_basic(cls.ELEM):
                limit = _basic_chunk_count(cls.ELEM, cls.LIMIT)
            else:
                limit = cls.LIMIT
            root = _htr_elements(cls.ELEM, values, limit)
            return mix_in_length_host(root, n)

        @classmethod
        def default(cls):
            if issubclass(cls.ELEM, _Uint) and cls.ELEM.BITS in _UINT_DTYPES:
                return np.zeros(0, dtype=_UINT_DTYPES[cls.ELEM.BITS])
            return []

    _List.__name__ = f"List[{elem_t.__name__},{limit}]"
    _list_cache[key] = _List
    return _List


# ---------------------------------------------------------------------------
# Bitfields
# ---------------------------------------------------------------------------

def _bits_to_bytes(bits: np.ndarray) -> bytes:
    return np.packbits(bits, bitorder="little").tobytes()


def _bytes_to_bits(data: bytes, n: int) -> np.ndarray:
    return np.unpackbits(
        np.frombuffer(data, dtype=np.uint8), bitorder="little")[:n].astype(bool)


def Bitvector(length: int) -> type:
    """``BitVector<N>`` (``/root/reference/consensus/ssz_types/src/bitfield.rs``)."""
    cls = _bitvector_cache.get(length)
    if cls is not None:
        return cls
    if length <= 0:
        raise SszError("Bitvector length must be positive")

    class _Bitvector(SszType):
        LENGTH = length

        @classmethod
        def is_fixed_size(cls) -> bool:
            return True

        @classmethod
        def fixed_size(cls) -> int:
            return (cls.LENGTH + 7) // 8

        @classmethod
        def serialize(cls, bits) -> bytes:
            bits = np.asarray(bits, dtype=bool)
            if bits.shape != (cls.LENGTH,):
                raise SszError(f"Bitvector[{cls.LENGTH}] shape mismatch")
            return _bits_to_bytes(bits)

        @classmethod
        def deserialize(cls, data: bytes) -> np.ndarray:
            if len(data) != cls.fixed_size():
                raise SszError("bitvector byte length mismatch")
            # Excess high bits in the last byte must be zero.
            all_bits = np.unpackbits(
                np.frombuffer(data, dtype=np.uint8), bitorder="little")
            if all_bits[cls.LENGTH:].any():
                raise SszError("bitvector has set padding bits")
            return all_bits[:cls.LENGTH].astype(bool)

        @classmethod
        def hash_tree_root(cls, bits) -> bytes:
            limit = (cls.LENGTH + 255) // 256
            return merkleize_host(_chunkify(cls.serialize(bits)),
                                  limit=max(limit, 1))

        @classmethod
        def default(cls) -> np.ndarray:
            return np.zeros(cls.LENGTH, dtype=bool)

    _Bitvector.__name__ = f"Bitvector[{length}]"
    _bitvector_cache[length] = _Bitvector
    return _Bitvector


def Bitlist(limit: int) -> type:
    """``BitList<N>`` with the trailing delimiter bit."""
    cls = _bitlist_cache.get(limit)
    if cls is not None:
        return cls

    class _Bitlist(SszType):
        LIMIT = limit

        @classmethod
        def is_fixed_size(cls) -> bool:
            return False

        @classmethod
        def serialize(cls, bits) -> bytes:
            bits = np.asarray(bits, dtype=bool)
            if bits.ndim != 1 or bits.shape[0] > cls.LIMIT:
                raise SszError(f"Bitlist[{cls.LIMIT}] length mismatch")
            with_delim = np.append(bits, True)
            return _bits_to_bytes(with_delim)

        @classmethod
        def deserialize(cls, data: bytes) -> np.ndarray:
            if not data:
                raise SszError("empty bitlist bytes")
            if data[-1] == 0:
                raise SszError("bitlist missing delimiter bit")
            all_bits = np.unpackbits(
                np.frombuffer(data, dtype=np.uint8), bitorder="little")
            # data[-1] != 0, so the delimiter (highest set bit) is in the
            # last byte.
            n = len(all_bits) - 1 - int(np.argmax(all_bits[::-1]))
            if n > cls.LIMIT:
                raise SszError("bitlist exceeds limit")
            return all_bits[:n].astype(bool)

        @classmethod
        def hash_tree_root(cls, bits) -> bytes:
            bits = np.asarray(bits, dtype=bool)
            if bits.shape[0] > cls.LIMIT:
                raise SszError("bitlist exceeds limit")
            limit = (cls.LIMIT + 255) // 256
            root = merkleize_host(_chunkify(_bits_to_bytes(bits)),
                                  limit=max(limit, 1))
            return mix_in_length_host(root, int(bits.shape[0]))

        @classmethod
        def default(cls) -> np.ndarray:
            return np.zeros(0, dtype=bool)

    _Bitlist.__name__ = f"Bitlist[{limit}]"
    _bitlist_cache[limit] = _Bitlist
    return _Bitlist


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------

class ContainerMeta(type):
    """Collects SSZ field layout from class annotations — the framework's
    stand-in for ``#[derive(Encode, Decode, TreeHash)]``
    (``/root/reference/consensus/ssz_derive/src/lib.rs``)."""

    def __new__(mcs, name, bases, ns):
        import sys
        cls = super().__new__(mcs, name, bases, ns)
        # Inherit already-resolved base layouts (base-first field order,
        # like superstruct's common-field prefix), then this class's own
        # annotations.
        fields: dict[str, type] = {}
        for base in bases:
            fields.update(getattr(base, "FIELDS", {}))
        try:
            defining_globals = sys._getframe(1).f_globals
        except Exception:
            defining_globals = {}
        for fname, ftype in ns.get("__annotations__", {}).items():
            if isinstance(ftype, str):
                # PEP 563 (`from __future__ import annotations`) turns
                # annotations into strings; resolve them in the defining
                # scope, loudly, rather than silently producing an empty
                # field layout.
                try:
                    ftype = eval(ftype, defining_globals, dict(ns))  # noqa: S307
                except Exception as e:
                    raise SszError(
                        f"{name}.{fname}: cannot resolve string annotation "
                        f"{ftype!r} (PEP 563)") from e
            if isinstance(ftype, type) and issubclass(ftype, SszType):
                fields[fname] = ftype
        cls.FIELDS = fields
        return cls


class Container(SszType, metaclass=ContainerMeta):
    """SSZ container; subclass with annotated fields:

    ``class Checkpoint(Container): epoch: uint64; root: Bytes32``

    Instances hold field values as attributes.  Field order = annotation
    order (MRO base-first), matching SSZ's struct field order.
    """

    FIELDS: dict[str, type] = {}

    def __init__(self, **kwargs):
        cls = type(self)
        for fname, ftype in cls.FIELDS.items():
            if fname in kwargs:
                setattr(self, fname, kwargs.pop(fname))
            else:
                setattr(self, fname, ftype.default())
        if kwargs:
            raise TypeError(
                f"{cls.__name__} has no fields {sorted(kwargs)}")

    def __eq__(self, other):
        if type(self) is not type(other):
            return NotImplemented
        for fname in type(self).FIELDS:
            a, b = getattr(self, fname), getattr(other, fname)
            if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
                if not np.array_equal(np.asarray(a), np.asarray(b)):
                    return False
            elif a != b:
                return False
        return True

    def __repr__(self):
        inner = ", ".join(f"{f}={getattr(self, f)!r}"
                          for f in list(type(self).FIELDS)[:4])
        more = "" if len(type(self).FIELDS) <= 4 else ", …"
        return f"{type(self).__name__}({inner}{more})"

    def copy(self):
        """Field-shallow copy: containers recurse, lists/arrays are copied,
        scalars/bytes shared (immutable)."""
        out = type(self).__new__(type(self))
        for fname in type(self).FIELDS:
            v = getattr(self, fname)
            if isinstance(v, Container):
                v = v.copy()
            elif isinstance(v, np.ndarray):
                v = v.copy()
            elif getattr(v, "__ssz_mutable__", False):
                v = v.copy()  # e.g. the SoA ValidatorRegistry
            elif isinstance(v, list):
                v = [e.copy() if isinstance(e, Container)
                     else (e.copy() if isinstance(e, np.ndarray) else e)
                     for e in v]
            setattr(out, fname, v)
        return out

    # -- SszType classmethods ------------------------------------------------

    @classmethod
    def is_fixed_size(cls) -> bool:
        return all(t.is_fixed_size() for t in cls.FIELDS.values())

    @classmethod
    def fixed_size(cls) -> int:
        if not cls.is_fixed_size():
            return super().fixed_size()
        return sum(t.fixed_size() for t in cls.FIELDS.values())

    @classmethod
    def serialize(cls, value) -> bytes:
        # Class-level API (uniform with every SszType); instances use
        # ``encode()``.
        self = value
        fixed_parts: list[bytes | None] = []
        variable_parts: list[bytes] = []
        for fname, ftype in cls.FIELDS.items():
            v = getattr(self, fname)
            if ftype.is_fixed_size():
                fixed_parts.append(ftype.serialize(v))
                variable_parts.append(b"")
            else:
                fixed_parts.append(None)
                variable_parts.append(ftype.serialize(v))
        fixed_len = sum(
            len(p) if p is not None else BYTES_PER_LENGTH_OFFSET
            for p in fixed_parts)
        out = []
        pos = fixed_len
        for p, v in zip(fixed_parts, variable_parts):
            if p is not None:
                out.append(p)
            else:
                out.append(pos.to_bytes(BYTES_PER_LENGTH_OFFSET, "little"))
                pos += len(v)
        out.extend(v for v in variable_parts if v)
        return b"".join(out)

    def encode(self) -> bytes:
        return type(self).serialize(self)

    @classmethod
    def deserialize(cls, data: bytes):
        """Strict offset-validated decode (``SszDecoderBuilder``,
        ``/root/reference/consensus/ssz/src/decode.rs:196-344``)."""
        fixed_len = sum(
            t.fixed_size() if t.is_fixed_size() else BYTES_PER_LENGTH_OFFSET
            for t in cls.FIELDS.values())
        if len(data) < fixed_len:
            raise SszError(
                f"{cls.__name__}: {len(data)} bytes < fixed length {fixed_len}")
        values = {}
        offsets: list[tuple[str, type, int]] = []
        pos = 0
        for fname, ftype in cls.FIELDS.items():
            if ftype.is_fixed_size():
                size = ftype.fixed_size()
                values[fname] = ftype.deserialize(data[pos:pos + size])
                pos += size
            else:
                off = int.from_bytes(
                    data[pos:pos + BYTES_PER_LENGTH_OFFSET], "little")
                offsets.append((fname, ftype, off))
                pos += BYTES_PER_LENGTH_OFFSET
        if offsets:
            if offsets[0][2] != fixed_len:
                raise SszError("first offset does not point at end of fixed part")
            bounds = [o for (_, _, o) in offsets] + [len(data)]
            for i, (fname, ftype, off) in enumerate(offsets):
                if bounds[i] > bounds[i + 1] or off > len(data):
                    raise SszError("container offsets invalid")
                values[fname] = ftype.deserialize(data[bounds[i]:bounds[i + 1]])
        elif len(data) != fixed_len:
            raise SszError("trailing bytes after fixed-size container")
        out = cls.__new__(cls)
        for fname in cls.FIELDS:
            setattr(out, fname, values[fname])
        return out

    @classmethod
    def hash_tree_root(cls, value) -> bytes:
        self = value
        leaves = [ftype.hash_tree_root(getattr(self, fname))
                  for fname, ftype in cls.FIELDS.items()]
        return merkleize_host(leaves)

    def tree_hash_root(self) -> bytes:
        return type(self).hash_tree_root(self)

    @classmethod
    def default(cls):
        return cls()
