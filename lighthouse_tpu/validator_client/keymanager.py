"""Keymanager HTTP API — the VC's standard key-management surface
(``validator_client/src/http_api``: ``keystores.rs`` / ``remotekeys.rs``,
implementing the Ethereum keymanager-API spec).

Routes (all require ``Authorization: Bearer <api-token>``; the reference
mints the token into ``api-token.txt`` at startup — ``api_secret.rs``):

- ``GET    /eth/v1/keystores``    — list local keys
- ``POST   /eth/v1/keystores``    — import EIP-2335 keystores (+ optional
  EIP-3076 slashing-protection interchange)
- ``DELETE /eth/v1/keystores``    — remove keys, export their
  slashing-protection history (the spec requires history to travel with
  the key so it can never attest unprotected elsewhere)
- ``GET/POST/DELETE /eth/v1/remotekeys`` — web3signer-backed keys
"""

from __future__ import annotations

import json
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

from ..common.logging import Logger, test_logger
from ..crypto.keystore import Keystore, KeystoreError
from .signing import Web3SignerMethod
from .store import ValidatorStore


def mint_api_token() -> str:
    """`api_secret.rs` — a bearer token the operator reads from disk."""
    return "api-token-0x" + secrets.token_hex(32)


class KeymanagerServer:
    def __init__(self, store: ValidatorStore, *,
                 genesis_validators_root: bytes = b"\x00" * 32,
                 token: Optional[str] = None, host: str = "127.0.0.1",
                 port: int = 0, log: Optional[Logger] = None):
        self.store = store
        self.gvr = genesis_validators_root
        self.token = token or mint_api_token()
        self.log = (log or test_logger()).child("keymanager")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authed(self) -> bool:
                import hmac as _hmac
                auth = self.headers.get("Authorization", "")
                if _hmac.compare_digest(auth, "Bearer " + outer.token):
                    return True
                self._json({"code": 401, "message": "invalid token"}, 401)
                return False

            def do_GET(self):
                if not self._authed():
                    return
                outer._route(self, "GET", b"")

            def do_POST(self):
                if not self._authed():
                    return
                n = int(self.headers.get("Content-Length", 0))
                outer._route(self, "POST", self.rfile.read(n))

            def do_DELETE(self):
                if not self._authed():
                    return
                n = int(self.headers.get("Content-Length", 0))
                outer._route(self, "DELETE", self.rfile.read(n))

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        self.log.info("keymanager API listening", port=self.port)

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- routing -------------------------------------------------------------

    def _route(self, h, method: str, body: bytes) -> None:
        path = urlparse(h.path).path.rstrip("/")
        try:
            if path == "/eth/v1/keystores":
                h._json(getattr(self, f"_keystores_{method.lower()}")(body))
            elif path == "/eth/v1/remotekeys":
                h._json(getattr(self, f"_remotekeys_{method.lower()}")(body))
            else:
                h._json({"code": 404, "message": "unknown route"}, 404)
        except (ValueError, KeyError) as e:
            h._json({"code": 400, "message": str(e)}, 400)

    # -- /eth/v1/keystores ---------------------------------------------------

    def _local_pubkeys(self):
        return [pk for pk, m in self.store.keys.items()
                if not isinstance(m, Web3SignerMethod)]

    def _keystores_get(self, body: bytes) -> dict:
        return {"data": [{
            "validating_pubkey": "0x" + pk.hex(),
            "derivation_path": "",
            "readonly": False,
        } for pk in self._local_pubkeys()]}

    def _keystores_post(self, body: bytes) -> dict:
        req = json.loads(body)
        keystores = req["keystores"]
        passwords = req["passwords"]
        if len(keystores) != len(passwords):
            raise ValueError("keystores/passwords length mismatch")
        if req.get("slashing_protection"):
            self.store.slashing_db.import_interchange(
                req["slashing_protection"], self.gvr)
        statuses = []
        for ks_json, password in zip(keystores, passwords):
            try:
                ks = Keystore.from_json(
                    ks_json if isinstance(ks_json, str)
                    else json.dumps(ks_json))
                pk = self.store.import_keystore(ks, password)
                statuses.append({"status": "imported",
                                 "message": "0x" + pk.hex()})
            except (KeystoreError, ValueError) as e:
                statuses.append({"status": "error", "message": str(e)})
        return {"data": statuses}

    def _keystores_delete(self, body: bytes) -> dict:
        req = json.loads(body)
        statuses = []
        for pk_hex in req["pubkeys"]:
            pk = bytes.fromhex(pk_hex[2:] if pk_hex.startswith("0x")
                               else pk_hex)
            if self.store.remove_validator(pk):
                statuses.append({"status": "deleted"})
            else:
                statuses.append({"status": "not_found"})
        # History for deleted keys travels with them (keymanager spec).
        interchange = self.store.slashing_db.export_interchange(self.gvr)
        return {"data": statuses, "slashing_protection": interchange}

    # -- /eth/v1/remotekeys --------------------------------------------------

    def _remote_methods(self):
        return {pk: m for pk, m in self.store.keys.items()
                if isinstance(m, Web3SignerMethod)}

    def _remotekeys_get(self, body: bytes) -> dict:
        return {"data": [{
            "pubkey": "0x" + pk.hex(),
            "url": m.url,
            "readonly": False,
        } for pk, m in self._remote_methods().items()]}

    def _remotekeys_post(self, body: bytes) -> dict:
        req = json.loads(body)
        statuses = []
        for item in req["remote_keys"]:
            try:
                pk_hex = item["pubkey"]
                pk = bytes.fromhex(pk_hex[2:] if pk_hex.startswith("0x")
                                   else pk_hex)
                if len(pk) != 48:
                    raise ValueError("pubkey must be 48 bytes")
                self.store.add_web3signer_validator(item["url"], pk)
                statuses.append({"status": "imported"})
            except (KeyError, ValueError) as e:
                statuses.append({"status": "error", "message": str(e)})
        return {"data": statuses}

    def _remotekeys_delete(self, body: bytes) -> dict:
        req = json.loads(body)
        statuses = []
        remote = self._remote_methods()
        for pk_hex in req["pubkeys"]:
            pk = bytes.fromhex(pk_hex[2:] if pk_hex.startswith("0x")
                               else pk_hex)
            if pk in remote and self.store.remove_validator(pk):
                statuses.append({"status": "deleted"})
            else:
                statuses.append({"status": "not_found"})
        return {"data": statuses}
