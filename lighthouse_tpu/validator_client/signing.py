"""Signing methods: local keystore vs web3signer remote signing —
``validator_client/src/signing_method.rs:78-89`` (the ``SigningMethod``
enum whose variants share one ``get_signature`` seam).

The remote method speaks the Consensys web3signer HTTP protocol
(``POST /api/v1/eth2/sign/{pubkey}`` with a typed JSON body carrying the
signing root and fork info); the local method holds the decrypted secret
key.  ``ValidatorStore`` computes roots and enforces slashing protection
identically for both — remote signing changes WHERE the key lives, not
what may be signed.
"""

from __future__ import annotations

import http.client
import json
from typing import Optional
from urllib.parse import urlparse

from ..crypto import bls


class SigningError(RuntimeError):
    pass


class LocalKeystore:
    """In-process secret key (`signing_method.rs` SigningMethod::LocalKeystore)."""

    def __init__(self, sk: bls.SecretKey):
        self.sk = sk

    @property
    def pubkey(self) -> bytes:
        return self.sk.public_key().serialize()

    def sign(self, signing_root: bytes, *, msg_type: str = "",
             fork_info: Optional[dict] = None,
             extra: Optional[dict] = None) -> bytes:
        return self.sk.sign(signing_root).serialize()


class Web3SignerMethod:
    """Remote signer (`signing_method.rs` SigningMethod::Web3Signer).

    One persistent connection per signer URL; the key never enters this
    process.  ``msg_type`` follows the web3signer API enum (BLOCK_V2,
    ATTESTATION, RANDAO_REVEAL, SYNC_COMMITTEE_MESSAGE, ...).
    """

    def __init__(self, url: str, pubkey: bytes, timeout: float = 5.0):
        self.url = url.rstrip("/")
        self._pubkey = pubkey
        self.timeout = timeout
        self._parsed = urlparse(self.url)
        self._conn: Optional[http.client.HTTPConnection] = None

    @property
    def pubkey(self) -> bytes:
        return self._pubkey

    def sign(self, signing_root: bytes, *, msg_type: str = "",
             fork_info: Optional[dict] = None,
             extra: Optional[dict] = None) -> bytes:
        body = {"type": msg_type or "AGGREGATION_SLOT",
                "signingRoot": "0x" + bytes(signing_root).hex()}
        if fork_info:
            body["fork_info"] = fork_info
        if extra:
            body.update(extra)
        path = (f"{self._parsed.path}/api/v1/eth2/sign/"
                f"0x{self._pubkey.hex()}")
        payload = json.dumps(body)
        headers = {"Content-Type": "application/json",
                   "Accept": "application/json"}
        for attempt in (0, 1):
            conn = self._conn
            if conn is None:
                conn = http.client.HTTPConnection(
                    self._parsed.hostname or "127.0.0.1",
                    self._parsed.port or 9000, timeout=self.timeout)
            try:
                conn.request("POST", path, payload, headers)
                resp = conn.getresponse()
                data = resp.read()
                self._conn = conn
                break
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                self._conn = None
                if attempt:
                    raise SigningError(f"web3signer transport failure: {e}")
        if resp.status == 404:
            raise SigningError("web3signer: key not found")
        if resp.status == 412:
            raise SigningError("web3signer: slashing-protection veto")
        if resp.status != 200:
            raise SigningError(f"web3signer: HTTP {resp.status}")
        text = data.decode().strip()
        if text.startswith("{"):
            text = json.loads(text).get("signature", "")
        if not text.startswith("0x"):
            raise SigningError(f"web3signer: malformed response {text[:40]!r}")
        return bytes.fromhex(text[2:])
