"""EIP-3076 slashing protection —
``validator_client/slashing_protection``
(``/root/reference/validator_client/slashing_protection/src/``): a SQLite
database of every signed block and attestation, consulted BEFORE every
signature; refuses double blocks, double votes and surround votes; imports
and exports the EIP-3076 interchange format."""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Optional


class SlashingProtectionError(ValueError):
    """A signing attempt that would be slashable."""


class SlashingDatabase:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            c = self._conn
            c.execute("""CREATE TABLE IF NOT EXISTS signed_blocks (
                pubkey BLOB NOT NULL, slot INTEGER NOT NULL,
                signing_root BLOB, PRIMARY KEY (pubkey, slot))""")
            c.execute("""CREATE TABLE IF NOT EXISTS signed_attestations (
                pubkey BLOB NOT NULL, source_epoch INTEGER NOT NULL,
                target_epoch INTEGER NOT NULL, signing_root BLOB,
                PRIMARY KEY (pubkey, target_epoch))""")
            c.execute("""CREATE TABLE IF NOT EXISTS metadata (
                key TEXT PRIMARY KEY, value BLOB)""")
            c.commit()

    # -- blocks --------------------------------------------------------------

    def check_and_insert_block_proposal(self, pubkey: bytes, slot: int,
                                        signing_root: bytes) -> None:
        """Refuse any proposal at or below the max seen slot, except an
        exact re-sign of the same root (EIP-3076 rules)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT slot, signing_root FROM signed_blocks WHERE "
                "pubkey=? AND slot=?", (pubkey, slot)).fetchone()
            if row is not None:
                if row[1] == signing_root:
                    return  # identical re-sign is safe
                raise SlashingProtectionError(
                    f"double block proposal at slot {slot}")
            mx = self._conn.execute(
                "SELECT MAX(slot) FROM signed_blocks WHERE pubkey=?",
                (pubkey,)).fetchone()[0]
            if mx is not None and slot <= mx:
                raise SlashingProtectionError(
                    f"proposal slot {slot} not above previous max {mx}")
            self._conn.execute(
                "INSERT INTO signed_blocks (pubkey, slot, signing_root) "
                "VALUES (?,?,?)", (pubkey, slot, signing_root))
            self._conn.commit()

    # -- attestations --------------------------------------------------------

    def check_and_insert_attestation(self, pubkey: bytes, source_epoch: int,
                                     target_epoch: int,
                                     signing_root: bytes) -> None:
        """Double-vote + surround-vote checks (both directions)."""
        if source_epoch > target_epoch:
            raise SlashingProtectionError("source after target")
        with self._lock:
            row = self._conn.execute(
                "SELECT source_epoch, signing_root FROM signed_attestations "
                "WHERE pubkey=? AND target_epoch=?",
                (pubkey, target_epoch)).fetchone()
            if row is not None:
                if row[1] == signing_root and row[0] == source_epoch:
                    return
                raise SlashingProtectionError(
                    f"double vote for target {target_epoch}")
            # This attestation surrounds a previous one.
            surrounded = self._conn.execute(
                "SELECT 1 FROM signed_attestations WHERE pubkey=? AND "
                "source_epoch>? AND target_epoch<?",
                (pubkey, source_epoch, target_epoch)).fetchone()
            if surrounded:
                raise SlashingProtectionError(
                    f"vote {source_epoch}->{target_epoch} surrounds a "
                    "previous vote")
            # A previous attestation surrounds this one.
            surrounding = self._conn.execute(
                "SELECT 1 FROM signed_attestations WHERE pubkey=? AND "
                "source_epoch<? AND target_epoch>?",
                (pubkey, source_epoch, target_epoch)).fetchone()
            if surrounding:
                raise SlashingProtectionError(
                    f"vote {source_epoch}->{target_epoch} is surrounded by "
                    "a previous vote")
            # Monotonic source guard (interchange minimality).
            mx = self._conn.execute(
                "SELECT MAX(target_epoch) FROM signed_attestations "
                "WHERE pubkey=?", (pubkey,)).fetchone()[0]
            if mx is not None and target_epoch <= mx:
                raise SlashingProtectionError(
                    f"target {target_epoch} not above previous max {mx}")
            self._conn.execute(
                "INSERT INTO signed_attestations (pubkey, source_epoch, "
                "target_epoch, signing_root) VALUES (?,?,?,?)",
                (pubkey, source_epoch, target_epoch, signing_root))
            self._conn.commit()

    # -- EIP-3076 interchange ------------------------------------------------

    def export_interchange(self, genesis_validators_root: bytes) -> str:
        with self._lock:
            data = []
            pubkeys = [r[0] for r in self._conn.execute(
                "SELECT DISTINCT pubkey FROM signed_blocks UNION "
                "SELECT DISTINCT pubkey FROM signed_attestations")]
            for pk in pubkeys:
                blocks = [{"slot": str(s),
                           "signing_root": "0x" + (sr or b"").hex()}
                          for s, sr in self._conn.execute(
                              "SELECT slot, signing_root FROM signed_blocks "
                              "WHERE pubkey=? ORDER BY slot", (pk,))]
                atts = [{"source_epoch": str(se), "target_epoch": str(te),
                         "signing_root": "0x" + (sr or b"").hex()}
                        for se, te, sr in self._conn.execute(
                            "SELECT source_epoch, target_epoch, signing_root "
                            "FROM signed_attestations WHERE pubkey=? "
                            "ORDER BY target_epoch", (pk,))]
                data.append({"pubkey": "0x" + pk.hex(),
                             "signed_blocks": blocks,
                             "signed_attestations": atts})
        return json.dumps({
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root":
                    "0x" + genesis_validators_root.hex()},
            "data": data})

    def import_interchange(self, payload: str,
                           genesis_validators_root: bytes) -> int:
        obj = json.loads(payload)
        gvr = obj["metadata"]["genesis_validators_root"]
        if bytes.fromhex(gvr[2:]) != genesis_validators_root:
            raise SlashingProtectionError(
                "interchange genesis_validators_root mismatch")
        n = 0
        with self._lock:
            for entry in obj["data"]:
                pk = bytes.fromhex(entry["pubkey"][2:])
                for b in entry.get("signed_blocks", []):
                    self._conn.execute(
                        "INSERT OR IGNORE INTO signed_blocks "
                        "(pubkey, slot, signing_root) VALUES (?,?,?)",
                        (pk, int(b["slot"]),
                         bytes.fromhex(b.get("signing_root",
                                             "0x")[2:] or "")))
                    n += 1
                for a in entry.get("signed_attestations", []):
                    self._conn.execute(
                        "INSERT OR IGNORE INTO signed_attestations "
                        "(pubkey, source_epoch, target_epoch, signing_root) "
                        "VALUES (?,?,?,?)",
                        (pk, int(a["source_epoch"]), int(a["target_epoch"]),
                         bytes.fromhex(a.get("signing_root",
                                             "0x")[2:] or "")))
                    n += 1
            self._conn.commit()
        return n
