"""Beacon-node handles for the validator client.

``InProcessBeaconNode`` adapts a :class:`~lighthouse_tpu.beacon_chain.
BeaconChain` to the duty/production/publish API the services consume (the
``common/eth2`` typed HTTP client's role, minus the wire);
``BeaconNodeFallback`` is the multi-node redundancy router
(``validator_client/src/beacon_node_fallback.rs:317,465`` —
``first_success`` over healthy nodes)."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..state_transition.helpers import (
    current_epoch,
    get_randao_mix,
)
from ..state_transition.per_block import get_expected_withdrawals
from ..types.chain_spec import ForkName


@dataclass
class ProposerDuty:
    slot: int
    validator_index: int


@dataclass
class AttesterDuty:
    slot: int
    committee_index: int
    committee_position: int
    committee_length: int
    validator_index: int


class BeaconNodeError(RuntimeError):
    pass


class InProcessBeaconNode:
    """Direct-object beacon node (node_test_rig style)."""

    def __init__(self, chain):
        self.chain = chain
        self.healthy = True

    # -- info ----------------------------------------------------------------

    def head_root(self) -> bytes:
        return self.chain.head.root

    def genesis_validators_root(self) -> bytes:
        return bytes(self.chain.head.state.genesis_validators_root)

    # -- duties --------------------------------------------------------------

    def proposer_duties(self, epoch: int) -> List[ProposerDuty]:
        """`DutiesService` proposer poll (`duties_service.rs`) — served
        from the chain's pre-materialized duty cache; the lookahead
        usually primed it during the slot tail, so this is a list read,
        not an epoch of shuffles."""
        preset = self.chain.preset
        cache = self.chain.duty_cache(epoch)
        first = epoch * preset.SLOTS_PER_EPOCH
        return [ProposerDuty(first + k, cache.proposers[k])
                for k in range(preset.SLOTS_PER_EPOCH)]

    def attester_duties(self, epoch: int,
                        indices: Sequence[int]) -> List[AttesterDuty]:
        cache = self.chain.duty_cache(epoch)
        n = len(self.chain.head.state.validators)
        out = []
        for vi in indices:
            duty = cache.attester_duty(int(vi), n)
            if duty is not None:
                slot, ci, pos, length = duty
                out.append(AttesterDuty(slot, ci, pos, length, int(vi)))
        return out

    def liveness(self, epoch: int, indices: Sequence[int]) -> List[bool]:
        """Doppelganger probe: was the validator seen attesting this
        epoch? (`/lighthouse/liveness` endpoint role)."""
        seen = self.chain.observed_attesters
        return [seen.has_attested(epoch, int(i)) for i in indices]

    # -- attestation data ----------------------------------------------------

    def attestation_data(self, slot: int, committee_index: int):
        """`produce_unaggregated_attestation` (`beacon_chain.rs`) via the
        attester caches — NO state copy or slot advance on the hot path
        (`attester_cache.rs` / `early_attester_cache.rs`; primed by the
        3/4-slot timer and at block import)."""
        chain = self.chain
        epoch = slot // chain.preset.SLOTS_PER_EPOCH
        entry = chain.attestation_data_parts(slot)
        T = chain.T
        return T.AttestationData(
            slot=slot, index=committee_index,
            beacon_block_root=chain.head.root,
            source=T.Checkpoint(epoch=entry.source_epoch,
                                root=entry.source_root),
            target=T.Checkpoint(epoch=epoch, root=entry.target_root))

    # -- production ----------------------------------------------------------

    def produce_block(self, slot: int, randao_reveal: bytes,
                      graffiti: bytes = b"\x00" * 32):
        """Unsigned block assembly from the pool + mock payload
        (`produce_block_on_state`, `beacon_chain.rs:4133`; payload via the
        MockExecutionLayer-style generator).  The hot path is
        `produce_block_components`: adopt the speculatively pre-advanced
        state → pack the pool on device → assemble; the whole assembly
        is timed into the ``block_production_ms`` SLO."""
        import time as _time
        chain = self.chain
        preset, spec, T = chain.preset, chain.spec, chain.T
        t0 = _time.perf_counter()
        parts = chain.produce_block_components(slot, randao_reveal,
                                               graffiti)
        state = parts["state"]
        fork = spec.fork_name_at_epoch(slot // preset.SLOTS_PER_EPOCH)
        body_kw = dict(
            randao_reveal=randao_reveal,
            eth1_data=state.eth1_data,
            graffiti=graffiti.ljust(32, b"\x00"),
            proposer_slashings=parts["proposer_slashings"],
            attester_slashings=parts["attester_slashings"],
            attestations=parts["attestations"],
            deposits=[],
            voluntary_exits=parts["voluntary_exits"],
        )
        if fork >= ForkName.ALTAIR:
            # Real aggregate from the naive sync-message pool: the block at
            # slot N carries votes for the parent root signed at slot N-1
            # (`process_sync_aggregate` previous-slot semantics).
            body_kw["sync_aggregate"] = self.chain.sync_message_pool.aggregate(
                slot - 1, chain.head.root, T)
        if fork >= ForkName.BELLATRIX:
            body_kw["execution_payload"] = self._payload(state, fork)
        if fork >= ForkName.CAPELLA:
            body_kw["bls_to_execution_changes"] = parts[
                "bls_to_execution_changes"]
        body = T.body_cls(fork)(**body_kw)
        block = T.block_cls(fork)(
            slot=slot, proposer_index=parts["proposer_index"],
            parent_root=parts["parent_root"], state_root=b"\x00" * 32,
            body=body)
        # Fill the state root (NoVerification scratch application).
        from ..state_transition.per_block import (
            SignatureStrategy, process_block)
        scratch = state.copy()
        dummy = T.signed_block_cls(fork)(
            message=block, signature=b"\xc0" + b"\x00" * 95)
        process_block(scratch, dummy, fork, preset, spec, T,
                      strategy=SignatureStrategy.NO_VERIFICATION)
        block.state_root = scratch.tree_hash_root()
        chain.note_block_production(_time.perf_counter() - t0)
        return block

    def _payload(self, state, fork: ForkName):
        T, preset, spec = self.chain.T, self.chain.preset, self.chain.spec
        parent_hash = bytes(state.latest_execution_payload_header.block_hash)
        kw = dict(
            parent_hash=parent_hash,
            prev_randao=get_randao_mix(
                state, current_epoch(state, preset), preset),
            block_number=int(
                state.latest_execution_payload_header.block_number) + 1,
            gas_limit=30_000_000,
            timestamp=int(state.genesis_time)
            + int(state.slot) * spec.seconds_per_slot,
            block_hash=hashlib.sha256(
                parent_hash + int(state.slot).to_bytes(8, "little")).digest(),
        )
        if fork >= ForkName.CAPELLA:
            kw["withdrawals"] = [
                T.Withdrawal(index=w[0], validator_index=w[1], address=w[2],
                             amount=w[3])
                for w in get_expected_withdrawals(state, preset)]
        return T.payload_cls(fork)(**kw)

    # -- publication ---------------------------------------------------------

    def publish_block(self, signed_block) -> bytes:
        self.chain.per_slot_task(int(signed_block.message.slot))
        return self.chain.process_block(signed_block, is_timely=True)

    def submit_attestations(self, atts: List) -> None:
        self.chain.process_attestation_batch(atts)

    # -- sync committee ------------------------------------------------------

    def _pk_to_index(self, reg) -> dict:
        """pubkey → validator index, maintained incrementally (the
        registry only grows and pubkeys are immutable once set — the
        `ValidatorPubkeyCache` role; rebuilding per slot would walk the
        whole 1M-entry registry inside the slot budget)."""
        cache = getattr(self, "_pk_index_cache", None)
        if cache is None:
            cache = self._pk_index_cache = [0, {}]
        n, table = cache
        if n < len(reg):
            pk = reg.pubkey
            for i in range(n, len(reg)):
                table[pk[i].tobytes()] = i
            cache[0] = len(reg)
        return table

    def sync_committee_positions(self, indices: Sequence[int]
                                 ) -> dict[int, list[int]]:
        """validator index → committee positions in the CURRENT sync
        committee (`/eth2/v1/validator/duties/sync` role)."""
        state = self.chain.head.state
        if not hasattr(state, "current_sync_committee"):
            return {}
        pk_to_index = self._pk_to_index(state.validators)
        out: dict[int, list[int]] = {}
        wanted = set(int(i) for i in indices)
        for pos, pk in enumerate(state.current_sync_committee.pubkeys):
            vi = pk_to_index.get(bytes(pk))
            if vi is not None and vi in wanted:
                out.setdefault(vi, []).append(pos)
        return out

    def submit_sync_messages(self, slot: int, block_root: bytes,
                             items: List) -> None:
        """items: (positions, signature) per validator — naive-aggregated
        for the next block's SyncAggregate."""
        for positions, sig in items:
            self.chain.sync_message_pool.insert(slot, block_root,
                                                positions, sig)

    # -- preparation ---------------------------------------------------------

    def prepare_proposers(self, preparations: List) -> None:
        """(validator_index, fee_recipient) registrations
        (`preparation_service.rs` → `prepare_beacon_proposer`)."""
        store = getattr(self.chain, "proposer_preparations", None)
        if store is None:
            store = self.chain.proposer_preparations = {}
        for idx, fee_recipient in preparations:
            store[int(idx)] = bytes(fee_recipient)


class BeaconNodeFallback:
    """`first_success` routing over candidate nodes."""

    def __init__(self, nodes: List):
        self.nodes = list(nodes)

    def first_success(self, fn: Callable):
        last_err: Optional[Exception] = None
        for node in self.nodes:
            if not getattr(node, "healthy", True):
                continue
            try:
                return fn(node)
            except Exception as e:  # noqa: BLE001 — try the next node
                last_err = e
        raise BeaconNodeError(f"all beacon nodes failed: {last_err}")
