"""Validator store: initialized keys + slashing-protected signing —
``validator_client/src/validator_store.rs`` and
``signing_method.rs:78-89`` (local-keystore signing; a remote-signer
method slots into the same seam)."""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..crypto import bls
from ..state_transition.helpers import compute_signing_root, get_domain
from ..types.chain_spec import Domain
from .slashing_protection import SlashingDatabase, SlashingProtectionError


class ValidatorStore:
    def __init__(self, slashing_db: Optional[SlashingDatabase] = None):
        self.keys: Dict[bytes, bls.SecretKey] = {}  # pubkey → sk
        self.index_by_pubkey: Dict[bytes, int] = {}
        self.slashing_db = slashing_db or SlashingDatabase()
        self.doppelganger_blocked: set[bytes] = set()

    # -- keys ----------------------------------------------------------------

    def add_validator(self, sk: bls.SecretKey,
                      index: Optional[int] = None) -> bytes:
        pk = sk.public_key().serialize()
        self.keys[pk] = sk
        if index is not None:
            self.index_by_pubkey[pk] = index
        return pk

    def import_keystore(self, keystore, password: str,
                        index: Optional[int] = None) -> bytes:
        secret = keystore.decrypt(password)
        return self.add_validator(bls.SecretKey.deserialize(secret), index)

    def pubkeys(self) -> List[bytes]:
        return list(self.keys)

    def indices(self) -> List[int]:
        return [self.index_by_pubkey[pk] for pk in self.keys
                if pk in self.index_by_pubkey]

    # -- signing (slashing-protected) ---------------------------------------

    def _check_doppelganger(self, pubkey: bytes) -> None:
        if pubkey in self.doppelganger_blocked:
            raise SlashingProtectionError(
                "doppelganger protection: signing disabled")

    def sign_block(self, pubkey: bytes, block, state, preset) -> bytes:
        self._check_doppelganger(pubkey)
        epoch = int(block.slot) // preset.SLOTS_PER_EPOCH
        domain = get_domain(state, Domain.BEACON_PROPOSER, epoch, preset)
        signing_root = compute_signing_root(block, domain)
        self.slashing_db.check_and_insert_block_proposal(
            pubkey, int(block.slot), signing_root)
        return self.keys[pubkey].sign(signing_root).serialize()

    def sign_attestation(self, pubkey: bytes, data, state, preset) -> bytes:
        self._check_doppelganger(pubkey)
        domain = get_domain(state, Domain.BEACON_ATTESTER,
                            int(data.target.epoch), preset)
        signing_root = compute_signing_root(data, domain)
        self.slashing_db.check_and_insert_attestation(
            pubkey, int(data.source.epoch), int(data.target.epoch),
            signing_root)
        return self.keys[pubkey].sign(signing_root).serialize()

    def sign_randao(self, pubkey: bytes, epoch: int, state, preset) -> bytes:
        self._check_doppelganger(pubkey)
        from ..ssz import uint64
        domain = get_domain(state, Domain.RANDAO, epoch, preset)
        root = compute_signing_root(uint64.hash_tree_root(epoch), domain)
        return self.keys[pubkey].sign(root).serialize()

    def sign_sync_committee_message(self, pubkey: bytes, slot: int,
                                    block_root: bytes, state,
                                    preset) -> bytes:
        """Sync-committee vote over a beacon block root (`sync_committee
        _service.rs` signing; not slashable — no DB entry)."""
        self._check_doppelganger(pubkey)
        domain = get_domain(state, Domain.SYNC_COMMITTEE,
                            slot // preset.SLOTS_PER_EPOCH, preset)
        root = compute_signing_root(bytes(block_root), domain)
        return self.keys[pubkey].sign(root).serialize()
