"""Validator store: initialized keys + slashing-protected signing —
``validator_client/src/validator_store.rs`` and ``signing_method.rs``
(each key is backed by a :class:`~.signing.LocalKeystore` or a
:class:`~.signing.Web3SignerMethod`; the store computes the signing roots
and enforces slashing protection identically for both)."""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..crypto import bls
from ..state_transition.helpers import compute_signing_root, get_domain
from ..types.chain_spec import Domain
from .signing import LocalKeystore, Web3SignerMethod
from .slashing_protection import SlashingDatabase, SlashingProtectionError


class ValidatorStore:
    def __init__(self, slashing_db: Optional[SlashingDatabase] = None):
        self.keys: Dict[bytes, object] = {}  # pubkey → signing method
        self.index_by_pubkey: Dict[bytes, int] = {}
        self.slashing_db = slashing_db or SlashingDatabase()
        self.doppelganger_blocked: set[bytes] = set()

    # -- keys ----------------------------------------------------------------

    def add_validator(self, sk: bls.SecretKey,
                      index: Optional[int] = None) -> bytes:
        return self.add_signing_method(LocalKeystore(sk), index)

    def add_web3signer_validator(self, url: str, pubkey: bytes,
                                 index: Optional[int] = None) -> bytes:
        return self.add_signing_method(Web3SignerMethod(url, pubkey), index)

    def add_signing_method(self, method,
                           index: Optional[int] = None) -> bytes:
        pk = method.pubkey
        self.keys[pk] = method
        if index is not None:
            self.index_by_pubkey[pk] = index
        return pk

    def remove_validator(self, pubkey: bytes) -> bool:
        self.index_by_pubkey.pop(pubkey, None)
        return self.keys.pop(pubkey, None) is not None

    def import_keystore(self, keystore, password: str,
                        index: Optional[int] = None) -> bytes:
        secret = keystore.decrypt(password)
        return self.add_validator(bls.SecretKey.deserialize(secret), index)

    def pubkeys(self) -> List[bytes]:
        return list(self.keys)

    def indices(self) -> List[int]:
        return [self.index_by_pubkey[pk] for pk in self.keys
                if pk in self.index_by_pubkey]

    # -- signing (slashing-protected) ---------------------------------------

    def _check_doppelganger(self, pubkey: bytes) -> None:
        if pubkey in self.doppelganger_blocked:
            raise SlashingProtectionError(
                "doppelganger protection: signing disabled")

    @staticmethod
    def _fork_info(state) -> dict:
        f = state.fork
        return {"fork": {
            "previous_version": "0x" + bytes(f.previous_version).hex(),
            "current_version": "0x" + bytes(f.current_version).hex(),
            "epoch": str(int(f.epoch))},
            "genesis_validators_root":
                "0x" + bytes(state.genesis_validators_root).hex()}

    def sign_block(self, pubkey: bytes, block, state, preset) -> bytes:
        self._check_doppelganger(pubkey)
        epoch = int(block.slot) // preset.SLOTS_PER_EPOCH
        domain = get_domain(state, Domain.BEACON_PROPOSER, epoch, preset)
        signing_root = compute_signing_root(block, domain)
        self.slashing_db.check_and_insert_block_proposal(
            pubkey, int(block.slot), signing_root)
        return self.keys[pubkey].sign(
            signing_root, msg_type="BLOCK_V2",
            fork_info=self._fork_info(state))

    def sign_attestation(self, pubkey: bytes, data, state, preset) -> bytes:
        self._check_doppelganger(pubkey)
        domain = get_domain(state, Domain.BEACON_ATTESTER,
                            int(data.target.epoch), preset)
        signing_root = compute_signing_root(data, domain)
        self.slashing_db.check_and_insert_attestation(
            pubkey, int(data.source.epoch), int(data.target.epoch),
            signing_root)
        return self.keys[pubkey].sign(
            signing_root, msg_type="ATTESTATION",
            fork_info=self._fork_info(state))

    def sign_randao(self, pubkey: bytes, epoch: int, state, preset) -> bytes:
        self._check_doppelganger(pubkey)
        from ..ssz import uint64
        domain = get_domain(state, Domain.RANDAO, epoch, preset)
        root = compute_signing_root(uint64.hash_tree_root(epoch), domain)
        return self.keys[pubkey].sign(
            root, msg_type="RANDAO_REVEAL",
            fork_info=self._fork_info(state))

    def sign_sync_committee_message(self, pubkey: bytes, slot: int,
                                    block_root: bytes, state,
                                    preset) -> bytes:
        """Sync-committee vote over a beacon block root (`sync_committee
        _service.rs` signing; not slashable — no DB entry)."""
        self._check_doppelganger(pubkey)
        domain = get_domain(state, Domain.SYNC_COMMITTEE,
                            slot // preset.SLOTS_PER_EPOCH, preset)
        root = compute_signing_root(bytes(block_root), domain)
        return self.keys[pubkey].sign(
            root, msg_type="SYNC_COMMITTEE_MESSAGE",
            fork_info=self._fork_info(state))
