"""Validator services — the per-slot machinery of
``ProductionValidatorClient::start_service``
(``/root/reference/validator_client/src/lib.rs:88-520``):

- :class:`DutiesService` — polls proposer/attester duties per epoch
  (``duties_service.rs``);
- :class:`BlockService` — randao sign → produce via BN → sign (slashing
  protected) → publish (``block_service.rs``);
- :class:`AttestationService` — attest at the duty slot
  (``attestation_service.rs``);
- :class:`DoppelgangerService` — refuse to sign for two epochs while
  watching liveness for our keys (``doppelganger_service.rs:253,421``);
- :class:`ValidatorClient` — wires them over a
  :class:`~.beacon_node.BeaconNodeFallback`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..common.logging import Logger, test_logger
from .beacon_node import AttesterDuty, BeaconNodeFallback, ProposerDuty
from .store import ValidatorStore


class DutiesService:
    def __init__(self, store: ValidatorStore, fallback: BeaconNodeFallback,
                 preset):
        self.store = store
        self.fallback = fallback
        self.preset = preset
        self.proposers: Dict[int, List[ProposerDuty]] = {}
        self.attesters: Dict[int, List[AttesterDuty]] = {}

    def poll(self, epoch: int) -> None:
        ours = set(self.store.indices())
        props = self.fallback.first_success(
            lambda bn: bn.proposer_duties(epoch))
        self.proposers[epoch] = [d for d in props
                                 if d.validator_index in ours]
        self.attesters[epoch] = self.fallback.first_success(
            lambda bn: bn.attester_duties(epoch, sorted(ours)))

    def proposer_at(self, slot: int) -> Optional[ProposerDuty]:
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        for d in self.proposers.get(epoch, []):
            if d.slot == slot:
                return d
        return None

    def attesters_at(self, slot: int) -> List[AttesterDuty]:
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        return [d for d in self.attesters.get(epoch, []) if d.slot == slot]


class BlockService:
    def __init__(self, store: ValidatorStore, duties: DutiesService,
                 fallback: BeaconNodeFallback, preset, log: Logger):
        self.store = store
        self.duties = duties
        self.fallback = fallback
        self.preset = preset
        self.log = log.child("block_service")

    def on_slot(self, slot: int) -> Optional[bytes]:
        duty = self.duties.proposer_at(slot)
        if duty is None:
            return None
        pk = next((p for p, i in self.store.index_by_pubkey.items()
                   if i == duty.validator_index), None)
        if pk is None or pk in self.store.doppelganger_blocked:
            return None  # doppelganger watch: don't attempt signing
        epoch = slot // self.preset.SLOTS_PER_EPOCH

        def produce(bn):
            state = bn.chain.head.state
            reveal = self.store.sign_randao(pk, epoch, state, self.preset)
            block = bn.produce_block(slot, reveal)
            sig = self.store.sign_block(pk, block, state, self.preset)
            T = bn.chain.T
            fork = bn.chain.spec.fork_name_at_epoch(epoch)
            signed = T.signed_block_cls(fork)(message=block, signature=sig)
            return bn.publish_block(signed)

        root = self.fallback.first_success(produce)
        self.log.info("block proposed", slot=slot,
                      validator=duty.validator_index)
        return root


class AttestationService:
    def __init__(self, store: ValidatorStore, duties: DutiesService,
                 fallback: BeaconNodeFallback, preset, log: Logger):
        self.store = store
        self.duties = duties
        self.fallback = fallback
        self.preset = preset
        self.log = log.child("attestation_service")

    def on_slot(self, slot: int) -> int:
        duties = self.duties.attesters_at(slot)
        if not duties:
            return 0

        def attest(bn):
            atts = []
            for duty in duties:
                pk = next((p for p, i in self.store.index_by_pubkey.items()
                           if i == duty.validator_index), None)
                if pk is None or pk in self.store.doppelganger_blocked:
                    continue
                data = bn.attestation_data(slot, duty.committee_index)
                sig = self.store.sign_attestation(
                    pk, data, bn.chain.head.state, self.preset)
                bits = [False] * duty.committee_length
                bits[duty.committee_position] = True
                T = bn.chain.T
                atts.append(T.Attestation(
                    aggregation_bits=bits, data=data, signature=sig))
            bn.submit_attestations(atts)
            return len(atts)

        n = self.fallback.first_success(attest)
        self.log.debug("attested", slot=slot, count=n)
        return n


class SyncCommitteeService:
    """Per-slot sync-committee signing (`sync_committee_service.rs`): each
    member signs the current head root; the BN naive-aggregates into the
    next block's SyncAggregate."""

    def __init__(self, store: ValidatorStore, fallback: BeaconNodeFallback,
                 preset, log: Logger):
        self.store = store
        self.fallback = fallback
        self.preset = preset
        self.log = log.child("sync_committee_service")

    def on_slot(self, slot: int) -> int:
        def run(bn):
            duties = bn.sync_committee_positions(self.store.indices())
            if not duties:
                return 0
            head_root = bn.chain.head.root
            state = bn.chain.head.state
            items = []
            for vi, positions in duties.items():
                pk = next((p for p, i in self.store.index_by_pubkey.items()
                           if i == vi), None)
                if pk is None or pk in self.store.doppelganger_blocked:
                    continue
                sig = self.store.sign_sync_committee_message(
                    pk, slot, head_root, state, self.preset)
                items.append((positions, sig))
            bn.submit_sync_messages(slot, head_root, items)
            return len(items)

        n = self.fallback.first_success(run)
        if n:
            self.log.debug("sync committee signed", slot=slot, count=n)
        return n


class PreparationService:
    """Fee-recipient registration (`preparation_service.rs`): tell the BN
    which execution address each managed proposer wants, once per epoch."""

    def __init__(self, store: ValidatorStore, fallback: BeaconNodeFallback,
                 preset, log: Logger,
                 fee_recipient: bytes = b"\x00" * 20):
        self.store = store
        self.fallback = fallback
        self.preset = preset
        self.fee_recipient = fee_recipient
        self.log = log.child("preparation_service")
        self._last_epoch = -1

    def on_slot(self, slot: int) -> None:
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        if epoch == self._last_epoch:
            return
        self._last_epoch = epoch
        preparations = [(i, self.fee_recipient)
                        for i in self.store.indices()]
        self.fallback.first_success(
            lambda bn: bn.prepare_proposers(preparations))
        self.log.debug("proposers prepared", epoch=epoch,
                       count=len(preparations))


class DoppelgangerService:
    """Two-epoch liveness watch before any signing
    (`doppelganger_service.rs:253,421`)."""

    EPOCHS_TO_WATCH = 2

    def __init__(self, store: ValidatorStore, fallback: BeaconNodeFallback,
                 start_epoch: int, log: Logger):
        self.store = store
        self.fallback = fallback
        self.start_epoch = start_epoch
        self.log = log.child("doppelganger")
        self.detected: set[bytes] = set()
        self.complete = False
        self._probed: set[int] = set()
        # Initially every key is blocked.
        store.doppelganger_blocked = set(store.pubkeys())

    def check_epoch(self, epoch: int) -> None:
        """Probe liveness for the previously-COMPLETED epoch only, and stop
        for good once the watch window is done.

        The reference never checks an epoch this VC itself may have signed
        in (``doppelganger_service.rs:253,421``): probing the in-progress
        epoch after the keys are released would observe our *own*
        attestations, mark every key as a doppelganger, and re-block them
        permanently.
        """
        if self.complete:
            return
        probe = epoch - 1
        if probe < self.start_epoch or probe in self._probed:
            return  # no fully-completed watch epoch yet / already probed
        pks = self.store.pubkeys()
        indices = [self.store.index_by_pubkey[pk] for pk in pks]
        live = self.fallback.first_success(
            lambda bn: bn.liveness(probe, indices))
        # Only a probe that actually RAN counts toward the watch window —
        # marking before the query would let a transient BN outage skip an
        # epoch's check while still counting it toward release.
        self._probed.add(probe)
        for pk, is_live in zip(pks, live):
            if is_live:
                self.detected.add(pk)
                self.log.crit("doppelganger detected", pubkey=pk.hex()[:12])
        if len(self._probed) >= self.EPOCHS_TO_WATCH \
                and probe >= self.start_epoch + self.EPOCHS_TO_WATCH - 1:
            # Watch over — but only after EPOCHS_TO_WATCH epochs were
            # actually probed: a VC resuming at epoch N ≥ start+2 must not
            # release on a single liveness query.  Release is permanent.
            self.store.doppelganger_blocked = set(self.detected)
            self.complete = True


class ValidatorClient:
    """`ProductionValidatorClient` — service assembly + slot driver."""

    def __init__(self, store: ValidatorStore, nodes: List, preset,
                 log: Optional[Logger] = None, doppelganger: bool = False):
        self.store = store
        self.preset = preset
        self.log = log or test_logger()
        self.fallback = BeaconNodeFallback(nodes)
        self.duties = DutiesService(store, self.fallback, preset)
        self.blocks = BlockService(store, self.duties, self.fallback,
                                   preset, self.log)
        self.attestations = AttestationService(store, self.duties,
                                               self.fallback, preset,
                                               self.log)
        self.sync_committee = SyncCommitteeService(store, self.fallback,
                                                   preset, self.log)
        self.preparation = PreparationService(store, self.fallback, preset,
                                              self.log)
        self.doppelganger: Optional[DoppelgangerService] = (
            DoppelgangerService(store, self.fallback, 0, self.log)
            if doppelganger else None)

    def on_slot(self, slot: int) -> None:
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        if epoch not in self.duties.proposers:
            self.duties.poll(epoch)
        if self.doppelganger is not None:
            self.doppelganger.check_epoch(epoch)
        self.preparation.on_slot(slot)
        self.blocks.on_slot(slot)
        self.attestations.on_slot(slot)
        self.sync_committee.on_slot(slot)
