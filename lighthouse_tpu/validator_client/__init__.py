"""Validator client — counterpart of ``validator_client``
(``/root/reference/validator_client/src/lib.rs:88-520``): duties, block
proposal, attestation production, doppelganger protection, multi-BN
fallback, all over a beacon-node handle seam (in-process here, HTTP in a
wire deployment) with EIP-3076 slashing protection enforced in the
validator store before every signature."""

from .slashing_protection import SlashingDatabase, SlashingProtectionError
from .store import ValidatorStore
from .beacon_node import BeaconNodeFallback, InProcessBeaconNode
from .services import (
    AttestationService,
    BlockService,
    DoppelgangerService,
    DutiesService,
    ValidatorClient,
)

__all__ = [
    "SlashingDatabase", "SlashingProtectionError", "ValidatorStore",
    "BeaconNodeFallback", "InProcessBeaconNode", "DutiesService",
    "BlockService", "AttestationService", "DoppelgangerService",
    "ValidatorClient",
]
