"""Device proto-array fork choice — batched score-delta application.

:class:`DeviceProtoArrayForkChoice` is API-compatible with the host
:class:`~.proto_array.ProtoArrayForkChoice` (the bit-for-bit oracle behind
``LIGHTHOUSE_TPU_DEVICE_FORKCHOICE=0``) but holds the tree as
:class:`~.columnar.NodeColumns` and votes in a
:class:`~.columnar.VoteBuffer`, so a whole slot's attestations apply as
ONE batched pass instead of a per-validator python loop.

Two engines share the columnar state:

- ``numpy`` — the vectorized host passes in :mod:`.columnar` (default off
  accelerator; this is what the whole test/sim fleet runs on CPU);
- ``jit`` — ``compute_deltas`` + ``apply_score_changes`` fused into one
  jitted XLA program per (node-bucket, validator-bucket) shape: a
  segment-sum of vote deltas over the registry followed by a bottom-up
  weight/best-child propagation driven by the precomputed level schedule
  (a ``fori_loop`` over tree depth — dynamic trip count, so depth never
  recompiles).  Validator-sized state (current/next votes, persisted
  balances) stays device-resident between flushes alongside the PR 6
  resident registry columns: per flush the host pushes only the CHANGED
  vote scatters, the new justified balances, and n-node-sized masks, and
  pulls back three small node columns (weight/best-child/best-descendant)
  plus nothing else.  Like the epoch sweep, the kernel traces and runs
  inside a local ``jax.experimental.enable_x64()`` so uint64 balance
  arithmetic matches numpy bit-for-bit.

Engine selection: ``LIGHTHOUSE_TPU_FORKCHOICE_JIT=1`` forces the jitted
engine, ``=0`` forces numpy, unset auto-selects jit only when a TPU is
attached (CPU jit is correctness-equal but compile-bound at test shapes).
All jitted programs here are merkle-scale — seconds to compile on CPU —
so the differential suite is quick-tier safe.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ops.merkle import _next_pow2
from .columnar import (
    NodeColumns,
    VoteBuffer,
    apply_scores,
    compute_deltas_host,
)
from .proto_array import (
    EXEC_INVALID,
    EXEC_IRRELEVANT,
    EXEC_VALID,
    ProtoArrayError,
    ProtoArrayForkChoice,
    VoteTracker,
    ZERO_ROOT,
)

_ENGINE_AUTO: Optional[str] = None


def device_fork_choice_enabled() -> bool:
    """The oracle knob: ``LIGHTHOUSE_TPU_DEVICE_FORKCHOICE=0`` routes
    :class:`~.fork_choice.ForkChoice` through the host proto-array."""
    from ..common.knobs import knob_bool
    return knob_bool("LIGHTHOUSE_TPU_DEVICE_FORKCHOICE")


def _resolve_engine(engine: Optional[str]) -> str:
    if engine in ("numpy", "jit"):
        return engine
    from ..common.knobs import knob_tribool
    forced = knob_tribool("LIGHTHOUSE_TPU_FORKCHOICE_JIT")
    if forced is not None:
        return "jit" if forced else "numpy"
    global _ENGINE_AUTO
    if _ENGINE_AUTO is None:
        try:
            import jax
            _ENGINE_AUTO = ("jit" if jax.default_backend() == "tpu"
                            else "numpy")
        except Exception:
            _ENGINE_AUTO = "numpy"
    return _ENGINE_AUTO


def _bucket(k: int, floor: int = 16) -> int:
    return max(_next_pow2(max(int(k), 1)), floor)


# ---------------------------------------------------------------------------
# Fused jitted kernel: vote-delta segment sum + level-scheduled propagation.
# One compiled program per (n_pad, nv_pad); cached here, persisted by the
# common compile cache like every other kernel.
# ---------------------------------------------------------------------------

_KERNELS: dict = {}
_MESH_KERNELS: dict = {}
_SCATTERS: dict = {}


def _kernel_parts(n_pad: int):
    """The fused round split at its one mesh-shardable seam: the vote
    segment-sum runs per validator shard (``local_delta``; partials are
    exact under an integer ``psum``) and everything node-indexed —
    proposer boosts included, so a ``psum`` over ``ndev`` shards never
    multiplies them — stays in the replicated ``propagate`` body.  The
    1-device fused kernel composes the same two parts back-to-back, so
    both engines share one arithmetic definition and stay bit-identical.
    """
    import jax
    import jax.numpy as jnp

    i64 = jnp.int64
    dummy = n_pad  # scatter sink for "no parent" / "no node"

    def local_delta(cur, nxt, old_b, new_b):
        # -- vote deltas: two segment scatter-adds over the registry -----
        delta = jnp.zeros(n_pad + 1, i64)
        ci = jnp.where(cur >= 0, cur, dummy)
        delta = delta.at[ci].add(jnp.where(cur >= 0, -old_b, i64(0)))
        ni = jnp.where(nxt >= 0, nxt, dummy)
        delta = delta.at[ni].add(jnp.where(nxt >= 0, new_b, i64(0)))
        return delta

    def propagate(delta, parent, depth, invalid, zroot, viable, rank,
                  weight, bc_in, bd_in, pb_idx, pb_score, b_idx, b_score,
                  max_depth):
        # proposer boost: remove last slot's, add this slot's
        delta = delta.at[jnp.where(pb_idx >= 0, pb_idx, dummy)].add(
            jnp.where(pb_idx >= 0, -pb_score, i64(0)))
        delta = delta.at[jnp.where(b_idx >= 0, b_idx, dummy)].add(
            jnp.where(b_idx >= 0, b_score, i64(0)))
        delta = delta.at[dummy].set(0)

        pidx = jnp.where(parent >= 0, parent, dummy)
        iota = jnp.arange(n_pad, dtype=jnp.int32)

        def body(k, carry):
            weight, delta, bc, bd = carry
            lvl = max_depth - k
            at = depth == lvl  # pad rows carry depth −1: never selected
            d_eff = jnp.where(
                at, jnp.where(zroot, i64(0),
                              jnp.where(invalid, -weight, delta[:n_pad])),
                i64(0))
            weight = jnp.where(
                at, jnp.where(invalid, i64(0),
                              jnp.where(zroot, weight, weight + d_eff)),
                weight)
            delta = delta.at[pidx].add(jnp.where(at, d_eff, i64(0)))
            # leads-to-viable: best descendant viable OR node itself
            bdc = jnp.maximum(bd, 0)
            lead = viable | ((bd >= 0) & viable[bdc])
            child = at & (parent >= 0)
            elig = child & lead

            def seg_argmax(mask):
                wmax = jnp.full(n_pad + 1, -1, i64).at[pidx].max(
                    jnp.where(mask, weight, i64(-1)))
                m2 = mask & (weight == wmax[pidx])
                rmax = jnp.full(n_pad + 1, -1, i64).at[pidx].max(
                    jnp.where(m2, rank, i64(-1)))
                m3 = m2 & (rank == rmax[pidx])
                return jnp.full(n_pad + 1, -1, jnp.int32).at[pidx].max(
                    jnp.where(m3, iota, jnp.int32(-1)))[:n_pad]

            # The host's incremental descending-index sweep, seeded with
            # LAST round's best child, in closed form (see the numpy
            # engine in columnar.apply_scores_host for the derivation).
            win_lead = seg_argmax(elig)
            win_all = seg_argmax(child)
            prev_at_parent = jnp.where(parent >= 0,
                                       bc[jnp.maximum(parent, 0)],
                                       jnp.int32(-1))
            win_ge = seg_argmax(child & (iota >= prev_at_parent))
            has = jnp.zeros(n_pad + 1, bool).at[pidx].max(child)[:n_pad]
            F = jnp.where(win_lead >= 0, win_lead,
                          jnp.where(bc == -1, jnp.int32(-1),
                                    jnp.where(win_ge == bc,
                                              jnp.int32(-1), win_all)))
            Fc = jnp.maximum(F, 0)
            fbd = jnp.where(F >= 0,
                            jnp.where(bd[Fc] >= 0, bd[Fc], F),
                            jnp.int32(-1))
            bc = jnp.where(has, F, bc)
            bd = jnp.where(has, fbd, bd)
            return weight, delta, bc, bd

        weight, delta, bc, bd = jax.lax.fori_loop(
            0, max_depth + 1, body, (weight, delta, bc_in, bd_in))
        neg = jnp.any(weight < 0)
        return weight, bc, bd, neg

    return local_delta, propagate


def _get_kernel(n_pad: int, nv_pad: int):
    key = (n_pad, nv_pad)
    fn = _KERNELS.get(key)
    if fn is not None:
        return fn
    import jax
    from jax.experimental import enable_x64

    local_delta, propagate = _kernel_parts(n_pad)

    def fused(cur, nxt, old_b, new_b, *node_args):
        return propagate(local_delta(cur, nxt, old_b, new_b), *node_args)

    jitted = jax.jit(fused)

    def call(*args):
        with enable_x64():
            return jitted(*args)

    _KERNELS[key] = call
    return call


def _get_mesh_kernel(n_pad: int, nv_pad: int):
    """The fused round as a mesh program: vote/balance columns arrive
    sharded over the validator (``batch``) axis, each shard scatter-adds
    its own delta partial, one ``psum`` folds the ``(n_pad + 1,)`` int64
    partials — exact, adds are associative — and the node-level
    propagation runs replicated.  Selected only when ``nv_pad`` divides
    the mesh; caller falls back to :func:`_get_kernel` otherwise."""
    from ..parallel import mesh as pmesh
    mesh = pmesh.get_mesh()
    key = (n_pad, nv_pad, mesh)
    fn = _MESH_KERNELS.get(key)
    if fn is not None:
        return fn
    import jax
    from jax.experimental import enable_x64
    from jax.sharding import PartitionSpec as P

    local_delta, propagate = _kernel_parts(n_pad)

    def mesh_fused(cur, nxt, old_b, new_b, *node_args):
        delta = local_delta(cur, nxt, old_b, new_b)
        delta = jax.lax.psum(delta, pmesh.BATCH_AXIS)
        return propagate(delta, *node_args)

    n_node_args = 14  # parent..max_depth, all replicated
    prog = pmesh.mesh_program(
        mesh_fused, mesh=mesh,
        in_specs=(P(pmesh.BATCH_AXIS),) * 4 + (P(),) * n_node_args,
        out_specs=(P(), P(), P(), P()))

    def call(*args):
        with enable_x64():
            return prog(*args)

    _MESH_KERNELS[key] = call
    return call


def _get_scatter(nv_pad: int, k_pad: int):
    key = (nv_pad, k_pad)
    fn = _SCATTERS.get(key)
    if fn is not None:
        return fn
    import jax
    from jax.experimental import enable_x64

    def scatter(nxt, idx, val):
        return nxt.at[idx].set(val)

    jitted = jax.jit(scatter, donate_argnums=())

    def call(*args):
        with enable_x64():
            return jitted(*args)

    _SCATTERS[key] = call
    return call


class _DeviceMirror:
    """HBM twins of the validator-sized vote/balance columns and the
    node-topology columns, with push/pull byte accounting routed through
    :mod:`~lighthouse_tpu.ops.device_tree` residency stats."""

    def __init__(self, votes: VoteBuffer, old_balances: np.ndarray,
                 n_nodes: int):
        from jax.experimental import enable_x64
        from ..common.device_ledger import LEDGER
        from ..parallel.mesh import mesh_put

        self.nv_pad = _bucket(max(len(votes), 1))
        self.n_pad = _bucket(max(n_nodes, 1))
        with enable_x64():
            cur = np.full(self.nv_pad, -1, np.int32)
            cur[:len(votes)] = votes.current
            nxt = np.full(self.nv_pad, -1, np.int32)
            nxt[:len(votes)] = votes.next
            ob = np.zeros(self.nv_pad, np.int64)
            m = min(old_balances.shape[0], len(votes))
            ob[:m] = old_balances[:m].astype(np.int64)
            self.cur = mesh_put("fc_votes", cur)
            self.nxt = mesh_put("fc_votes", nxt)
            self.old_b = mesh_put("fc_votes", ob)
        self.topo_version = -1  # force first topology push
        self.parent = None
        self.depth = None
        self.weight = None
        self._res = LEDGER.track(self, "fork_choice", 0)
        self._note_residency()

    def _note_residency(self) -> None:
        # Dedupe by buffer identity: after a jitted apply `cur` IS
        # `nxt` (the device-side vote move aliases them) — summing both
        # would overstate residency by a full vote column.
        cols = {id(c): c for c in (self.cur, self.nxt, self.old_b,
                                   self.parent, self.depth, self.weight)
                if c is not None}
        self._res.set(sum(int(c.nbytes) for c in cols.values()))

    def fits(self, votes: VoteBuffer, n_nodes: int) -> bool:
        return len(votes) <= self.nv_pad and n_nodes <= self.n_pad

    def fits_pending(self, votes: VoteBuffer, n_nodes: int) -> bool:
        """Like :meth:`fits`, but sized for the POST-flush validator
        count: a buffered vote beyond the bucket would otherwise drop
        the mirror between the fit check and the kernel call."""
        pend = max((int(v.max()) + 1 for v in votes._buf_val
                    if v.shape[0]), default=0)
        return max(len(votes), pend) <= self.nv_pad \
            and n_nodes <= self.n_pad

    def scatter_votes(self, wv: np.ndarray, wn: np.ndarray) -> None:
        if wv.shape[0] == 0:
            return
        from jax.experimental import enable_x64
        from ..parallel.mesh import mesh_put
        k_pad = _bucket(wv.shape[0], floor=8)
        idx = np.empty(k_pad, np.int32)
        val = np.empty(k_pad, np.int32)
        idx[:wv.shape[0]] = wv
        idx[wv.shape[0]:] = wv[0]  # duplicate-set padding: idempotent
        val[:wn.shape[0]] = wn
        val[wn.shape[0]:] = wn[0]
        with enable_x64():
            self.nxt = _get_scatter(self.nv_pad, k_pad)(
                self.nxt, mesh_put("fc_dirty", idx),
                mesh_put("fc_dirty", val))
        self._note_residency()  # cur/nxt diverge into two buffers here

    def push_topology(self, cols: NodeColumns, version: int) -> None:
        if self.topo_version == version and self.parent is not None:
            return
        from jax.experimental import enable_x64
        from ..parallel.mesh import mesh_put
        n = cols.n
        parent = np.full(self.n_pad, -1, np.int32)
        parent[:n] = cols.parent[:n]
        depth = np.full(self.n_pad, -1, np.int32)
        depth[:n] = cols.depth[:n]
        weight = np.zeros(self.n_pad, np.int64)
        weight[:n] = cols.weight[:n]
        with enable_x64():
            self.parent = mesh_put("fc_topology", parent)
            self.depth = mesh_put("fc_topology", depth)
            self.weight = mesh_put("fc_topology", weight)
        self.topo_version = version
        self._note_residency()


class DeviceProtoArrayForkChoice:
    """Columnar twin of :class:`~.proto_array.ProtoArrayForkChoice`."""

    def __init__(self, prune_threshold: int = 256,
                 engine: Optional[str] = None,
                 jit_max_depth: Optional[int] = None):
        self.cols = NodeColumns()
        self.votes_store = VoteBuffer()
        self.old_balances = np.zeros(0, np.uint64)
        self.justified_checkpoint: Tuple[int, bytes] = (0, ZERO_ROOT)
        self.finalized_checkpoint: Tuple[int, bytes] = (0, ZERO_ROOT)
        self.prev_boost_root: bytes = ZERO_ROOT
        self.prev_boost_score: int = 0
        self.prune_threshold = prune_threshold
        self.engine = _resolve_engine(engine)
        # The fused kernel's fori_loop serializes one step per tree
        # level; past this depth (chain-shaped trees, long non-finality)
        # the round runs on host instead — mirrors stay in sync.
        from ..common.knobs import knob_int
        self.jit_max_depth = jit_max_depth if jit_max_depth is not None \
            else knob_int("LIGHTHOUSE_TPU_FORKCHOICE_JIT_MAX_DEPTH")
        self._mirror: Optional[_DeviceMirror] = None
        self._topo_version = 0
        self._pending_new_b: Optional[np.ndarray] = None

    # -- host-API parity surface --------------------------------------------

    @property
    def indices(self) -> Dict[bytes, int]:
        return self.cols.indices

    @property
    def equivocating(self) -> set:
        return self.votes_store.equivocating

    @property
    def votes(self) -> VoteTracker:
        """Host-shaped view of the latest-message columns (pending buffered
        votes are merged first so the view is observation-equivalent)."""
        self._flush_votes()
        v = self.votes_store
        return VoteTracker(v.current, v.next, v.next_epoch)

    @property
    def nodes(self) -> List:
        return self.cols.export_nodes()

    def slot_of(self, root: bytes) -> int:
        idx = self.cols.indices.get(bytes(root))
        if idx is None:
            raise ProtoArrayError("unknown block")
        return int(self.cols.slot[idx])

    # -- block tree ----------------------------------------------------------

    def on_block(self, *, slot: int, root: bytes, parent_root: bytes,
                 state_root: bytes, justified_epoch: int,
                 justified_root: bytes, finalized_epoch: int,
                 finalized_root: bytes,
                 execution_status: int = EXEC_IRRELEVANT,
                 execution_block_hash: Optional[bytes] = None) -> None:
        if bytes(root) in self.cols.indices:
            return
        parent = self.cols.indices.get(bytes(parent_root), -1)
        self.cols.append(
            slot=slot, root=root, parent=parent, state_root=state_root,
            justified_epoch=justified_epoch, justified_root=justified_root,
            finalized_epoch=finalized_epoch, finalized_root=finalized_root,
            execution_status=execution_status,
            execution_block_hash=execution_block_hash)
        self._topo_version += 1

    # -- votes ---------------------------------------------------------------

    def process_attestation(self, validator_index: int, block_root: bytes,
                            target_epoch: int) -> None:
        if validator_index in self.votes_store.equivocating:
            return
        idx = self.cols.indices.get(bytes(block_root))
        if idx is None:
            raise ProtoArrayError("attestation for unknown block")
        self.votes_store.push_votes(
            np.asarray([validator_index], np.int64), idx, target_epoch)

    def process_attestation_batch(self, batch) -> None:
        """Whole-slot ingest: ``batch`` is ``[(indices, block_root,
        target_epoch), …]``; each attestation's votes land in the buffer as
        one vectorized push (order preserved — the merge at flush is
        bit-equivalent to the host's sequential fold)."""
        for indices, block_root, target_epoch in batch:
            idx = self.cols.indices.get(bytes(block_root))
            if idx is None:
                # Host raises on the FIRST non-equivocating index; an
                # attestation whose voters all equivocate passes silently.
                if any(int(i) not in self.votes_store.equivocating
                       for i in np.asarray(indices, np.int64)):
                    raise ProtoArrayError("attestation for unknown block")
                continue
            self.votes_store.push_votes(
                np.asarray(indices, np.int64), idx, int(target_epoch))

    def process_equivocation(self, validator_index: int) -> None:
        # Zeroing happens in the host-computed balance column each flush;
        # a growth past the validator bucket rematerializes via fits().
        self.votes_store.push_equivocation(validator_index)

    def _flush_votes(self) -> None:
        wv, wn, _we = self.votes_store.flush()
        if self._mirror is not None and wv.shape[0]:
            if self._mirror.fits(self.votes_store, self.cols.n):
                self._mirror.scatter_votes(wv, wn)
            else:
                self._mirror = None

    # -- score changes -------------------------------------------------------

    def compute_deltas(self, new_balances: np.ndarray):
        """Flush the vote buffer and compute per-node deltas.  The numpy
        engine returns them; the jit engine defers the segment-sum into the
        fused apply program and returns an opaque marker."""
        if self.engine == "jit":
            if self._pending_new_b is not None and self._mirror is not None:
                # compute_deltas without an intervening apply: the host
                # still moves votes/balances — replicate the device move.
                from jax.experimental import enable_x64
                from ..parallel.mesh import mesh_put
                nb = np.zeros(self._mirror.nv_pad, np.int64)
                nb[:self._pending_new_b.shape[0]] = \
                    self._pending_new_b.astype(np.int64)
                with enable_x64():
                    self._mirror.old_b = mesh_put("fc_votes", nb)
                    self._mirror.cur = self._mirror.nxt
                self._mirror._note_residency()
                self._pending_new_b = None
            if self.cols.max_depth() > self.jit_max_depth:
                # Chain-shaped tree: run this head round on host, but
                # keep the device vote/balance mirrors moving so a later
                # shallow round resumes without a rematerialize.
                return self._compute_deltas_host_round(new_balances)
            if self._mirror is None \
                    or not self._mirror.fits_pending(self.votes_store,
                                                     max(self.cols.n, 1)):
                # (Re)materialize BEFORE the flush so the device copy holds
                # the pre-move current votes the delta pass subtracts —
                # sized for the POST-flush validator count (a buffered
                # vote can cross the pow-2 bucket).
                self._materialize()
            self._flush_votes()
            if self._mirror is None:
                self._materialize()  # flush outgrew the bucket anyway
            nv = len(self.votes_store)
            new_b = np.zeros(nv, np.uint64)
            m = min(np.asarray(new_balances).shape[0], nv)
            new_b[:m] = np.asarray(new_balances, np.uint64)[:m]
            if self.votes_store.equivocating:
                eq = np.fromiter(self.votes_store.equivocating, np.int64,
                                 len(self.votes_store.equivocating))
                new_b[eq[eq < nv]] = 0
            self._pending_new_b = new_b
            # host-mirror move (the device move happens post-kernel)
            self.votes_store.current = self.votes_store.next.copy()
            self.old_balances = new_b.copy()
            return _DEVICE_DELTAS
        self._flush_votes()
        deltas, new_b = compute_deltas_host(
            self.votes_store, self.old_balances,
            np.asarray(new_balances, np.uint64), self.cols.n)
        self.old_balances = new_b.copy()
        return deltas

    def _materialize(self) -> None:
        # Grow-before-flush: the buffer may reference validators beyond the
        # current columns; flush grows them, so bucket on the post-flush
        # size without applying yet.
        pend = (max((int(v.max()) + 1 for v in self.votes_store._buf_val
                     if v.shape[0]), default=0))
        self.votes_store.grow(pend)
        self._mirror = _DeviceMirror(self.votes_store, self.old_balances,
                                     max(self.cols.n, 1))

    def _compute_deltas_host_round(self, new_balances) -> np.ndarray:
        """Deep-tree (or mirror-less) jit round run on host: numpy deltas
        out, device vote/balance mirrors kept in lock-step so the next
        shallow round needs no rematerialize."""
        self._flush_votes()
        deltas, new_b = compute_deltas_host(
            self.votes_store, self.old_balances,
            np.asarray(new_balances, np.uint64), self.cols.n)
        if self._mirror is not None \
                and self._mirror.fits(self.votes_store, 1):
            from jax.experimental import enable_x64
            from ..parallel.mesh import mesh_put
            nb = np.zeros(self._mirror.nv_pad, np.int64)
            nb[:new_b.shape[0]] = new_b.astype(np.int64)
            with enable_x64():
                self._mirror.old_b = mesh_put("fc_votes", nb)
                self._mirror.cur = self._mirror.nxt
            self._mirror._note_residency()
            # host apply will move weights: force a weight re-push on
            # the next kernel dispatch even if the topology is unchanged
            self._mirror.topo_version = -1
        else:
            self._mirror = None
        self.old_balances = new_b.copy()
        return deltas

    def apply_score_changes(self, deltas, justified_checkpoint,
                            finalized_checkpoint, proposer_boost_root,
                            proposer_boost_score, current_slot) -> None:
        cols = self.cols
        n = cols.n
        self.justified_checkpoint = justified_checkpoint
        self.finalized_checkpoint = finalized_checkpoint
        viable = cols.viable_mask(justified_checkpoint, finalized_checkpoint)
        invalid = cols.exec_status[:n] == EXEC_INVALID
        pb_idx = (cols.indices.get(self.prev_boost_root, -1)
                  if self.prev_boost_root != ZERO_ROOT else -1)
        if pb_idx >= 0 and invalid[pb_idx]:
            pb_idx = -1
        b_idx = (cols.indices.get(bytes(proposer_boost_root), -1)
                 if bytes(proposer_boost_root) != ZERO_ROOT else -1)
        new_boost = 0
        if b_idx >= 0 and invalid[b_idx]:
            b_idx = -1
        elif b_idx >= 0:
            new_boost = proposer_boost_score
        if deltas is _DEVICE_DELTAS and self.engine == "jit":
            self._apply_jit(viable, invalid, pb_idx, self.prev_boost_score,
                            b_idx, proposer_boost_score)
        else:
            if deltas is _DEVICE_DELTAS:
                raise ProtoArrayError("device deltas on a numpy engine")
            if len(deltas) != n:
                raise ProtoArrayError("delta length mismatch")
            apply_scores(cols, np.asarray(deltas, np.int64), viable,
                         pb_idx, self.prev_boost_score,
                         b_idx, proposer_boost_score)
        self.prev_boost_root = bytes(proposer_boost_root)
        self.prev_boost_score = new_boost

    def _apply_jit(self, viable, invalid, pb_idx, pb_score, b_idx,  # device-io: fork_choice
                   b_score) -> None:
        import time as _time
        import jax.numpy as jnp
        from jax.experimental import enable_x64
        from ..common.device_ledger import LEDGER
        from ..parallel import mesh as pmesh

        cols = self.cols
        n = cols.n
        mir = self._mirror
        assert mir is not None and self._pending_new_b is not None
        mir.push_topology(cols, self._topo_version)
        n_pad = mir.n_pad
        inv = np.zeros(n_pad, bool)
        inv[:n] = invalid
        zr = np.zeros(n_pad, bool)
        zr[:n] = cols.zero_root_mask()
        via = np.zeros(n_pad, bool)
        via[:n] = viable
        rank = np.full(n_pad, -1, np.int64)
        rank[:n] = cols.ranks()
        bc_in = np.full(n_pad, -1, np.int32)
        bc_in[:n] = cols.best_child[:n]
        bd_in = np.full(n_pad, -1, np.int32)
        bd_in[:n] = cols.best_desc[:n]
        new_b = np.zeros(mir.nv_pad, np.int64)
        new_b[:self._pending_new_b.shape[0]] = \
            self._pending_new_b.astype(np.int64)
        # Clock from the staging/kernel block only (the slasher/kzg/bls
        # convention): the np.full marshalling above is host prep, not
        # device-verify time.
        t_dispatch = _time.perf_counter()
        ndev = pmesh.axis_size()
        use_mesh = ndev > 1 and mir.nv_pad % ndev == 0
        with enable_x64():
            kernel = (_get_mesh_kernel(n_pad, mir.nv_pad) if use_mesh
                      else _get_kernel(n_pad, mir.nv_pad))
            new_b_dev = pmesh.mesh_put("fc_votes", new_b)
            weight, bc, bd, negflag = kernel(
                mir.cur, mir.nxt, mir.old_b, new_b_dev,
                mir.parent, mir.depth,
                jnp.asarray(inv), jnp.asarray(zr), jnp.asarray(via),
                jnp.asarray(rank), mir.weight,
                jnp.asarray(bc_in), jnp.asarray(bd_in),
                jnp.int32(pb_idx), jnp.int64(pb_score),
                jnp.int32(b_idx), jnp.int64(b_score),
                jnp.int32(cols.max_depth()))
            # device-side vote move + balance persistence (no pull)
            mir.cur = mir.nxt
            mir.old_b = new_b_dev
            mir.weight = weight
            w_host = np.asarray(weight)[:n]    # device-io: fork_choice
            bc_host = np.asarray(bc)[:n]       # device-io: fork_choice
            bd_host = np.asarray(bd)[:n]       # device-io: fork_choice
            neg = bool(negflag)
        # new_b is settled by mesh_put above; these masks ride plain
        # jnp.asarray into the jit call.
        LEDGER.note_transfer(
            "h2d", inv.nbytes + zr.nbytes + via.nbytes + rank.nbytes
            + bc_in.nbytes + bd_in.nbytes,
            subsystem="fork_choice")
        LEDGER.note_transfer(
            "d2h", w_host.nbytes + bc_host.nbytes + bd_host.nbytes + 1,
            subsystem="fork_choice")
        LEDGER.note_dispatch(
            "fork_choice", (_time.perf_counter() - t_dispatch) * 1e3)
        mir._note_residency()
        cols.weight[:n] = w_host
        cols.best_child[:n] = bc_host
        cols.best_desc[:n] = bd_host
        self._pending_new_b = None
        if neg:
            raise ProtoArrayError("negative node weight")

    # -- head ----------------------------------------------------------------

    def find_head(self, justified_root: bytes, current_slot: int) -> bytes:
        idx = self.cols.indices.get(bytes(justified_root))
        if idx is None:
            raise ProtoArrayError("justified root unknown to fork choice")
        if self.cols.exec_status[idx] == EXEC_INVALID:
            raise ProtoArrayError("justified node has invalid payload")
        best = int(self.cols.best_desc[idx])
        best = idx if best < 0 else best
        viable = self.cols.viable_mask(self.justified_checkpoint,
                                       self.finalized_checkpoint)
        if not viable[best]:
            raise ProtoArrayError("best node not viable for head")
        return self.cols.root_bytes(best)

    # -- pruning -------------------------------------------------------------

    def maybe_prune(self, finalized_root: bytes) -> None:
        fin_idx = self.cols.indices.get(bytes(finalized_root))
        if fin_idx is None or fin_idx < self.prune_threshold:
            return
        # Buffered votes reference node indices: merge them into the
        # latest-message columns first (merge order is flush-point
        # invariant), then remap like the host.
        self._flush_votes()
        old = self.cols
        n = old.n
        remap = np.full(n + 1, -1, np.int32)
        remap[fin_idx:n] = np.arange(n - fin_idx, dtype=np.int32)
        new = NodeColumns(capacity=max(n - fin_idx, 8))
        for i in range(fin_idx, n):
            p = int(old.parent[i])
            p = -1 if p < 0 or remap[p] < 0 else int(remap[p])
            j = new.append(
                slot=int(old.slot[i]), root=old.root_bytes(i), parent=p,
                state_root=old.state_roots[i].tobytes(),
                justified_epoch=int(old.justified_epoch[i]),
                justified_root=old.justified_roots[i].tobytes(),
                finalized_epoch=int(old.finalized_epoch[i]),
                finalized_root=old.finalized_roots[i].tobytes(),
                execution_status=int(old.exec_status[i]),
                execution_block_hash=old.exec_hash[i])
            new.weight[j] = old.weight[i]
            for col in ("best_child", "best_desc"):
                v = int(getattr(old, col)[i])
                getattr(new, col)[j] = -1 if v < 0 or remap[v] < 0 \
                    else int(remap[v])
        self.cols = new
        self.votes_store.remap(remap)
        self._topo_version += 1
        self._mirror = None  # full rematerialize on next jit flush

    # -- execution status (optimistic sync) ----------------------------------

    def on_valid_execution_payload(self, root: bytes) -> None:
        idx = self.cols.indices.get(bytes(root))
        while idx is not None and idx >= 0:
            st = int(self.cols.exec_status[idx])
            if st == EXEC_INVALID:
                raise ProtoArrayError("valid payload above invalid ancestor")
            if st in (EXEC_VALID, EXEC_IRRELEVANT):
                break
            self.cols.exec_status[idx] = EXEC_VALID
            p = int(self.cols.parent[idx])
            idx = None if p < 0 else p

    def on_invalid_execution_payload(self, root: bytes) -> None:
        """Invalidate a node and every descendant — one masked OR per tree
        level below it (weights stay; the next score pass computes
        ``d = -weight`` and propagates the removal to ancestors)."""
        start = self.cols.indices.get(bytes(root))
        if start is None:
            return
        n = self.cols.n
        inv = np.zeros(n, bool)
        inv[start] = True
        parent = self.cols.parent
        for lvl in range(int(self.cols.depth[start]) + 1,
                         self.cols.max_depth() + 1):
            c = self.cols.levels()[lvl]
            pc = parent[c]
            m = (pc >= 0) & inv[pc]
            inv[c[m]] = True
        self.cols.exec_status[:n][inv] = EXEC_INVALID

    # -- host interop ---------------------------------------------------------

    def to_host(self) -> ProtoArrayForkChoice:
        """Bit-exact host snapshot (persistence + differential oracle)."""
        self._flush_votes()
        pa = ProtoArrayForkChoice(prune_threshold=self.prune_threshold)
        pa.nodes = self.cols.export_nodes()
        pa.indices = dict(self.cols.indices)
        v = self.votes_store
        pa.votes = VoteTracker(v.current.copy(), v.next.copy(),
                               v.next_epoch.copy())
        pa.old_balances = self.old_balances.copy()
        pa.equivocating = set(v.equivocating)
        pa.justified_checkpoint = self.justified_checkpoint
        pa.finalized_checkpoint = self.finalized_checkpoint
        pa.prev_boost_root = self.prev_boost_root
        pa.prev_boost_score = self.prev_boost_score
        return pa

    @classmethod
    def from_host(cls, pa: ProtoArrayForkChoice,
                  engine: Optional[str] = None
                  ) -> "DeviceProtoArrayForkChoice":
        self = cls(prune_threshold=pa.prune_threshold, engine=engine)
        for node in pa.nodes:
            i = self.cols.append(
                slot=node.slot, root=node.root,
                parent=-1 if node.parent is None else node.parent,
                state_root=node.state_root,
                justified_epoch=node.justified_epoch,
                justified_root=node.justified_root,
                finalized_epoch=node.finalized_epoch,
                finalized_root=node.finalized_root,
                execution_status=node.execution_status,
                execution_block_hash=node.execution_block_hash)
            self.cols.weight[i] = node.weight
            self.cols.best_child[i] = \
                -1 if node.best_child is None else node.best_child
            self.cols.best_desc[i] = \
                -1 if node.best_descendant is None else node.best_descendant
        v = self.votes_store
        v.current = pa.votes.current.copy()
        v.next = pa.votes.next.copy()
        v.next_epoch = pa.votes.next_epoch.copy()
        v.equivocating = set(pa.equivocating)
        self.old_balances = pa.old_balances.copy()
        self.justified_checkpoint = pa.justified_checkpoint
        self.finalized_checkpoint = pa.finalized_checkpoint
        self.prev_boost_root = pa.prev_boost_root
        self.prev_boost_score = pa.prev_boost_score
        self._topo_version += 1
        return self


class _DeviceDeltasMarker:
    """Sentinel: deltas live on device, fused into apply_score_changes."""

    def __len__(self):  # defensive: host apply on a device marker
        raise ProtoArrayError("device deltas on a numpy engine")


_DEVICE_DELTAS = _DeviceDeltasMarker()


def warmup(n_nodes: int, n_validators: int) -> None:
    """Pre-compile the fused kernel for the given shape buckets (the
    scripts' ``--warmup`` hook; compiles persist via the common cache)."""
    import jax.numpy as jnp
    from jax.experimental import enable_x64
    from ..parallel import mesh as pmesh
    n_pad = _bucket(n_nodes)
    nv_pad = _bucket(n_validators)
    ndev = pmesh.axis_size()
    with enable_x64():
        kernel = (_get_mesh_kernel(n_pad, nv_pad)
                  if ndev > 1 and nv_pad % ndev == 0
                  else _get_kernel(n_pad, nv_pad))
        i32 = jnp.int32
        kernel(jnp.full(nv_pad, -1, i32), jnp.full(nv_pad, -1, i32),
               jnp.zeros(nv_pad, jnp.int64), jnp.zeros(nv_pad, jnp.int64),
               jnp.full(n_pad, -1, i32), jnp.full(n_pad, -1, i32),
               jnp.zeros(n_pad, bool), jnp.zeros(n_pad, bool),
               jnp.zeros(n_pad, bool), jnp.full(n_pad, -1, jnp.int64),
               jnp.zeros(n_pad, jnp.int64),
               jnp.full(n_pad, -1, i32), jnp.full(n_pad, -1, i32),
               jnp.int32(-1), jnp.int64(0), jnp.int32(-1), jnp.int64(0),
               jnp.int32(0))
