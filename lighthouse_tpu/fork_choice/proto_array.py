"""Proto-array LMD-GHOST fork choice — columnar redesign.

Counterpart of the reference's ``consensus/proto_array``
(``/root/reference/consensus/proto_array/src/proto_array.rs``,
``proto_array_fork_choice.rs``).  The node graph is a small append-only
table (parents always precede children), while the validator-side state —
latest messages and deltas — is columnar numpy sized by the validator set:

- votes are (current_node, next_node, next_epoch) int32/uint64 columns;
- ``compute_deltas`` (``proto_array_fork_choice.rs:819``) is two
  ``np.bincount`` scatter-adds over the whole validator set instead of a
  per-validator loop — the 1M-validator work is one vector op;
- the backward weight propagation and best-child sweep walk the node table
  (hundreds of entries after pruning), exactly the reference's two reverse
  passes (``proto_array.rs:167-320``).

Execution-status tracking (optimistic sync) keeps the reference's
valid/optimistic/invalid trichotomy at node granularity: invalid nodes are
pinned to zero weight and never viable (``proto_array.rs:209-216,897``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

ZERO_ROOT = b"\x00" * 32

# Execution status per node (`proto_array.rs` ExecutionStatus).
EXEC_VALID = 0
EXEC_OPTIMISTIC = 1
EXEC_INVALID = 2
EXEC_IRRELEVANT = 3  # pre-merge


class ProtoArrayError(ValueError):
    pass


@dataclass
class ProtoNode:
    """One block in the tree (`proto_array.rs` ProtoNode)."""
    slot: int
    root: bytes
    parent: Optional[int]
    state_root: bytes
    justified_epoch: int
    justified_root: bytes
    finalized_epoch: int
    finalized_root: bytes
    execution_status: int = EXEC_IRRELEVANT
    execution_block_hash: Optional[bytes] = None
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None


@dataclass
class VoteTracker:
    """Columnar latest-message store (`proto_array_fork_choice.rs`
    VoteTracker per validator, here as whole-registry columns)."""
    current: np.ndarray  # int32 node index, -1 = none
    next: np.ndarray     # int32 node index, -1 = none
    next_epoch: np.ndarray  # uint64

    @classmethod
    def new(cls, n: int = 0) -> "VoteTracker":
        return cls(np.full(n, -1, np.int32), np.full(n, -1, np.int32),
                   np.zeros(n, np.uint64))

    def grow(self, n: int) -> None:
        old = self.current.shape[0]
        if n <= old:
            return
        self.current = np.concatenate([self.current, np.full(n - old, -1, np.int32)])
        self.next = np.concatenate([self.next, np.full(n - old, -1, np.int32)])
        self.next_epoch = np.concatenate([self.next_epoch,
                                          np.zeros(n - old, np.uint64)])


class ProtoArrayForkChoice:
    """`ProtoArrayForkChoice` (`proto_array_fork_choice.rs:318`)."""

    def __init__(self, prune_threshold: int = 256):
        self.nodes: List[ProtoNode] = []
        self.indices: Dict[bytes, int] = {}
        self.votes = VoteTracker.new()
        self.old_balances = np.zeros(0, np.uint64)
        self.equivocating: set[int] = set()
        self.justified_checkpoint: Tuple[int, bytes] = (0, ZERO_ROOT)
        self.finalized_checkpoint: Tuple[int, bytes] = (0, ZERO_ROOT)
        self.prev_boost_root: bytes = ZERO_ROOT
        self.prev_boost_score: int = 0
        self.prune_threshold = prune_threshold

    # -- block tree ----------------------------------------------------------

    def on_block(self, *, slot: int, root: bytes, parent_root: bytes,
                 state_root: bytes, justified_epoch: int,
                 justified_root: bytes, finalized_epoch: int,
                 finalized_root: bytes,
                 execution_status: int = EXEC_IRRELEVANT,
                 execution_block_hash: Optional[bytes] = None) -> None:
        if root in self.indices:
            return
        parent = self.indices.get(parent_root)
        node = ProtoNode(
            slot=slot, root=root, parent=parent, state_root=state_root,
            justified_epoch=justified_epoch, justified_root=justified_root,
            finalized_epoch=finalized_epoch, finalized_root=finalized_root,
            execution_status=execution_status,
            execution_block_hash=execution_block_hash)
        idx = len(self.nodes)
        self.nodes.append(node)
        self.indices[root] = idx

    def slot_of(self, root: bytes) -> int:
        """Slot of a known block (shared API with the columnar twin)."""
        idx = self.indices.get(bytes(root))
        if idx is None:
            raise ProtoArrayError("unknown block")
        return self.nodes[idx].slot

    def process_attestation(self, validator_index: int, block_root: bytes,
                            target_epoch: int) -> None:
        """Latest-message update (`proto_array_fork_choice.rs:370`): keep
        the vote with the highest target epoch."""
        if validator_index in self.equivocating:
            return
        idx = self.indices.get(block_root)
        if idx is None:
            raise ProtoArrayError("attestation for unknown block")
        self.votes.grow(validator_index + 1)
        if target_epoch > int(self.votes.next_epoch[validator_index]) \
                or self.votes.next[validator_index] == -1:
            self.votes.next[validator_index] = idx
            self.votes.next_epoch[validator_index] = target_epoch

    def process_attestation_batch(self, batch) -> None:
        """Whole-slot vote ingest: ``[(indices, block_root, target_epoch),
        …]``.  The host oracle applies them as the sequential per-validator
        fold (the definition of correct ordering semantics); the columnar
        twin overrides this with one vectorized buffer push per
        attestation."""
        for indices, block_root, target_epoch in batch:
            for i in indices:
                self.process_attestation(int(i), block_root,
                                         int(target_epoch))

    def process_equivocation(self, validator_index: int) -> None:
        """Remove an equivocating validator's weight forever (spec's
        equivocating_indices)."""
        self.votes.grow(validator_index + 1)
        self.equivocating.add(validator_index)

    # -- score changes -------------------------------------------------------

    def compute_deltas(self, new_balances: np.ndarray) -> np.ndarray:
        """Per-node weight deltas from vote changes — two vectorized
        scatter-adds (`proto_array_fork_choice.rs:819`)."""
        n_nodes = len(self.nodes)
        v = self.votes
        nv = v.current.shape[0]
        old_b = np.zeros(nv, np.uint64)
        m = min(self.old_balances.shape[0], nv)
        old_b[:m] = self.old_balances[:m]
        new_b = np.zeros(nv, np.uint64)
        m2 = min(new_balances.shape[0], nv)
        new_b[:m2] = new_balances[:m2]
        if self.equivocating:
            eq = np.fromiter(self.equivocating, dtype=np.int64)
            new_b[eq[eq < nv]] = 0
        deltas = np.zeros(n_nodes, np.int64)
        cur_mask = v.current >= 0
        np.subtract.at(deltas, v.current[cur_mask],
                       old_b[cur_mask].astype(np.int64))
        nxt_mask = v.next >= 0
        np.add.at(deltas, v.next[nxt_mask], new_b[nxt_mask].astype(np.int64))
        # Votes move: current ← next.  Persist the EQUIVOCATION-ZEROED
        # balances: an equivocator's weight was removed this round and must
        # not be re-subtracted on the next call.
        v.current = v.next.copy()
        self.old_balances = new_b.copy()
        return deltas

    def apply_score_changes(self, deltas: np.ndarray,
                            justified_checkpoint: Tuple[int, bytes],
                            finalized_checkpoint: Tuple[int, bytes],
                            proposer_boost_root: bytes,
                            proposer_boost_score: int,
                            current_slot: int) -> None:
        """Backward weight pass + best-child sweep (`proto_array.rs:167`)."""
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError("delta length mismatch")
        self.justified_checkpoint = justified_checkpoint
        self.finalized_checkpoint = finalized_checkpoint
        deltas = deltas.copy()
        new_boost_score = 0
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.root == ZERO_ROOT:
                continue
            invalid = node.execution_status == EXEC_INVALID
            d = -node.weight if invalid else int(deltas[i])
            if self.prev_boost_root != ZERO_ROOT \
                    and self.prev_boost_root == node.root and not invalid:
                d -= self.prev_boost_score
            if proposer_boost_root != ZERO_ROOT \
                    and proposer_boost_root == node.root and not invalid:
                new_boost_score = proposer_boost_score
                d += proposer_boost_score
            node.weight = 0 if invalid else node.weight + d
            if node.weight < 0:
                raise ProtoArrayError("negative node weight")
            if node.parent is not None:
                deltas[node.parent] += d
        self.prev_boost_root = proposer_boost_root
        self.prev_boost_score = new_boost_score
        for i in range(len(self.nodes) - 1, -1, -1):
            parent = self.nodes[i].parent
            if parent is not None:
                self._maybe_update_best_child(parent, i, current_slot)

    # -- head ----------------------------------------------------------------

    def find_head(self, justified_root: bytes, current_slot: int) -> bytes:
        """`proto_array.rs:644`."""
        idx = self.indices.get(justified_root)
        if idx is None:
            raise ProtoArrayError("justified root unknown to fork choice")
        jnode = self.nodes[idx]
        if jnode.execution_status == EXEC_INVALID:
            raise ProtoArrayError("justified node has invalid payload")
        best = jnode.best_descendant
        best = idx if best is None else best
        node = self.nodes[best]
        if not self._viable_for_head(node):
            raise ProtoArrayError("best node not viable for head")
        return node.root

    def _viable_for_head(self, node: ProtoNode) -> bool:
        """`filter_block_tree` predicate (`proto_array.rs:897`)."""
        if node.execution_status == EXEC_INVALID:
            return False
        je, jr = self.justified_checkpoint
        fe, fr = self.finalized_checkpoint
        correct_j = (node.justified_epoch, node.justified_root) == (je, jr) \
            or je == 0
        # Compare the finalized ROOT too: a node descending from a
        # conflicting block finalized at the same epoch number must not
        # pass viability (`proto_array.rs:897` checks the checkpoint, not
        # just the epoch).  Nodes at/above the finalized slot carry their
        # own ancestor root; require it to match ours.
        correct_f = (node.finalized_epoch, node.finalized_root) == (fe, fr) \
            or fe == 0
        return correct_j and correct_f

    def _leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None \
                and self._viable_for_head(self.nodes[node.best_descendant]):
            return True
        return self._viable_for_head(node)

    def _maybe_update_best_child(self, parent_idx: int, child_idx: int,
                                 current_slot: int) -> None:
        """`proto_array.rs:778` — three-way best-child decision."""
        child = self.nodes[child_idx]
        parent = self.nodes[parent_idx]
        child_viable = self._leads_to_viable_head(child)
        to_child = (child_idx,
                    child.best_descendant if child.best_descendant is not None
                    else child_idx)
        if parent.best_child is not None:
            if parent.best_child == child_idx and not child_viable:
                new = (None, None)
            elif parent.best_child == child_idx:
                new = to_child
            else:
                best = self.nodes[parent.best_child]
                best_viable = self._leads_to_viable_head(best)
                if child_viable and not best_viable:
                    new = to_child
                elif not child_viable and best_viable:
                    new = (parent.best_child, parent.best_descendant)
                elif child.weight == best.weight:
                    new = to_child if child.root >= best.root \
                        else (parent.best_child, parent.best_descendant)
                else:
                    new = to_child if child.weight >= best.weight \
                        else (parent.best_child, parent.best_descendant)
        else:
            new = to_child if child_viable \
                else (parent.best_child, parent.best_descendant)
        parent.best_child, parent.best_descendant = new

    # -- pruning -------------------------------------------------------------

    def maybe_prune(self, finalized_root: bytes) -> None:
        """Drop everything before the finalized root once the prefix is big
        enough (`proto_array.rs` maybe_prune); vote indices remap via one
        np.take."""
        fin_idx = self.indices.get(finalized_root)
        if fin_idx is None or fin_idx < self.prune_threshold:
            return
        keep = list(range(fin_idx, len(self.nodes)))
        remap = np.full(len(self.nodes) + 1, -1, np.int32)
        for new_i, old_i in enumerate(keep):
            remap[old_i] = new_i
        new_nodes = []
        for old_i in keep:
            node = self.nodes[old_i]
            node.parent = (None if node.parent is None
                           or remap[node.parent] < 0
                           else int(remap[node.parent]))
            for attr in ("best_child", "best_descendant"):
                v = getattr(node, attr)
                setattr(node, attr,
                        None if v is None or remap[v] < 0 else int(remap[v]))
            new_nodes.append(node)
        self.nodes = new_nodes
        self.indices = {n.root: i for i, n in enumerate(new_nodes)}
        # Remap votes in one gather (dangling votes become -1).
        self.votes.current = remap[self.votes.current]
        self.votes.next = remap[self.votes.next]

    # -- execution status (optimistic sync) ----------------------------------

    def on_valid_execution_payload(self, root: bytes) -> None:
        """Mark a node and its ancestors valid (`proto_array.rs`
        propagate_execution_payload_validation)."""
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            if node.execution_status == EXEC_INVALID:
                raise ProtoArrayError("valid payload above invalid ancestor")
            if node.execution_status in (EXEC_VALID, EXEC_IRRELEVANT):
                break
            node.execution_status = EXEC_VALID
            idx = node.parent

    def on_invalid_execution_payload(self, root: bytes) -> None:
        """Invalidate a node and all its descendants
        (`proto_array.rs` InvalidationOperation::InvalidateOne)."""
        start = self.indices.get(root)
        if start is None:
            return
        # Mark only; weights stay intact so the next apply_score_changes
        # can compute d = -weight and propagate the REMOVAL to ancestors —
        # pre-zeroing here would leave phantom subtree weight above the
        # invalidated block (`proto_array.rs:209-216` relies on the same).
        invalid = {start}
        self.nodes[start].execution_status = EXEC_INVALID
        for i in range(start + 1, len(self.nodes)):
            node = self.nodes[i]
            if node.parent in invalid:
                node.execution_status = EXEC_INVALID
                invalid.add(i)
