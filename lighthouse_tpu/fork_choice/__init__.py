"""Fork choice: proto-array LMD-GHOST + spec wrapper.

Counterparts of ``consensus/proto_array`` and ``consensus/fork_choice``
(``/root/reference/consensus/{proto_array,fork_choice}/``).
"""

from .device_proto_array import (
    DeviceProtoArrayForkChoice,
    device_fork_choice_enabled,
)
from .fork_choice import ForkChoice, ForkChoiceError
from .proto_array import (
    EXEC_INVALID,
    EXEC_IRRELEVANT,
    EXEC_OPTIMISTIC,
    EXEC_VALID,
    ProtoArrayError,
    ProtoArrayForkChoice,
)

__all__ = [
    "ForkChoice", "ForkChoiceError", "ProtoArrayForkChoice",
    "DeviceProtoArrayForkChoice", "device_fork_choice_enabled",
    "ProtoArrayError", "EXEC_VALID", "EXEC_OPTIMISTIC", "EXEC_INVALID",
    "EXEC_IRRELEVANT",
]
