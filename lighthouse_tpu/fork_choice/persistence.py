"""Fork-choice persistence — `PersistedForkChoice`
(``/root/reference/beacon_node/beacon_chain/src/persisted_fork_choice.rs``
+ ``consensus/proto_array/src/ssz_container.rs``).

A restart must resume with the identical head: the proto-array node graph,
the per-validator latest-message votes, equivocations, checkpoints,
proposer boost and queued attestations all round-trip through one binary
blob (fixed-width struct records, little-endian — the role of the
reference's SSZ container).  The justified state itself is NOT embedded;
it reloads from the store by block root at boot.
"""

from __future__ import annotations

import struct

import numpy as np

from .fork_choice import ForkChoice, QueuedAttestation
from .proto_array import ProtoArrayForkChoice, ProtoNode, VoteTracker

_MAGIC = b"LTFC\x01"
_ZERO32 = b"\x00" * 32


def _opt(i) -> int:
    return -1 if i is None else int(i)


def _unopt(i: int):
    return None if i < 0 else i


_NODE = struct.Struct("<q32s q32s q32s q32s bq qq 32s")


def _pack_node(n: ProtoNode) -> bytes:
    return _NODE.pack(
        n.slot, n.root, _opt(n.parent), n.state_root,
        n.justified_epoch, n.justified_root,
        n.finalized_epoch, n.finalized_root,
        n.execution_status, n.weight,
        _opt(n.best_child), _opt(n.best_descendant),
        n.execution_block_hash or _ZERO32)


def _unpack_node(data: bytes) -> ProtoNode:
    (slot, root, parent, state_root, je, jr, fe, fr, ex, weight, bc, bd,
     ebh) = _NODE.unpack(data)
    return ProtoNode(
        slot=slot, root=root, parent=_unopt(parent), state_root=state_root,
        justified_epoch=je, justified_root=jr, finalized_epoch=fe,
        finalized_root=fr, execution_status=ex,
        execution_block_hash=None if ebh == _ZERO32 else ebh,
        weight=weight, best_child=_unopt(bc), best_descendant=_unopt(bd))


def _pack_arr(a: np.ndarray) -> bytes:
    raw = np.ascontiguousarray(a).tobytes()
    return struct.pack("<I", len(raw)) + raw


def _unpack_arr(buf: memoryview, off: int, dtype) -> tuple[np.ndarray, int]:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    arr = np.frombuffer(buf[off:off + n], dtype=dtype).copy()
    return arr, off + n


def encode_fork_choice(fc: ForkChoice) -> bytes:
    p = fc.proto
    if hasattr(p, "to_host"):
        # Columnar proto-array: snapshot through the bit-exact host view
        # (pending buffered votes merge first) so the blob format is
        # identical across both flavours — a restart can flip the knob.
        p = p.to_host()
    out = [_MAGIC]
    out.append(struct.pack("<I", len(p.nodes)))
    out.extend(_pack_node(n) for n in p.nodes)
    out.append(_pack_arr(p.votes.current))
    out.append(_pack_arr(p.votes.next))
    out.append(_pack_arr(p.votes.next_epoch))
    out.append(_pack_arr(p.old_balances))
    eq = np.fromiter(sorted(p.equivocating), dtype=np.int64,
                     count=len(p.equivocating))
    out.append(_pack_arr(eq))
    out.append(struct.pack("<q32s q32s 32sq",
                           p.justified_checkpoint[0], p.justified_checkpoint[1],
                           p.finalized_checkpoint[0], p.finalized_checkpoint[1],
                           p.prev_boost_root, p.prev_boost_score))
    out.append(struct.pack(
        "<q32s q32s 32sq q",
        fc.justified_checkpoint[0], fc.justified_checkpoint[1],
        fc.finalized_checkpoint[0], fc.finalized_checkpoint[1],
        fc.proposer_boost_root, fc.current_slot, len(fc.queued)))
    for q in fc.queued:
        out.append(struct.pack("<qq32s", q.slot, q.target_epoch,
                               q.block_root))
        out.append(_pack_arr(np.asarray(q.indices, np.int64)))
    return b"".join(out)


def decode_fork_choice(data: bytes, *, preset, spec,
                       justified_state) -> ForkChoice:
    """Rebuild a ForkChoice.  ``justified_state`` must be the post-state of
    the persisted justified checkpoint's block (the caller resolves it from
    the store — `beacon_chain/builder.rs` does the same at boot)."""
    buf = memoryview(data)
    if bytes(buf[:5]) != _MAGIC:
        raise ValueError("bad fork-choice blob")
    off = 5
    (n_nodes,) = struct.unpack_from("<I", buf, off)
    off += 4
    proto = ProtoArrayForkChoice()
    for _ in range(n_nodes):
        node = _unpack_node(bytes(buf[off:off + _NODE.size]))
        off += _NODE.size
        proto.indices[node.root] = len(proto.nodes)
        proto.nodes.append(node)
    cur, off = _unpack_arr(buf, off, np.int32)
    nxt, off = _unpack_arr(buf, off, np.int32)
    nxte, off = _unpack_arr(buf, off, np.uint64)
    proto.votes = VoteTracker(cur, nxt, nxte)
    proto.old_balances, off = _unpack_arr(buf, off, np.uint64)
    eq, off = _unpack_arr(buf, off, np.int64)
    proto.equivocating = set(int(i) for i in eq)
    s = struct.Struct("<q32s q32s 32sq")
    je, jr, fe, fr, boost, boost_score = s.unpack_from(buf, off)
    off += s.size
    proto.justified_checkpoint = (je, jr)
    proto.finalized_checkpoint = (fe, fr)
    proto.prev_boost_root = boost
    proto.prev_boost_score = boost_score
    s2 = struct.Struct("<q32s q32s 32sq q")
    fje, fjr, ffe, ffr, pboost, cur_slot, n_q = s2.unpack_from(buf, off)
    off += s2.size
    fc = ForkChoice.__new__(ForkChoice)
    fc.preset = preset
    fc.spec = spec
    from .device_proto_array import (DeviceProtoArrayForkChoice,
                                     device_fork_choice_enabled)
    if device_fork_choice_enabled():
        # Restore INTO the columnar form (the device path resumes with
        # weights/best-children/votes exactly where the snapshot left
        # them — no replay needed).
        proto = DeviceProtoArrayForkChoice.from_host(proto)
    fc.proto = proto
    fc.justified_state = justified_state
    fc.justified_checkpoint = (fje, fjr)
    fc.finalized_checkpoint = (ffe, ffr)
    fc.proposer_boost_root = pboost
    fc.current_slot = cur_slot
    fc.queued = []
    for _ in range(n_q):
        s3 = struct.Struct("<qq32s")
        slot, target, root = s3.unpack_from(buf, off)
        off += s3.size
        idx, off = _unpack_arr(buf, off, np.int64)
        fc.queued.append(QueuedAttestation(
            slot=slot, indices=idx, block_root=root, target_epoch=target))
    return fc
