"""Spec-level fork choice over the proto-array
(``/root/reference/consensus/fork_choice/src/fork_choice.rs``).

``ForkChoice`` binds the proto-array to consensus types: blocks arrive with
their post-states (``on_block`` — ``fork_choice.rs:748``), attestations
arrive indexed (``on_attestation`` — ``:1165``), and ``get_head``
(``:528``) replays queued votes into deltas and runs the two-pass score
update.  Justified balances come from the justified state's effective
balances (active validators only), as one numpy mask-select over the SoA
registry columns.
"""

from __future__ import annotations

from dataclasses import dataclass
import numpy as np

from ..common.tracing import TRACER
from .proto_array import (
    EXEC_IRRELEVANT,
    EXEC_OPTIMISTIC,
    ProtoArrayError,
    ProtoArrayForkChoice,
    ZERO_ROOT,
)


class ForkChoiceError(ValueError):
    pass


@dataclass
class QueuedAttestation:
    """Attestations for the current slot wait one slot before counting
    (`fork_choice.rs` QueuedAttestation)."""
    slot: int
    indices: np.ndarray
    block_root: bytes
    target_epoch: int


def _active_balances(state, epoch: int) -> np.ndarray:
    reg = state.validators
    active = ((reg.col("activation_epoch") <= epoch)
              & (epoch < reg.col("exit_epoch")))
    out = np.where(active, reg.col("effective_balance"), 0).astype(np.uint64)
    return out


def _make_proto(device):
    """Columnar device proto-array by default; the host walk stays
    bit-for-bit available as the differential oracle behind
    ``LIGHTHOUSE_TPU_DEVICE_FORKCHOICE=0`` (or ``device=False``)."""
    from .device_proto_array import (DeviceProtoArrayForkChoice,
                                     device_fork_choice_enabled)
    if device is None:
        device = device_fork_choice_enabled()
    return DeviceProtoArrayForkChoice() if device else ProtoArrayForkChoice()


class ForkChoice:
    """`ForkChoice` (`fork_choice.rs:244`), single-process flavour."""

    def __init__(self, preset, spec, *, genesis_root: bytes,
                 genesis_state, current_slot: int = 0, device=None):
        self.preset = preset
        self.spec = spec
        self.proto = _make_proto(device)
        self.queued: list[QueuedAttestation] = []
        self.justified_state = genesis_state
        jcp = (int(genesis_state.current_justified_checkpoint.epoch),
               bytes(genesis_state.current_justified_checkpoint.root))
        fcp = (int(genesis_state.finalized_checkpoint.epoch),
               bytes(genesis_state.finalized_checkpoint.root))
        # Genesis anchor: checkpoints root to the anchor block itself.
        self.justified_checkpoint = (jcp[0], genesis_root)
        self.finalized_checkpoint = (fcp[0], genesis_root)
        self.proposer_boost_root = ZERO_ROOT
        self.current_slot = current_slot
        self.proto.on_block(
            slot=int(genesis_state.slot), root=genesis_root,
            parent_root=ZERO_ROOT,
            state_root=bytes(genesis_state.latest_block_header.state_root),
            justified_epoch=self.justified_checkpoint[0],
            justified_root=genesis_root,
            finalized_epoch=self.finalized_checkpoint[0],
            finalized_root=genesis_root,
            execution_status=EXEC_IRRELEVANT)

    # -- time ----------------------------------------------------------------

    def on_tick(self, slot: int) -> None:
        """Slot rollover: reset the proposer boost (`fork_choice.rs:
        update_time/on_tick`)."""
        if slot > self.current_slot:
            self.current_slot = slot
            self.proposer_boost_root = ZERO_ROOT

    # -- block import --------------------------------------------------------

    def on_block(self, signed_block, block_root: bytes, state,
                 *, is_timely: bool = False,
                 execution_status: int = EXEC_IRRELEVANT,
                 execution_block_hash: bytes = None) -> None:
        """`fork_choice.rs:748`; ``state`` is the block's post-state.

        A block carrying a live execution payload imports OPTIMISTICALLY
        by default (`fork_choice.rs` payload_verification_status): the
        payload is only proven by the EL, so `on_invalid_execution_payload`
        must be able to revert it later; pre-merge blocks stay IRRELEVANT.
        """
        block = signed_block.message
        if int(block.slot) > self.current_slot:
            self.current_slot = int(block.slot)
        if execution_status == EXEC_IRRELEVANT:
            payload = getattr(block.body, "execution_payload", None)
            if payload is not None \
                    and bytes(payload.block_hash) != ZERO_ROOT:
                execution_status = EXEC_OPTIMISTIC
                execution_block_hash = bytes(payload.block_hash)
        jcp = (int(state.current_justified_checkpoint.epoch),
               bytes(state.current_justified_checkpoint.root))
        fcp = (int(state.finalized_checkpoint.epoch),
               bytes(state.finalized_checkpoint.root))
        if jcp[0] > self.justified_checkpoint[0]:
            self.justified_checkpoint = jcp
            self.justified_state = state
        if fcp[0] > self.finalized_checkpoint[0]:
            self.finalized_checkpoint = fcp
            self.proto.maybe_prune(fcp[1])
        if is_timely and self.proposer_boost_root == ZERO_ROOT:
            self.proposer_boost_root = block_root
        self.proto.on_block(
            slot=int(block.slot), root=block_root,
            parent_root=bytes(block.parent_root),
            state_root=bytes(block.state_root),
            justified_epoch=jcp[0], justified_root=jcp[1],
            finalized_epoch=fcp[0], finalized_root=fcp[1],
            execution_status=execution_status,
            execution_block_hash=execution_block_hash)

    # -- attestations --------------------------------------------------------

    def on_attestation(self, indexed_attestation, *,
                       is_from_block: bool = False) -> None:
        """`fork_choice.rs:1165` — validate + queue the latest messages."""
        data = indexed_attestation.data
        target_epoch = int(data.target.epoch)
        block_root = bytes(data.beacon_block_root)
        if block_root not in self.proto.indices:
            raise ForkChoiceError("unknown attestation head block")
        if self.proto.slot_of(block_root) > int(data.slot):
            raise ForkChoiceError("attestation to a future block")
        indices = np.asarray(list(indexed_attestation.attesting_indices),
                             dtype=np.int64)
        self.queued.append(QueuedAttestation(
            slot=int(data.slot), indices=indices, block_root=block_root,
            target_epoch=target_epoch))

    def on_attester_slashing(self, attester_slashing) -> None:
        """Equivocating validators lose fork-choice weight forever
        (`fork_choice.rs` on_attester_slashing)."""
        a = set(int(i) for i in attester_slashing.attestation_1.attesting_indices)
        b = set(int(i) for i in attester_slashing.attestation_2.attesting_indices)
        for idx in a & b:
            self.proto.process_equivocation(idx)

    def _drain_queued(self) -> None:
        """Votes only count from the slot after they were cast
        (`queued_attestations`, `fork_choice.rs:300-330`).  The whole
        slot's due attestations apply as ONE batch — attestations whose
        block was pruned between queue and drain drop atomically (the
        host raised before any mutation for those, so filtering first is
        bit-identical)."""
        queued = self.queued  # snapshot: appends race with drain (as
        # before this was batched); one list is partitioned exactly once
        due = [q for q in queued if q.slot < self.current_slot]
        if not due:
            return
        self.queued = [q for q in queued if q.slot >= self.current_slot]
        batch = [(q.indices, q.block_root, q.target_epoch)
                 for q in due if q.block_root in self.proto.indices]
        if batch:
            self.proto.process_attestation_batch(batch)

    # -- head ----------------------------------------------------------------

    def get_head(self) -> bytes:
        """`fork_choice.rs:528` → `proto_array.find_head`."""
        with TRACER.span("fork_choice_apply", cat="fork_choice",
                         nodes=len(self.proto.indices)) as _sp:
            with TRACER.span("drain_votes", cat="fork_choice",
                             queued=len(self.queued)):
                self._drain_queued()
            # Justified balances: active validators AT the justified
            # epoch, from the justified state
            # (`JustifiedBalances::from_justified_state`).
            balances = _active_balances(self.justified_state,
                                        self.justified_checkpoint[0])
            with TRACER.span("compute_deltas", cat="fork_choice"):
                deltas = self.proto.compute_deltas(balances)
            boost_score = 0
            if self.proposer_boost_root != ZERO_ROOT:
                committee_weight = (int(balances.sum())
                                    // self.preset.SLOTS_PER_EPOCH)
                boost_score = (committee_weight
                               * self.spec.proposer_score_boost // 100)
            with TRACER.span("apply_scores", cat="fork_choice"):
                self.proto.apply_score_changes(
                    deltas, self.justified_checkpoint,
                    self.finalized_checkpoint,
                    self.proposer_boost_root, boost_score,
                    self.current_slot)
            with TRACER.span("find_head", cat="fork_choice"):
                head = self.proto.find_head(self.justified_checkpoint[1],
                                            self.current_slot)
            _sp.set(head=head.hex())
            return head

    # -- optimistic sync hooks ----------------------------------------------

    def on_valid_execution_payload(self, root: bytes) -> None:
        self.proto.on_valid_execution_payload(root)

    def on_invalid_execution_payload(self, root: bytes) -> None:
        self.proto.on_invalid_execution_payload(root)

    def contains_block(self, root: bytes) -> bool:
        return root in self.proto.indices

    def block_slot(self, root: bytes) -> int:
        """Slot of a known block (works on both proto-array flavours)."""
        return self.proto.slot_of(root)
