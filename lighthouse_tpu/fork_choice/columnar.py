"""Columnar proto-array data plane — flat node columns + batched votes.

The host :class:`~.proto_array.ProtoArrayForkChoice` walks a python list of
``ProtoNode`` objects twice per head recompute; at mainnet shapes (16k
unfinalized nodes, 2M validators) that walk is the last per-slot host loop
(PAPER.md layer 4).  This module holds the same state as **flat columns**
sized by the node count

    slot · parent · depth · justified/finalized epoch+root · execution
    status · weight · best_child · best_descendant · root bytes

plus a **level schedule**: ``depth`` is maintained on insert (parents
always precede children), so the backward weight pass and the best-child
sweep become one masked vector step per tree level instead of one python
iteration per node — the same columnar playbook that vectorized the block
transition (PR 3) and made the registry HBM-resident (PR 6).

Votes are a whole-registry column triple (``current``/``next``/
``next_epoch``) fronted by a :class:`VoteBuffer`: per-attestation
``process_attestation`` calls append (validator, target-node, epoch)
triples, and one flush per slot merges them with the host's
latest-message rule (strictly-greater epoch wins; first arrival wins
ties) as a lexsort + segment-take instead of a per-validator loop.
Equivocations drop later votes at the buffer door, so a vote pushed
*before* the slashing still lands and one pushed *after* is blocked —
bit-identical to the host's call-order semantics.

The level sweep's cost is ``O(depth)`` vector steps, which wins on bushy
trees (healthy finality: a few epochs of forked heads) and loses badly on
chain-shaped ones (long non-finality: depth ≈ node count).
:func:`apply_scores` therefore dispatches adaptively: the masked level
sweep for shallow trees, :func:`apply_scores_walk` — an exact O(n)
python port of the host's two reverse walks over the columns — for deep
ones.  Both produce bit-identical results (fuzzed against each other and
the host oracle).

Everything here is pure numpy; :mod:`.device_proto_array` mirrors the hot
columns in HBM and fuses the delta/propagation passes into one jitted
program.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .proto_array import (
    EXEC_INVALID,
    EXEC_IRRELEVANT,
    ProtoArrayError,
    ProtoNode,
)

def _as_root_row(root: bytes) -> np.ndarray:
    return np.frombuffer(bytes(root), dtype=np.uint8)


class NodeColumns:
    """Append-only struct-of-arrays node table with a level schedule."""

    _ROOT_FIELDS = ("roots", "state_roots", "justified_roots",
                    "finalized_roots")

    def __init__(self, capacity: int = 64):
        cap = max(int(capacity), 8)
        self.n = 0
        self.slot = np.zeros(cap, np.int64)
        self.parent = np.full(cap, -1, np.int32)
        self.depth = np.zeros(cap, np.int32)
        self.justified_epoch = np.zeros(cap, np.int64)
        self.finalized_epoch = np.zeros(cap, np.int64)
        self.exec_status = np.full(cap, EXEC_IRRELEVANT, np.int8)
        self.weight = np.zeros(cap, np.int64)
        self.best_child = np.full(cap, -1, np.int32)
        self.best_desc = np.full(cap, -1, np.int32)
        self.roots = np.zeros((cap, 32), np.uint8)
        self.state_roots = np.zeros((cap, 32), np.uint8)
        self.justified_roots = np.zeros((cap, 32), np.uint8)
        self.finalized_roots = np.zeros((cap, 32), np.uint8)
        self.exec_hash: List[Optional[bytes]] = []
        self.indices: Dict[bytes, int] = {}
        # level schedule: node indices grouped by depth (python lists while
        # building, np arrays served cached)
        self._levels: List[List[int]] = []
        self._levels_np: Optional[List[np.ndarray]] = None
        self._ranks: Optional[np.ndarray] = None
        self._zero_root: Optional[np.ndarray] = None

    # -- growth --------------------------------------------------------------

    def _ensure(self, n: int) -> None:
        cap = self.slot.shape[0]
        if n <= cap:
            return
        new = max(cap * 2, n)
        for name in ("slot", "parent", "depth", "justified_epoch",
                     "finalized_epoch", "exec_status", "weight",
                     "best_child", "best_desc"):
            old = getattr(self, name)
            grown = np.empty(new, old.dtype)
            grown[:cap] = old
            grown[cap:] = -1 if name in ("parent", "best_child",
                                         "best_desc") else 0
            setattr(self, name, grown)
        for name in self._ROOT_FIELDS:
            old = getattr(self, name)
            grown = np.zeros((new, 32), np.uint8)
            grown[:cap] = old
            setattr(self, name, grown)

    def append(self, *, slot: int, root: bytes, parent: int,
               state_root: bytes, justified_epoch: int, justified_root: bytes,
               finalized_epoch: int, finalized_root: bytes,
               execution_status: int,
               execution_block_hash: Optional[bytes]) -> int:
        i = self.n
        self._ensure(i + 1)
        self.slot[i] = slot
        self.parent[i] = parent
        self.depth[i] = 0 if parent < 0 else int(self.depth[parent]) + 1
        self.justified_epoch[i] = justified_epoch
        self.finalized_epoch[i] = finalized_epoch
        self.exec_status[i] = execution_status
        self.weight[i] = 0
        self.best_child[i] = -1
        self.best_desc[i] = -1
        self.roots[i] = _as_root_row(root)
        self.state_roots[i] = _as_root_row(state_root)
        self.justified_roots[i] = _as_root_row(justified_root)
        self.finalized_roots[i] = _as_root_row(finalized_root)
        self.exec_hash.append(execution_block_hash)
        self.indices[bytes(root)] = i
        d = int(self.depth[i])
        while len(self._levels) <= d:
            self._levels.append([])
        self._levels[d].append(i)
        self.n = i + 1
        self._levels_np = None
        self._ranks = None
        self._zero_root = None
        return i

    # -- derived (cached) columns -------------------------------------------

    def levels(self) -> List[np.ndarray]:
        if self._levels_np is None:
            self._levels_np = [np.asarray(lv, np.int64)
                               for lv in self._levels]
        return self._levels_np

    def max_depth(self) -> int:
        return len(self._levels) - 1

    def ranks(self) -> np.ndarray:
        """Per-node rank of the block root under bytes-lexicographic order
        (the host tie-break ``child.root >= best.root``); rank order
        preserves every comparison the host makes."""
        if self._ranks is None:
            n = self.n
            flat = np.ascontiguousarray(self.roots[:n]).view("S32").ravel()
            order = np.argsort(flat, kind="stable")
            ranks = np.empty(n, np.int64)
            ranks[order] = np.arange(n, dtype=np.int64)
            self._ranks = ranks
        return self._ranks

    def zero_root_mask(self) -> np.ndarray:
        if self._zero_root is None:
            self._zero_root = ~self.roots[:self.n].any(axis=1)
        return self._zero_root

    def viable_mask(self, justified_checkpoint: Tuple[int, bytes],
                    finalized_checkpoint: Tuple[int, bytes]) -> np.ndarray:
        """`_viable_for_head` over all nodes at once (`proto_array.rs:897`):
        checkpoint-epoch AND root must match (epoch 0 passes all), and
        invalid-payload nodes are never viable."""
        n = self.n
        je, jr = justified_checkpoint
        fe, fr = finalized_checkpoint
        if je == 0:
            correct_j = np.ones(n, bool)
        else:
            correct_j = ((self.justified_epoch[:n] == je)
                         & (self.justified_roots[:n]
                            == _as_root_row(jr)).all(axis=1))
        if fe == 0:
            correct_f = np.ones(n, bool)
        else:
            correct_f = ((self.finalized_epoch[:n] == fe)
                         & (self.finalized_roots[:n]
                            == _as_root_row(fr)).all(axis=1))
        return (correct_j & correct_f
                & (self.exec_status[:n] != EXEC_INVALID))

    def root_bytes(self, i: int) -> bytes:
        return self.roots[i].tobytes()

    def export_nodes(self) -> List[ProtoNode]:
        """Materialize the host ``ProtoNode`` view (persistence/debug)."""
        out = []
        for i in range(self.n):
            out.append(ProtoNode(
                slot=int(self.slot[i]), root=self.root_bytes(i),
                parent=None if self.parent[i] < 0 else int(self.parent[i]),
                state_root=self.state_roots[i].tobytes(),
                justified_epoch=int(self.justified_epoch[i]),
                justified_root=self.justified_roots[i].tobytes(),
                finalized_epoch=int(self.finalized_epoch[i]),
                finalized_root=self.finalized_roots[i].tobytes(),
                execution_status=int(self.exec_status[i]),
                execution_block_hash=self.exec_hash[i],
                weight=int(self.weight[i]),
                best_child=None if self.best_child[i] < 0
                else int(self.best_child[i]),
                best_descendant=None if self.best_desc[i] < 0
                else int(self.best_desc[i])))
        return out

class VoteBuffer:
    """Whole-registry latest-message store + per-slot vote buffer.

    ``current``/``next``/``next_epoch`` mirror the host ``VoteTracker``
    columns exactly; buffered (validator, node, epoch) triples carry an
    arrival counter so a single :meth:`flush` reproduces the host's
    sequential ``process_attestation`` fold bit-for-bit (see module
    docstring for the equivalence argument)."""

    def __init__(self, n: int = 0):
        self.current = np.full(n, -1, np.int32)
        self.next = np.full(n, -1, np.int32)
        self.next_epoch = np.zeros(n, np.uint64)
        self.equivocating: set[int] = set()
        self._buf_val: List[np.ndarray] = []
        self._buf_node: List[np.ndarray] = []
        self._buf_epoch: List[np.ndarray] = []
        self._buf_arr: List[np.ndarray] = []
        self._arrival = 0

    def __len__(self) -> int:
        return self.current.shape[0]

    def grow(self, n: int) -> None:
        old = self.current.shape[0]
        if n <= old:
            return
        self.current = np.concatenate(
            [self.current, np.full(n - old, -1, np.int32)])
        self.next = np.concatenate(
            [self.next, np.full(n - old, -1, np.int32)])
        self.next_epoch = np.concatenate(
            [self.next_epoch, np.zeros(n - old, np.uint64)])

    def pending(self) -> int:
        return sum(v.shape[0] for v in self._buf_val)

    # -- ingest --------------------------------------------------------------

    def push_votes(self, validators: np.ndarray, node_idx: int,
                   target_epoch: int) -> None:
        """Buffer one attestation's votes (already filtered to a known
        target node).  Equivocating validators are dropped at the door —
        this IS the host's call-order semantics: a vote pushed before
        ``push_equivocation(v)`` is already in the buffer and lands at
        flush; one pushed after never enters.  (The host returns before
        growing for equivocators, and membership implies the columns are
        already grown.)"""
        v = np.asarray(validators, np.int64)
        if self.equivocating:
            eq = np.fromiter(self.equivocating, np.int64,
                             len(self.equivocating))
            v = v[~np.isin(v, eq)]
        k = v.shape[0]
        if k == 0:
            return
        self._buf_val.append(v)
        self._buf_node.append(np.full(k, node_idx, np.int32))
        self._buf_epoch.append(np.full(k, target_epoch, np.int64))
        self._buf_arr.append(np.arange(self._arrival, self._arrival + k,
                                       dtype=np.int64))
        self._arrival += k

    def push_equivocation(self, validator_index: int) -> None:
        v = int(validator_index)
        self.grow(v + 1)
        self.equivocating.add(v)

    # -- flush ---------------------------------------------------------------

    def flush(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Apply every buffered vote in arrival order (vectorized) and
        return the applied ``(validators, nodes, epochs)`` — the scatter
        the device mirror needs.  Empty arrays when nothing changed."""
        empty = (np.zeros(0, np.int64), np.zeros(0, np.int32),
                 np.zeros(0, np.int64))
        if not self._buf_val:
            return empty
        vals = np.concatenate(self._buf_val)
        nodes = np.concatenate(self._buf_node)
        epochs = np.concatenate(self._buf_epoch)
        arr = np.concatenate(self._buf_arr)
        self._buf_val, self._buf_node = [], []
        self._buf_epoch, self._buf_arr = [], []
        self.grow(int(vals.max()) + 1)
        # Per-validator winner of the sequential fold: the highest epoch,
        # earliest arrival among equals (lexsort: last row per validator).
        order = np.lexsort((-arr, epochs, vals))
        v_sorted = vals[order]
        is_last = np.ones(v_sorted.shape[0], bool)
        is_last[:-1] = v_sorted[1:] != v_sorted[:-1]
        sel = order[is_last]
        wv, wn, we = vals[sel], nodes[sel], epochs[sel]
        # Host update rule: strictly-greater epoch, or no latest message.
        apply = (we > self.next_epoch[wv].astype(np.int64)) \
            | (self.next[wv] == -1)
        wv, wn, we = wv[apply], wn[apply], we[apply]
        self.next[wv] = wn
        self.next_epoch[wv] = we.astype(np.uint64)
        return wv, wn, we

    def remap(self, remap: np.ndarray) -> None:
        """Post-prune node-index remap (host ``maybe_prune`` gather):
        ``remap[-1]`` must be -1 so empty votes stay empty."""
        self.current = remap[self.current]
        self.next = remap[self.next]


# ---------------------------------------------------------------------------
# Numpy passes — the host-vectorized engine (and the oracle the jitted
# kernel in device_proto_array must match bit-for-bit).
# ---------------------------------------------------------------------------


def compute_deltas_host(votes: VoteBuffer, old_balances: np.ndarray,
                        new_balances: np.ndarray,
                        n_nodes: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-node weight deltas from vote movement — two scatter-adds over
    the whole validator set (`proto_array_fork_choice.rs:819`).  Moves
    ``current ← next`` and returns ``(deltas, persisted_new_balances)``
    exactly like the host (equivocation-zeroed balances persist)."""
    nv = len(votes)
    old_b = np.zeros(nv, np.uint64)
    m = min(old_balances.shape[0], nv)
    old_b[:m] = old_balances[:m]
    new_b = np.zeros(nv, np.uint64)
    m2 = min(new_balances.shape[0], nv)
    new_b[:m2] = new_balances[:m2]
    if votes.equivocating:
        eq = np.fromiter(votes.equivocating, dtype=np.int64,
                         count=len(votes.equivocating))
        new_b[eq[eq < nv]] = 0
    deltas = np.zeros(n_nodes, np.int64)
    cur_mask = votes.current >= 0
    np.subtract.at(deltas, votes.current[cur_mask],
                   old_b[cur_mask].astype(np.int64))
    nxt_mask = votes.next >= 0
    np.add.at(deltas, votes.next[nxt_mask], new_b[nxt_mask].astype(np.int64))
    votes.current = votes.next.copy()
    return deltas, new_b


def apply_scores_host(cols: NodeColumns, deltas: np.ndarray,
                      viable: np.ndarray,
                      prev_boost_idx: int, prev_boost_score: int,
                      boost_idx: int, boost_score: int) -> None:
    """Bottom-up weight propagation + best-child sweep, one masked vector
    step per tree level (the host's two reverse node walks,
    `proto_array.rs:167-320`).  Mutates ``cols.weight/best_child/
    best_desc`` in place.

    Equivalence to the host walk: parents always precede children, so the
    reverse index order the host uses IS a topological order; processing
    whole levels deepest-first visits every parent→child edge after the
    child's own subtree is final, which is the only ordering property the
    host result depends on (the running best-child max over a total order
    converges to the same argmax regardless of sibling order).
    """
    n = cols.n
    d = np.zeros(n, np.int64)
    d[:deltas.shape[0]] = deltas
    invalid = cols.exec_status[:n] == EXEC_INVALID
    zroot = cols.zero_root_mask()
    weight = cols.weight
    bc, bd = cols.best_child, cols.best_desc
    parent = cols.parent
    rank = cols.ranks()
    if prev_boost_idx >= 0 and not invalid[prev_boost_idx]:
        d[prev_boost_idx] -= prev_boost_score
    if boost_idx >= 0 and not invalid[boost_idx]:
        d[boost_idx] += boost_score
    neg = np.int64(-1)
    for lvl in range(cols.max_depth(), -1, -1):
        c = cols.levels()[lvl]
        if c.size == 0:
            continue
        inv_c = invalid[c]
        zr_c = zroot[c]
        # Finalize this level's weights: every deeper delta has arrived.
        # Zero-root nodes are skipped wholesale (delta discarded, nothing
        # propagates); invalid nodes remove their pre-update weight from
        # ancestors and pin to zero (`proto_array.rs:209-216`).
        d_eff = np.where(zr_c, 0, np.where(inv_c, -weight[c], d[c]))
        weight[c] = np.where(inv_c, 0,
                             np.where(zr_c, weight[c], weight[c] + d_eff))
        pc = parent[c]
        has_parent = pc >= 0
        if not has_parent.any():
            continue
        np.add.at(d, pc[has_parent], d_eff[has_parent])
        # Best-child recompute for every parent with a child at this
        # level (all of a parent's children share one depth): a 3-stage
        # segment argmax — max weight, then max root-rank among ties,
        # then the unique winner — over viable-leading children only.
        cc = c[has_parent]
        pp = pc[has_parent]
        # leads-to-viable (`proto_array.rs` node_leads_to_viable_head):
        # the best descendant is viable OR the node itself is.
        lead = viable[cc].copy()
        bdc = bd[cc]
        mbd = bdc >= 0
        lead[mbd] |= viable[bdc[mbd]]

        def seg_argmax(mask):
            """Per-parent argmax over masked children by the host's total
            order: weight, then root rank (roots unique ⇒ unique winner).
            Returns a node-indexed array, −1 where the mask is empty."""
            wmax = np.full(n, neg)
            np.maximum.at(wmax, pp[mask], weight[cc[mask]])
            m2 = mask & (weight[cc] == wmax[pp])
            rmax = np.full(n, neg)
            np.maximum.at(rmax, pp[m2], rank[cc[m2]])
            m3 = m2 & (rank[cc] == rmax[pp])
            win = np.full(n, neg)
            np.maximum.at(win, pp[m3], cc[m3])
            return win

        # The host's incremental sweep (descending child index, seeded
        # with LAST round's best child) reduces to a closed form:
        # - any viable-leading child  → argmax over those (pure);
        # - none, previous best None  → None;
        # - none, previous best j     → None if j is still the max over
        #   children with index ≥ j (the sweep reaches j while it is
        #   still best and resets), else the global argmax (j is beaten
        #   by a higher-index child first, and the reset never fires).
        win_lead = seg_argmax(lead)
        win_all = seg_argmax(np.ones(cc.shape[0], bool))
        prevb = bc[:n].astype(np.int64)
        win_ge = seg_argmax(cc >= prevb[pp])
        F = np.where(win_lead >= 0, win_lead,
                     np.where(prevb == -1, neg,
                              np.where(win_ge == prevb, neg, win_all)))
        touched = np.unique(pp)
        newF = F[touched]
        bc[touched] = newF.astype(np.int32)
        fc = np.maximum(newF, 0)
        wbd = bd[fc]
        bd[touched] = np.where(newF >= 0,
                               np.where(wbd >= 0, wbd,
                                        newF.astype(np.int32)),
                               np.int32(-1))
    if (weight[:n] < 0).any():
        raise ProtoArrayError("negative node weight")


def apply_scores_walk(cols: NodeColumns, deltas: np.ndarray,
                      viable: np.ndarray,
                      prev_boost_idx: int, prev_boost_score: int,
                      boost_idx: int, boost_score: int) -> None:
    """Exact O(n) python port of the host's two reverse walks over the
    columns (`proto_array.rs:167-320`) — the deep-tree arm of
    :func:`apply_scores`: on a chain-shaped proto-array the level sweep
    pays one full vector step per node of depth, while this walk costs
    the same as the host oracle."""
    n = cols.n
    d = [0] * n
    for i in range(min(deltas.shape[0], n)):
        d[i] = int(deltas[i])
    invalid = (cols.exec_status[:n] == EXEC_INVALID).tolist()
    zroot = cols.zero_root_mask().tolist()
    lead_ok = viable.tolist()
    weight = cols.weight[:n].tolist()
    parent = cols.parent[:n].tolist()
    bc = cols.best_child[:n].tolist()
    bd = cols.best_desc[:n].tolist()
    rank = cols.ranks().tolist()
    for i in range(n - 1, -1, -1):
        if zroot[i]:
            continue
        inv = invalid[i]
        di = -weight[i] if inv else d[i]
        if i == prev_boost_idx and not inv:
            di -= prev_boost_score
        if i == boost_idx and not inv:
            di += boost_score
        weight[i] = 0 if inv else weight[i] + di
        if weight[i] < 0:
            raise ProtoArrayError("negative node weight")
        p = parent[i]
        if p >= 0:
            d[p] += di

    def leads(c: int) -> bool:
        b = bd[c]
        return (b >= 0 and lead_ok[b]) or lead_ok[c]

    for c in range(n - 1, -1, -1):
        p = parent[c]
        if p < 0:
            continue
        child_lead = leads(c)
        tc = (c, bd[c] if bd[c] >= 0 else c)
        if bc[p] >= 0:
            if bc[p] == c and not child_lead:
                new = (-1, -1)
            elif bc[p] == c:
                new = tc
            else:
                b = bc[p]
                best_lead = leads(b)
                if child_lead and not best_lead:
                    new = tc
                elif not child_lead and best_lead:
                    new = (bc[p], bd[p])
                elif weight[c] == weight[b]:
                    new = tc if rank[c] >= rank[b] else (bc[p], bd[p])
                else:
                    new = tc if weight[c] >= weight[b] else (bc[p], bd[p])
        else:
            new = tc if child_lead else (bc[p], bd[p])
        bc[p], bd[p] = new
    cols.weight[:n] = weight
    cols.best_child[:n] = bc
    cols.best_desc[:n] = bd


# Past this depth (AND depth beyond n/32) the chain-shaped walk beats the
# per-level vector sweep; the measured crossover sits well above it in
# the bushy direction and well below in the chain direction.
_WALK_DEPTH = 96


def apply_scores(cols: NodeColumns, deltas: np.ndarray, viable: np.ndarray,
                 prev_boost_idx: int, prev_boost_score: int,
                 boost_idx: int, boost_score: int) -> None:
    """Adaptive dispatch between the vectorized level sweep (bushy trees)
    and the exact host-port walk (deep/chain-shaped trees)."""
    md = cols.max_depth()
    if md > _WALK_DEPTH and md > cols.n // 32:
        apply_scores_walk(cols, deltas, viable, prev_boost_idx,
                          prev_boost_score, boost_idx, boost_score)
    else:
        apply_scores_host(cols, deltas, viable, prev_boost_idx,
                          prev_boost_score, boost_idx, boost_score)
