"""Kill-at-every-op crash drill for the persistence layer.

The crash-consistency claim is behavioral: *whatever store op the
process dies after, a restart recovers a chain identical to one that
never crashed*.  This module proves it by construction — a
fault-injecting KV wrapper (:class:`CrashingStore`, driven by the same
seeded :class:`~.faults.FaultInjector` plans as the streaming-verify
drills) kills the node at store op N, the drill restarts from the
surviving bytes, finishes the import sequence, and diffs the result
against a never-crashed oracle — for EVERY N.

Shared by ``tests/test_store_recovery.py`` (randomized/quick),
``scripts/validate_crash_recovery.py`` (exhaustive + SIGKILL subprocess
mode) and the bench ``restart_recovery`` row.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ..beacon_chain import BeaconChain
from ..store import HotColdDB, KeyValueStore, MemoryStore, SqliteStore
from .faults import FaultInjector, InjectedFault

# Effectively-infinite outage end: once the kill fires, NOTHING later
# lands (a dead process issues no more writes).
_FOREVER = 1 << 62


class CrashingStore(KeyValueStore):
    """KV wrapper with a failure point in front of every MUTATION.

    Reads pass through untouched (a dead process's reads are moot);
    ``put``/``delete``/``do_atomically`` each count as ONE op at the
    ``"store_op"`` site — a batch is atomic at the engine layer (SQLite
    rolls an uncommitted transaction back; MemoryStore applies under
    one lock), so "killed inside a batch" and "killed before it" are
    the same store state.
    """

    SITE = "store_op"

    def __init__(self, inner: KeyValueStore, injector: FaultInjector):
        self.inner = inner
        self.injector = injector

    def get(self, column, key):
        return self.inner.get(column, key)

    def iter_column(self, column):
        return self.inner.iter_column(column)

    def put(self, column, key, value):
        self.injector.check(self.SITE)
        self.inner.put(column, key, value)

    def delete(self, column, key):
        self.injector.check(self.SITE)
        self.inner.delete(column, key)

    def do_atomically(self, ops):
        self.injector.check(self.SITE)
        self.inner.do_atomically(ops)

    def close(self):
        self.inner.close()

    @property
    def mutations(self) -> int:
        return self.injector.calls.get(self.SITE, 0)


# -- deterministic fixture ----------------------------------------------------


@dataclass
class ChainFixture:
    """A pre-built deterministic block sequence every drill run (and the
    oracle, and a SIGKILL'd subprocess's parent) can regenerate
    bit-identically: the harness uses interop keys and no entropy."""
    preset: object
    spec: object
    T: object
    genesis_state: object
    genesis_root: bytes
    blocks: List[Tuple[int, bytes, object]]  # (slot, root, signed_block)


def build_chain_fixture(slots: int = 32, n_validators: int = 16,
                        preset=None) -> ChainFixture:
    from ..types.presets import MINIMAL
    from .harness import StateHarness

    h = StateHarness(n_validators=n_validators, preset=preset or MINIMAL)
    hdr = h.state.latest_block_header.copy()
    hdr.state_root = h.state.tree_hash_root()
    genesis_root = hdr.tree_hash_root()
    genesis_state = h.state.copy()
    blocks = []
    for _ in range(slots):
        sb = h.build_block()
        h.apply_block(sb)
        blocks.append((int(sb.message.slot),
                       sb.message.tree_hash_root(), sb))
    return ChainFixture(preset=h.preset, spec=h.spec, T=h.T,
                        genesis_state=genesis_state,
                        genesis_root=genesis_root, blocks=blocks)


def make_chain(store: HotColdDB, fixture: ChainFixture) -> BeaconChain:
    return BeaconChain(store=store,
                       genesis_state=fixture.genesis_state.copy(),
                       genesis_block_root=fixture.genesis_root,
                       preset=fixture.preset, spec=fixture.spec,
                       T=fixture.T)


def import_sequence(chain: BeaconChain, fixture: ChainFixture) -> None:
    """Drive the fixture's blocks through the full import pipeline,
    skipping roots fork choice already holds (the post-restart resume
    path re-drives the same loop).  Ends on a final tick + head
    recompute so queued votes drain identically on every run."""
    for slot, root, sb in fixture.blocks:
        chain.per_slot_task(slot)
        if not chain.fork_choice.contains_block(root):
            chain.process_block(sb)
    chain.per_slot_task(fixture.blocks[-1][0] + 1)
    chain.recompute_head()


# -- backends -----------------------------------------------------------------


class MemoryBackend:
    """The MemoryStore object IS the disk: it survives the simulated
    process death and the restart reads the same dict."""

    name = "memory"

    def fresh(self) -> KeyValueStore:
        return MemoryStore()

    def reopen(self, kv: KeyValueStore) -> KeyValueStore:
        return kv


class SqliteBackend:
    """A fresh file per run; restart closes the crashed process's
    connection and opens a new one against the same file."""

    name = "sqlite"

    def __init__(self, directory: str):
        self.directory = directory
        self._n = 0
        self._paths: dict[int, str] = {}

    def fresh(self) -> KeyValueStore:
        self._n += 1
        path = os.path.join(self.directory, f"drill-{self._n}.sqlite")
        kv = SqliteStore(path)
        self._paths[id(kv)] = path
        return kv

    def reopen(self, kv: KeyValueStore) -> KeyValueStore:
        path = self._paths[id(kv)]
        kv.close()
        return SqliteStore(path)


# -- comparison ---------------------------------------------------------------


def chain_fingerprint(chain: BeaconChain) -> dict:
    """Everything a restart must preserve: head, checkpoints, and the
    full fork-choice weight surface."""
    fc = chain.fork_choice
    proto = fc.proto.to_host() if hasattr(fc.proto, "to_host") else fc.proto
    return {
        "head": chain.head.root.hex(),
        "head_slot": chain.head.slot,
        "justified": (fc.justified_checkpoint[0],
                      fc.justified_checkpoint[1].hex()),
        "finalized": (fc.finalized_checkpoint[0],
                      fc.finalized_checkpoint[1].hex()),
        "weights": {n.root.hex(): int(n.weight) for n in proto.nodes},
    }


def compare_chains(recovered: BeaconChain,
                   oracle: BeaconChain) -> List[str]:
    """Human-readable divergences (empty == identical)."""
    a, b = chain_fingerprint(recovered), chain_fingerprint(oracle)
    out = []
    for field in ("head", "head_slot", "justified", "finalized"):
        if a[field] != b[field]:
            out.append(f"{field}: recovered={a[field]} oracle={b[field]}")
    if a["weights"] != b["weights"]:
        only_a = sorted(set(a["weights"]) - set(b["weights"]))
        only_b = sorted(set(b["weights"]) - set(a["weights"]))
        diff = sorted(r for r in set(a["weights"]) & set(b["weights"])
                      if a["weights"][r] != b["weights"][r])
        out.append(f"weights: extra={only_a[:3]} missing={only_b[:3]} "
                   f"changed={[(r[:12], a['weights'][r], b['weights'][r]) for r in diff[:3]]}")
    return out


# -- drill --------------------------------------------------------------------


def run_oracle(fixture: ChainFixture, backend) -> BeaconChain:
    store = HotColdDB(backend.fresh(), fixture.preset, fixture.spec,
                      fixture.T)
    chain = make_chain(store, fixture)
    import_sequence(chain, fixture)
    return chain


def count_store_ops(fixture: ChainFixture, backend) -> int:
    """Total mutation count of a clean run — the drill's kill-point
    universe (the chain-construction ops are excluded: the drill arms
    the injector only once the node is up, matching a process that
    completed its boot)."""
    inj = FaultInjector(seed=0)
    kv = CrashingStore(backend.fresh(), inj)
    store = HotColdDB(kv, fixture.preset, fixture.spec, fixture.T)
    chain = make_chain(store, fixture)
    before = kv.mutations
    import_sequence(chain, fixture)
    return kv.mutations - before


def run_kill_point(fixture: ChainFixture, backend, kill_at: int,
                   *, seed: int = 0) -> Tuple[BeaconChain, bool, object]:
    """One drill run: import, die after store op ``kill_at`` (counted
    from the armed point), restart, recover, finish the sequence.
    Returns (recovered_chain, crashed?, recovery_report)."""
    inj = FaultInjector(seed=seed)
    inner = backend.fresh()
    crashing = CrashingStore(inner, inj)
    store = HotColdDB(crashing, fixture.preset, fixture.spec, fixture.T)
    chain = make_chain(store, fixture)
    # The injector's outage window is an ABSOLUTE per-site sequence
    # range, and chain construction already consumed a few mutations
    # (schema put, genesis state, anchor persist): arm relative to the
    # current counter so kill point N means "the Nth op of the IMPORT
    # sequence" — otherwise points 0..C-1 alias to one crash and the
    # final C ops (the finalization persist tail) are never killed.
    armed_at = crashing.mutations
    inj.plan(CrashingStore.SITE, outage=(armed_at + kill_at, _FOREVER))
    crashed = False
    try:
        import_sequence(chain, fixture)
    except InjectedFault:
        crashed = True
    # "Restart": a brand-new process sees only the surviving bytes.
    kv2 = backend.reopen(inner)
    store2 = HotColdDB(kv2, fixture.preset, fixture.spec, fixture.T)
    chain2 = BeaconChain.from_store(store=store2, preset=fixture.preset,
                                    spec=fixture.spec, T=fixture.T)
    import_sequence(chain2, fixture)
    return chain2, crashed, chain2.last_recovery


# -- checkpoint-sync backfill drill -------------------------------------------


@dataclass
class BackfillFixture:
    """A checkpoint-sync scenario: a trusted anchor block + its
    post-state partway up a deterministic chain, with the FULL history
    available from a stub peer.  The drill boots from the anchor,
    backfills toward genesis, and SIGKILLs mid-batch."""
    preset: object
    spec: object
    T: object
    anchor_slot: int
    anchor_root: bytes
    anchor_block: object
    anchor_state: object
    blocks: List[Tuple[int, bytes, object]]  # (slot, root, signed_block)


def build_backfill_fixture(slots: int = 24, n_validators: int = 16,
                           preset=None,
                           anchor_slot: Optional[int] = None
                           ) -> BackfillFixture:
    from ..types.presets import MINIMAL
    from .harness import StateHarness

    h = StateHarness(n_validators=n_validators, preset=preset or MINIMAL)
    blocks: List[Tuple[int, bytes, object]] = []
    anchor_state = None
    for _ in range(slots):
        sb = h.build_block()
        h.apply_block(sb)
        blocks.append((int(sb.message.slot),
                       sb.message.tree_hash_root(), sb))
        if anchor_slot is not None and int(sb.message.slot) == anchor_slot:
            anchor_state = h.state.copy()
    if anchor_slot is None:
        anchor_slot = blocks[-1][0]
        anchor_state = h.state.copy()
    if anchor_state is None:
        raise ValueError(f"no block at anchor slot {anchor_slot}")
    anchor = next((b for b in blocks if b[0] == anchor_slot))
    return BackfillFixture(preset=h.preset, spec=h.spec, T=h.T,
                           anchor_slot=anchor_slot,
                           anchor_root=bytes(anchor[1]),
                           anchor_block=anchor[2],
                           anchor_state=anchor_state, blocks=blocks)


class HistoryPeer:
    """Stub peer serving the fixture's full history; records every
    range it was asked for (the "no re-import" invariant reads it)."""

    def __init__(self, fixture: BackfillFixture):
        self._blocks = fixture.blocks
        self.requests: List[Tuple[int, int]] = []

    def blocks_by_range(self, req):
        self.requests.append((int(req.start_slot), int(req.count)))
        return [sb for slot, _root, sb in self._blocks
                if req.start_slot <= slot < req.start_slot + req.count]


def _boot_checkpoint(store: HotColdDB, fixture: BackfillFixture):
    return BeaconChain.from_checkpoint(
        store=store, anchor_state=fixture.anchor_state.copy(),
        anchor_block=fixture.anchor_block, preset=fixture.preset,
        spec=fixture.spec, T=fixture.T)


def _run_backfill(chain, fixture: BackfillFixture,
                  batch_size: int = 8) -> None:
    from ..network.backfill import BackfillSync
    bf = BackfillSync(chain, batch_size=batch_size)
    peer = HistoryPeer(fixture)
    while not bf.progress.complete:
        if not bf.fill_from(peer):
            break


def count_backfill_ops(fixture: BackfillFixture, backend,
                       batch_size: int = 8) -> int:
    """Mutations of a clean checkpoint-boot + full backfill, counted
    from after the boot (the drill's kill-point universe).  The small
    default ``batch_size`` forces SEVERAL atomic batches out of a
    modest fixture, so the drill has mid-backfill kill points."""
    inj = FaultInjector(seed=0)
    kv = CrashingStore(backend.fresh(), inj)
    store = HotColdDB(kv, fixture.preset, fixture.spec, fixture.T)
    chain = _boot_checkpoint(store, fixture)
    before = kv.mutations
    _run_backfill(chain, fixture, batch_size=batch_size)
    return kv.mutations - before


def run_backfill_kill_point(fixture: BackfillFixture, backend,
                            kill_at: int, *, seed: int = 0,
                            batch_size: int = 8) -> List[str]:
    """One run: checkpoint boot, backfill, die after store op
    ``kill_at``, restart, recover, RESUME backfill.  Returns the list
    of violated invariants (empty == green):

    - recovery must not orphan any committed backfill block (they sit
      below the anchor with parents outside fork choice — the
      historical-floor rule classifies them ``skipped_stale``);
    - the resumed backfill must start exactly at the oldest committed
      block (atomic per-batch commits → no torn batch) and never
      re-request a slot range it already holds;
    - the finished history must be complete down to genesis.
    """
    from ..network.backfill import BackfillSync

    inj = FaultInjector(seed=seed)
    inner = backend.fresh()
    crashing = CrashingStore(inner, inj)
    store = HotColdDB(crashing, fixture.preset, fixture.spec, fixture.T)
    chain = _boot_checkpoint(store, fixture)
    armed_at = crashing.mutations
    inj.plan(CrashingStore.SITE, outage=(armed_at + kill_at, _FOREVER))
    try:
        _run_backfill(chain, fixture, batch_size=batch_size)
    except InjectedFault:
        pass
    # "Restart": a brand-new process sees only the surviving bytes.
    kv2 = backend.reopen(inner)
    store2 = HotColdDB(kv2, fixture.preset, fixture.spec, fixture.T)
    chain2 = BeaconChain.from_store(store=store2, preset=fixture.preset,
                                    spec=fixture.spec, T=fixture.T)
    failures: List[str] = []
    report = chain2.last_recovery
    if report is not None and report.orphans_removed:
        failures.append(
            f"recovery orphaned {len(report.orphans_removed)} committed "
            f"backfill blocks (historical-floor rule violated)")
    # Oldest committed block BELOW the anchor, by direct store probe.
    committed = [slot for slot, root, _sb in fixture.blocks
                 if slot < fixture.anchor_slot
                 and store2.get_block(bytes(root)) is not None]
    oldest_committed = min(committed) if committed else fixture.anchor_slot
    bf2 = BackfillSync(chain2, batch_size=batch_size)
    if bf2.progress.oldest_slot != oldest_committed:
        failures.append(
            f"resume point {bf2.progress.oldest_slot} != oldest committed "
            f"slot {oldest_committed} (would re-download history)")
    peer2 = HistoryPeer(fixture)
    while not bf2.progress.complete:
        if not bf2.fill_from(peer2):
            break
    for start, count in peer2.requests:
        if start + count > oldest_committed:
            failures.append(
                f"resumed backfill re-requested [{start}, {start + count})"
                f" overlapping committed history >= {oldest_committed}")
            break
    if not bf2.progress.complete:
        failures.append("resumed backfill did not complete")
    missing = [slot for slot, root, _sb in fixture.blocks
               if slot < fixture.anchor_slot
               and store2.get_block(bytes(root)) is None]
    if missing:
        failures.append(f"history incomplete after resume: missing "
                        f"slots {missing[:5]}")
    return failures


def backfill_kill_point_drill(fixture: BackfillFixture, backend,
                              kill_points: Optional[List[int]] = None,
                              *, seed: int = 0, batch_size: int = 8,
                              on_progress: Optional[Callable] = None
                              ) -> dict:
    """Kill the backfill at every requested store op (``None`` =
    exhaustive); ``report["failures"]`` empty == green."""
    total_ops = count_backfill_ops(fixture, backend, batch_size=batch_size)
    if kill_points is None:
        kill_points = list(range(total_ops))
    failures = []
    for n in kill_points:
        bad = run_backfill_kill_point(fixture, backend, n, seed=seed,
                                      batch_size=batch_size)
        if bad:
            failures.append({"kill_at": n, "violations": bad})
        if on_progress is not None:
            on_progress(n, len(kill_points), bool(bad))
    return {
        "backend": backend.name,
        "anchor_slot": fixture.anchor_slot,
        "total_ops": total_ops,
        "kill_points": len(kill_points),
        "failures": failures,
    }


def kill_point_drill(fixture: ChainFixture, backend,
                     kill_points: Optional[List[int]] = None,
                     *, seed: int = 0,
                     on_progress: Optional[Callable] = None) -> dict:
    """The full drill: oracle once, then every requested kill point.
    ``kill_points=None`` means EVERY op of a clean run (exhaustive).
    Returns a report dict; ``report["failures"]`` empty == green."""
    oracle = run_oracle(fixture, backend)
    total_ops = count_store_ops(fixture, backend)
    if kill_points is None:
        kill_points = list(range(total_ops))
    failures = []
    crashes = 0
    replayed_total = 0
    for n in kill_points:
        chain2, crashed, report = run_kill_point(fixture, backend, n,
                                                 seed=seed)
        crashes += int(crashed)
        replayed_total += len(report.replayed) if report else 0
        divergences = compare_chains(chain2, oracle)
        if divergences:
            failures.append({"kill_at": n, "divergences": divergences})
        if on_progress is not None:
            on_progress(n, len(kill_points), bool(divergences))
    return {
        "backend": backend.name,
        "slots": len(fixture.blocks),
        "total_ops": total_ops,
        "kill_points": len(kill_points),
        "crashes": crashes,
        "replayed_total": replayed_total,
        "failures": failures,
    }
