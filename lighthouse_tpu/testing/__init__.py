"""In-process test rigs — the ``BeaconChainHarness`` layer.

Counterpart of ``/root/reference/beacon_node/beacon_chain/src/test_utils.rs``
and ``consensus/types/src/test_utils/``: deterministic interop keypairs, a
block-building harness that signs every message kind, and manual slot
control.  Used by the test suite and usable by downstream integration rigs.
"""

from .harness import StateHarness

__all__ = ["StateHarness"]
