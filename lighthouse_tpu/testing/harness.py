"""Block-building harness over the pure state-transition layer.

Counterpart of ``BeaconChainHarness``
(``/root/reference/beacon_node/beacon_chain/src/test_utils.rs:579``): builds
*valid* signed blocks — correct proposer, randao reveal, state root,
attestations with full committee participation, sync aggregates, deposits
with real Merkle proofs, slashings, exits, BLS-to-execution changes — against
a live state, using the interop keypairs.

Signing honours the active BLS backend: under ``python`` every signature is
real; under ``fake`` a fixed valid-encoding G2 point stands in (the backend
ignores pairings but deserialization validity rules still apply), mirroring
how the reference runs its harness under ``fake_crypto``.
"""

from __future__ import annotations

import numpy as np

from ..crypto import bls as B
from ..crypto import curve as C
from ..ops.merkle_proof import DepositTree
from ..types.chain_spec import ChainSpec, Domain, ForkName
from ..types.factory import spec_types
from ..types.presets import MINIMAL, Preset
from ..state_transition import (
    SignatureStrategy,
    interop_genesis_state,
    interop_secret_key,
    state_transition,
)
from ..state_transition.committees import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
)
from ..state_transition.genesis import bls_withdrawal_credentials, interop_pubkey
from ..state_transition.helpers import (
    compute_epoch_at_slot,
    compute_signing_root,
    current_epoch,
    get_block_root,
    get_block_root_at_slot,
    get_domain,
    get_randao_mix,
)
from ..state_transition.per_block import get_expected_withdrawals
from ..state_transition.per_slot import process_slots

# A valid non-infinity G2 encoding for fake-backend signing.
_DUMMY_SIG = C.g2_compress(C.G2_GEN)


def _real_signing() -> bool:
    return B.get_backend().name != "fake"


def _sign(validator_index: int, signing_root: bytes) -> bytes:
    if not _real_signing():
        return _DUMMY_SIG
    return interop_secret_key(validator_index).sign(signing_root).serialize()


class StateHarness:
    """Drives a beacon state forward with self-built valid blocks."""

    def __init__(self, n_validators: int = 64,
                 fork: ForkName = ForkName.CAPELLA,
                 preset: Preset = MINIMAL,
                 spec: ChainSpec | None = None,
                 genesis_time: int = 0):
        self.preset = preset
        self.spec = spec or ChainSpec.minimal().with_forks_at_genesis(fork)
        self.T = spec_types(preset)
        self.state = interop_genesis_state(
            n_validators, genesis_time, preset, self.spec, self.T, fork=fork)
        # Deposit tree pre-seeded with the genesis validators, so new
        # deposits continue the contract's index sequence
        # (state.eth1_deposit_index == n_validators at interop genesis).
        self.deposit_tree = DepositTree(preset.DEPOSIT_CONTRACT_TREE_DEPTH)
        for i in range(n_validators):
            pk = interop_pubkey(i)
            self.deposit_tree.push(self.T.DepositData(
                pubkey=pk,
                withdrawal_credentials=bls_withdrawal_credentials(pk),
                amount=preset.MAX_EFFECTIVE_BALANCE,
                signature=_DUMMY_SIG).tree_hash_root())
        self.pending_deposits: list = []
        self.blocks: list = []  # applied signed blocks, in order

    # -- fork plumbing -------------------------------------------------------

    def fork_at(self, slot: int) -> ForkName:
        return self.spec.fork_name_at_epoch(
            compute_epoch_at_slot(slot, self.preset.SLOTS_PER_EPOCH))

    # -- attestation building ------------------------------------------------

    def _committee_att_data(self, state, slot: int):
        """Per-committee ``(index, committee, AttestationData, signing
        root)`` tuples for ``slot`` — the ONE construction aggregates
        AND single-bit attestations share (they must vote identical
        AttestationData or a drill's aggregate conflicts with its own
        singles)."""
        T, preset = self.T, self.preset
        epoch = compute_epoch_at_slot(slot, preset.SLOTS_PER_EPOCH)
        head_root = get_block_root_at_slot(state, slot, preset)
        epoch_start = epoch * preset.SLOTS_PER_EPOCH
        if epoch_start < state.slot:
            target_root = get_block_root_at_slot(state, epoch_start, preset)
        else:
            target_root = head_root
        if epoch == current_epoch(state, preset):
            source = state.current_justified_checkpoint
        else:
            source = state.previous_justified_checkpoint
        domain = get_domain(state, Domain.BEACON_ATTESTER, epoch, preset)
        out = []
        for index in range(get_committee_count_per_slot(state, epoch,
                                                        preset)):
            committee = get_beacon_committee(state, slot, index, preset)
            data = T.AttestationData(
                slot=slot, index=index, beacon_block_root=head_root,
                source=T.Checkpoint(epoch=source.epoch, root=source.root),
                target=T.Checkpoint(epoch=epoch, root=target_root))
            out.append((index, committee, data,
                        compute_signing_root(data, domain)))
        return out

    def attestations_for_slot(self, state, slot: int,
                              participation: float = 1.0) -> list:
        """One aggregate attestation per committee at ``slot``, signed by the
        (first ``participation`` fraction of the) committee.

        ``state`` must be advanced past ``slot`` so the block root exists.
        """
        T = self.T
        out = []
        for _index, committee, data, root in \
                self._committee_att_data(state, slot):
            n_sign = max(1, int(len(committee) * participation))
            bits = np.zeros(len(committee), dtype=bool)
            bits[:n_sign] = True
            if _real_signing():
                sig = B.aggregate_signatures([
                    interop_secret_key(int(v)).sign(root)
                    for v in committee[:n_sign]]).serialize()
            else:
                sig = _DUMMY_SIG
            out.append(T.Attestation(aggregation_bits=bits, data=data,
                                     signature=sig))
        return out

    def single_attestations_for_slot(self, state, slot: int,
                                     fraction: float = 1.0) -> list:
        """Unaggregated single-bit attestations — the subnet-gossip
        shape the sustained-load drill streams.  One attestation per
        committee member for the first ``fraction`` of each committee
        at ``slot``, each with exactly its own aggregation bit set and
        its own signature.  ``state`` must be advanced past ``slot``."""
        T = self.T
        out = []
        for _index, committee, data, root in \
                self._committee_att_data(state, slot):
            n_sign = max(1, int(len(committee) * fraction))
            for pos in range(n_sign):
                bits = np.zeros(len(committee), dtype=bool)
                bits[pos] = True
                if _real_signing():
                    sig = interop_secret_key(
                        int(committee[pos])).sign(root).serialize()
                else:
                    sig = _DUMMY_SIG
                out.append(T.Attestation(aggregation_bits=bits, data=data,
                                         signature=sig))
        return out

    # -- sync aggregate ------------------------------------------------------

    def sync_aggregate_for(self, state, block_slot: int) -> object:
        """Full-participation sync aggregate for a block at ``block_slot``
        (signs the previous slot's block root with the current committee)."""
        T, preset = self.T, self.preset
        prev_slot = max(block_slot, 1) - 1
        root = get_block_root_at_slot(state, prev_slot, preset)
        domain = get_domain(
            state, Domain.SYNC_COMMITTEE,
            compute_epoch_at_slot(prev_slot, preset.SLOTS_PER_EPOCH), preset)
        signing_root = compute_signing_root(root, domain)
        bits = np.ones(preset.SYNC_COMMITTEE_SIZE, dtype=bool)
        if _real_signing():
            cache = self._pubkey_to_index(state)
            sig = B.aggregate_signatures([
                interop_secret_key(cache[bytes(pk)]).sign(signing_root)
                for pk in state.current_sync_committee.pubkeys]).serialize()
        else:
            sig = _DUMMY_SIG
        return T.SyncAggregate(sync_committee_bits=bits,
                               sync_committee_signature=sig)

    def empty_sync_aggregate(self) -> object:
        return self.T.SyncAggregate(
            sync_committee_bits=np.zeros(self.preset.SYNC_COMMITTEE_SIZE,
                                         dtype=bool),
            sync_committee_signature=B.INFINITY_SIGNATURE)

    def _pubkey_to_index(self, state) -> dict:
        return {state.validators.col("pubkey")[i].tobytes(): i
                for i in range(len(state.validators))}

    # -- operations ----------------------------------------------------------

    def make_deposit(self, validator_index: int, amount: int | None = None,
                     valid_signature: bool = True):
        """Build a deposit (new validator keyed by ``validator_index``'s
        interop key) and register it in the harness deposit tree.  The next
        built block includes pending deposits and updates ``eth1_data``."""
        T, preset = self.T, self.preset
        amount = amount or preset.MAX_EFFECTIVE_BALANCE
        pk = interop_pubkey(validator_index)
        msg = T.DepositMessage(
            pubkey=pk,
            withdrawal_credentials=bls_withdrawal_credentials(pk),
            amount=amount)
        from ..state_transition.helpers import compute_domain
        domain = compute_domain(Domain.DEPOSIT, self.spec.genesis_fork_version)
        root = compute_signing_root(msg, domain)
        if valid_signature:
            sig = interop_secret_key(validator_index).sign(root).serialize()
        else:
            sig = _DUMMY_SIG if _real_signing() else B.INFINITY_SIGNATURE
        data = T.DepositData(
            pubkey=pk, withdrawal_credentials=msg.withdrawal_credentials,
            amount=amount, signature=sig)
        self.deposit_tree.push(data.tree_hash_root())
        self.pending_deposits.append(data)

    def make_exit(self, state, validator_index: int):
        T, preset = self.T, self.preset
        epoch = current_epoch(state, preset)
        exit_msg = T.VoluntaryExit(epoch=epoch,
                                   validator_index=validator_index)
        domain = get_domain(state, Domain.VOLUNTARY_EXIT, epoch, preset)
        sig = _sign(validator_index, compute_signing_root(exit_msg, domain))
        return T.SignedVoluntaryExit(message=exit_msg, signature=sig)

    def make_proposer_slashing(self, state, proposer_index: int):
        """Two distinct signed headers at the same slot."""
        T, preset = self.T, self.preset
        slot = state.slot
        domain = get_domain(state, Domain.BEACON_PROPOSER,
                            compute_epoch_at_slot(slot,
                                                  preset.SLOTS_PER_EPOCH),
                            preset)

        def header(graffiti: bytes):
            h = T.BeaconBlockHeader(
                slot=slot, proposer_index=proposer_index,
                parent_root=b"\x11" * 32, state_root=graffiti,
                body_root=b"\x22" * 32)
            return T.SignedBeaconBlockHeader(
                message=h,
                signature=_sign(proposer_index,
                                compute_signing_root(h, domain)))

        return T.ProposerSlashing(signed_header_1=header(b"\x01" * 32),
                                  signed_header_2=header(b"\x02" * 32))

    def make_attester_slashing(self, state, indices: list[int]):
        """Double vote by ``indices``: two attestations, same target epoch,
        different data."""
        T, preset = self.T, self.preset
        epoch = current_epoch(state, preset)
        domain = get_domain(state, Domain.BEACON_ATTESTER, epoch, preset)

        def indexed(root: bytes):
            data = T.AttestationData(
                slot=state.slot, index=0, beacon_block_root=root,
                source=T.Checkpoint(epoch=max(epoch, 1) - 1, root=b"\x00" * 32),
                target=T.Checkpoint(epoch=epoch, root=root))
            signing = compute_signing_root(data, domain)
            if _real_signing():
                sig = B.aggregate_signatures([
                    interop_secret_key(i).sign(signing)
                    for i in indices]).serialize()
            else:
                sig = _DUMMY_SIG
            return T.IndexedAttestation(
                attesting_indices=sorted(indices), data=data, signature=sig)

        return T.AttesterSlashing(attestation_1=indexed(b"\xaa" * 32),
                                  attestation_2=indexed(b"\xbb" * 32))

    def make_bls_to_execution_change(self, validator_index: int,
                                     address: bytes = b"\x0b" * 20):
        T = self.T
        change = T.BLSToExecutionChange(
            validator_index=validator_index,
            from_bls_pubkey=interop_pubkey(validator_index),
            to_execution_address=address)
        from ..state_transition.helpers import compute_domain
        domain = compute_domain(Domain.BLS_TO_EXECUTION_CHANGE,
                                self.spec.genesis_fork_version,
                                self.state.genesis_validators_root)
        sig = _sign(validator_index, compute_signing_root(change, domain))
        return T.SignedBLSToExecutionChange(message=change, signature=sig)

    # -- block building ------------------------------------------------------

    def build_block(self, slot: int | None = None, *,
                    attestations: list | None = None,
                    proposer_slashings: list = (),
                    attester_slashings: list = (),
                    voluntary_exits: list = (),
                    bls_to_execution_changes: list = (),
                    blob_kzg_commitments: list = (),
                    sync_participation: float = 1.0,
                    compute_state_root: bool = True,
                    pre_merge: bool = False,
                    graffiti: bytes = b"\x00" * 32):
        """Build a valid signed block on top of the current state.

        Default attestations: full participation for ``slot - 1``.  Returns
        the signed block without applying it.
        """
        T, preset, spec = self.T, self.preset, self.spec
        state = self.state
        if slot is None:
            slot = state.slot + 1
        fork = self.fork_at(slot)

        # Pending deposits: pre-set eth1_data on the live state BEFORE
        # advancing, so the builder and the verifier hash identical pre-states
        # (tests mutate eth1_data directly, like the reference harness
        # pre-loading its deposit cache).
        if self.pending_deposits:
            self.state.eth1_data = T.Eth1Data(
                deposit_root=self.deposit_tree.root(),
                deposit_count=self.deposit_tree.count,
                block_hash=b"\x42" * 32)

        advanced = state.copy()
        advanced = process_slots(advanced, slot, preset, spec, T)
        # A slashed proposer cannot propose (process_block_header rejects);
        # on mainnet that slot simply stays empty — skip forward.
        while bool(advanced.validators.col("slashed")[
                get_beacon_proposer_index(advanced, preset)]):
            slot += 1
            advanced = process_slots(advanced, slot, preset, spec, T)
            fork = self.fork_at(slot)

        if attestations is None:
            if slot > 0 and state.slot <= slot - 1:
                attestations = self.attestations_for_slot(advanced, slot - 1)
            else:
                attestations = []

        proposer = get_beacon_proposer_index(advanced, preset)
        epoch = compute_epoch_at_slot(slot, preset.SLOTS_PER_EPOCH)

        # Randao reveal signs the epoch.
        from ..ssz import uint64 as _u64
        randao_domain = get_domain(advanced, Domain.RANDAO, epoch, preset)
        reveal = _sign(proposer, compute_signing_root(
            _u64.hash_tree_root(epoch), randao_domain))

        # Deposits: include everything pending (eth1_data pre-set above).
        deposits = []
        eth1_data = advanced.eth1_data
        if self.pending_deposits:
            start = advanced.eth1_deposit_index
            for i, data in enumerate(self.pending_deposits):
                deposits.append(T.Deposit(
                    proof=self.deposit_tree.proof(start + i), data=data))
            self.pending_deposits = []

        body_kw = dict(
            randao_reveal=reveal,
            eth1_data=eth1_data,
            graffiti=graffiti,
            proposer_slashings=list(proposer_slashings),
            attester_slashings=list(attester_slashings),
            attestations=list(attestations),
            deposits=deposits,
            voluntary_exits=list(voluntary_exits),
        )
        if fork >= ForkName.ALTAIR:
            if sync_participation > 0:
                body_kw["sync_aggregate"] = self.sync_aggregate_for(
                    advanced, slot)
            else:
                body_kw["sync_aggregate"] = self.empty_sync_aggregate()
        if fork >= ForkName.BELLATRIX:
            # ``pre_merge``: default payload — valid only while the merge
            # transition is incomplete (the is_execution_enabled gate).
            body_kw["execution_payload"] = (
                T.payload_cls(fork)() if pre_merge
                else self._execution_payload(advanced, fork))
        if fork >= ForkName.CAPELLA:
            body_kw["bls_to_execution_changes"] = list(
                bls_to_execution_changes)
        if fork >= ForkName.DENEB:
            body_kw["blob_kzg_commitments"] = [
                bytes(c) for c in blob_kzg_commitments]

        body = T.body_cls(fork)(**body_kw)
        block = T.block_cls(fork)(
            slot=slot, proposer_index=proposer,
            parent_root=advanced.latest_block_header.tree_hash_root(),
            state_root=b"\x00" * 32, body=body)

        # State root: apply without verification on a scratch copy.
        # ``compute_state_root=False`` for deliberately-invalid blocks whose
        # application would fail here (rejection tests).
        if compute_state_root:
            from ..state_transition.per_block import process_block
            scratch = advanced.copy()
            process_block(scratch, T.signed_block_cls(fork)(
                message=block, signature=_DUMMY_SIG), fork, preset, spec, T,
                strategy=SignatureStrategy.NO_VERIFICATION)
            block.state_root = scratch.tree_hash_root()

        proposal_domain = get_domain(advanced, Domain.BEACON_PROPOSER, epoch,
                                     preset)
        sig = _sign(proposer, compute_signing_root(block, proposal_domain))
        return T.signed_block_cls(fork)(message=block, signature=sig)

    def _execution_payload(self, advanced, fork: ForkName):
        """A linking payload over the mock EL (``MockExecutionLayer`` role)."""
        T, preset, spec = self.T, self.preset, self.spec
        import hashlib
        parent_hash = advanced.latest_execution_payload_header.block_hash
        kw = dict(
            parent_hash=parent_hash,
            prev_randao=get_randao_mix(
                advanced, current_epoch(advanced, preset), preset),
            block_number=advanced.latest_execution_payload_header.block_number
            + 1,
            gas_limit=30_000_000,
            timestamp=advanced.genesis_time
            + advanced.slot * spec.seconds_per_slot,
            block_hash=hashlib.sha256(
                parent_hash + int(advanced.slot).to_bytes(8, "little")
            ).digest(),
        )
        if fork >= ForkName.CAPELLA:
            kw["withdrawals"] = [
                T.Withdrawal(index=w[0], validator_index=w[1],
                             address=w[2], amount=w[3])
                for w in get_expected_withdrawals(advanced, preset)]
        return T.payload_cls(fork)(**kw)

    # -- chain driving -------------------------------------------------------

    def apply_block(self, signed_block,
                    strategy: SignatureStrategy = SignatureStrategy.VERIFY_BULK,
                    validate_state_root: bool = True):
        self.state = state_transition(
            self.state, signed_block, self.preset, self.spec, self.T,
            strategy=strategy, validate_state_root=validate_state_root)
        self.blocks.append(signed_block)
        return self.state

    def extend_chain(self, n_blocks: int,
                     strategy: SignatureStrategy = SignatureStrategy.VERIFY_BULK,
                     **build_kw):
        out = []
        for _ in range(n_blocks):
            sb = self.build_block(**build_kw)
            self.apply_block(sb, strategy=strategy)
            out.append(sb)
        return out
