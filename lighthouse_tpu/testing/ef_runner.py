"""EF consensus-spec-tests conformance runner.

Counterpart of the reference's ``testing/ef_tests`` crate: a handler walk
over the standard spec-tests directory layout

    <root>/tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>/<files>

(``/root/reference/testing/ef_tests/src/handler.rs:10-46``), with per-case
modules for the runners this framework implements
(``handler.rs``'s ``ssz_static``, ``shuffling``, ``sanity``, ``operations``,
``epoch_processing``, and the 8 BLS handlers under ``src/cases/bls_*.rs``).

Two properties are enforced exactly like the reference's CI:

- **No silent skips.**  Every file under the tree must be consumed by some
  handler (``check_all_files_accessed.py`` role,
  ``testing/ef_tests/Makefile:130``); an unknown runner/handler or an
  untouched file fails the run.
- **Backend matrix.**  The whole tree can run under each registered BLS
  backend (``Makefile:125-129`` runs blst/milagro/fake_crypto); here
  {python, fake} on CPU plus the tpu backend when a chip is attached.

Vector provenance: this environment has no network access, so
:mod:`.ef_gen` generates vectors **from this framework's own executable
spec** into the same layout (as VERDICT r3 prescribed for the offline
case).  They are regression/cross-backend-consistency vectors, not
external conformance — drop a real ``consensus-spec-tests`` tarball at the
same root and the runner consumes it unchanged (``.ssz_snappy`` files are
supported when the ``snappy`` module is importable).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, Tuple

import numpy as np
import yaml

from ..crypto import bls as B
from ..ssz import Container, List, uint64
from ..state_transition import per_block as PB
from ..state_transition import per_epoch as PE
from ..state_transition import signature_sets as sigs
from ..state_transition import per_epoch_phase0 as P0
from ..state_transition.per_slot import process_slots
from ..state_transition.shuffle import shuffle_list
from ..types.chain_spec import ChainSpec, ForkName
from ..types.factory import spec_types
from ..types.presets import MAINNET, MINIMAL

FORKS = {f.value: f for f in ForkName}


class EfTestFailure(AssertionError):
    pass


@dataclass
class CaseCtx:
    """Everything a case handler needs to resolve types and run spec fns."""
    config: str
    fork: ForkName
    case_dir: str
    tracker: "FileTracker"

    def __post_init__(self):
        self.preset = MINIMAL if self.config == "minimal" else MAINNET
        self.spec = (ChainSpec.minimal() if self.config == "minimal"
                     else ChainSpec.mainnet()).with_forks_at_genesis(self.fork)
        self.T = spec_types(self.preset)

    # -- file loading (every read is tracked) -------------------------------

    def _read(self, name: str) -> bytes | None:
        p = os.path.join(self.case_dir, name)
        for cand, decomp in ((p, False), (p + "_snappy", True)):
            if os.path.exists(cand):
                self.tracker.touch(cand)
                data = open(cand, "rb").read()
                if decomp:
                    import snappy
                    data = snappy.decompress(data)
                return data
        return None

    def yaml(self, name: str):
        data = self._read(name)
        return None if data is None else yaml.safe_load(data)

    def has(self, name: str) -> bool:
        """File present in either plain or snappy form (no tracking)."""
        p = os.path.join(self.case_dir, name)
        return os.path.exists(p) or os.path.exists(p + "_snappy")

    def ssz(self, name: str) -> bytes | None:
        return self._read(name)

    def state(self, name: str):
        data = self.ssz(name + ".ssz")
        if data is None:
            return None
        return self.T.state_cls(self.fork).deserialize(data)

    def expect_post(self, got_state, name: str = "post") -> None:
        post = self.state(name)
        if post is None:
            raise EfTestFailure(f"{self.case_dir}: missing {name}.ssz")
        g = type(got_state).serialize(got_state)
        w = type(post).serialize(post)
        if g != w:
            raise EfTestFailure(
                f"{self.case_dir}: post-state mismatch "
                f"(root {type(got_state).hash_tree_root(got_state).hex()} "
                f"vs {type(post).hash_tree_root(post).hex()})")


class FileTracker:
    def __init__(self):
        self.accessed: set[str] = set()

    def touch(self, path: str) -> None:
        self.accessed.add(os.path.realpath(path))

    def unaccessed(self, root: str) -> list[str]:
        out = []
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                p = os.path.realpath(os.path.join(dirpath, f))
                if p not in self.accessed:
                    out.append(p)
        return sorted(out)


# ---------------------------------------------------------------------------
# Case handlers.  Each takes (ctx, handler_name) and raises on failure.
# ---------------------------------------------------------------------------


def _case_ssz_static(ctx: CaseCtx, handler: str) -> None:
    roots = ctx.yaml("roots.yaml")
    serialized = ctx.ssz("serialized.ssz")
    if roots is None or serialized is None:
        raise EfTestFailure(f"{ctx.case_dir}: incomplete ssz_static case")
    cls = _resolve_type(ctx, handler)
    value = cls.deserialize(serialized)
    if cls.serialize(value) != serialized:
        raise EfTestFailure(f"{ctx.case_dir}: reserialization mismatch")
    got = cls.hash_tree_root(value)
    want = bytes.fromhex(roots["root"].removeprefix("0x"))
    if got != want:
        raise EfTestFailure(
            f"{ctx.case_dir}: root {got.hex()} != {want.hex()}")


def _resolve_type(ctx: CaseCtx, name: str):
    T = ctx.T
    fork = ctx.fork
    table = {
        "BeaconState": lambda: T.state_cls(fork),
        "BeaconBlock": lambda: T.block_cls(fork),
        "SignedBeaconBlock": lambda: T.signed_block_cls(fork),
        "BeaconBlockBody": lambda: T.body_cls(fork),
    }
    if name in table:
        return table[name]()
    cls = getattr(T, name, None)
    if cls is None:
        raise EfTestFailure(f"unknown ssz_static type {name}")
    return cls


def _case_shuffling(ctx: CaseCtx, handler: str) -> None:
    m = ctx.yaml("mapping.yaml")
    seed = bytes.fromhex(m["seed"].removeprefix("0x"))
    count = int(m["count"])
    want = [int(x) for x in m["mapping"]]
    got = list(shuffle_list(np.arange(count, dtype=np.uint64), seed,
                            ctx.preset.SHUFFLE_ROUND_COUNT))
    if got != want:
        raise EfTestFailure(f"{ctx.case_dir}: shuffle mismatch")


def _case_sanity_slots(ctx: CaseCtx, handler: str) -> None:
    pre = ctx.state("pre")
    n_slots = int(ctx.yaml("slots.yaml"))
    got = process_slots(pre, int(pre.slot) + n_slots, ctx.preset, ctx.spec,
                        ctx.T)
    ctx.expect_post(got)


def _case_sanity_blocks(ctx: CaseCtx, handler: str) -> None:
    meta = ctx.yaml("meta.yaml") or {}
    n = int(meta.get("blocks_count", 1))
    state = ctx.state("pre")
    try:
        for i in range(n):
            raw = ctx.ssz(f"blocks_{i}.ssz")
            sb = ctx.T.signed_block_cls(ctx.fork).deserialize(raw)
            from ..state_transition.per_slot import state_transition
            state = state_transition(state, sb, ctx.preset, ctx.spec, ctx.T,
                                     strategy=PB.SignatureStrategy.VERIFY_BULK)
    except Exception as e:
        if ctx.state("post") is None:
            return  # expected-invalid case
        raise EfTestFailure(f"{ctx.case_dir}: unexpected failure: {e}") from e
    if ctx.has("post.ssz"):
        ctx.expect_post(state)
    else:
        raise EfTestFailure(f"{ctx.case_dir}: expected failure, got success")


_OPERATION_APPLY: Dict[str, Callable] = {}


def _op(name: str, file_name: str, fn):
    _OPERATION_APPLY[name] = (file_name, fn)


def _init_operations():
    def att(ctx, state, data):
        a = ctx.T.Attestation.deserialize(data)
        acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
        PB.process_attestation(state, a, ctx.fork, ctx.preset, ctx.spec,
                               ctx.T, acc, sigs.PubkeyCache())
        acc.finish()

    def att_slashing(ctx, state, data):
        s = ctx.T.AttesterSlashing.deserialize(data)
        acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
        PB.process_attester_slashing(state, s, ctx.fork, ctx.preset,
                                     ctx.spec, acc, sigs.PubkeyCache())
        acc.finish()

    def prop_slashing(ctx, state, data):
        s = ctx.T.ProposerSlashing.deserialize(data)
        acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
        PB.process_proposer_slashing(state, s, ctx.fork, ctx.preset,
                                     ctx.spec, acc, sigs.PubkeyCache())
        acc.finish()

    def exit_(ctx, state, data):
        e = ctx.T.SignedVoluntaryExit.deserialize(data)
        acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
        PB.process_voluntary_exit(state, e, ctx.fork, ctx.preset, ctx.spec,
                                  acc, sigs.PubkeyCache())
        acc.finish()

    def deposit(ctx, state, data):
        d = ctx.T.Deposit.deserialize(data)
        PB.process_deposit(state, d, ctx.preset, ctx.spec, ctx.T)

    def sync_agg(ctx, state, data):
        a = ctx.T.SyncAggregate.deserialize(data)
        acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
        PB.process_sync_aggregate(state, a, ctx.preset, ctx.spec, ctx.T, acc)
        acc.finish()

    def bls_change(ctx, state, data):
        c = ctx.T.SignedBLSToExecutionChange.deserialize(data)
        acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
        PB.process_bls_to_execution_change(state, c, ctx.spec, acc)
        acc.finish()

    def block_header(ctx, state, data):
        b = ctx.T.block_cls(ctx.fork).deserialize(data)
        PB.process_block_header(state, b, ctx.preset, ctx.T)

    def withdrawals(ctx, state, data):
        p = ctx.T.payload_cls(ctx.fork).deserialize(data)
        PB.process_withdrawals(state, p, ctx.preset, ctx.T)

    _op("attestation", "attestation.ssz", att)
    _op("attester_slashing", "attester_slashing.ssz", att_slashing)
    _op("proposer_slashing", "proposer_slashing.ssz", prop_slashing)
    _op("voluntary_exit", "voluntary_exit.ssz", exit_)
    _op("deposit", "deposit.ssz", deposit)
    _op("sync_aggregate", "sync_aggregate.ssz", sync_agg)
    _op("bls_to_execution_change", "address_change.ssz", bls_change)
    _op("block_header", "block.ssz", block_header)
    _op("withdrawals", "execution_payload.ssz", withdrawals)


_init_operations()


def _case_operations(ctx: CaseCtx, handler: str) -> None:
    if handler not in _OPERATION_APPLY:
        raise EfTestFailure(f"unknown operations handler {handler}")
    file_name, fn = _OPERATION_APPLY[handler]
    state = ctx.state("pre")
    data = ctx.ssz(file_name)
    try:
        fn(ctx, state, data)
    except Exception as e:
        if ctx.state("post") is None:
            return
        raise EfTestFailure(f"{ctx.case_dir}: unexpected failure: {e}") from e
    if ctx.has("post.ssz"):
        ctx.expect_post(state)
    else:
        raise EfTestFailure(f"{ctx.case_dir}: expected failure, got success")


def _epoch_steps(fork: ForkName, preset, spec, T) -> Dict[str, Callable]:
    if fork == ForkName.PHASE0:
        return {
            "justification_and_finalization": lambda s:
                P0.process_justification_and_finalization_phase0(
                    s, preset, T, PE.EpochSummary()),
            "rewards_and_penalties": lambda s:
                P0.process_rewards_and_penalties_phase0(
                    s, preset, spec, PE.EpochSummary()),
            "registry_updates": lambda s: PE.process_registry_updates(
                s, preset, spec, PE.EpochSummary()),
            "slashings": lambda s: PE.process_slashings(s, fork, preset),
            "eth1_data_reset": lambda s: PE.process_eth1_data_reset(
                s, preset),
            "effective_balance_updates": lambda s:
                PE.process_effective_balance_updates(s, preset),
            "slashings_reset": lambda s: PE.process_slashings_reset(
                s, preset),
            "randao_mixes_reset": lambda s: PE.process_randao_mixes_reset(
                s, preset),
            "historical_roots_update": lambda s: PE.process_historical_update(
                s, fork, preset, T),
            "participation_record_updates": lambda s:
                P0.process_participation_record_updates(s),
        }
    steps = {
        "justification_and_finalization": lambda s:
            PE.process_justification_and_finalization(
                s, preset, T, PE.EpochSummary()),
        "inactivity_updates": lambda s: PE.process_inactivity_updates(
            s, preset, spec),
        "rewards_and_penalties": lambda s: PE.process_rewards_and_penalties(
            s, fork, preset, spec, PE.EpochSummary()),
        "registry_updates": lambda s: PE.process_registry_updates(
            s, preset, spec, PE.EpochSummary()),
        "slashings": lambda s: PE.process_slashings(s, fork, preset),
        "eth1_data_reset": lambda s: PE.process_eth1_data_reset(s, preset),
        "effective_balance_updates": lambda s:
            PE.process_effective_balance_updates(s, preset),
        "slashings_reset": lambda s: PE.process_slashings_reset(s, preset),
        "randao_mixes_reset": lambda s: PE.process_randao_mixes_reset(
            s, preset),
        "participation_flag_updates": lambda s:
            PE.process_participation_flag_updates(s),
        "sync_committee_updates": lambda s:
            PE.process_sync_committee_updates(s, preset, T),
    }
    name = ("historical_roots_update" if fork < ForkName.CAPELLA
            else "historical_summaries_update")
    steps[name] = lambda s: PE.process_historical_update(s, fork, preset, T)
    return steps


def _case_epoch_processing(ctx: CaseCtx, handler: str) -> None:
    steps = _epoch_steps(ctx.fork, ctx.preset, ctx.spec, ctx.T)
    if handler not in steps:
        raise EfTestFailure(f"unknown epoch_processing handler {handler}")
    state = ctx.state("pre")
    steps[handler](state)
    ctx.expect_post(state)


# -- BLS handlers (general config) ------------------------------------------


def _bls_in(v: str) -> bytes:
    return bytes.fromhex(v.removeprefix("0x"))


def _case_bls(ctx: CaseCtx, handler: str) -> None:
    data = ctx.yaml("data.yaml")
    inp, out = data["input"], data["output"]

    def pk(v):
        return B.PublicKey.deserialize(_bls_in(v))

    def sig(v):
        return B.Signature.deserialize(_bls_in(v))

    try:
        if handler == "sign":
            sk = B.SecretKey(int.from_bytes(_bls_in(inp["privkey"]), "big"))
            got = "0x" + sk.sign(_bls_in(inp["message"])).serialize().hex()
        elif handler == "verify":
            got = sig(inp["signature"]).verify(pk(inp["pubkey"]),
                                               _bls_in(inp["message"]))
        elif handler == "aggregate":
            sigs_ = [sig(s) for s in inp]
            got = "0x" + B.aggregate_signatures(sigs_).serialize().hex()
        elif handler == "aggregate_verify":
            got = sig(inp["signature"]).aggregate_verify(
                [pk(p) for p in inp["pubkeys"]],
                [_bls_in(m) for m in inp["messages"]])
        elif handler == "fast_aggregate_verify":
            got = sig(inp["signature"]).fast_aggregate_verify(
                [pk(p) for p in inp["pubkeys"]], _bls_in(inp["message"]))
        elif handler == "eth_aggregate_pubkeys":
            from ..crypto import curve as C
            point = B.aggregate_public_keys([pk(p) for p in inp])
            got = "0x" + C.g1_compress(point).hex()
        elif handler == "batch_verify":
            sets = [B.SignatureSet(signature=sig(s), signing_keys=[pk(p)],
                                   message=_bls_in(m))
                    for p, m, s in zip(inp["pubkeys"], inp["messages"],
                                       inp["signatures"])]
            got = B.verify_signature_sets(sets)
        else:
            raise EfTestFailure(f"unknown bls handler {handler}")
    except EfTestFailure:
        raise
    except Exception:
        got = None  # deserialization failures ⇒ expected output null/false
        if out in (False, None):
            return
        raise
    if got != out:
        raise EfTestFailure(f"{ctx.case_dir}: bls {handler} {got!r} != "
                            f"{out!r}")


# -- transition (fork boundary) runner --------------------------------------

_PRE_FORK = {ForkName.ALTAIR: ForkName.PHASE0,
             ForkName.BELLATRIX: ForkName.ALTAIR,
             ForkName.CAPELLA: ForkName.BELLATRIX,
             ForkName.DENEB: ForkName.CAPELLA}
_FORK_EPOCH_ATTR = {ForkName.ALTAIR: "altair_fork_epoch",
                    ForkName.BELLATRIX: "bellatrix_fork_epoch",
                    ForkName.CAPELLA: "capella_fork_epoch",
                    ForkName.DENEB: "deneb_fork_epoch"}


def _case_transition(ctx: CaseCtx, handler: str) -> None:
    """Fork-boundary transition (`testing/ef_tests/src/cases/
    transition.rs`): the case's fork DIR names the POST fork; blocks span
    the boundary, with `meta.yaml`'s `fork_block` the index of the last
    pre-fork block."""
    from dataclasses import replace

    from ..state_transition.per_slot import state_transition

    meta = ctx.yaml("meta.yaml")
    post_fork = FORKS[meta["post_fork"]]
    if post_fork != ctx.fork:
        raise EfTestFailure(
            f"{ctx.case_dir}: post_fork {meta['post_fork']} does not match "
            f"the case's fork dir")
    pre_fork = _PRE_FORK[post_fork]
    fork_epoch = int(meta["fork_epoch"])
    spec = replace(
        (ChainSpec.minimal() if ctx.config == "minimal"
         else ChainSpec.mainnet()).with_forks_at_genesis(pre_fork),
        **{_FORK_EPOCH_ATTR[post_fork]: fork_epoch})
    fork_block = int(meta.get("fork_block", -1))
    state = ctx.T.state_cls(pre_fork).deserialize(ctx.ssz("pre.ssz"))
    for i in range(int(meta["blocks_count"])):
        raw = ctx.ssz(f"blocks_{i}.ssz")
        blk_fork = pre_fork if i <= fork_block else post_fork
        sb = ctx.T.signed_block_cls(blk_fork).deserialize(raw)
        state = state_transition(state, sb, ctx.preset, spec, ctx.T,
                                 strategy=PB.SignatureStrategy.VERIFY_BULK)
    ctx.expect_post(state)


# -- fork_choice runner ------------------------------------------------------


class _FcIndexed:
    def __init__(self, data, indices):
        self.data = data
        self.attesting_indices = indices


def _run_fork_choice_steps(ctx: CaseCtx, steps, anchor_block, anchor_state,
                           device: bool) -> list:
    """Replay one step stream against a ForkChoice instance (host oracle
    when ``device`` is False, columnar device path when True); returns the
    head sequence observed at the check steps."""
    from ..beacon_chain.attestation_verification import attesting_indices
    from ..fork_choice import ForkChoice
    from ..state_transition.per_slot import process_slots, state_transition

    anchor_root = anchor_block.tree_hash_root()
    fc = ForkChoice(ctx.preset, ctx.spec, genesis_root=anchor_root,
                    genesis_state=anchor_state.copy(), device=device)
    states = {anchor_root: anchor_state}
    spt = ctx.spec.seconds_per_slot
    genesis_time = int(anchor_state.genesis_time)
    heads = []
    for step in steps:
        if "tick" in step:
            fc.on_tick((int(step["tick"]) - genesis_time) // spt)
        elif "block" in step:
            raw = ctx.ssz(step["block"] + ".ssz")
            sb = ctx.T.signed_block_cls(ctx.fork).deserialize(raw)
            pre = states[bytes(sb.message.parent_root)]
            post = state_transition(
                pre.copy(), sb, ctx.preset, ctx.spec, ctx.T,
                strategy=PB.SignatureStrategy.VERIFY_BULK)
            root = sb.message.tree_hash_root()
            states[root] = post
            if int(sb.message.slot) > fc.current_slot:
                fc.on_tick(int(sb.message.slot))
            fc.on_block(sb, root, post)
        elif "attestation" in step:
            raw = ctx.ssz(step["attestation"] + ".ssz")
            att = ctx.T.Attestation.deserialize(raw)
            st = states[bytes(att.data.beacon_block_root)]
            if int(st.slot) < int(att.data.slot):
                st = process_slots(st.copy(), int(att.data.slot),
                                   ctx.preset, ctx.spec, ctx.T)
            idx, _c = attesting_indices(st, att, ctx.preset)
            fc.on_attestation(_FcIndexed(att.data, idx.tolist()))
        elif "attester_slashing" in step:
            raw = ctx.ssz(step["attester_slashing"] + ".ssz")
            slashing = ctx.T.AttesterSlashing.deserialize(raw)
            fc.on_attester_slashing(slashing)
        elif "payload_status" in step:
            info = step["payload_status"]
            root = bytes.fromhex(info["block_root"].removeprefix("0x"))
            if info["status"] == "INVALID":
                fc.on_invalid_execution_payload(root)
            else:
                fc.on_valid_execution_payload(root)
        elif "checks" in step:
            head = fc.get_head()
            heads.append(head)
            c = step["checks"]
            path = "device" if device else "host"
            if "head" in c:
                want = bytes.fromhex(c["head"]["root"].removeprefix("0x"))
                if head != want:
                    raise EfTestFailure(
                        f"{ctx.case_dir} [{path}]: head {head.hex()} != "
                        f"{want.hex()}")
                if fc.block_slot(head) != int(c["head"]["slot"]):
                    raise EfTestFailure(
                        f"{ctx.case_dir} [{path}]: head slot mismatch")
            for key, got in (("justified_checkpoint",
                              fc.justified_checkpoint),
                             ("finalized_checkpoint",
                              fc.finalized_checkpoint)):
                if key in c:
                    want = (int(c[key]["epoch"]), bytes.fromhex(
                        c[key]["root"].removeprefix("0x")))
                    if got != want:
                        raise EfTestFailure(
                            f"{ctx.case_dir} [{path}]: {key} {got} != "
                            f"{want}")
            if "proposer_boost_root" in c:
                want = bytes.fromhex(
                    c["proposer_boost_root"].removeprefix("0x"))
                if fc.proposer_boost_root != want:
                    raise EfTestFailure(
                        f"{ctx.case_dir} [{path}]: boost root mismatch")
        else:
            raise EfTestFailure(f"{ctx.case_dir}: unknown step {step}")
    return heads


def _case_fork_choice(ctx: CaseCtx, handler: str) -> None:
    """EF fork_choice case: replay the step stream against BOTH the host
    proto-array and the columnar device path; every checks step must pass
    on each, and the two head sequences must be identical."""
    anchor_state = ctx.state("anchor_state")
    raw = ctx.ssz("anchor_block.ssz")
    if anchor_state is None or raw is None:
        raise EfTestFailure(f"{ctx.case_dir}: incomplete fork_choice case")
    anchor_block = ctx.T.block_cls(ctx.fork).deserialize(raw)
    steps = ctx.yaml("steps.yaml")
    host_heads = _run_fork_choice_steps(ctx, steps, anchor_block,
                                        anchor_state, device=False)
    dev_heads = _run_fork_choice_steps(ctx, steps, anchor_block,
                                       anchor_state, device=True)
    if host_heads != dev_heads:
        raise EfTestFailure(
            f"{ctx.case_dir}: host/device head divergence "
            f"({[h.hex()[:8] for h in host_heads]} vs "
            f"{[h.hex()[:8] for h in dev_heads]})")


# -- rewards runner ----------------------------------------------------------

class Deltas(Container):
    """EF rewards-runner component deltas (`cases/rewards.rs` Deltas)."""
    rewards: List(uint64, 1 << 40)
    penalties: List(uint64, 1 << 40)


def _case_rewards(ctx: CaseCtx, handler: str) -> None:
    """EF rewards runner (`cases/rewards.rs`): per-component attestation
    deltas compared against the committed Deltas SSZ files."""
    from ..state_transition.per_epoch import flag_deltas
    from ..state_transition.per_epoch_phase0 import attestation_deltas_phase0

    state = ctx.state("pre")
    if ctx.fork == ForkName.PHASE0:
        deltas = attestation_deltas_phase0(state, ctx.preset, ctx.spec)
        components = ("source", "target", "head", "inclusion_delay",
                      "inactivity_penalty")
    else:
        deltas = flag_deltas(state, ctx.fork, ctx.preset, ctx.spec)
        components = ("source", "target", "head", "inactivity_penalty")
    for name in components:
        raw = ctx.ssz(f"{name}_deltas.ssz")
        if raw is None:
            raise EfTestFailure(f"{ctx.case_dir}: missing {name}_deltas.ssz")
        want = Deltas.deserialize(raw)
        r, p = deltas[name]
        got_r = [int(x) for x in r]
        got_p = [int(x) for x in p]
        if got_r != [int(x) for x in want.rewards] or \
                got_p != [int(x) for x in want.penalties]:
            raise EfTestFailure(
                f"{ctx.case_dir}: {name} deltas mismatch")


_RUNNERS: Dict[str, Callable] = {
    "ssz_static": _case_ssz_static,
    "shuffling": _case_shuffling,
    "sanity": None,  # dispatched by handler below
    "operations": _case_operations,
    "epoch_processing": _case_epoch_processing,
    "bls": _case_bls,
    "transition": _case_transition,
    "rewards": _case_rewards,
    "fork_choice": _case_fork_choice,
}


def _dispatch(runner: str, handler: str) -> Callable:
    if runner == "sanity":
        if handler == "slots":
            return _case_sanity_slots
        if handler == "blocks":
            return _case_sanity_blocks
        raise EfTestFailure(f"unknown sanity handler {handler}")
    fn = _RUNNERS.get(runner)
    if fn is None:
        raise EfTestFailure(f"unknown runner {runner}")
    return fn


@dataclass
class Report:
    passed: Dict[Tuple[str, str], int] = field(default_factory=dict)
    failures: list = field(default_factory=list)

    def ok(self) -> bool:
        return not self.failures

    def summary(self) -> str:
        lines = [f"  {r}/{h}: {n} passed"
                 for (r, h), n in sorted(self.passed.items())]
        for f in self.failures:
            lines.append(f"  FAIL {f}")
        return "\n".join(lines)


def run_tree(root: str, fail_fast: bool = False) -> Report:
    """Walk ``<root>/tests/...`` and run every case.  Raises if any file is
    left unconsumed (the no-silent-skips rule)."""
    tests_root = os.path.join(root, "tests")
    tracker = FileTracker()
    report = Report()
    for config in sorted(os.listdir(tests_root)):
        cdir = os.path.join(tests_root, config)
        for fork_s in sorted(os.listdir(cdir)):
            fork = FORKS.get(fork_s)
            if fork is None:
                raise EfTestFailure(f"unknown fork dir {fork_s}")
            fdir = os.path.join(cdir, fork_s)
            for runner in sorted(os.listdir(fdir)):
                rdir = os.path.join(fdir, runner)
                for handler in sorted(os.listdir(rdir)):
                    hdir = os.path.join(rdir, handler)
                    fn = _dispatch(runner, handler)
                    for suite in sorted(os.listdir(hdir)):
                        sdir = os.path.join(hdir, suite)
                        for case in sorted(os.listdir(sdir)):
                            ctx = CaseCtx(config, fork,
                                          os.path.join(sdir, case), tracker)
                            try:
                                fn(ctx, handler)
                                key = (runner, handler)
                                report.passed[key] = report.passed.get(
                                    key, 0) + 1
                            except Exception as e:
                                report.failures.append(
                                    f"{config}/{fork_s}/{runner}/{handler}"
                                    f"/{suite}/{case}: {e}")
                                if fail_fast:
                                    raise
    missed = tracker.unaccessed(tests_root)
    if missed:
        report.failures.append(
            f"{len(missed)} files never accessed, e.g. {missed[:3]}")
    return report
