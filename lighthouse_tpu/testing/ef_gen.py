"""Generate consensus-spec-test vectors from this framework's own
executable spec, in the standard EF directory layout.

This environment has no network access, so the official
``ethereum/consensus-spec-tests`` tarballs cannot be fetched; as VERDICT r3
prescribed for that case, these vectors are produced by OUR state
transition + crypto (python backend) and serve as (a) regression pins,
(b) cross-backend consistency checks (fake / tpu backends must agree), and
(c) proof the runner infrastructure consumes the real layout — a genuine
tarball dropped at the same root runs through the identical walker.

Layout written (mirrors ``handler.rs:10-46``):

    <root>/tests/minimal/<fork>/{sanity,operations,epoch_processing,
                                 shuffling,ssz_static}/...
    <root>/tests/general/phase0/bls/<handler>/small/<case>/data.yaml
"""

from __future__ import annotations

import os

import numpy as np
import yaml

from ..crypto import bls as B
from ..state_transition import per_block as PB
from ..state_transition import signature_sets as sigs
from ..state_transition.shuffle import shuffle_list
from ..types.chain_spec import ChainSpec, ForkName
from ..types.presets import MAINNET, MINIMAL
from .ef_runner import _FcIndexed, _epoch_steps
from .harness import StateHarness

GEN_FORKS = (ForkName.PHASE0, ForkName.ALTAIR, ForkName.BELLATRIX,
             ForkName.CAPELLA, ForkName.DENEB)


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def _write_yaml(path: str, obj) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(obj, f)


def _case(root: str, config: str, fork: ForkName, runner: str, handler: str,
          suite: str, case: str) -> str:
    return os.path.join(root, "tests", config, fork.value, runner, handler,
                        suite, case)


def _dump_state(d: str, name: str, state) -> None:
    _write(os.path.join(d, name + ".ssz"), type(state).serialize(state))


def _harness(fork: ForkName) -> StateHarness:
    return StateHarness(n_validators=16, fork=fork, preset=MINIMAL,
                        spec=ChainSpec.minimal().with_forks_at_genesis(fork))


def _gen_sanity(root: str, fork: ForkName) -> None:
    h = _harness(fork)
    h.extend_chain(3)
    spe = h.preset.SLOTS_PER_EPOCH

    # slots: single slot + across an epoch boundary
    for case, n_slots in (("slots_1", 1), ("over_epoch", spe + 1)):
        d = _case(root, "minimal", fork, "sanity", "slots", "pyspec_tests",
                  case)
        pre = h.state.copy()
        _dump_state(d, "pre", pre)
        from ..state_transition.per_slot import process_slots
        post = process_slots(pre.copy(), int(pre.slot) + n_slots, h.preset,
                             h.spec, h.T)
        _write_yaml(os.path.join(d, "slots.yaml"), n_slots)
        _dump_state(d, "post", post)

    # blocks: valid single block; invalid (wrong state root) without post
    d = _case(root, "minimal", fork, "sanity", "blocks", "pyspec_tests",
              "valid_block")
    pre = h.state.copy()
    _dump_state(d, "pre", pre)
    sb = h.build_block()
    _write(os.path.join(d, "blocks_0.ssz"), type(sb).serialize(sb))
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})
    from ..state_transition.per_slot import state_transition
    post = state_transition(pre.copy(), sb, h.preset, h.spec, h.T,
                            strategy=PB.SignatureStrategy.VERIFY_BULK)
    _dump_state(d, "post", post)

    d = _case(root, "minimal", fork, "sanity", "blocks", "pyspec_tests",
              "invalid_state_root")
    _dump_state(d, "pre", h.state)
    bad = type(sb).deserialize(type(sb).serialize(sb))
    bad.message.state_root = b"\xba" * 32
    _write(os.path.join(d, "blocks_0.ssz"), type(bad).serialize(bad))
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})


def _gen_operations(root: str, fork: ForkName) -> None:
    h = _harness(fork)
    h.extend_chain(3)
    state = h.state
    T = h.T

    def emit(handler: str, file_name: str, op_cls, op, apply_fn,
             case: str = "ok", expect_valid: bool = True) -> None:
        d = _case(root, "minimal", fork, "operations", handler,
                  "pyspec_tests", case)
        pre = state.copy()
        _dump_state(d, "pre", pre)
        _write(os.path.join(d, file_name), op_cls.serialize(op))
        post = pre.copy()
        try:
            apply_fn(post, op)
        except Exception:
            if expect_valid:
                # A generation-time failure on an intended-valid vector is
                # a REGRESSION — silently emitting it as expected-invalid
                # would turn the conformance suite green on broken code.
                raise
            return  # intended-invalid: no post written
        if not expect_valid:
            raise AssertionError(
                f"{handler}/{case}: intended-invalid op applied cleanly")
        _dump_state(d, "post", post)

    def bulk(fn, *args):
        acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
        fn(*args, acc, sigs.PubkeyCache())
        acc.finish()

    atts = h.attestations_for_slot(state, int(state.slot) - 1)
    emit("attestation", "attestation.ssz", T.Attestation, atts[0],
         lambda s, op: bulk(PB.process_attestation, s, op, fork, h.preset,
                            h.spec, T))
    emit("proposer_slashing", "proposer_slashing.ssz", T.ProposerSlashing,
         h.make_proposer_slashing(state, 3),
         lambda s, op: bulk(PB.process_proposer_slashing, s, op, fork,
                            h.preset, h.spec))
    emit("attester_slashing", "attester_slashing.ssz", T.AttesterSlashing,
         h.make_attester_slashing(state, [4, 5]),
         lambda s, op: bulk(PB.process_attester_slashing, s, op, fork,
                            h.preset, h.spec))
    # voluntary exit requires the shard-committee-period wait on a fresh
    # chain → this is the expected-invalid case (no post file).
    emit("voluntary_exit", "voluntary_exit.ssz", T.SignedVoluntaryExit,
         h.make_exit(state, 6),
         lambda s, op: bulk(PB.process_voluntary_exit, s, op, fork,
                            h.preset, h.spec), case="too_early",
         expect_valid=False)
    if fork >= ForkName.ALTAIR:
        agg = h.sync_aggregate_for(state, int(state.slot))
        emit("sync_aggregate", "sync_aggregate.ssz", T.SyncAggregate, agg,
             lambda s, op: (lambda acc: (PB.process_sync_aggregate(
                 s, op, h.preset, h.spec, T, acc), acc.finish()))(
                 PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)))
    if fork >= ForkName.CAPELLA:
        emit("bls_to_execution_change", "address_change.ssz",
             T.SignedBLSToExecutionChange,
             h.make_bls_to_execution_change(7),
             lambda s, op: (lambda acc: (PB.process_bls_to_execution_change(
                 s, op, h.spec, acc), acc.finish()))(
                 PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)))


def _gen_epoch_processing(root: str, fork: ForkName) -> None:
    h = _harness(fork)
    spe = h.preset.SLOTS_PER_EPOCH
    h.extend_chain(2 * spe)  # into epoch 2 with real participation
    from ..state_transition.per_slot import process_slots
    # advance to the last slot of the epoch (epoch processing is next)
    state = h.state.copy()
    target = (int(state.slot) // spe + 1) * spe - 1
    if int(state.slot) < target:
        state = process_slots(state, target, h.preset, h.spec, h.T)
    # second starting point: an INACTIVITY-LEAK state (5 empty epochs
    # stall finality), exercising the leak arms of justification,
    # rewards, and inactivity updates.
    leak = process_slots(h.state.copy(), int(h.state.slot) + 5 * spe - 1,
                         h.preset, h.spec, h.T)
    steps = _epoch_steps(fork, h.preset, h.spec, h.T)
    for case, start in (("from_chain", state), ("leak", leak)):
        cur = start
        for handler, fn in steps.items():
            d = _case(root, "minimal", fork, "epoch_processing", handler,
                      "pyspec_tests", case)
            _dump_state(d, "pre", cur)
            nxt = cur.copy()
            fn(nxt)
            _dump_state(d, "post", nxt)
            cur = nxt  # EF semantics: each step's pre has priors applied


def _gen_ssz_static(root: str, fork: ForkName) -> None:
    h = _harness(fork)
    h.extend_chain(2)
    T = h.T
    sb = h.build_block()
    values = {
        "BeaconState": (T.state_cls(fork), h.state),
        "SignedBeaconBlock": (type(sb), sb),
        "BeaconBlock": (T.block_cls(fork), sb.message),
        "Attestation": (T.Attestation,
                        h.attestations_for_slot(h.state,
                                                int(h.state.slot) - 1)[0]),
        "Checkpoint": (T.Checkpoint, h.state.finalized_checkpoint),
        "Validator": (None, None),  # filled below
        "Fork": (T.Fork, h.state.fork),
        "BeaconBlockHeader": (T.BeaconBlockHeader,
                              h.state.latest_block_header),
    }
    from ..types.validators import Validator
    values["Validator"] = (Validator, h.state.validators[0])
    for name, (cls, value) in values.items():
        d = _case(root, "minimal", fork, "ssz_static", name, "ssz_minimal",
                  "case_0")
        enc = cls.serialize(value)
        _write(os.path.join(d, "serialized.ssz"), enc)
        _write_yaml(os.path.join(d, "roots.yaml"),
                    {"root": "0x" + cls.hash_tree_root(value).hex()})


def _gen_shuffling(root: str, fork: ForkName) -> None:
    if fork != ForkName.PHASE0:
        return
    for i, count in enumerate((1, 7, 64)):
        seed = bytes([i]) * 32
        mapping = shuffle_list(np.arange(count, dtype=np.uint64), seed,
                               MINIMAL.SHUFFLE_ROUND_COUNT)
        d = _case(root, "minimal", fork, "shuffling", "core", "shuffle",
                  f"shuffle_0x{seed[:2].hex()}_{count}")
        _write_yaml(os.path.join(d, "mapping.yaml"), {
            "seed": "0x" + seed.hex(),
            "count": count,
            "mapping": [int(x) for x in mapping],
        })


def _gen_bls(root: str) -> None:
    fork = ForkName.PHASE0

    def case(handler: str, name: str, inp, out) -> None:
        d = _case(root, "general", fork, "bls", handler, "small", name)
        _write_yaml(os.path.join(d, "data.yaml"),
                    {"input": inp, "output": out})

    sks = [B.SecretKey(i + 1) for i in range(4)]
    pks = [sk.public_key() for sk in sks]
    msgs = [bytes([i]) * 32 for i in range(4)]
    sigs = [sk.sign(m) for sk, m in zip(sks, msgs)]

    def hx(b: bytes) -> str:
        return "0x" + b.hex()

    case("sign", "sign_case_0",
         {"privkey": hx(sks[0].serialize()), "message": hx(msgs[0])},
         hx(sigs[0].serialize()))
    case("verify", "verify_valid",
         {"pubkey": hx(pks[0].serialize()), "message": hx(msgs[0]),
          "signature": hx(sigs[0].serialize())}, True)
    case("verify", "verify_wrong_message",
         {"pubkey": hx(pks[0].serialize()), "message": hx(msgs[1]),
          "signature": hx(sigs[0].serialize())}, False)
    case("verify", "verify_infinity_pubkey",
         {"pubkey": hx(b"\xc0" + b"\x00" * 47), "message": hx(msgs[0]),
          "signature": hx(sigs[0].serialize())}, False)
    agg = B.aggregate_signatures(sigs)
    case("aggregate", "aggregate_4",
         [hx(s.serialize()) for s in sigs], hx(agg.serialize()))
    case("aggregate_verify", "aggregate_verify_valid",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "messages": [hx(m) for m in msgs],
          "signature": hx(agg.serialize())}, True)
    case("aggregate_verify", "aggregate_verify_tampered",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "messages": [hx(m) for m in reversed(msgs)],
          "signature": hx(agg.serialize())}, False)
    same = [sk.sign(msgs[0]) for sk in sks]
    fagg = B.aggregate_signatures(same)
    case("fast_aggregate_verify", "fast_valid",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "message": hx(msgs[0]), "signature": hx(fagg.serialize())}, True)
    case("fast_aggregate_verify", "fast_no_pubkeys",
         {"pubkeys": [], "message": hx(msgs[0]),
          "signature": hx(b"\xc0" + b"\x00" * 95)}, False)
    from ..crypto import curve as C
    agg_pk = B.aggregate_public_keys(pks)
    case("eth_aggregate_pubkeys", "aggregate_pubkeys_4",
         [hx(p.serialize()) for p in pks], hx(C.g1_compress(agg_pk)))
    case("batch_verify", "batch_valid",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "messages": [hx(m) for m in msgs],
          "signatures": [hx(s.serialize()) for s in sigs]}, True)
    case("batch_verify", "batch_one_bad",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "messages": [hx(m) for m in msgs],
          "signatures": [hx(s.serialize())
                         for s in [sigs[1]] + sigs[1:]]}, False)


def _reser(obj):
    """Deep copy via SSZ roundtrip (mutation-safe)."""
    cls = type(obj)
    return cls.deserialize(cls.serialize(obj))


class _OpEmitter:
    """Emit valid/invalid operation cases with generation-time assertions:
    an intended-valid vector that fails, or an intended-invalid one that
    applies cleanly, is a REGRESSION and raises (the adversarial zoo is
    only worth anything if every invalid case demonstrably trips a real
    check — VERDICT r4 #5)."""

    def __init__(self, root: str, config: str, fork: ForkName, h):
        self.root, self.config, self.fork, self.h = root, config, fork, h

    def __call__(self, handler: str, file_name: str, op_cls, op, apply_fn,
                 case: str, expect_valid: bool, state=None) -> None:
        state = state if state is not None else self.h.state
        d = _case(self.root, self.config, self.fork, "operations", handler,
                  "pyspec_tests", case)
        pre = state.copy()
        _dump_state(d, "pre", pre)
        _write(os.path.join(d, file_name), op_cls.serialize(op))
        post = pre.copy()
        try:
            apply_fn(post, op)
        except (TypeError, AttributeError, NameError):
            raise  # a generator/mutator bug, not a tripped spec check
        except Exception:
            if expect_valid:
                raise
            return  # intended-invalid: no post written
        if not expect_valid:
            raise AssertionError(
                f"{handler}/{case}: intended-invalid op applied cleanly")
        _dump_state(d, "post", post)


def _bulk(fn, *args):
    acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
    fn(*args, acc, sigs.PubkeyCache())
    acc.finish()


def _gen_operations_invalid(root: str, fork: ForkName,
                            config: str = "minimal") -> None:
    """The per-handler invalid zoo: every case trips a distinct spec
    check (bad indices, wrong committees, window violations, bad
    signatures, malformed proofs)."""
    h = _harness(fork) if config == "minimal" else _mainnet_harness(fork)
    h.extend_chain(3)
    state = h.state
    T = h.T
    emit = _OpEmitter(root, config, fork, h)

    def apply_att(s, op):
        _bulk(PB.process_attestation, s, op, fork, h.preset, h.spec, T)

    att = h.attestations_for_slot(state, int(state.slot) - 1)[0]

    def mut_att(fn):
        a = _reser(att)
        fn(a)
        return a

    emit("attestation", "attestation.ssz", T.Attestation,
         mut_att(lambda a: setattr(a.data, "index", 64)), apply_att,
         "invalid_committee_index", False)
    emit("attestation", "attestation.ssz", T.Attestation,
         mut_att(lambda a: setattr(a.data, "slot", int(state.slot))),
         apply_att, "invalid_too_new", False)
    emit("attestation", "attestation.ssz", T.Attestation,
         mut_att(lambda a: setattr(a.data.target, "epoch",
                                   int(att.data.target.epoch) + 5)),
         apply_att, "invalid_future_target", False)
    emit("attestation", "attestation.ssz", T.Attestation,
         mut_att(lambda a: setattr(a.data.source, "root", b"\xee" * 32)),
         apply_att, "invalid_source_root", False)
    emit("attestation", "attestation.ssz", T.Attestation,
         mut_att(lambda a: setattr(
             a, "signature", att.signature[:-1] + b"\x00")), apply_att,
         "invalid_signature", False)

    def apply_ps(s, op):
        _bulk(PB.process_proposer_slashing, s, op, fork, h.preset, h.spec)

    ps = h.make_proposer_slashing(state, 3)
    emit("proposer_slashing", "proposer_slashing.ssz", T.ProposerSlashing,
         ps, apply_ps, "ok_again", True)

    def mut_ps(fn):
        p = _reser(ps)
        fn(p)
        return p

    emit("proposer_slashing", "proposer_slashing.ssz", T.ProposerSlashing,
         mut_ps(lambda p: setattr(p.signed_header_2.message,
                                  "proposer_index", 4)),
         apply_ps, "invalid_proposer_mismatch", False)
    emit("proposer_slashing", "proposer_slashing.ssz", T.ProposerSlashing,
         mut_ps(lambda p: setattr(p, "signed_header_2",
                                  _reser(p.signed_header_1))),
         apply_ps, "invalid_headers_identical", False)
    emit("proposer_slashing", "proposer_slashing.ssz", T.ProposerSlashing,
         mut_ps(lambda p: setattr(p.signed_header_1.message,
                                  "proposer_index", 10_000)),
         apply_ps, "invalid_proposer_unknown", False)
    emit("proposer_slashing", "proposer_slashing.ssz", T.ProposerSlashing,
         mut_ps(lambda p: setattr(
             p.signed_header_1, "signature",
             ps.signed_header_1.signature[:-1] + b"\x01")),
         apply_ps, "invalid_sig_1", False)

    def apply_as(s, op):
        _bulk(PB.process_attester_slashing, s, op, fork, h.preset, h.spec)

    asl = h.make_attester_slashing(state, [4, 5])

    def mut_as(fn):
        a = _reser(asl)
        fn(a)
        return a

    emit("attester_slashing", "attester_slashing.ssz", T.AttesterSlashing,
         mut_as(lambda a: setattr(a, "attestation_2",
                                  _reser(a.attestation_1))),
         apply_as, "invalid_not_slashable", False)
    emit("attester_slashing", "attester_slashing.ssz", T.AttesterSlashing,
         mut_as(lambda a: setattr(a.attestation_1, "attesting_indices",
                                  [5, 4])),
         apply_as, "invalid_indices_unsorted", False)
    emit("attester_slashing", "attester_slashing.ssz", T.AttesterSlashing,
         mut_as(lambda a: setattr(
             a.attestation_1, "signature",
             asl.attestation_1.signature[:-1] + b"\x02")),
         apply_as, "invalid_sig", False)

    def apply_exit(s, op):
        _bulk(PB.process_voluntary_exit, s, op, fork, h.preset, h.spec)

    # A VALID exit needs shard_committee_period epochs of age: fast-forward
    # an empty-slot copy of the chain state (exercises deep skip-slot
    # processing too).
    from ..state_transition.per_slot import process_slots
    spe = h.preset.SLOTS_PER_EPOCH
    aged = process_slots(
        state.copy(),
        int(state.slot) + h.spec.shard_committee_period * spe, h.preset,
        h.spec, h.T)
    aged_exit = h.make_exit(aged, 6)
    emit("voluntary_exit", "voluntary_exit.ssz", T.SignedVoluntaryExit,
         aged_exit, apply_exit, "ok_aged", True, state=aged)
    emit("voluntary_exit", "voluntary_exit.ssz", T.SignedVoluntaryExit,
         T.SignedVoluntaryExit(
             message=T.VoluntaryExit(
                 epoch=aged_exit.message.epoch, validator_index=10_000),
             signature=aged_exit.signature),
         apply_exit, "invalid_unknown_validator", False, state=aged)
    emit("voluntary_exit", "voluntary_exit.ssz", T.SignedVoluntaryExit,
         T.SignedVoluntaryExit(message=aged_exit.message,
                               signature=aged_exit.signature[:-1] + b"\x03"),
         apply_exit, "invalid_sig", False, state=aged)

    already = aged.copy()
    apply_exit(already, aged_exit)  # pre-state has the exit applied
    emit("voluntary_exit", "voluntary_exit.ssz", T.SignedVoluntaryExit,
         aged_exit, apply_exit, "invalid_already_exited", False,
         state=already)

    # Deposits: valid create, top-up, invalid-signature-is-ignored (spec:
    # a bad deposit signature skips the deposit but the op SUCCEEDS), and
    # a corrupted Merkle proof (hard failure).
    def apply_dep(s, op):
        PB.process_deposit(s, op, h.preset, h.spec, T)

    h2 = _harness(fork) if config == "minimal" else _mainnet_harness(fork)
    h2.extend_chain(2)
    h2.make_deposit(100)
    sb = h2.build_block()
    h2.apply_block(sb)
    dep_state = h2.state
    # the deposit got included; build the NEXT deposit for vectors
    h2.make_deposit(101)
    sb2 = h2.build_block()
    dep = sb2.message.body.deposits[0]
    pre_dep = h2.state.copy()
    pre_dep.eth1_data = sb2.message.body.eth1_data
    emit("deposit", "deposit.ssz", T.Deposit, dep, apply_dep,
         "ok_new_validator", True, state=pre_dep)

    bad_proof = _reser(dep)
    bad_proof.proof = [bytes(32)] * len(dep.proof)
    emit("deposit", "deposit.ssz", T.Deposit, bad_proof, apply_dep,
         "invalid_proof", False, state=pre_dep)

    h3 = _harness(fork) if config == "minimal" else _mainnet_harness(fork)
    h3.extend_chain(2)
    h3.make_deposit(102, valid_signature=False)
    sb3 = h3.build_block()
    dep3 = sb3.message.body.deposits[0]
    pre3 = h3.state.copy()
    pre3.eth1_data = sb3.message.body.eth1_data
    emit("deposit", "deposit.ssz", T.Deposit, dep3, apply_dep,
         "bad_sig_ignored", True, state=pre3)

    if fork >= ForkName.ALTAIR:
        def apply_sync(s, op):
            acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
            PB.process_sync_aggregate(s, op, h.preset, h.spec, T, acc)
            acc.finish()

        agg = h.sync_aggregate_for(state, int(state.slot))
        bad = _reser(agg)
        bad.sync_committee_signature = \
            bytes(agg.sync_committee_signature[:-1]) + b"\x04"
        emit("sync_aggregate", "sync_aggregate.ssz", T.SyncAggregate, bad,
             apply_sync, "invalid_sig", False)

    if fork >= ForkName.CAPELLA:
        def apply_blsch(s, op):
            acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
            PB.process_bls_to_execution_change(s, op, h.spec, acc)
            acc.finish()

        ch = h.make_bls_to_execution_change(8)
        bad_ch = _reser(ch)
        bad_ch.message.validator_index = 10_000
        emit("bls_to_execution_change", "address_change.ssz",
             T.SignedBLSToExecutionChange, bad_ch, apply_blsch,
             "invalid_unknown_validator", False)
        bad_sig = _reser(ch)
        bad_sig.signature = bytes(ch.signature[:-1]) + b"\x05"
        emit("bls_to_execution_change", "address_change.ssz",
             T.SignedBLSToExecutionChange, bad_sig, apply_blsch,
             "invalid_sig", False)

        def apply_wd(s, op):
            PB.process_withdrawals(s, op, h.preset, T)

        payload = h.build_block().message.body.execution_payload
        emit("withdrawals", "execution_payload.ssz",
             T.payload_cls(fork), payload, apply_wd, "ok_empty", True)
        bad_wd = _reser(payload)
        bad_wd.withdrawals = [T.Withdrawal(
            index=0, validator_index=0, address=b"\x01" * 20,
            amount=12345)]
        emit("withdrawals", "execution_payload.ssz",
             T.payload_cls(fork), bad_wd, apply_wd,
             "invalid_unexpected_withdrawal", False)

    # block_header: valid + zoo (pre-state advanced to the block slot,
    # as process_block_header runs after per-slot processing).
    def apply_hdr(s, op):
        PB.process_block_header(s, op, h.preset, T)

    blk = h.build_block(compute_state_root=False).message
    hdr_pre = process_slots(state.copy(), int(blk.slot), h.preset, h.spec,
                            h.T)
    emit("block_header", "block.ssz", T.block_cls(fork), blk, apply_hdr,
         "ok", True, state=hdr_pre)

    def mut_blk(fn):
        b = _reser(blk)
        fn(b)
        return b

    emit("block_header", "block.ssz", T.block_cls(fork),
         mut_blk(lambda b: setattr(b, "slot", int(blk.slot) + 3)),
         apply_hdr, "invalid_slot_mismatch", False, state=hdr_pre)
    emit("block_header", "block.ssz", T.block_cls(fork),
         mut_blk(lambda b: setattr(b, "parent_root", b"\x66" * 32)),
         apply_hdr, "invalid_parent_root", False, state=hdr_pre)
    emit("block_header", "block.ssz", T.block_cls(fork),
         mut_blk(lambda b: setattr(
             b, "proposer_index",
             (int(blk.proposer_index) + 1) % len(state.validators))),
         apply_hdr, "invalid_proposer_index", False, state=hdr_pre)


def _gen_sanity_invalid(root: str, fork: ForkName) -> None:
    """sanity/blocks adversarial zoo + a multi-block valid case."""
    h = _harness(fork)
    h.extend_chain(3)
    from ..state_transition.per_slot import state_transition

    def emit_blocks(case: str, blocks, expect_valid: bool,
                    pre=None) -> None:
        d = _case(root, "minimal", fork, "sanity", "blocks",
                  "pyspec_tests", case)
        pre = pre if pre is not None else h.state
        _dump_state(d, "pre", pre)
        for i, sb in enumerate(blocks):
            _write(os.path.join(d, f"blocks_{i}.ssz"),
                   type(sb).serialize(sb))
        _write_yaml(os.path.join(d, "meta.yaml"),
                    {"blocks_count": len(blocks)})
        state = pre.copy()
        try:
            for sb in blocks:
                state = state_transition(
                    state, sb, h.preset, h.spec, h.T,
                    strategy=PB.SignatureStrategy.VERIFY_BULK)
        except (TypeError, AttributeError, NameError):
            raise  # a generator/mutator bug, not a tripped spec check
        except Exception:
            if expect_valid:
                raise
            return
        if not expect_valid:
            raise AssertionError(f"sanity/blocks/{case}: invalid case "
                                 "applied cleanly")
        _dump_state(d, "post", state)

    # multi-block valid chain segment
    h2 = _harness(fork)
    h2.extend_chain(2)
    pre_multi = h2.state.copy()
    seg = h2.extend_chain(3)
    emit_blocks("multi_block", seg, True, pre=pre_multi)

    sb = h.build_block(compute_state_root=True)

    def mut(fn):
        b = _reser(sb)
        fn(b)
        return b

    emit_blocks("invalid_proposer_signature",
                [mut(lambda b: setattr(
                    b, "signature", bytes(sb.signature[:-1]) + b"\x07"))],
                False)
    emit_blocks("invalid_future_slot",
                [mut(lambda b: setattr(b.message, "slot",
                                       int(sb.message.slot) + 100))], False)
    emit_blocks("invalid_parent_root",
                [mut(lambda b: setattr(b.message, "parent_root",
                                       b"\x99" * 32))], False)
    emit_blocks("invalid_randao",
                [mut(lambda b: setattr(
                    b.message.body, "randao_reveal",
                    bytes(sb.message.body.randao_reveal[:-1]) + b"\x08"))],
                False)
    emit_blocks("invalid_duplicate_block", [sb, _reser(sb)], False)


def _gen_rewards(root: str, fork: ForkName) -> None:
    """rewards runner vectors (`cases/rewards.rs`): per-component deltas
    for a healthy chain and an inactivity-leak state."""
    from ..state_transition.per_epoch import flag_deltas
    from ..state_transition.per_epoch_phase0 import attestation_deltas_phase0
    from ..state_transition.per_slot import process_slots
    from .ef_runner import Deltas

    def emit(case: str, state) -> None:
        d = _case(root, "minimal", fork, "rewards", "core", "pyspec_tests",
                  case)
        _dump_state(d, "pre", state)
        spec = ChainSpec.minimal().with_forks_at_genesis(fork)
        if fork == ForkName.PHASE0:
            deltas = attestation_deltas_phase0(state, MINIMAL, spec)
        else:
            deltas = flag_deltas(state, fork, MINIMAL, spec)
        for name, (r, p) in deltas.items():
            obj = Deltas(rewards=[int(x) for x in r],
                         penalties=[int(x) for x in p])
            _write(os.path.join(d, f"{name}_deltas.ssz"),
                   Deltas.serialize(obj))

    h = _harness(fork)
    spe = h.preset.SLOTS_PER_EPOCH
    h.extend_chain(2 * spe)
    emit("basic", h.state.copy())

    # leak: advance 6 empty epochs (no attestations → finality stalls)
    leak = process_slots(h.state.copy(), int(h.state.slot) + 6 * spe,
                         h.preset, h.spec, h.T)
    emit("leak", leak)


def _gen_transition(root: str) -> None:
    """Fork-boundary transition vectors for all three upgrades
    (`cases/transition.rs`): blocks crossing fork_epoch, pre-fork state
    in, post-fork state out."""
    from dataclasses import replace

    from .ef_runner import _FORK_EPOCH_ATTR, _PRE_FORK
    from .harness import StateHarness

    for post in (ForkName.ALTAIR, ForkName.BELLATRIX, ForkName.CAPELLA,
                 ForkName.DENEB):
        pre_fork = _PRE_FORK[post]
        attr = _FORK_EPOCH_ATTR[post]
        fork_epoch = 1
        spec = replace(
            ChainSpec.minimal().with_forks_at_genesis(pre_fork),
            **{attr: fork_epoch})
        h = StateHarness(n_validators=16, fork=pre_fork, preset=MINIMAL,
                         spec=spec)
        h.extend_chain(2)
        pre = h.state.copy()
        spe = MINIMAL.SLOTS_PER_EPOCH
        boundary_slot = fork_epoch * spe
        blocks = h.extend_chain(spe)  # crosses the boundary
        fork_block = max(i for i, sb in enumerate(blocks)
                         if int(sb.message.slot) < boundary_slot)
        d = _case(root, "minimal", post, "transition", "core",
                  "pyspec_tests", f"transition_to_{post.value}")
        _dump_state(d, "pre", pre)
        for i, sb in enumerate(blocks):
            _write(os.path.join(d, f"blocks_{i}.ssz"),
                   type(sb).serialize(sb))
        _write_yaml(os.path.join(d, "meta.yaml"), {
            "post_fork": post.value,
            "fork_epoch": fork_epoch,
            "fork_block": fork_block,
            "blocks_count": len(blocks),
        })
        _dump_state(d, "post", h.state)


def _mainnet_harness(fork: ForkName) -> StateHarness:
    return StateHarness(n_validators=128, fork=fork, preset=MAINNET,
                        spec=ChainSpec.mainnet().with_forks_at_genesis(fork))


# -- fork_choice runner vectors ---------------------------------------------


class _FcRecorder:
    """Drive a HOST-oracle ForkChoice while recording the EF
    ``fork_choice`` step stream (`cases/fork_choice.rs` layout: anchor
    state/block + steps.yaml + per-step ssz files).  Every check is the
    oracle's own answer at generation time — the runner replays them
    against BOTH the host and columnar paths."""

    def __init__(self, d: str, h: StateHarness):
        from ..fork_choice import ForkChoice

        self.d = d
        self.h = h
        self.steps: list = []
        state = h.state.copy()
        hdr = state.latest_block_header.copy()
        hdr.state_root = state.tree_hash_root()
        self.genesis_root = hdr.tree_hash_root()
        body = h.T.body_cls(h.fork_at(0))()
        anchor = h.T.block_cls(h.fork_at(0))(
            slot=int(hdr.slot), proposer_index=int(hdr.proposer_index),
            parent_root=bytes(hdr.parent_root),
            state_root=bytes(hdr.state_root), body=body)
        if anchor.tree_hash_root() != self.genesis_root:
            raise AssertionError("anchor block root != genesis header root")
        _dump_state(d, "anchor_state", state)
        _write(os.path.join(d, "anchor_block.ssz"),
               type(anchor).serialize(anchor))
        self.fc = ForkChoice(h.preset, h.spec,
                             genesis_root=self.genesis_root,
                             genesis_state=state.copy(), device=False)
        self.states = {self.genesis_root: state}
        self.genesis_time = int(state.genesis_time)

    def tick(self, slot: int) -> None:
        self.steps.append(
            {"tick": self.genesis_time
             + slot * self.h.spec.seconds_per_slot})
        self.fc.on_tick(slot)

    def block(self, sb) -> bytes:
        root = sb.message.tree_hash_root()
        from ..state_transition.per_slot import state_transition
        pre = self.states[bytes(sb.message.parent_root)]
        post = state_transition(
            pre.copy(), sb, self.h.preset, self.h.spec, self.h.T,
            strategy=PB.SignatureStrategy.VERIFY_BULK)
        self.states[root] = post
        name = "block_0x" + root.hex()[:16]
        _write(os.path.join(self.d, name + ".ssz"),
               type(sb).serialize(sb))
        self.steps.append({"block": name})
        if int(sb.message.slot) > self.fc.current_slot:
            self.fc.on_tick(int(sb.message.slot))
        self.fc.on_block(sb, root, post.copy())
        return root

    def attestation(self, att) -> None:
        from ..beacon_chain.attestation_verification import attesting_indices
        from ..state_transition.per_slot import process_slots
        name = ("attestation_0x"
                + att.data.tree_hash_root().hex()[:16])
        _write(os.path.join(self.d, name + ".ssz"),
               type(att).serialize(att))
        self.steps.append({"attestation": name})
        st = self.states[bytes(att.data.beacon_block_root)]
        if int(st.slot) < int(att.data.slot):
            st = process_slots(st.copy(), int(att.data.slot),
                               self.h.preset, self.h.spec, self.h.T)
        idx, _c = attesting_indices(st, att, self.h.preset)
        self.fc.on_attestation(_FcIndexed(att.data, idx.tolist()))

    def attester_slashing(self, slashing) -> None:
        name = ("attester_slashing_0x"
                + slashing.tree_hash_root().hex()[:16])
        _write(os.path.join(self.d, name + ".ssz"),
               type(slashing).serialize(slashing))
        self.steps.append({"attester_slashing": name})
        self.fc.on_attester_slashing(slashing)

    def invalid_payload(self, block_root: bytes) -> None:
        # Framework extension step (our vectors are self-generated; a
        # real tarball's on_payload_info steps would map the same way).
        self.steps.append({"payload_status": {
            "block_root": "0x" + block_root.hex(), "status": "INVALID"}})
        self.fc.on_invalid_execution_payload(block_root)

    def checks(self) -> bytes:
        head = self.fc.get_head()
        jcp = self.fc.justified_checkpoint
        fcp = self.fc.finalized_checkpoint
        self.steps.append({"checks": {
            "head": {"slot": self.fc.block_slot(head),
                     "root": "0x" + head.hex()},
            "justified_checkpoint": {"epoch": jcp[0],
                                     "root": "0x" + jcp[1].hex()},
            "finalized_checkpoint": {"epoch": fcp[0],
                                     "root": "0x" + fcp[1].hex()},
            "proposer_boost_root":
                "0x" + self.fc.proposer_boost_root.hex(),
        }})
        return head

    def finish(self) -> None:
        _write_yaml(os.path.join(self.d, "steps.yaml"), self.steps)

    def branch_block(self, state, slot: int, graffiti: bytes,
                     **build_kw):
        """Build a signed block on an arbitrary branch state (the harness
        builds on its live state; swap it in and out)."""
        saved = self.h.state
        self.h.state = state.copy()
        try:
            sb = self.h.build_block(slot=slot, graffiti=graffiti,
                                    **build_kw)
        finally:
            self.h.state = saved
        return sb


def _branch_attestations(rec: _FcRecorder, block_root: bytes, slot: int):
    """Committee attestations for ``slot`` naming ``block_root``'s branch
    as head (built on that branch's post-state, advanced one slot)."""
    from ..state_transition.per_slot import process_slots
    st = rec.states[block_root]
    adv = process_slots(st.copy(), slot + 1, rec.h.preset, rec.h.spec,
                       rec.h.T)
    return rec.h.attestations_for_slot(adv, slot)


def _gen_fork_choice(root: str, fork: ForkName,
                     config: str = "minimal") -> None:
    """fork_choice runner slice: head tracking, a forked vote flip, an
    equivocation slashing, EL invalidation revert (post-merge forks), and
    a finality advance — each case's checks are oracle pins."""
    mainnet = config == "mainnet"

    def case(name: str) -> _FcRecorder:
        h = _mainnet_harness(fork) if mainnet else _harness(fork)
        d = _case(root, config, fork, "fork_choice", "get_head",
                  "pyspec_tests", name)
        return _FcRecorder(d, h)

    # Mainnet sync committees are 512 keys of pure-python signing per
    # block — skip the aggregate (empty one is valid), keep vectors cheap.
    bkw = {"sync_participation": 0.0} if mainnet else {}

    # -- linear chain: head tracks the tip, votes confirm it ---------------
    rec = case("chain_head_tracks")
    rec.tick(1)
    b1 = rec.block(rec.branch_block(rec.h.state, 1, b"\x01" * 32, **bkw))
    assert rec.checks() == b1
    rec.tick(2)
    b2 = rec.block(rec.branch_block(rec.states[b1], 2, b"\x02" * 32, **bkw))
    assert rec.checks() == b2
    rec.tick(3)
    for att in _branch_attestations(rec, b2, 2):
        rec.attestation(att)
    assert rec.checks() == b2
    rec.finish()

    # -- two-branch fork: votes flip the head off the tie-break winner -----
    rec = case("fork_vote_flip")
    rec.tick(1)
    b1 = rec.block(rec.branch_block(rec.h.state, 1, b"\x01" * 32, **bkw))
    c2a = rec.block(rec.branch_block(rec.states[b1], 2, b"\xaa" * 32,
                                     **bkw))
    c2b = rec.block(rec.branch_block(rec.states[b1], 2, b"\xbb" * 32,
                                     **bkw))
    rec.tick(3)
    tie_winner = rec.checks()
    assert tie_winner in (c2a, c2b)
    loser = c2b if tie_winner == c2a else c2a
    flip_atts = _branch_attestations(rec, loser, 2)
    for att in flip_atts:
        rec.attestation(att)
    assert rec.checks() == loser, "votes must flip the head"
    # -- the voters equivocate: their weight vanishes, tie-break returns --
    from ..beacon_chain.attestation_verification import attesting_indices
    from ..state_transition.per_slot import process_slots
    adv = process_slots(rec.states[loser].copy(), 3, rec.h.preset,
                        rec.h.spec, rec.h.T)
    voters: set = set()
    for att in flip_atts:
        idx, _c = attesting_indices(adv, att, rec.h.preset)
        voters.update(int(i) for i in idx)
    slashing = rec.h.make_attester_slashing(adv, sorted(voters))
    rec.attester_slashing(slashing)
    assert rec.checks() == tie_winner, "equivocation must revert the flip"
    rec.finish()

    if fork >= ForkName.BELLATRIX:
        # -- EL invalidation: descendants die, head reverts to sibling ----
        rec = case("invalidation_revert")
        rec.tick(1)
        b1 = rec.block(rec.branch_block(rec.h.state, 1, b"\x01" * 32,
                                        **bkw))
        c2a = rec.block(rec.branch_block(rec.states[b1], 2, b"\xaa" * 32,
                                         **bkw))
        c2b = rec.block(rec.branch_block(rec.states[b1], 2, b"\xbb" * 32,
                                         **bkw))
        b3 = rec.block(rec.branch_block(rec.states[c2a], 3, b"\x03" * 32,
                                        **bkw))
        rec.tick(4)
        for att in _branch_attestations(rec, b3, 3):
            rec.attestation(att)
        assert rec.checks() == b3
        rec.invalid_payload(c2a)
        assert rec.checks() == c2b, "invalidation must revert to sibling"
        rec.finish()

    if not mainnet:
        # -- finality advances through imported checkpoints ----------------
        rec = case("finality_advances")
        h = rec.h
        spe = h.preset.SLOTS_PER_EPOCH
        # Full participation justifies epoch 2 at the slot-3·spe boundary
        # (the genesis epoch never accumulates enough weighted target).
        for sb in h.extend_chain(3 * spe + 2):
            rec.tick(int(sb.message.slot))
            rec.block(sb)
        rec.checks()
        assert rec.fc.justified_checkpoint[0] >= 1, "no justification"
        rec.finish()


def _gen_fork_choice_all(root: str) -> None:
    for fork in (ForkName.PHASE0, ForkName.CAPELLA):
        _gen_fork_choice(root, fork, config="minimal")
    _gen_fork_choice(root, ForkName.CAPELLA, config="mainnet")


def _gen_mainnet_slice(root: str) -> None:
    """A mainnet-preset slice (capella) so preset-dependent constants
    (committee sizes, epochs, churn) aren't only exercised on minimal."""
    fork = ForkName.CAPELLA
    h = _mainnet_harness(fork)
    h.extend_chain(3)

    d = _case(root, "mainnet", fork, "sanity", "blocks", "pyspec_tests",
              "valid_block")
    pre = h.state.copy()
    _dump_state(d, "pre", pre)
    sb = h.build_block()
    _write(os.path.join(d, "blocks_0.ssz"), type(sb).serialize(sb))
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})
    from ..state_transition.per_slot import state_transition
    post = state_transition(pre.copy(), sb, h.preset, h.spec, h.T,
                            strategy=PB.SignatureStrategy.VERIFY_BULK)
    _dump_state(d, "post", post)

    emit = _OpEmitter(root, "mainnet", fork, h)
    att = h.attestations_for_slot(h.state, int(h.state.slot) - 1)[0]

    def apply_att(s, op):
        _bulk(PB.process_attestation, s, op, fork, h.preset, h.spec, h.T)

    emit("attestation", "attestation.ssz", h.T.Attestation, att,
         apply_att, "ok", True)
    bad = _reser(att)
    bad.data.index = 64
    emit("attestation", "attestation.ssz", h.T.Attestation, bad,
         apply_att, "invalid_committee_index", False)


def generate(root: str) -> None:
    """Write the full tree under ``root`` (idempotent: wipes first)."""
    import shutil
    tests = os.path.join(root, "tests")
    if os.path.isdir(tests):
        shutil.rmtree(tests)
    prev = B.get_backend().name
    B.set_backend("python")
    try:
        for fork in GEN_FORKS:
            _gen_sanity(root, fork)
            _gen_sanity_invalid(root, fork)
            _gen_operations(root, fork)
            _gen_operations_invalid(root, fork)
            _gen_epoch_processing(root, fork)
            _gen_rewards(root, fork)
            _gen_ssz_static(root, fork)
            _gen_shuffling(root, fork)
        _gen_transition(root)
        _gen_mainnet_slice(root)
        _gen_fork_choice_all(root)
        _gen_bls(root)
    finally:
        B.set_backend(prev)
