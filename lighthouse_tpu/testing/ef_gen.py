"""Generate consensus-spec-test vectors from this framework's own
executable spec, in the standard EF directory layout.

This environment has no network access, so the official
``ethereum/consensus-spec-tests`` tarballs cannot be fetched; as VERDICT r3
prescribed for that case, these vectors are produced by OUR state
transition + crypto (python backend) and serve as (a) regression pins,
(b) cross-backend consistency checks (fake / tpu backends must agree), and
(c) proof the runner infrastructure consumes the real layout — a genuine
tarball dropped at the same root runs through the identical walker.

Layout written (mirrors ``handler.rs:10-46``):

    <root>/tests/minimal/<fork>/{sanity,operations,epoch_processing,
                                 shuffling,ssz_static}/...
    <root>/tests/general/phase0/bls/<handler>/small/<case>/data.yaml
"""

from __future__ import annotations

import os

import numpy as np
import yaml

from ..crypto import bls as B
from ..state_transition import per_block as PB
from ..state_transition import signature_sets as sigs
from ..state_transition.shuffle import shuffle_list
from ..types.chain_spec import ChainSpec, ForkName
from ..types.presets import MINIMAL
from .ef_runner import _epoch_steps
from .harness import StateHarness

GEN_FORKS = (ForkName.PHASE0, ForkName.ALTAIR, ForkName.BELLATRIX,
             ForkName.CAPELLA)


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def _write_yaml(path: str, obj) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(obj, f)


def _case(root: str, config: str, fork: ForkName, runner: str, handler: str,
          suite: str, case: str) -> str:
    return os.path.join(root, "tests", config, fork.value, runner, handler,
                        suite, case)


def _dump_state(d: str, name: str, state) -> None:
    _write(os.path.join(d, name + ".ssz"), type(state).serialize(state))


def _harness(fork: ForkName) -> StateHarness:
    return StateHarness(n_validators=16, fork=fork, preset=MINIMAL,
                        spec=ChainSpec.minimal().with_forks_at_genesis(fork))


def _gen_sanity(root: str, fork: ForkName) -> None:
    h = _harness(fork)
    h.extend_chain(3)
    spe = h.preset.SLOTS_PER_EPOCH

    # slots: single slot + across an epoch boundary
    for case, n_slots in (("slots_1", 1), ("over_epoch", spe + 1)):
        d = _case(root, "minimal", fork, "sanity", "slots", "pyspec_tests",
                  case)
        pre = h.state.copy()
        _dump_state(d, "pre", pre)
        from ..state_transition.per_slot import process_slots
        post = process_slots(pre.copy(), int(pre.slot) + n_slots, h.preset,
                             h.spec, h.T)
        _write_yaml(os.path.join(d, "slots.yaml"), n_slots)
        _dump_state(d, "post", post)

    # blocks: valid single block; invalid (wrong state root) without post
    d = _case(root, "minimal", fork, "sanity", "blocks", "pyspec_tests",
              "valid_block")
    pre = h.state.copy()
    _dump_state(d, "pre", pre)
    sb = h.build_block()
    _write(os.path.join(d, "blocks_0.ssz"), type(sb).serialize(sb))
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})
    from ..state_transition.per_slot import state_transition
    post = state_transition(pre.copy(), sb, h.preset, h.spec, h.T,
                            strategy=PB.SignatureStrategy.VERIFY_BULK)
    _dump_state(d, "post", post)

    d = _case(root, "minimal", fork, "sanity", "blocks", "pyspec_tests",
              "invalid_state_root")
    _dump_state(d, "pre", h.state)
    bad = type(sb).deserialize(type(sb).serialize(sb))
    bad.message.state_root = b"\xba" * 32
    _write(os.path.join(d, "blocks_0.ssz"), type(bad).serialize(bad))
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})


def _gen_operations(root: str, fork: ForkName) -> None:
    h = _harness(fork)
    h.extend_chain(3)
    state = h.state
    T = h.T

    def emit(handler: str, file_name: str, op_cls, op, apply_fn,
             case: str = "ok", expect_valid: bool = True) -> None:
        d = _case(root, "minimal", fork, "operations", handler,
                  "pyspec_tests", case)
        pre = state.copy()
        _dump_state(d, "pre", pre)
        _write(os.path.join(d, file_name), op_cls.serialize(op))
        post = pre.copy()
        try:
            apply_fn(post, op)
        except Exception:
            if expect_valid:
                # A generation-time failure on an intended-valid vector is
                # a REGRESSION — silently emitting it as expected-invalid
                # would turn the conformance suite green on broken code.
                raise
            return  # intended-invalid: no post written
        if not expect_valid:
            raise AssertionError(
                f"{handler}/{case}: intended-invalid op applied cleanly")
        _dump_state(d, "post", post)

    def bulk(fn, *args):
        acc = PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)
        fn(*args, acc, sigs.PubkeyCache())
        acc.finish()

    atts = h.attestations_for_slot(state, int(state.slot) - 1)
    emit("attestation", "attestation.ssz", T.Attestation, atts[0],
         lambda s, op: bulk(PB.process_attestation, s, op, fork, h.preset,
                            h.spec, T))
    emit("proposer_slashing", "proposer_slashing.ssz", T.ProposerSlashing,
         h.make_proposer_slashing(state, 3),
         lambda s, op: bulk(PB.process_proposer_slashing, s, op, fork,
                            h.preset, h.spec))
    emit("attester_slashing", "attester_slashing.ssz", T.AttesterSlashing,
         h.make_attester_slashing(state, [4, 5]),
         lambda s, op: bulk(PB.process_attester_slashing, s, op, fork,
                            h.preset, h.spec))
    # voluntary exit requires the shard-committee-period wait on a fresh
    # chain → this is the expected-invalid case (no post file).
    emit("voluntary_exit", "voluntary_exit.ssz", T.SignedVoluntaryExit,
         h.make_exit(state, 6),
         lambda s, op: bulk(PB.process_voluntary_exit, s, op, fork,
                            h.preset, h.spec), case="too_early",
         expect_valid=False)
    if fork >= ForkName.ALTAIR:
        agg = h.sync_aggregate_for(state, int(state.slot))
        emit("sync_aggregate", "sync_aggregate.ssz", T.SyncAggregate, agg,
             lambda s, op: (lambda acc: (PB.process_sync_aggregate(
                 s, op, h.preset, h.spec, T, acc), acc.finish()))(
                 PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)))
    if fork >= ForkName.CAPELLA:
        emit("bls_to_execution_change", "address_change.ssz",
             T.SignedBLSToExecutionChange,
             h.make_bls_to_execution_change(7),
             lambda s, op: (lambda acc: (PB.process_bls_to_execution_change(
                 s, op, h.spec, acc), acc.finish()))(
                 PB.SigAccumulator(PB.SignatureStrategy.VERIFY_BULK)))


def _gen_epoch_processing(root: str, fork: ForkName) -> None:
    h = _harness(fork)
    spe = h.preset.SLOTS_PER_EPOCH
    h.extend_chain(2 * spe)  # into epoch 2 with real participation
    from ..state_transition.per_slot import process_slots
    # advance to the last slot of the epoch (epoch processing is next)
    state = h.state.copy()
    target = (int(state.slot) // spe + 1) * spe - 1
    if int(state.slot) < target:
        state = process_slots(state, target, h.preset, h.spec, h.T)
    steps = _epoch_steps(fork, h.preset, h.spec, h.T)
    cur = state
    for handler, fn in steps.items():
        d = _case(root, "minimal", fork, "epoch_processing", handler,
                  "pyspec_tests", "from_chain")
        _dump_state(d, "pre", cur)
        nxt = cur.copy()
        fn(nxt)
        _dump_state(d, "post", nxt)
        cur = nxt  # EF semantics: each step's pre has prior steps applied


def _gen_ssz_static(root: str, fork: ForkName) -> None:
    h = _harness(fork)
    h.extend_chain(2)
    T = h.T
    sb = h.build_block()
    values = {
        "BeaconState": (T.state_cls(fork), h.state),
        "SignedBeaconBlock": (type(sb), sb),
        "BeaconBlock": (T.block_cls(fork), sb.message),
        "Attestation": (T.Attestation,
                        h.attestations_for_slot(h.state,
                                                int(h.state.slot) - 1)[0]),
        "Checkpoint": (T.Checkpoint, h.state.finalized_checkpoint),
        "Validator": (None, None),  # filled below
        "Fork": (T.Fork, h.state.fork),
        "BeaconBlockHeader": (T.BeaconBlockHeader,
                              h.state.latest_block_header),
    }
    from ..types.validators import Validator
    values["Validator"] = (Validator, h.state.validators[0])
    for name, (cls, value) in values.items():
        d = _case(root, "minimal", fork, "ssz_static", name, "ssz_minimal",
                  "case_0")
        enc = cls.serialize(value)
        _write(os.path.join(d, "serialized.ssz"), enc)
        _write_yaml(os.path.join(d, "roots.yaml"),
                    {"root": "0x" + cls.hash_tree_root(value).hex()})


def _gen_shuffling(root: str, fork: ForkName) -> None:
    if fork != ForkName.PHASE0:
        return
    for i, count in enumerate((1, 7, 64)):
        seed = bytes([i]) * 32
        mapping = shuffle_list(np.arange(count, dtype=np.uint64), seed,
                               MINIMAL.SHUFFLE_ROUND_COUNT)
        d = _case(root, "minimal", fork, "shuffling", "core", "shuffle",
                  f"shuffle_0x{seed[:2].hex()}_{count}")
        _write_yaml(os.path.join(d, "mapping.yaml"), {
            "seed": "0x" + seed.hex(),
            "count": count,
            "mapping": [int(x) for x in mapping],
        })


def _gen_bls(root: str) -> None:
    fork = ForkName.PHASE0

    def case(handler: str, name: str, inp, out) -> None:
        d = _case(root, "general", fork, "bls", handler, "small", name)
        _write_yaml(os.path.join(d, "data.yaml"),
                    {"input": inp, "output": out})

    sks = [B.SecretKey(i + 1) for i in range(4)]
    pks = [sk.public_key() for sk in sks]
    msgs = [bytes([i]) * 32 for i in range(4)]
    sigs = [sk.sign(m) for sk, m in zip(sks, msgs)]

    def hx(b: bytes) -> str:
        return "0x" + b.hex()

    case("sign", "sign_case_0",
         {"privkey": hx(sks[0].serialize()), "message": hx(msgs[0])},
         hx(sigs[0].serialize()))
    case("verify", "verify_valid",
         {"pubkey": hx(pks[0].serialize()), "message": hx(msgs[0]),
          "signature": hx(sigs[0].serialize())}, True)
    case("verify", "verify_wrong_message",
         {"pubkey": hx(pks[0].serialize()), "message": hx(msgs[1]),
          "signature": hx(sigs[0].serialize())}, False)
    case("verify", "verify_infinity_pubkey",
         {"pubkey": hx(b"\xc0" + b"\x00" * 47), "message": hx(msgs[0]),
          "signature": hx(sigs[0].serialize())}, False)
    agg = B.aggregate_signatures(sigs)
    case("aggregate", "aggregate_4",
         [hx(s.serialize()) for s in sigs], hx(agg.serialize()))
    case("aggregate_verify", "aggregate_verify_valid",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "messages": [hx(m) for m in msgs],
          "signature": hx(agg.serialize())}, True)
    case("aggregate_verify", "aggregate_verify_tampered",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "messages": [hx(m) for m in reversed(msgs)],
          "signature": hx(agg.serialize())}, False)
    same = [sk.sign(msgs[0]) for sk in sks]
    fagg = B.aggregate_signatures(same)
    case("fast_aggregate_verify", "fast_valid",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "message": hx(msgs[0]), "signature": hx(fagg.serialize())}, True)
    case("fast_aggregate_verify", "fast_no_pubkeys",
         {"pubkeys": [], "message": hx(msgs[0]),
          "signature": hx(b"\xc0" + b"\x00" * 95)}, False)
    from ..crypto import curve as C
    agg_pk = B.aggregate_public_keys(pks)
    case("eth_aggregate_pubkeys", "aggregate_pubkeys_4",
         [hx(p.serialize()) for p in pks], hx(C.g1_compress(agg_pk)))
    case("batch_verify", "batch_valid",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "messages": [hx(m) for m in msgs],
          "signatures": [hx(s.serialize()) for s in sigs]}, True)
    case("batch_verify", "batch_one_bad",
         {"pubkeys": [hx(p.serialize()) for p in pks],
          "messages": [hx(m) for m in msgs],
          "signatures": [hx(s.serialize())
                         for s in [sigs[1]] + sigs[1:]]}, False)


def generate(root: str) -> None:
    """Write the full tree under ``root`` (idempotent: wipes first)."""
    import shutil
    tests = os.path.join(root, "tests")
    if os.path.isdir(tests):
        shutil.rmtree(tests)
    prev = B.get_backend().name
    B.set_backend("python")
    try:
        for fork in GEN_FORKS:
            _gen_sanity(root, fork)
            _gen_operations(root, fork)
            _gen_epoch_processing(root, fork)
            _gen_ssz_static(root, fork)
            _gen_shuffling(root, fork)
        _gen_bls(root)
    finally:
        B.set_backend(prev)
