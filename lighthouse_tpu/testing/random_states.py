"""Randomized beacon-state builder for differential testing.

Produces structurally valid altair+ states with adversarial corners the
EF-style harness chains never reach — zero balances, slashed validators,
huge inactivity scores, exit/withdrawable epochs in every phase — so the
vectorized state-transition paths can be diffed bit-for-bit against the
scalar oracle over a hostile input distribution
(``scripts/validate_transition.py`` and ``tests/test_vectorized_transition``).
"""

from __future__ import annotations

import numpy as np

FAR_FUTURE = 2 ** 64 - 1


def random_epoch_state(rng: np.random.Generator, n: int, T, preset, fork):
    """A random state parked on the last slot of a random epoch (the
    process_epoch entry shape)."""
    from ..types.validators import ValidatorRegistry

    state = T.state_cls(fork)()
    reg = ValidatorRegistry(n)
    reg._n = n
    exit_epoch = np.full(n, FAR_FUTURE, dtype=np.uint64)
    exiting = rng.random(n) < 0.1
    exit_epoch[exiting] = rng.integers(4, 16, int(exiting.sum()))
    wd_epoch = np.full(n, FAR_FUTURE, dtype=np.uint64)
    wd = rng.random(n) < 0.2
    wd_epoch[wd] = rng.integers(4, 24, int(wd.sum()))
    reg.init_columns(
        pubkey=rng.integers(0, 256, (n, 48), dtype=np.uint8),
        withdrawal_credentials=rng.integers(0, 256, (n, 32), dtype=np.uint8),
        effective_balance=(rng.integers(0, 33, n) * 10 ** 9).astype(
            np.uint64),
        slashed=rng.random(n) < 0.05,
        activation_epoch=rng.integers(0, 12, n).astype(np.uint64),
        exit_epoch=exit_epoch,
        withdrawable_epoch=wd_epoch)
    state.validators = reg
    state.balances = rng.integers(0, 40 * 10 ** 9, n).astype(np.uint64)
    state.previous_epoch_participation = rng.integers(0, 8, n).astype(np.uint8)
    state.current_epoch_participation = rng.integers(0, 8, n).astype(np.uint8)
    scores = rng.integers(0, 200, n).astype(np.uint64)
    scores[rng.random(n) < 0.02] = np.uint64(2 ** 63)  # adversarial tails
    state.inactivity_scores = scores
    # Avoid sync-committee-update boundaries: the random pubkeys are not
    # valid G1 points, and (epoch+1) % EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0
    # would make process_epoch aggregate them.
    period = preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    epoch = int(rng.integers(2, 10))
    while (epoch + 1) % period == 0:
        epoch += 1
    state.slot = epoch * preset.SLOTS_PER_EPOCH + preset.SLOTS_PER_EPOCH - 1
    state.finalized_checkpoint = T.Checkpoint(
        epoch=max(epoch - int(rng.integers(1, 6)), 0), root=b"\x01" * 32)
    state.previous_justified_checkpoint = T.Checkpoint(
        epoch=max(epoch - 2, 0), root=b"\x01" * 32)
    state.current_justified_checkpoint = T.Checkpoint(
        epoch=epoch - 1, root=b"\x02" * 32)
    bits = state.justification_bits
    bits[:] = rng.random(4) < 0.5
    return state


def diff_states(tag: str, got, want) -> list:
    """Human-readable list of every mismatching column/field (empty when
    the post-states are bit-identical)."""
    reg_columns = ("pubkey", "withdrawal_credentials", "effective_balance",
                   "slashed", "activation_eligibility_epoch",
                   "activation_epoch", "exit_epoch", "withdrawable_epoch")
    out = []
    for col in reg_columns:
        g, w = got.validators.col(col), want.validators.col(col)
        if g.shape != w.shape:
            out.append(f"validators.{col}: {g.shape} vs {w.shape}")
        elif not np.array_equal(g, w):
            bad = np.flatnonzero(~np.all(np.atleast_2d(g == w), axis=-1))
            out.append(f"validators.{col}: mismatch at {bad[:8]}")
    for field in ("balances", "inactivity_scores",
                  "previous_epoch_participation",
                  "current_epoch_participation"):
        g = np.asarray(getattr(got, field))
        w = np.asarray(getattr(want, field))
        if g.shape != w.shape:
            out.append(f"{field}: {g.shape} vs {w.shape}")
        elif not np.array_equal(g, w):
            out.append(f"{field}: mismatch at {np.flatnonzero(g != w)[:8]}")
    if type(got).serialize(got) != type(want).serialize(want):
        out.append(f"serialized state differs (root "
                   f"{got.tree_hash_root().hex()[:16]} vs "
                   f"{want.tree_hash_root().hex()[:16]})")
    return [f"[{tag}] {line}" for line in out]
