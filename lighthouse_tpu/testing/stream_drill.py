"""Shared hostile-drill driver for the streaming verification service.

One implementation of the drill the acceptance criterion describes —
replay a deterministic message stream (steady arrivals + gossip bursts)
through a :class:`~lighthouse_tpu.beacon_chain.verification_service.
VerificationService` with seeded fault injection on the device-dispatch
site, then account for every message — used by BOTH
``scripts/validate_stream_verify.py`` (CLI, exit-code contract) and
``bench.py``'s ``stream_verify`` row (p50/p99 vs SLO, batch-size
histogram, shed/fallback counts), so the number the bench reports is the
number the validator checks.

The drill's claim is *zero valid messages lost*: every submitted message
completes verified — on the device path, after a retry, on a half-open
probe, or on the host-fallback path while the circuit breaker is open —
and nothing is shed or rejected.  ``run_drill`` raises nothing on loss;
it reports ``lost`` / ``zero_loss`` and leaves the verdict to callers.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from .faults import FaultInjector, burst_schedule


def build_sets(n: int, *, keys_per_set: int = 1, real_keys: bool = False,
               seed: int = 0):
    """``n`` valid single-message SignatureSets.  ``real_keys`` signs
    for real (interop-style secrets — the python/tpu backends verify
    them); otherwise the sets are structural stand-ins the fake backend
    accepts (non-infinity signature, non-empty key list)."""
    from ..crypto import bls

    if not real_keys:
        sig = bls.Signature((0, 0))
        pks = [bls.PublicKey((1 + i, 2)) for i in range(keys_per_set)]
        return [bls.SignatureSet(sig, list(pks), b"drill-%05d" % i)
                for i in range(n)]
    from ..crypto.fields import R
    sk_ints = [0x20000 + 13 * (seed + 1) + 7 * i
               for i in range(keys_per_set)]
    sks = [bls.SecretKey(v) for v in sk_ints]
    pks = [k.public_key() for k in sks]
    agg = bls.SecretKey(sum(sk_ints) % R)
    out = []
    for i in range(n):
        m = b"drill-%05d" % i
        out.append(bls.SignatureSet(agg.sign(m), list(pks), m))
    return out


def run_drill(*, n_messages: int = 96, rate_per_s: float = 200.0,
              burst_every: int = 16, burst_size: int = 8,
              fail_rate: float = 0.10,
              outage: Optional[Tuple[int, int]] = None,
              h2d_stall: Tuple[float, float] = (0.0, 0.0),
              slo_ms: float = 250.0, max_batch: int = 32,
              keys_per_set: int = 1, backend: Optional[str] = None,
              real_keys: bool = False, realtime: bool = True,
              dispatch_model_ms: Optional[Tuple[float, float]] = None,
              aggregate_every: int = 8, seed: int = 0,
              retries: int = 2, breaker_threshold: int = 3,
              probe_cooldown_s: float = 0.05,
              backoff_base_s: float = 0.01,
              recovery_tail: int = 8) -> dict:
    """Run one drill and return the full accounting dict.

    ``backend``            switch the active bls backend for the drill
                           (restored after); None keeps the current one.
    ``dispatch_model_ms``  ``(base, per_set)`` — replace the backend
                           dispatch with a modeled fixed-cost verify
                           (sleep base + per_set·|sets| ms, then
                           structural validity).  The bench row uses
                           this: it measures the SERVICE's batching /
                           resilience policy, not crypto throughput
                           (the bls rows own that number).
    ``realtime``           honor inter-arrival gaps against the wall
                           clock (p50/p99 then measure the SLO policy);
                           False replays the stream compressed.
    ``outage``             (start, stop) per-site dispatch sequence
                           window where EVERY device attempt fails —
                           the sustained-outage scenario that must trip
                           the breaker and route to host.
    ``recovery_tail``      after the main stream, disarm injection and
                           trickle this many extra messages so the
                           half-open probe has traffic to ride — the
                           drill ends with the breaker re-closed and
                           traffic back on the device (``recovered`` in
                           the result).  0 skips the tail.
    """
    from ..crypto import bls

    prev_backend = bls.get_backend()
    if backend is not None:
        bls.set_backend(backend)
    try:
        inj = FaultInjector(seed=seed)
        plan_kw: dict = {}
        if fail_rate > 0:
            plan_kw["fail_rate"] = fail_rate
        if outage is not None:
            plan_kw["outage"] = tuple(outage)
        if plan_kw:
            inj.plan("bls_dispatch", **plan_kw)
        if h2d_stall[0] > 0:
            inj.plan("h2d", stall_rate=h2d_stall[0], stall_s=h2d_stall[1])

        from ..beacon_chain.verification_service import VerificationService

        device_verify = None
        if dispatch_model_ms is not None:
            base_s = dispatch_model_ms[0] / 1e3
            per_s = dispatch_model_ms[1] / 1e3

            def device_verify(sets):  # noqa: F811 — the modeled dispatch
                time.sleep(base_s + per_s * len(sets))
                return all(s.signature is not None and s.signing_keys
                           for s in sets)

        svc = VerificationService(
            slo_ms=slo_ms, max_batch=max_batch, retries=retries,
            backoff_base_s=backoff_base_s,
            breaker_threshold=breaker_threshold,
            probe_cooldown_s=probe_cooldown_s, seed=seed, faults=inj,
            device_verify=device_verify, name="drill")

        sets = build_sets(n_messages, keys_per_set=keys_per_set,
                          real_keys=real_keys, seed=seed)
        offsets = burst_schedule(n_messages, rate_per_s,
                                 burst_every=burst_every,
                                 burst_size=burst_size, seed=seed)
        offsets = offsets[:n_messages]

        results = []
        t_start = time.monotonic()
        for i, off in enumerate(offsets):
            if realtime:
                while True:
                    svc.pump()  # SLO-due buckets dispatch while we wait
                    now = time.monotonic() - t_start
                    if off <= now:
                        break
                    time.sleep(min(0.002, off - now))
            kind = ("aggregate" if aggregate_every > 0
                    and i % aggregate_every == 0 else "attestation")
            svc.submit(kind, [sets[i]],
                       on_result=lambda ok, path: results.append((ok, path)))
            if not realtime and i % max_batch == max_batch - 1:
                svc.pump()
        svc.flush()

        # Recovery tail: the stream may end mid-outage with the breaker
        # open — disarm injection and trickle a few more messages so the
        # half-open probe has traffic to ride and the drill can assert
        # the device RESUMED, not just that host fallback carried it.
        n_tail = 0
        if recovery_tail > 0 and plan_kw:
            inj.disarm("bls_dispatch")
            tail_sets = build_sets(recovery_tail,
                                   keys_per_set=keys_per_set,
                                   real_keys=real_keys, seed=seed + 1)
            deadline = time.monotonic() + max(
                5.0, 20 * probe_cooldown_s)
            while n_tail < recovery_tail:
                svc.submit("attestation", [tail_sets[n_tail]],
                           on_result=lambda ok, path:
                           results.append((ok, path)))
                n_tail += 1
                time.sleep(svc.envelope.breaker.cooldown_s)
                svc.flush()
                if svc.envelope.breaker.state == "closed" \
                        and n_tail >= min(2, recovery_tail):
                    break
                if time.monotonic() > deadline:
                    break
        wall_s = time.monotonic() - t_start

        st = svc.stats()
        paths: dict = {}
        for _ok, p in results:
            paths[p] = paths.get(p, 0) + 1
        ok_count = sum(1 for ok, _ in results if ok)
        n_total = n_messages + n_tail
        lost = n_total - ok_count
        return {
            "messages": n_total,
            "stream_messages": n_messages,
            "recovery_tail_messages": n_tail,
            "recovered": svc.envelope.breaker.state == "closed",
            "completed": len(results),
            "verified_ok": ok_count,
            "lost": lost,
            "zero_loss": lost == 0 and st["shed"] == 0
            and st["rejected"] == 0,
            "result_paths": paths,
            "wall_s": round(wall_s, 3),
            "slo_ms": st["slo_ms"],
            "latency_p50_ms": st["latency_p50_ms"],
            "latency_p99_ms": st["latency_p99_ms"],
            "latency_max_ms": st["latency_max_ms"],
            "slo_violations": st["slo_violations"],
            "batch_size_hist": st["batch_size_hist"],
            "dispatches": st["dispatches"],
            "splits": st["splits"],
            "shed": st["shed"],
            "rejected": st["rejected"],
            "envelope": st["bls"],
            "injector": inj.stats(),
            "pipeline": st["pipeline"],
        }
    finally:
        if backend is not None:  # only restore when we actually switched
            bls.set_backend(getattr(prev_backend, "name", "python"))
