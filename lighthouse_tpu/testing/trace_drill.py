"""One traced slot, end to end — the shared driver behind
``scripts/trace_slot.py`` and the tracing test suite.

Drives a single in-process node through one full slot of the real
pipeline — gossip block arrival → gossip-verify → (streamed) attestation
verification → state transition → fork-choice apply → head — with the
tracer enabled, and returns the assembled slot trace.  This is the
CI-able completeness check for the instrumentation itself: if a future
refactor drops a pipeline stage's spans, :func:`drive_traced_slot`
reports it in ``missing_stages``.

The drill toggles the process-global tracer and ambient slot: run it in
a dedicated process (the script, tests), never inside a live node with
concurrent traced traffic.
"""

from __future__ import annotations

from typing import Tuple

from ..beacon_chain import BeaconChain
from ..common.tracing import PIPELINE_STAGES, TRACER
from ..network import GossipBus, NetworkNode
from ..state_transition.per_slot import process_slots
from ..store import HotColdDB
from ..types.presets import MINIMAL


def drive_traced_slot(n_validators: int = 16, n_atts: int = 4,
                      device: bool = False, ring: int = 8,
                      ) -> Tuple[dict, dict]:
    """Run one simulated slot with tracing on.

    Returns ``(trace, info)``: the assembled slot-trace dict (spans +
    ``missing_stages``) and a small info dict (slot, counters, chrome
    trace).  ``device=False`` pins the fake BLS backend (host logic
    only — quick-tier safe); ``device=True`` leaves the configured
    backend in place so device dispatches are traced for real.
    """
    from ..crypto import bls
    from .harness import StateHarness

    prev_backend = next(
        k for k, v in bls._BACKENDS.items() if v is bls.get_backend())
    if not device:
        bls.set_backend("fake")
    was_enabled = TRACER.enabled
    prev_ring = TRACER.max_slots
    # The drill toggles the PROCESS tracer (off for prep, on for the
    # drive) and sets the ambient slot through its chain tick — it is a
    # dedicated-process driver (scripts/trace_slot.py, tests), NOT safe
    # to run inside a live node with concurrent traced traffic.  An
    # already-enabled tracer keeps its ring and previously assembled
    # traces; a previously-disabled one gets the drill's private ring.
    if not was_enabled:
        TRACER.reset()
        TRACER.enable(ring=ring)
    node = None
    try:
        # ALL driver-side prep runs with the tracer state it found the
        # harness in... specifically: block/attestation BUILDING happens
        # off-trace, so the artifact holds only the NODE's pipeline —
        # the harness's own transitions (apply_block, the slot advance
        # that resolves attestation roots) would otherwise land in the
        # same slot bucket and multiply the apparent transition cost.
        TRACER.disable()
        h = StateHarness(n_validators=n_validators, preset=MINIMAL)
        hdr = h.state.latest_block_header.copy()
        hdr.state_root = h.state.tree_hash_root()
        chain = BeaconChain(
            store=HotColdDB.memory(h.preset, h.spec, h.T),
            genesis_state=h.state.copy(),
            genesis_block_root=hdr.tree_hash_root(),
            preset=h.preset, spec=h.spec, T=h.T)
        node = NetworkNode(chain, GossipBus(), name="trace-node")

        slot = 1
        signed = h.build_block(slot=slot)
        h.apply_block(signed)
        adv = process_slots(h.state.copy(), slot + 1, h.preset, h.spec,
                            h.T)
        atts = h.attestations_for_slot(adv, slot)[:max(1, n_atts)]

        # The traced section: ONLY the node's real pipeline.
        TRACER.enable()
        chain.per_slot_task(slot)  # tick → ambient slot scope

        # Block through the REAL gossip path: arrival stamp → processor
        # queue → gossip verify → transition → fork choice → head.
        node._on_gossip_block(signed)
        node.processor.run_until_idle()
        assert chain.head.slot == slot, "traced block failed to import"

        # Attestations for the imported block via the subnet gossip
        # path (the sheddable class → streaming verification service).
        for att in atts:
            subnet = int(att.data.index) % 64
            node.subscribe_subnet(subnet)
            node.publish_attestation_to_subnet(att, subnet)
        node.processor.run_until_idle()  # drains the verify service too

        trace = TRACER.slot_trace(slot) or {
            "slot": slot, "spans": [],
            "missing_stages": list(PIPELINE_STAGES)}
        info = {
            "slot": slot,
            "n_validators": n_validators,
            "attestations_published": len(atts),
            "verify_stats": (chain.verification_service.stats()
                             if chain.verification_service else {}),
            "chrome_trace": TRACER.chrome_trace(slot),
            "summaries": TRACER.slot_summaries(),
        }
        return trace, info
    finally:
        if node is not None:
            node.close()
        TRACER.max_slots = prev_ring
        # Restore from was_enabled even on a prep exception (prep runs
        # with the tracer toggled off — an early raise must not leave an
        # operator-enabled tracer dark for the rest of the process).
        if was_enabled:
            TRACER.enable()
        else:
            TRACER.disable()
        if not device:
            bls.set_backend(prev_backend)
