"""Deterministic, seedable fault injection for the device dispatch path.

The streaming verification service (:mod:`..beacon_chain.
verification_service`) wraps every device dispatch in a resilience
envelope — deadline, retry-with-backoff, circuit breaker, host fallback.
Proving those paths actually fire needs failures on demand, and proving
the *drill* is reproducible needs them deterministic: this module is the
single failure-point registry both the hostile-drill simulator and the
unit tests drive.

Failure points are named **sites** (``"bls_dispatch"``, ``"kzg_dispatch"``,
``"h2d"``); each site carries a :class:`FaultPlan` deciding, per call and
from a seeded PRNG, whether the call

- raises :class:`InjectedFault` (a dispatch failure — the shape of a
  wedged axon tunnel surfacing an ``XlaRuntimeError``),
- stalls for ``stall_s`` before proceeding (an H2D stall; with
  ``stall_s`` above the envelope's deadline this is the deadline-blowout
  scenario), or
- proceeds untouched.

An ``outage`` window fails EVERY call whose per-site sequence number
falls inside ``[start, stop)`` — the sustained-outage scenario that must
trip the circuit breaker — independent of the random ``fail_rate``
(which models intermittent 1-in-N faults).  All decisions come from one
``random.Random(seed)``, so a drill replays bit-identically.

Usage::

    inj = FaultInjector(seed=7, plans={
        "bls_dispatch": FaultPlan(fail_rate=0.1, outage=(20, 35)),
        "h2d": FaultPlan(stall_rate=0.05, stall_s=0.2),
    })
    service = VerificationService(..., faults=inj)

The injector also generates the *traffic* side of a drill:
:func:`burst_schedule` produces deterministic message arrival offsets
(steady rate + gossip bursts) shared by ``scripts/validate_stream_verify
.py`` and the hostile-drill test.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


class InjectedFault(RuntimeError):
    """A deliberately injected device-dispatch failure."""


@dataclass
class FaultPlan:
    """Per-site failure policy.

    ``fail_rate``   — P(raise InjectedFault) per call (intermittent).
    ``outage``      — (start, stop) half-open window of per-site call
                      sequence numbers that ALL fail (sustained outage).
    ``stall_rate``  — P(sleep ``stall_s`` before proceeding).
    ``stall_s``     — stall duration; combined with an envelope deadline
                      shorter than this it becomes a deadline blowout.
    ``fail_first``  — fail the first N calls unconditionally (a cold
                      start / compile-stall shape).
    """
    fail_rate: float = 0.0
    outage: Optional[Tuple[int, int]] = None
    stall_rate: float = 0.0
    stall_s: float = 0.0
    fail_first: int = 0


class FaultInjector:
    """Seeded failure-point registry; thread-safe (the beacon processor
    dispatches from worker threads)."""

    def __init__(self, seed: int = 0,
                 plans: Optional[Dict[str, FaultPlan]] = None,
                 sleep=time.sleep):
        self._rng = random.Random(seed)
        self.plans: Dict[str, FaultPlan] = dict(plans or {})
        self._sleep = sleep
        self._lock = threading.Lock()
        self.calls: Dict[str, int] = {}     # per-site sequence counter
        self.injected: Dict[str, int] = {}  # per-site raises
        self.stalls: Dict[str, int] = {}    # per-site stalls

    def plan(self, site: str, **kw) -> None:
        """(Re)arm a site — drills flip plans mid-run (outage → recovery)."""
        with self._lock:
            self.plans[site] = FaultPlan(**kw)

    def disarm(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self.plans.clear()
            else:
                self.plans.pop(site, None)

    def check(self, site: str) -> None:
        """One failure-point decision.  Raises or stalls per the site's
        plan; always counts the call."""
        with self._lock:
            seq = self.calls.get(site, 0)
            self.calls[site] = seq + 1
            plan = self.plans.get(site)
            if plan is None:
                return
            # All PRNG draws happen under the lock, in call order — the
            # determinism contract.
            fail = seq < plan.fail_first
            if plan.outage is not None \
                    and plan.outage[0] <= seq < plan.outage[1]:
                fail = True
            if not fail and plan.fail_rate > 0:
                fail = self._rng.random() < plan.fail_rate
            stall = (not fail and plan.stall_rate > 0
                     and self._rng.random() < plan.stall_rate)
        if fail:
            with self._lock:
                self.injected[site] = self.injected.get(site, 0) + 1
            raise InjectedFault(f"injected fault at {site} (call #{seq})")
        if stall:
            with self._lock:
                self.stalls[site] = self.stalls.get(site, 0) + 1
            self._sleep(plan.stall_s)

    def wrap(self, site: str, fn):
        """``fn`` with this site's failure point in front of it."""
        def wrapped(*args, **kw):
            self.check(site)
            return fn(*args, **kw)
        return wrapped

    def stage_wrapper(self, stage_fn):
        """H2D failure point for a ``StagedExecutor(stage=...)`` seam:
        the staging call (async ``device_put``) checks the ``"h2d"``
        site first, so a plan there produces staging failures the
        executor's sync-retry path must absorb."""
        def staged(host):
            self.check("h2d")
            return stage_fn(host)
        return staged

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {"calls": dict(self.calls),
                    "injected": dict(self.injected),
                    "stalls": dict(self.stalls)}


def burst_schedule(n: int, rate_per_s: float, *,
                   burst_every: int = 0, burst_size: int = 0,
                   seed: int = 0) -> List[float]:
    """Deterministic arrival offsets (seconds) for a drill's message
    stream: Poisson-ish steady arrivals at ``rate_per_s``, plus, every
    ``burst_every`` messages, ``burst_size`` extra arrivals at the same
    instant (the gossip-burst shape: a whole committee's attestations
    landing in one mesh flush).  Sorted ascending; length ≥ ``n``."""
    rng = random.Random(seed)
    out: List[float] = []
    t = 0.0
    i = 0
    while len(out) < n:
        t += rng.expovariate(rate_per_s) if rate_per_s > 0 else 0.0
        out.append(t)
        i += 1
        if burst_every > 0 and i % burst_every == 0:
            out.extend([t] * burst_size)
    out.sort()
    return out
