"""Randomized fork-choice differential fuzzer.

Drives the host :class:`~lighthouse_tpu.fork_choice.ProtoArrayForkChoice`
(the bit-for-bit oracle) and the columnar
:class:`~lighthouse_tpu.fork_choice.DeviceProtoArrayForkChoice` through one
shuffled interleaving of

    block inserts (random parents, disconnected roots, FFG mismatches) ·
    attestation batches (random subsets/targets/epochs, stale re-votes) ·
    equivocations · payload invalidation/validation · pruning ·
    head rounds (random balances, proposer boost, checkpoint flips)

and asserts the full observable state is identical after every head round:
the head itself (or the identical error), per-node weights, best-child/
best-descendant links, the latest-message vote columns, persisted
balances, and the index map.  Used by both
``scripts/validate_fork_choice.py`` and the quick-tier differential tests.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..fork_choice.device_proto_array import DeviceProtoArrayForkChoice
from ..fork_choice.proto_array import (
    EXEC_OPTIMISTIC,
    ProtoArrayError,
    ProtoArrayForkChoice,
    ZERO_ROOT,
)


def _root(i: int) -> bytes:
    return int(i).to_bytes(4, "little") + b"\xab" * 28


class MismatchError(AssertionError):
    pass


def _call_both(host_fn, dev_fn, label: str):
    """Run the same op on both sides; identical results OR identical
    errors are required."""
    he = de = None
    hr = dr = None
    try:
        hr = host_fn()
    except ProtoArrayError as e:
        he = str(e)
    try:
        dr = dev_fn()
    except ProtoArrayError as e:
        de = str(e)
    if he != de:
        raise MismatchError(f"{label}: host error {he!r} vs device {de!r}")
    return hr, dr, he


class DifferentialRun:
    """One seeded interleaving.  ``engine`` selects the columnar engine
    ("numpy" or "jit"); mismatches raise :class:`MismatchError`."""

    def __init__(self, seed: int, *, n_validators: int = 64,
                 engine: str = "numpy", prune_threshold: int = 4,
                 max_nodes: Optional[int] = None,
                 chain_bias: float = 0.0,
                 jit_max_depth: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.nv = n_validators
        self.max_nodes = max_nodes
        self.chain_bias = chain_bias  # P(new block extends the last tip)
        self.last_root: Optional[bytes] = None
        self.host = ProtoArrayForkChoice(prune_threshold=prune_threshold)
        self.dev = DeviceProtoArrayForkChoice(
            prune_threshold=prune_threshold, engine=engine,
            jit_max_depth=jit_max_depth)
        self.anchor = _root(0)
        self.next_id = 1
        self.slot = 1
        self.jcp = (1, _root(0))
        self.fcp = (1, _root(0))
        self.head_rounds = 0
        for pa in (self.host, self.dev):
            pa.on_block(slot=0, root=self.anchor, parent_root=ZERO_ROOT,
                        state_root=self.anchor, justified_epoch=1,
                        justified_root=_root(0), finalized_epoch=1,
                        finalized_root=_root(0),
                        execution_status=EXEC_OPTIMISTIC)

    # -- ops -----------------------------------------------------------------

    def _known_roots(self) -> List[bytes]:
        return list(self.host.indices.keys())

    def _pick_root(self) -> bytes:
        roots = self._known_roots()
        return roots[int(self.rng.integers(len(roots)))]

    def op_block(self) -> None:
        if self.max_nodes is not None \
                and len(self.host.nodes) >= self.max_nodes:
            return
        root = _root(self.next_id)
        self.next_id += 1
        self.slot += 1
        if self.last_root is not None \
                and self.rng.random() < self.chain_bias \
                and self.last_root in self.host.indices:
            parent = self.last_root  # chain-shaped growth (non-finality)
        elif self.rng.random() < 0.06:
            parent = _root(10_000_000 + self.next_id)  # unknown: new root
        else:
            parent = self._pick_root()
        self.last_root = root
        je, jr = (2, _root(0)) if self.rng.random() < 0.15 else (1, _root(0))
        for pa in (self.host, self.dev):
            pa.on_block(slot=self.slot, root=root, parent_root=parent,
                        state_root=root, justified_epoch=je,
                        justified_root=jr, finalized_epoch=1,
                        finalized_root=_root(0),
                        execution_status=EXEC_OPTIMISTIC)

    def op_attestation(self) -> None:
        k = int(self.rng.integers(1, 9))
        vals = self.rng.choice(self.nv, size=k, replace=False).astype(
            np.int64)
        epoch = int(self.rng.integers(0, 7))
        if self.rng.random() < 0.05:
            target = _root(20_000_000)  # unknown target: identical raise
        else:
            target = self._pick_root()
        batch = [(vals, target, epoch)]
        _call_both(lambda: self.host.process_attestation_batch(batch),
                   lambda: self.dev.process_attestation_batch(batch),
                   "attestation")

    def op_equivocation(self) -> None:
        v = int(self.rng.integers(self.nv))
        for pa in (self.host, self.dev):
            pa.process_equivocation(v)

    def op_invalidate(self) -> None:
        root = self._pick_root()
        if root == self.anchor:
            return  # keep the walk productive: a dead anchor ends heads
        for pa in (self.host, self.dev):
            pa.on_invalid_execution_payload(root)

    def op_validate(self) -> None:
        root = self._pick_root()
        _call_both(lambda: self.host.on_valid_execution_payload(root),
                   lambda: self.dev.on_valid_execution_payload(root),
                   "on_valid")

    def op_prune(self) -> None:
        root = self._pick_root()
        for pa in (self.host, self.dev):
            pa.maybe_prune(root)
        if root in self.host.indices \
                and self.host.indices[root] == 0:
            self.anchor = root

    def op_head(self) -> None:
        bal = self.rng.integers(0, 100, self.nv).astype(np.uint64)
        boost_root, boost_score = ZERO_ROOT, 0
        if self.rng.random() < 0.3:
            boost_root = self._pick_root()
            boost_score = int(self.rng.integers(0, 50))
        if self.rng.random() < 0.1:
            self.jcp = (2, _root(0)) if self.jcp[0] == 1 else (1, _root(0))

        def run(pa):
            deltas = pa.compute_deltas(bal.copy())
            pa.apply_score_changes(deltas, self.jcp, self.fcp,
                                   boost_root, boost_score, self.slot)
            return pa.find_head(self.anchor, self.slot)

        hh, dh, err = _call_both(lambda: run(self.host),
                                 lambda: run(self.dev), "head")
        if err is None and hh != dh:
            raise MismatchError(
                f"head mismatch: {hh.hex()[:8]} vs {dh.hex()[:8]}")
        self.head_rounds += 1
        self.compare_state()

    # -- differential --------------------------------------------------------

    def compare_state(self) -> None:
        host, dev = self.host, self.dev
        if host.indices != dev.indices:
            raise MismatchError("indices diverged")
        n = len(host.nodes)
        cols = dev.cols
        if cols.n != n:
            raise MismatchError("node count diverged")
        for i, node in enumerate(host.nodes):
            got = (int(cols.weight[i]),
                   None if cols.best_child[i] < 0
                   else int(cols.best_child[i]),
                   None if cols.best_desc[i] < 0
                   else int(cols.best_desc[i]),
                   int(cols.exec_status[i]))
            want = (node.weight, node.best_child, node.best_descendant,
                    node.execution_status)
            if got != want:
                raise MismatchError(
                    f"node {i}: columnar {got} != host {want}")
        dv = dev.votes
        hv = host.votes
        for name in ("current", "next", "next_epoch"):
            a, b = getattr(hv, name), getattr(dv, name)
            if a.shape != b.shape or not np.array_equal(a, b):
                raise MismatchError(f"votes.{name} diverged")
        if not np.array_equal(host.old_balances, dev.old_balances):
            raise MismatchError("old_balances diverged")
        if host.equivocating != dev.equivocating:
            raise MismatchError("equivocating set diverged")

    # -- schedule ------------------------------------------------------------

    def run(self, *, blocks: int = 30, atts: int = 40,
            equivocations: int = 3, invalidations: int = 3,
            validations: int = 3, prunes: int = 2,
            head_rounds: int = 10) -> int:
        """Execute one shuffled interleaving; returns the number of head
        rounds compared."""
        ops = (["block"] * blocks + ["att"] * atts
               + ["equiv"] * equivocations + ["invalid"] * invalidations
               + ["valid"] * validations + ["prune"] * prunes
               + ["head"] * head_rounds)
        self.rng.shuffle(ops)
        fns = {"block": self.op_block, "att": self.op_attestation,
               "equiv": self.op_equivocation,
               "invalid": self.op_invalidate, "valid": self.op_validate,
               "prune": self.op_prune, "head": self.op_head}
        for op in ops:
            fns[op]()
        # Always end on a compared head round.
        self.op_head()
        return self.head_rounds


def run_fuzz(*, seeds, engine: str = "numpy", n_validators: int = 64,
             max_nodes: Optional[int] = None, chain_bias: float = 0.0,
             jit_max_depth: Optional[int] = None, **schedule) -> int:
    """Run one DifferentialRun per seed; returns total compared head
    rounds (raises MismatchError on the first divergence)."""
    total = 0
    for seed in seeds:
        run = DifferentialRun(int(seed), n_validators=n_validators,
                              engine=engine, max_nodes=max_nodes,
                              chain_bias=chain_bias,
                              jit_max_depth=jit_max_depth)
        total += run.run(**schedule)
    return total
