"""Sustained mainnet-cadence load drill — the SLO scoreboard's proving
ground.

The streaming service (PR 7) was drilled with synthetic bursts and the
tracer (PR 9) with single slots; nothing sustained mainnet *shape* —
a block every slot, unaggregated attestations streaming across subnets
all slot long, committee aggregates on cadence — long enough to answer
"does the node keep up?".  This driver does, through the REAL pipeline:
gossip arrival → beacon processor (threaded production mode, so the
manager/worker/idle-pump machinery is what gets measured) → streaming
verification service → fork choice → op pool, with the chain's
:class:`~lighthouse_tpu.common.slo.SloEngine` evaluating continuously
and the slot-trace ring assembling every slot.

Wall-clock slot driver with a **compressed-time mode**: ``slot_s``
scales the slot (tests run 0.25–0.5 s slots; ``--realtime`` in the
validator script uses the spec cadence), and every latency budget
scales with it (per-message SLO = slot/3, like mainnet's intra-slot
attestation deadline).  Message counts scale with the validator set —
the MINIMAL-preset committee structure is the mainnet topology in
miniature (committees × subnets × aggregates), so "mainnet-shape"
means every class of traffic at the rate the validator count implies,
not a literal 1,800 atts/s.

The claim, verified per slot and end-to-end:

- **zero valid-message loss** — every gossiped attester is observed by
  the chain after the slot's drain (the post-verify registration that
  feeds fork choice + op pool), and the service counters account every
  submission (``verified == submitted``, ``rejected == shed == 0``).
- **scoreboard** — per-objective attainment/burn/p50/p99 from the SLO
  engine, health-transition log, shed/fallback counts, per-slot trace
  summaries.
- **fault attribution** — ``faults_outage_slots`` arms a full device
  outage for a slot window; the drill then asserts the health state
  walked degraded→healthy and reports which objectives burned, so a
  violation is attributed to the injected outage instead of
  free-floating.

Used by ``scripts/validate_sustained.py`` (exit-code contract +
scoreboard artifact) and ``bench.py``'s ``sustained_slo`` row.  Like
``trace_drill``, this toggles the process tracer: dedicated-process
driver, not for use inside a live node.
"""

from __future__ import annotations

import threading
import time
import urllib.request
from typing import List, Optional, Tuple

import numpy as np

from ..api.http_api import HttpApiServer
from ..beacon_chain import BeaconChain
from ..common.tracing import TRACER
from ..network import GossipBus, NetworkNode
from ..state_transition.committees import compute_subnet_for_attestation
from ..state_transition.per_slot import process_slots
from ..store import HotColdDB
from ..types.presets import MINIMAL
from .faults import FaultInjector

# Objectives a device outage legitimately drives into burn: the host
# fallback carries the traffic (rate spikes by design) and per-message
# latency absorbs the retry/backoff of the tripping window.  A burn on
# anything else during a fault drill is NOT explained by the injection.
FAULT_ATTRIBUTABLE = ("host_fallback_rate", "gossip_to_verified",
                      "block_import")


def _drain(processor, svc, timeout_s: float = 15.0) -> bool:
    """Slot-end settle for the threaded processor: wait until queues,
    workers and in-flight verdicts are all quiet.  pump(), NOT flush():
    a flush would dispatch not-yet-due buckets early and un-measure the
    wait-till-due batching policy — pending messages become due within
    the service's own SLO, so the loop converges in ≤ that bound while
    every dispatch still fires at the instant the policy chose."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if svc is not None and svc.pending():
            svc.pump()
        if processor.quiescent():
            return True
        time.sleep(0.002)
    return False


def _attesters_of(state, att, preset) -> List[int]:
    from ..beacon_chain.attestation_verification import attesting_indices
    idx, _committee = attesting_indices(state, att, preset)
    return [int(v) for v in idx]


def run_sustained(*, slots: int = 24, slot_s: float = 0.5,
                  n_validators: int = 64,
                  singles_fraction: float = 0.75,
                  aggregates: bool = True,
                  faults_outage_slots: Optional[Tuple[int, int]] = None,
                  seed: int = 0, backend: str = "fake",
                  fast_window_slots: int = 3,
                  slow_window_slots: int = 10,
                  hysteresis: int = 2,
                  warmup_slots: int = 1,
                  max_batch: int = 32,
                  proof_consumers: int = 2) -> dict:
    """Run the drill; returns the scoreboard dict (raises nothing on a
    violated invariant — callers apply the exit-code contract).

    ``singles_fraction`` of each committee streams as single-bit subnet
    attestations; the withheld tail arrives only in the committee
    aggregate (so the never-shed aggregate class carries fresh
    attesters, not pure duplicates).  ``faults_outage_slots`` is a
    half-open ``(start, stop)`` window of 0-based measured-slot indices
    during which EVERY device dispatch of the streaming service fails.
    ``proof_consumers`` threads hammer the light-client bootstrap and
    state-proof HTTP routes for the whole measured run (the serving
    plane under import load — the proof_serve_ms objective's signal)."""
    from ..crypto import bls
    from .harness import StateHarness

    prev_backend = next(
        k for k, v in bls._BACKENDS.items() if v is bls.get_backend())
    if backend is not None:
        bls.set_backend(backend)
    # Recovery-tail slot budget (fault drills): bounded so tail slots
    # can never evict the MEASURED slots from the trace ring — the
    # outage-era traces are exactly what the scoreboard's worst_slots
    # links must still point at after a slow recovery.
    max_tail_slots = fast_window_slots + hysteresis + 6
    was_enabled = TRACER.enabled
    prev_ring = TRACER.max_slots
    ring_needed = slots + warmup_slots + max_tail_slots + 4
    # The ledger's slot-delta ring must also hold the WHOLE run: the
    # budget check walks every measured slot, and a default-sized (64)
    # ring would silently evict the early slots of a long drill —
    # a violation there would never be seen.
    from ..common.device_ledger import LEDGER
    prev_ledger_slots = LEDGER.max_slots
    LEDGER.max_slots = max(prev_ledger_slots, ring_needed)
    # Drills restart slot numbering at genesis: a previous run's ring
    # entries under the SAME slot numbers would be evaluated against
    # this run's budget — start from an empty ring.
    LEDGER.clear_slot_ring()
    if not was_enabled:
        TRACER.reset()
        TRACER.enable(ring=max(ring_needed, prev_ring))
    elif prev_ring < ring_needed:
        # An operator-enabled tracer keeps its assembled slots (never
        # reset a live ring) but must still hold the WHOLE drill —
        # otherwise the outage-era slots the scoreboard's worst_slots
        # links point at are evicted by the tail.  Growing is safe
        # (eviction only happens on overflow); the finally restores
        # prev_ring, which shrinks back lazily as new slots record.
        TRACER.enable(ring=ring_needed)
    node = None
    api_srv = None
    stop_consumers = threading.Event()
    consumer_threads: List[threading.Thread] = []
    proof_counts = {"requests": 0, "errors": 0}
    try:
        # Prep off-trace (trace_drill rule: the harness's own
        # transitions must not pollute the node's slot buckets).
        TRACER.disable()
        h = StateHarness(n_validators=n_validators, preset=MINIMAL)
        genesis_for_catchup = h.state.copy()
        hdr = h.state.latest_block_header.copy()
        hdr.state_root = h.state.tree_hash_root()
        chain = BeaconChain(
            store=HotColdDB.memory(h.preset, h.spec, h.T),
            genesis_state=h.state.copy(),
            genesis_block_root=hdr.tree_hash_root(),
            preset=h.preset, spec=h.spec, T=h.T)
        inj = FaultInjector(seed=seed) if faults_outage_slots else None
        # The service must exist (with the drill's knobs + injector)
        # BEFORE NetworkNode, whose no-kwarg ensure adopts it.  The
        # service's own batching SLO sits at slot/8 — its wait-till-due
        # policy parks sparse messages until ~that deadline by design,
        # so the slot/3 OBJECTIVE needs the batching target well inside
        # the budget (headroom > the processor's 50 ms idle tick).
        chain.ensure_verification_service(
            slo_ms=slot_s * 1e3 / 8.0, max_batch=max_batch,
            retries=1, backoff_base_s=min(0.01, slot_s / 50.0),
            breaker_threshold=3,
            probe_cooldown_s=min(0.05, slot_s / 10.0),
            cooldown_max_s=slot_s, seed=seed, faults=inj)
        node = NetworkNode(chain, GossipBus(), name="sustained")
        node.processor.start()  # production threaded mode
        svc = chain.verification_service

        engine = chain.slo_engine
        engine.enabled = False  # warmup runs un-evaluated (see below)
        # min_eval_interval at 0.6 slots: the driver's explicit
        # post-drain evaluate() is THE one evaluation per slot —
        # per_slot_task's tick (driver + the node's own block-import
        # tick) is rate-limited away, so hysteresis stays sized in
        # SLOTS instead of being halved by double stepping.
        engine.configure(fast_window_s=fast_window_slots * slot_s,
                         slow_window_s=slow_window_slots * slot_s,
                         hysteresis=hysteresis,
                         min_eval_interval_s=0.6 * slot_s)
        # Compressed-time budget: the per-message objective scales with
        # the drill slot exactly like the service's batching SLO does.
        engine.set_budget("gossip_to_verified", slot_s / 3.0)
        # The proposer deadline compresses with the slot too: a block
        # must be produced within the first third (mainnet's broadcast
        # deadline) or the proposal is forfeit.
        engine.set_budget("block_production_ms", slot_s / 3.0)

        from ..validator_client.beacon_node import InProcessBeaconNode
        bn = InProcessBeaconNode(chain)
        production = {"produced": 0, "ms": [], "deadline_misses": [],
                      "pack_divergence": [], "errors": []}

        def _with_pack(value: str, fn):
            # Restore the operator's setting (or its absence) afterwards
            # — the knob steers the whole drill, not just this call.
            # The prior value is read through the registry's raw
            # accessor (knob-registry invariant: env reads live in
            # common/knobs.py only; writes are the drill's to make).
            import os

            from ..common.knobs import _raw
            prior = _raw("LIGHTHOUSE_TPU_DEVICE_PACK")
            os.environ["LIGHTHOUSE_TPU_DEVICE_PACK"] = value
            try:
                return fn()
            finally:
                if prior is None:
                    os.environ.pop("LIGHTHOUSE_TPU_DEVICE_PACK", None)
                else:
                    os.environ["LIGHTHOUSE_TPU_DEVICE_PACK"] = prior

        def produce_lane(slot: int, check_divergence: bool) -> None:
            """The proposer lane: the drill node IS the designated
            proposer every slot — production runs the REAL pipeline
            (adopt pre-advanced state → pack the pool → assemble →
            state-root fill) and is measured against the slot/3
            deadline.  The produced block is discarded (the harness's
            block stays canonical: the lane measures the hot path, it
            must not fork the drill chain).  ``check_divergence``
            additionally packs the same pool through BOTH engines and
            fails the drill on any selection drift — the differential
            oracle riding the live traffic."""
            t_p = time.monotonic()
            try:
                bn.produce_block(slot, b"\x00" * 96)
            except Exception as e:  # noqa: BLE001 — scoreboard signal
                # A production that DIED is worse than a slow one:
                # reported distinctly so the failure names the bug, not
                # a phantom deadline miss.
                production["errors"].append((slot, repr(e)))
                return
            ms = (time.monotonic() - t_p) * 1e3
            production["produced"] += 1
            production["ms"].append(ms)
            if ms > slot_s * 1e3 / 3.0:
                production["deadline_misses"].append(slot)
            if check_divergence:
                st = chain.head.state
                dev = _with_pack("1", lambda: chain.op_pool
                                 .get_attestations(st, chain.T))
                host = _with_pack("0", lambda: chain.op_pool
                                  .get_attestations(st, chain.T))
                if [bytes(a.tree_hash_root()) for a in dev] != \
                        [bytes(a.tree_hash_root()) for a in host]:
                    production["pack_divergence"].append(slot)

        def drive_slot(slot: int, t_slot: Optional[float],
                       fraction: float, with_aggs: bool,
                       expected: Optional[set]) -> dict:
            """One slot of mainnet-shape traffic: block at slot start,
            singles spread through the slot, aggregates at ~3/4 slot,
            then a full drain.  ``t_slot`` None = compressed (no
            pacing sleeps).  Harness-side work (block building, the
            advance that resolves attestation roots, attestation
            construction) runs OFF-trace — the trace_drill rule: the
            artifact must hold only the NODE's pipeline, and on
            epoch-boundary slots the harness's duplicate transitions
            would double the apparent state-transition cost.  Safe to
            toggle the process tracer here: the previous slot fully
            drained, so no node work is concurrent with the window."""
            chain.per_slot_task(slot)
            # Proposer lane first — production runs at slot start on the
            # previous head (mainnet ordering: the proposer builds
            # before its own block arrives over gossip).
            produce_lane(slot, check_divergence=expected is not None)
            tracing = TRACER.enabled
            TRACER.disable()
            try:
                signed = h.build_block(slot=slot, attestations=[],
                                       sync_participation=0.0)
                h.apply_block(signed)
            finally:
                if tracing:
                    TRACER.enable()
            node._on_gossip_block(signed)
            # Attestations for this slot vote the block's root; wait
            # for the import so cheap checks can resolve the head.
            deadline = time.monotonic() + 10.0
            while chain.head.slot < slot \
                    and time.monotonic() < deadline:
                time.sleep(0.002)
            TRACER.disable()
            try:
                adv = process_slots(h.state.copy(), slot + 1, h.preset,
                                    h.spec, h.T)
                singles = h.single_attestations_for_slot(
                    adv, slot, fraction=fraction)
            finally:
                if tracing:
                    TRACER.enable()
            n = len(singles)
            for j, att in enumerate(singles):
                if t_slot is not None:
                    t_arr = t_slot + (0.1 + 0.6 * j / max(n - 1, 1)) \
                        * slot_s
                    wait = t_arr - time.monotonic()
                    if wait > 0:
                        time.sleep(wait)
                subnet = compute_subnet_for_attestation(adv, att.data,
                                                        h.preset)
                node.subscribe_subnet(subnet)
                node.publish_attestation_to_subnet(att, subnet)
                if expected is not None:
                    expected.update(_attesters_of(adv, att, h.preset))
            aggs = h.attestations_for_slot(adv, slot) if with_aggs \
                else []
            if aggs:
                if t_slot is not None:
                    wait = t_slot + 0.75 * slot_s - time.monotonic()
                    if wait > 0:
                        time.sleep(wait)
                node._on_gossip_attestation(aggs)
                if expected is not None:
                    for att in aggs:
                        expected.update(_attesters_of(adv, att,
                                                      h.preset))
            drained = _drain(node.processor, svc)
            return {"singles": n, "aggregates": len(aggs),
                    "drained": drained}

        # Warmup slots: the first block import pays one-off process
        # costs (numpy/jit warmups, cache fills) that are startup
        # artifacts, not steady-state SLO signal.  Run them before the
        # engine's first snapshot so the cumulative-feed diffs exclude
        # them; gossip flows too, warming the verify path.
        for w in range(1, warmup_slots + 1):
            drive_slot(w, None, 0.25, False, None)
        if proof_consumers > 0:
            # Warm the proof plane BEFORE measurement: the first request
            # pays the gather-jit trace + first field-tree materialize —
            # startup artifacts, same rule as the block-import warmup.
            from ..light_client import LightClientServer
            chain.proof_server.state_proof(chain.head.state, [3])
            LightClientServer(chain).bootstrap()
            api_srv = HttpApiServer(chain)
            api_srv.start()
            base = f"http://127.0.0.1:{api_srv.port}"
            # A few always-valid field gindices of the state container
            # (width + index), plus an interior node.
            width = 1
            while width < len(chain.head.state.__class__.FIELDS):
                width *= 2
            gindices = [3, width, width + 1, width + 5,
                        f"{width + 2},{width + 9}"]

            def consume(k: int) -> None:
                i = k
                while not stop_consumers.is_set():
                    root = chain.head.root
                    urls = [
                        f"{base}/eth/v1/beacon/states/head/proof"
                        f"?gindex={gindices[i % len(gindices)]}",
                        f"{base}/eth/v1/beacon/light_client/bootstrap/"
                        f"0x{bytes(root).hex()}",
                    ]
                    url = urls[i % len(urls)]
                    i += 1
                    try:
                        with urllib.request.urlopen(url, timeout=10) as r:
                            r.read()
                        proof_counts["requests"] += 1
                    except Exception:
                        proof_counts["errors"] += 1
                    stop_consumers.wait(slot_s / 8.0)

            for k in range(proof_consumers):
                t = threading.Thread(target=consume, args=(k,),
                                     daemon=True,
                                     name=f"proof-consumer-{k}")
                t.start()
                consumer_threads.append(t)
        engine.enabled = True

        # The measured run.
        TRACER.enable()
        first = warmup_slots + 1
        last = warmup_slots + slots
        counts = {"blocks": 0, "singles": 0, "aggregates": 0}
        missing: List[Tuple[int, int]] = []  # (slot, validator) lost
        drain_timeouts: List[int] = []       # slots whose drain expired
        per_slot: List[dict] = []
        t0 = time.monotonic()
        engine.evaluate()  # baseline snapshot at drill start
        for slot in range(first, last + 1):
            i = slot - first  # 0-based measured-slot index
            t_slot = t0 + i * slot_s
            wait = t_slot - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            if inj is not None:
                start, stop = faults_outage_slots
                if i == start:
                    inj.plan("bls_dispatch", fail_rate=1.0)
                if i == stop:
                    inj.disarm("bls_dispatch")
            expected: set = set()
            sent = drive_slot(slot, t_slot, singles_fraction,
                              aggregates, expected)
            counts["blocks"] += 1
            counts["singles"] += sent["singles"]
            counts["aggregates"] += sent["aggregates"]
            # Loss check: every gossiped attester registered post-verify.
            # Only meaningful after a COMPLETE drain — a drain timeout
            # (box slowness: verdicts still in flight) is its own
            # scoreboard signal, not "loss".
            if not sent["drained"]:
                drain_timeouts.append(slot)
            else:
                epoch = slot // h.preset.SLOTS_PER_EPOCH
                for v in sorted(expected):
                    if not chain.observed_attesters.has_attested(epoch,
                                                                 v):
                        missing.append((slot, v))
            report = engine.evaluate()
            per_slot.append({
                "slot": slot,
                "health": report["state"],
                "burning": report["burning"],
                "pending": svc.pending(),
            })
        wall_s = time.monotonic() - t0

        # Recovery tail: a drill ending mid- or just-post-outage must
        # let the breaker re-close and the fast window clear before the
        # final verdict (the health claim is degraded→healthy, not
        # "degraded at exit").  Disarm first — the tail exists to prove
        # recovery, not to extend the outage.
        if inj is not None:
            inj.disarm("bls_dispatch")
            deadline = time.monotonic() + max(
                5.0, (fast_window_slots + hysteresis + 3) * slot_s)
            tail_slot = last
            while time.monotonic() < deadline \
                    and tail_slot - last < max_tail_slots:
                tail_slot += 1
                res = drive_slot(tail_slot, None, 0.5, False, None)
                if not res["drained"]:
                    # Tail traffic counts in the final service totals:
                    # an expired tail drain must surface as a drain
                    # timeout, not read later as "verified<submitted
                    # loss".
                    drain_timeouts.append(tail_slot)
                report = engine.evaluate()
                if report["state"] == "healthy" \
                        and svc.envelope.breaker.state == "closed":
                    break
                time.sleep(min(slot_s / 2,
                               svc.envelope.breaker.cooldown_s))

        # The fleet stops before the final verdict: its traffic belongs
        # to the measured window, and a consumer mid-request during
        # node.close() would read as a spurious error.
        stop_consumers.set()
        for t in consumer_threads:
            t.join(timeout=5.0)
        if api_srv is not None:
            api_srv.stop()
            api_srv = None

        final = engine.evaluate()
        st = svc.stats()
        # Warm-slot transfer budget (device ledger): close the open
        # ledger slot, then check every MEASURED slot's per-subsystem
        # transfer deltas against the declarative budget — "the hot
        # path went host-roundtrip-shaped" must fail the drill, not
        # hide as a silent 2x regression.  Exported as an SLO-style
        # attainment row next to the engine's objectives.
        from ..common.device_ledger import evaluate_budget
        LEDGER.mark_slot(last + max_tail_slots + 1000)
        measured_deltas = [d for d in LEDGER.slot_deltas()
                           if first <= d["slot"] <= last]
        budget_eval = evaluate_budget(measured_deltas)
        attainments = {
            row["name"]: row["slow"].get("attainment")
            for row in final["objectives"]}
        attainments["device_transfer_budget"] = budget_eval["attainment"]
        zero_loss = (not missing and st["rejected"] == 0
                     and st["shed"] == 0
                     and st["verified"] == st["submitted"])
        # Catch-up lane (batched-replay PR): after the measured run,
        # replay the drill's whole block history onto a fresh genesis
        # copy through the EpochReplayer — the rate a node that missed
        # the run would close the gap at, in the drill's own shape.
        # The per-window decomposition comes through the ONE stage
        # adapter (tracing.stage_split — never the raw timings dict);
        # the cross-shape reference number is bench.py's
        # ``epoch_replay_blocks_per_s`` row.
        catch_up: dict = {"blocks": len(h.blocks)}
        if h.blocks:
            from ..common.tracing import stage_split
            from ..state_transition import EpochReplayer
            try:
                rep = EpochReplayer(genesis_for_catchup.copy(),
                                    h.preset, h.spec, h.T,
                                    verify_signatures=False)
                t0 = time.perf_counter()
                spe = h.preset.SLOTS_PER_EPOCH
                for i in range(0, len(h.blocks), spe):
                    rep.apply_window(h.blocks[i:i + spe])
                catch_s = time.perf_counter() - t0
                catch_up.update({
                    "blocks_per_s": round(len(h.blocks) / catch_s, 1)
                    if catch_s > 0 else None,
                    "wall_s": round(catch_s, 3),
                    "stage": stage_split("replay"),
                    "bench_row": "epoch_replay_blocks_per_s",
                })
            except Exception as e:  # noqa: BLE001 — scoreboard signal
                catch_up["error"] = f"{type(e).__name__}: {e}"
        scoreboard = {
            "config": {
                "slots": slots, "slot_s": slot_s,
                "n_validators": n_validators,
                "singles_fraction": singles_fraction,
                "aggregates": aggregates,
                "faults_outage_slots": (list(faults_outage_slots)
                                        if faults_outage_slots else None),
                "seed": seed, "backend": backend,
                "windows_slots": [fast_window_slots, slow_window_slots],
                "hysteresis": hysteresis,
                "proof_consumers": proof_consumers,
            },
            "wall_s": round(wall_s, 3),
            "rate_atts_per_s": round(
                (counts["singles"] + counts["aggregates"]) / wall_s, 1)
            if wall_s > 0 else None,
            "messages": {**counts,
                         "submitted": st["submitted"],
                         "verified": st["verified"],
                         "rejected": st["rejected"],
                         "shed": st["shed"],
                         "dispatches": st["dispatches"],
                         "splits": st["splits"],
                         "service_slo_violations": st["slo_violations"],
                         "latency_p50_ms": st["latency_p50_ms"],
                         "latency_p99_ms": st["latency_p99_ms"]},
            "loss": {"missing_observed": len(missing),
                     "missing_sample": missing[:8],
                     "drain_timeouts": drain_timeouts,
                     "zero_loss": zero_loss},
            "health": {"state": final["state"],
                       "transitions": final["transitions"],
                       "burning": final["burning"]},
            "objectives": final["objectives"],
            "attainment": attainments,
            "attainment_complete": all(
                a is not None for a in attainments.values()),
            "proof": {
                "consumers": proof_consumers,
                "consumer_requests": proof_counts["requests"],
                "consumer_errors": proof_counts["errors"],
                "server": (chain.proof_server.stats()
                           if proof_consumers > 0 else None),
            },
            "production": {
                "produced": production["produced"],
                "deadline_ms": round(slot_s * 1e3 / 3.0, 3),
                "deadline_misses": production["deadline_misses"],
                "pack_divergence": production["pack_divergence"],
                "errors": production["errors"],
                "p50_ms": round(float(np.percentile(
                    production["ms"], 50)), 3) if production["ms"]
                else None,
                "p99_ms": round(float(np.percentile(
                    production["ms"], 99)), 3) if production["ms"]
                else None,
                "adopted": chain._produce_adopted,
                "serial": chain._produce_serial,
            },
            "catch_up": catch_up,
            "host_fallbacks": st["bls"]["host_fallbacks"],
            "breaker": st["bls"]["breaker"],
            "per_slot": per_slot,
            "trace_slots": TRACER.slot_summaries(),
            "device_budget": {
                "slots_checked": budget_eval["slots_checked"],
                "attainment": budget_eval["attainment"],
                "ok": budget_eval["ok"],
                "violations": [r for r in budget_eval["rows"]
                               if not r["ok"]],
                "ledger": LEDGER.snapshot()["subsystems"],
            },
        }
        if inj is not None:
            burned = set()
            for tr in final["transitions"]:
                burned.update(tr["reasons"])
            stats = inj.stats()
            scoreboard["injector"] = stats
            scoreboard["fault_attribution"] = {
                "injected": stats["injected"].get("bls_dispatch", 0),
                "burned_objectives": sorted(burned),
                "went_degraded": any(tr["to"] != "healthy"
                                     for tr in final["transitions"]),
                "recovered_healthy": final["state"] == "healthy",
                "attributed": (
                    stats["injected"].get("bls_dispatch", 0) > 0
                    and burned.issubset(set(FAULT_ATTRIBUTABLE))),
            }
        return scoreboard
    finally:
        stop_consumers.set()
        for t in consumer_threads:
            t.join(timeout=5.0)
        if api_srv is not None:
            api_srv.stop()
        if node is not None:
            node.close()
        LEDGER.max_slots = prev_ledger_slots
        TRACER.max_slots = prev_ring
        if was_enabled:
            TRACER.enable()
        else:
            TRACER.disable()
            TRACER.reset()
        if backend is not None:
            bls.set_backend(prev_backend)
