"""Multi-node local-network simulator — the role of
``/root/reference/testing/simulator`` (``local_network.rs`` +
``eth1_sim.rs``): N full nodes with wire networking and discovery, the
validator set split across per-node validator clients, a stepped clock,
and assertions on convergence and finalization.

Used by ``tests/test_simulator.py`` and runnable directly:

    python -m lighthouse_tpu.testing.simulator --nodes 3 --slots 12
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..beacon_chain import BeaconChain
from ..network.discovery import BootNode
from ..network.transport import WireNetwork
from ..store import HotColdDB
from ..state_transition.genesis import interop_secret_key
from ..validator_client import (
    InProcessBeaconNode,
    ValidatorClient,
    ValidatorStore,
)


class _GossipingBeaconNode(InProcessBeaconNode):
    """VC-facing node handle that broadcasts productions over the wire
    (the reference VC talks HTTP to its BN, which gossips; in-process we
    splice the gossip in at the same point — `publish_blocks.rs`)."""

    def __init__(self, net: WireNetwork):
        super().__init__(net.node.chain)
        self._net = net

    def publish_block(self, signed_block) -> bytes:
        root = super().publish_block(signed_block)  # own import first
        self._net._wire_block_out(signed_block)
        return root

    def submit_attestations(self, atts: List) -> None:
        super().submit_attestations(atts)
        if atts:
            self._net._wire_atts_out(list(atts))


@dataclass
class SimNode:
    net: WireNetwork
    vc: Optional[ValidatorClient]
    discovery: object

    @property
    def chain(self) -> BeaconChain:
        return self.net.node.chain


class Simulator:
    """``secure=True`` (the default) runs every inter-node TCP byte
    through the noise-xx AEAD channel — the production shape; the CLI's
    ``--insecure`` escape hatch maps to ``secure=False`` for wire-format
    debugging."""

    def __init__(self, n_nodes: int = 3, n_validators: int = 16,
                 preset=None, secure: bool = True,
                 datadir: Optional[str] = None):
        """``datadir`` switches every node's store from in-memory to an
        on-disk SQLite file under ``datadir/node{i}.sqlite`` — the shape
        the crash/restart scenario needs (a SIGKILL'd node's datadir
        survives; :meth:`crash_node` + :meth:`restart_node`)."""
        import os

        from .harness import StateHarness
        from ..store.kv import SqliteStore
        from ..types.presets import MINIMAL

        self.preset = preset or MINIMAL
        self.secure = secure
        self.datadir = datadir
        self.harness = StateHarness(n_validators=n_validators,
                                    preset=self.preset)
        h = self.harness
        hdr = h.state.latest_block_header.copy()
        hdr.state_root = h.state.tree_hash_root()
        genesis_root = hdr.tree_hash_root()
        self.genesis_root = genesis_root

        self.boot = BootNode()
        self.nodes: List[SimNode] = []
        self._down: dict[int, dict] = {}  # crashed nodes awaiting restart
        share = n_validators // n_nodes
        self._node_cfg: List[dict] = []
        for i in range(n_nodes):
            lo = i * share
            hi = n_validators if i == n_nodes - 1 else lo + share
            path = (os.path.join(datadir, f"node{i}.sqlite")
                    if datadir else None)
            self._node_cfg.append({"lo": lo, "hi": hi, "path": path})
            kv = SqliteStore(path) if path else None
            chain = BeaconChain(
                store=(HotColdDB(kv, h.preset, h.spec, h.T) if kv
                       else HotColdDB.memory(h.preset, h.spec, h.T)),
                genesis_state=h.state.copy(),
                genesis_block_root=genesis_root,
                preset=h.preset, spec=h.spec, T=h.T)
            self.nodes.append(self._start_node(i, chain))

    def _start_node(self, i: int, chain: BeaconChain) -> SimNode:
        h = self.harness
        cfg = self._node_cfg[i]
        net = WireNetwork(chain, name=f"node{i}", secure=self.secure)
        disco = net.discover("127.0.0.1", self.boot.port, interval=0.2)
        vstore = ValidatorStore()
        for v in range(cfg["lo"], cfg["hi"]):
            vstore.add_validator(interop_secret_key(v), index=v)
        vc = ValidatorClient(vstore, [_GossipingBeaconNode(net)], h.preset)
        return SimNode(net=net, vc=vc, discovery=disco)

    # -- crash / restart -----------------------------------------------------

    def crash_node(self, i: int) -> None:
        """SIGKILL stand-in: the node's sockets drop and its process
        state evaporates — ``persist=False`` means NOTHING beyond the
        already-committed atomic import batches reaches the store.  The
        datadir (SQLite file) survives for :meth:`restart_node`."""
        node = self.nodes[i]
        node.discovery.close()
        node.net.close(persist=False)
        node.chain.store.kv.close()
        self._down[i] = {"cfg": self._node_cfg[i]}
        self.nodes[i] = None  # type: ignore[assignment]

    def restart_node(self, i: int) -> SimNode:
        """Boot a fresh node from the crashed node's datadir: resume +
        startup recovery rebuild the chain at exactly the last committed
        import; range sync then catches it up to its peers."""
        from ..store.kv import SqliteStore

        assert i in self._down, "node was not crashed"
        cfg = self._down.pop(i)["cfg"]
        assert cfg["path"], "restart requires an on-disk datadir"
        h = self.harness
        kv = SqliteStore(cfg["path"])
        store = HotColdDB(kv, h.preset, h.spec, h.T)
        chain = BeaconChain.from_store(store=store, preset=h.preset,
                                       spec=h.spec, T=h.T)
        node = self._start_node(i, chain)
        self.nodes[i] = node
        return node

    # -- partition / heal ----------------------------------------------------

    def partition_node(self, i: int) -> None:
        """NETWORK partition (vs :meth:`crash_node`'s process death):
        the node's sockets and discovery drop but its chain, store and
        validator keys stay alive in-process — the classic
        partition → heal → range-sync convergence race.  The clean
        ``persist=True`` close keeps the fork-choice snapshot coherent;
        the store handle stays OPEN (the process never died)."""
        node = self.nodes[i]
        node.discovery.close()
        node.net.close(persist=True)
        self._down[i] = {"cfg": self._node_cfg[i], "chain": node.chain,
                         "partitioned": True}
        self.nodes[i] = None  # type: ignore[assignment]

    def heal_node(self, i: int) -> SimNode:
        """Re-wire a partitioned node around its LIVE chain: fresh
        sockets + discovery, same state.  The healed node is behind the
        mesh by however many slots the partition lasted; range sync
        (epoch-batched replay underneath) closes the gap."""
        down = self._down.get(i)
        assert down and down.get("partitioned"), "node was not partitioned"
        self._down.pop(i)
        node = self._start_node(i, down["chain"])
        self.nodes[i] = node
        return node

    @property
    def live_nodes(self) -> List[SimNode]:
        return [n for n in self.nodes if n is not None]

    def wait_for_mesh(self, timeout: float = 20.0) -> bool:
        """Every live node discovers every other live node."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            live = self.live_nodes
            want = len(live) - 1
            if all(len(n.net.node.peers) >= want for n in live):
                return True
            time.sleep(0.05)
        return False

    def run_slot(self, slot: int) -> None:
        """One slot: tick every chain, drive every VC, drain queues,
        then fire the 3/4-slot state-advance timer for the next slot."""
        for n in self.live_nodes:
            n.chain.per_slot_task(slot)
        for n in self.live_nodes:
            n.vc.on_slot(slot)
        # Let gossip propagate and queues drain (bounded settle loop).
        for _ in range(40):
            busy = False
            for n in self.live_nodes:
                if n.net.node.processor.run_until_idle():
                    busy = True
            if not busy:
                time.sleep(0.02)
                drained = all(not n.net.node.processor.run_until_idle()
                              for n in self.live_nodes)
                if drained:
                    break
        for n in self.live_nodes:  # `state_advance_timer.rs` 3/4-slot hook
            n.chain.on_three_quarters_slot(slot)

    def run(self, n_slots: int) -> None:
        for slot in range(1, n_slots + 1):
            self.run_slot(slot)

    # -- assertions ----------------------------------------------------------

    def heads(self) -> set:
        return {n.chain.head.root for n in self.live_nodes}

    def finalized_epochs(self) -> List[int]:
        return [n.chain.fork_choice.finalized_checkpoint[0]
                for n in self.live_nodes]

    def close(self) -> None:
        for n in self.live_nodes:
            n.discovery.close()
            n.net.close()
        self.boot.close()


def main() -> int:
    import argparse
    from ..crypto import bls as B

    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--validators", type=int, default=16)
    ap.add_argument("--slots", type=int, default=12)
    ap.add_argument("--insecure", action="store_true",
                    help="plaintext transport (wire debugging)")
    args = ap.parse_args()

    B.set_backend("fake")
    sim = Simulator(n_nodes=args.nodes, n_validators=args.validators,
                    secure=not args.insecure)
    try:
        assert sim.wait_for_mesh(), "discovery mesh failed"
        sim.run(args.slots)
        heads = sim.heads()
        fins = sim.finalized_epochs()
        print(f"heads={len(heads)} finalized_epochs={fins}")
        ok = len(heads) == 1 and min(fins) >= 1
        print("CONVERGED + FINALIZED" if ok else "FAILED")
        return 0 if ok else 1
    finally:
        sim.close()


if __name__ == "__main__":
    raise SystemExit(main())
