"""lighthouse_tpu — a TPU-native Ethereum consensus framework.

From-scratch rebuild of the capabilities of Lighthouse (the Rust consensus
client, see /root/reference) with the per-slot cryptographic hot path —
batched BLS12-381 aggregate-verification and SSZ Merkleization — executed on
TPU via JAX/XLA (jnp + Pallas kernels), and the host client logic written
idiomatically in Python/C++ rather than translated from Rust.

Layer map (mirrors SURVEY.md §1):

- ``ops/``      device kernels: SHA-256, Merkle reduction, 381-bit bigint,
                field towers, curve ops, pairing (JAX/Pallas).
- ``crypto/``   host crypto API: BLS backend seam (tpu / python / fake),
                hashing, keystores, key derivation.
- ``ssz/``      SimpleSerialize encode/decode, typed containers, tree hash,
                merkle proofs.
- ``types/``    consensus datatypes across forks, EthSpec presets, ChainSpec.
- ``state_transition/``  pure spec state transition + signature-set batching.
- ``fork_choice/``       proto-array LMD-GHOST.
- ``store/``    hot/cold storage.
- ``chain/``    beacon chain runtime: verification pipelines, op pool, head.
- ``parallel/`` device mesh / sharding helpers for multi-chip scaling.
- ``utils/``    metrics, slot clock, logging, safe arithmetic.
"""

__version__ = "0.1.0"
