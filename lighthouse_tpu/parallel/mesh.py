"""The ONE named mesh — residency layer for every device subsystem.

One logical axis, ``batch``: every hot-path workload (signature sets,
Merkle leaves, registry rows, fork-choice votes, slasher planes) is
embarrassingly parallel over its batch dimension, so the natural mesh is
1-D data-parallel over all chips — collectives only appear at the final
cross-chip reduction (sub-tree roots / pairing product / vote-delta
all-reduce).

Since PR 20 this module is the repo's single residency layer, not just a
mesh constructor.  Five subsystems (BLS shard, DeviceTree / registry
mirror, packed-column cache, fork-choice vote columns, slasher planes)
used to own ad-hoc ``jax.device_put`` spellings; they now place every
persistent column through the seams here:

- :func:`get_mesh` — the process-wide named mesh.  Axis size comes from
  the ``LIGHTHOUSE_TPU_MESH_DEVICES`` knob (0 = auto: all local devices
  on a real TPU backend, 1 otherwise), so a CPU test process with 8
  virtual XLA devices still degenerates to the single-device spelling
  unless a test/driver opts in.  1-device meshes degenerate cleanly:
  ``P("batch")`` over one device IS the unsharded placement.
- :func:`register_column` — the per-column PartitionSpec registry.
  Registry rows / balances / participation, fork-choice vote columns
  and slasher planes shard over ``"batch"``; tree upper levels, Fq12
  partials, selection matrices and scatter payloads replicate.
- :func:`mesh_put` / :func:`mesh_place` / :func:`mesh_gather` — the
  resharding seams.  Every placement/pull reports bytes into the device
  ledger per subsystem (host-wire totals, same families as before) AND
  per shard (:meth:`DeviceLedger.note_shard_transfer` — delivered
  bytes: 1/d per shard for a batch-sharded column, full size on every
  shard for a replicated one).  Attribution: explicit ``subsystem=``
  argument > ambient :meth:`DeviceLedger.attribute` scope > the
  column's registered subsystem.
- :func:`mesh_program` — the one proven ``shard_map`` spelling
  (``jax.experimental.shard_map`` + ``check_rep=False``; see
  merkle_shard's note on why the SHA IV constant trips the replication
  checker) wrapped in ``jax.jit``.

The graftlint ``mesh-residency`` checker enforces the contract from the
other side: raw ``jax.device_put`` in the five persistent-residency
modules and ``Mesh(...)`` construction outside this file are findings.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.device_ledger import LEDGER, SUBSYSTEMS


BATCH_AXIS = "batch"


def make_mesh(devices=None) -> Mesh:
    """1-D ``batch`` mesh over ``devices`` (default: all available)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices).reshape(-1), (BATCH_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 over the batch axis, replicate the rest."""
    return NamedSharding(mesh, P(BATCH_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (constants: zero-hash tables, generators)."""
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# The process mesh
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_MESHES: Dict[int, Mesh] = {}  # axis size -> mesh, guarded-by: _LOCK


def mesh_devices() -> int:
    """Resolved axis size of the process mesh (knob-selectable).

    ``LIGHTHOUSE_TPU_MESH_DEVICES=0`` (auto) means all local devices on
    a real TPU backend and 1 otherwise — the CPU test process exposes 8
    virtual XLA devices for the differential suites, and defaulting the
    whole tree onto them would silently turn every quick-tier test into
    a sharded compile.  Explicit N clamps to the local device count.
    """
    from ..common.knobs import knob_int
    n = knob_int("LIGHTHOUSE_TPU_MESH_DEVICES")
    if n <= 0:
        n = len(jax.devices()) if jax.default_backend() == "tpu" else 1
    return max(1, min(n, len(jax.devices())))


def get_mesh() -> Mesh:
    """The process-wide named mesh every subsystem places residency on.

    Cached per resolved axis size — flipping the knob mid-process (the
    differential tests, validate_mesh) picks up a new mesh on the next
    call without invalidating programs compiled against the old one.
    """
    n = mesh_devices()
    with _LOCK:
        mesh = _MESHES.get(n)
        if mesh is None:
            mesh = _MESHES[n] = make_mesh(jax.devices()[:n])
        return mesh


def axis_size(mesh: Optional[Mesh] = None) -> int:
    """Size of the ``batch`` axis (the shard count)."""
    mesh = get_mesh() if mesh is None else mesh
    return int(mesh.shape[BATCH_AXIS])


def reset_mesh() -> None:
    """Drop the mesh cache (tests flipping the device-count knob)."""
    with _LOCK:
        _MESHES.clear()


# ---------------------------------------------------------------------------
# Per-column PartitionSpec registry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ColumnSpec:
    """One registered column family: how its arrays lay out on the mesh.

    ``spec`` is the INTENDED PartitionSpec; placement falls back to
    replicated when a concrete array's sharded dims don't divide the
    axis size (the seams degrade, they never fail).  ``pad_bucket`` is
    the pow2 bucket floor the family's transient payloads pad to
    (:func:`bucket_rows`) — bucketing and divisibility are the same
    concern: a pow2 bucket ≥ the axis size always shards cleanly.
    """
    name: str
    spec: P
    subsystem: str
    dtype: Optional[str] = None
    pad_bucket: Optional[int] = None
    doc: str = ""

    @property
    def sharded(self) -> bool:
        return any(ax is not None for ax in self.spec)


COLUMNS: Dict[str, ColumnSpec] = {}


def register_column(name: str, spec: P, *, subsystem: str,
                    dtype: Optional[str] = None,
                    pad_bucket: Optional[int] = None,
                    doc: str = "") -> ColumnSpec:
    """Declare a column family's mesh layout (idempotent re-register of
    an identical row is allowed; a conflicting one is a bug)."""
    assert subsystem in SUBSYSTEMS, subsystem
    col = ColumnSpec(name, spec, subsystem, dtype, pad_bucket, doc)
    prev = COLUMNS.get(name)
    if prev is not None and prev != col:
        raise ValueError(
            f"column {name!r} already registered with a different "
            f"layout ({prev.spec} vs {spec})")
    COLUMNS[name] = col
    return col


def bucket_rows(name: str, k: int) -> int:
    """Pow2 bucket for ``k`` rows of family ``name`` (floor = the
    registered ``pad_bucket``) — one bucketing rule for every transient
    payload, and the reason sharded dims always divide the mesh."""
    floor = COLUMNS[name].pad_bucket or 1
    return max(floor, 1 << max(int(k) - 1, 0).bit_length())


def _spec_for(col: ColumnSpec, shape: Tuple[int, ...],
              ndev: int) -> P:
    """The column's spec, degraded to replicated when a sharded dim of
    this concrete array doesn't divide the axis size."""
    if ndev == 1:
        return col.spec  # 1-device: any spec is the unsharded placement
    for dim, ax in enumerate(col.spec):
        if ax is None:
            continue
        if dim >= len(shape) or shape[dim] % ndev:
            return P()
    return col.spec


def column_sharding(name: str, shape: Optional[Tuple[int, ...]] = None,
                    mesh: Optional[Mesh] = None) -> NamedSharding:
    """NamedSharding for one concrete array of family ``name``."""
    mesh = get_mesh() if mesh is None else mesh
    col = COLUMNS[name]
    spec = col.spec if shape is None \
        else _spec_for(col, tuple(shape), axis_size(mesh))
    return NamedSharding(mesh, spec)


# ---------------------------------------------------------------------------
# Resharding seams (ALL persistent placement goes through here)
# ---------------------------------------------------------------------------

def _resolve_subsystem(col: Optional[ColumnSpec],
                       subsystem: Optional[str]) -> str:
    if subsystem is not None:
        assert subsystem in SUBSYSTEMS, subsystem
        return subsystem
    amb = LEDGER.ambient()
    if amb is not None:
        return amb
    return col.subsystem if col is not None else "device_tree"


def _note_shards(direction: str, sub: str, nbytes: int,
                 spec: P, ndev: int) -> None:
    """Per-shard delivered bytes for one placement: 1/d per shard when
    sharded, the full size on every shard when replicated (one host
    copy fans out over ICI)."""
    if any(ax is not None for ax in spec):
        per = nbytes // ndev
        LEDGER.note_shard_transfer(
            direction, {i: per for i in range(ndev)}, subsystem=sub)
    else:
        LEDGER.note_shard_transfer(
            direction, {i: nbytes for i in range(ndev)}, subsystem=sub)


def mesh_put(name: str, arr, mesh: Optional[Mesh] = None,
             subsystem: Optional[str] = None) -> jax.Array:
    """Place a host array as column family ``name`` (H2D, accounted
    per subsystem and per shard).  An already-on-device array routes
    through :func:`mesh_place` instead — no host-wire bytes."""
    if isinstance(arr, jax.Array):
        return mesh_place(name, arr, mesh=mesh)
    mesh = get_mesh() if mesh is None else mesh
    col = COLUMNS[name]
    host = np.asarray(arr)
    ndev = axis_size(mesh)
    spec = _spec_for(col, host.shape, ndev)
    out = jax.device_put(host, NamedSharding(mesh, spec))
    sub = _resolve_subsystem(col, subsystem)
    LEDGER.note_transfer("h2d", host.nbytes, subsystem=sub)
    _note_shards("h2d", sub, host.nbytes, spec, ndev)
    return out


def mesh_place(name: str, arr: jax.Array, mesh: Optional[Mesh] = None,
               subsystem: Optional[str] = None,
               h2d_bytes: Optional[int] = None) -> jax.Array:
    """Reshard an array that is ALREADY on device onto the column's
    registered layout (stager concatenations, width growth, adopted jit
    outputs).  Moves no host-wire bytes itself; ``h2d_bytes`` lets a
    caller whose actual push happened upstream UNACCOUNTED (a
    ChunkStager driven with ``subsystem=None``) settle the wire total +
    per-shard split at this seam instead."""
    mesh = get_mesh() if mesh is None else mesh
    col = COLUMNS[name]
    ndev = axis_size(mesh)
    spec = _spec_for(col, arr.shape, ndev)
    want = NamedSharding(mesh, spec)
    out = arr if getattr(arr, "sharding", None) == want \
        else jax.device_put(arr, want)
    if h2d_bytes:
        sub = _resolve_subsystem(col, subsystem)
        LEDGER.note_transfer("h2d", h2d_bytes, subsystem=sub)
        _note_shards("h2d", sub, int(h2d_bytes), spec, ndev)
    return out


def mesh_gather(arr, subsystem: Optional[str] = None,
                name: Optional[str] = None) -> np.ndarray:
    """Pull a device array to host (D2H, accounted per subsystem and
    per shard: bytes read FROM each shard — 1/d each when sharded, all
    from shard 0 when replicated)."""
    col = COLUMNS.get(name) if name else None
    sub = _resolve_subsystem(col, subsystem)
    out = np.asarray(arr)
    sharding = getattr(arr, "sharding", None)
    ndev = len(sharding.device_set) if sharding is not None else 1
    LEDGER.note_transfer("d2h", out.nbytes, subsystem=sub)
    if ndev > 1 and sharding is not None \
            and not sharding.is_fully_replicated:
        per = out.nbytes // ndev
        LEDGER.note_shard_transfer(
            "d2h", {i: per for i in range(ndev)}, subsystem=sub)
    else:
        LEDGER.note_shard_transfer("d2h", {0: out.nbytes}, subsystem=sub)
    return out


# ---------------------------------------------------------------------------
# Mesh programs
# ---------------------------------------------------------------------------

def mesh_program(fn, *, mesh: Optional[Mesh] = None, in_specs,
                 out_specs, **jit_kwargs):
    """The standard sharded-program spelling: ``jax.jit`` around
    ``shard_map(fn, ..., check_rep=False)``.

    ``check_rep=False`` is load-bearing, not a shrug: every kernel here
    closes over replicated constant tables (the SHA-256 IV/round
    constants, curve generators), and the replication checker flags
    those as possibly-divergent per-shard values; see
    ``parallel/merkle_shard.py`` for the full note.  Centralizing the
    spelling keeps the jax-hygiene checker's contract in ONE place.
    """
    mesh = get_mesh() if mesh is None else mesh
    mapped = shard_map(fn, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False)
    return jax.jit(mapped, **jit_kwargs)


# ---------------------------------------------------------------------------
# The column families (the axis/PartitionSpec table in README "One mesh")
# ---------------------------------------------------------------------------

# DeviceTree: leaf plane + the level stack's wide rows shard over the
# leaf axis (pow2 contiguous ranges keep every child shard-local until
# level width reaches the axis size); scatter payloads replicate.
register_column("tree_leaves", P(BATCH_AXIS), subsystem="device_tree",
                dtype="uint32", pad_bucket=8,
                doc="DeviceTree leaf/level rows (w, 8) u32 words")
register_column("tree_dirty", P(), subsystem="device_tree",
                pad_bucket=8,
                doc="scatter payloads: dirty leaf indices + rows")
# Registry mirror: raw record columns shard over the validator axis;
# scatter payloads replicate.
register_column("registry_cols", P(BATCH_AXIS),
                subsystem="registry_mirror", pad_bucket=8,
                doc="validator-registry raw record columns (w, ...)")
register_column("registry_dirty", P(), subsystem="registry_mirror",
                pad_bucket=8,
                doc="registry scatter payloads: indices + raw rows")
# Packed-column cache: leaf planes shard over the chunk axis (a 2M-
# validator balances plane splits across chips' HBM).
register_column("packed_leaves", P(BATCH_AXIS),
                subsystem="packed_cache", dtype="uint32", pad_bucket=8,
                doc="packed-column leaf planes (w, 8) u32 words")
# Fork choice: vote/balance columns shard over validators (the delta
# segment-sum runs as per-shard partials + one small all-reduce);
# node-indexed topology columns and scatter payloads replicate.
register_column("fc_votes", P(BATCH_AXIS), subsystem="fork_choice",
                pad_bucket=16,
                doc="per-validator vote indices + balances (nv_pad,)")
register_column("fc_topology", P(), subsystem="fork_choice",
                pad_bucket=16,
                doc="per-node parent/depth/weight columns (n_pad,)")
register_column("fc_dirty", P(), subsystem="fork_choice", pad_bucket=8,
                doc="changed-vote scatter payloads: indices + values")
# Slasher: min/max span planes shard over the validator axis; group
# payloads (bit-packed masks, epochs) replicate.
register_column("slasher_planes", P(BATCH_AXIS), subsystem="slasher",
                dtype="uint16",
                doc="min/max span planes (n_validators, history) u16")
register_column("slasher_groups", P(), subsystem="slasher",
                doc="ingest payloads: packed masks, epochs, group ids")
# BLS shard: marshalled signature-set blocks shard over the set axis;
# Fq12 pairing partials and the mont-mul selection matrices replicate.
register_column("bls_sets", P(BATCH_AXIS), subsystem="bls",
                doc="marshalled signature-set limb blocks (n_sets, ...)")
register_column("fq12_partials", P(), subsystem="bls",
                doc="per-shard Fq12 pairing partials (replicated)")
register_column("selection_matrices", P(), subsystem="bls",
                doc="mont-mul limb selection matrices (constants)")
