"""Device-mesh construction for the crypto data plane.

One logical axis, ``batch``: every hot-path workload (signature sets, Merkle
leaves, shuffle indices) is embarrassingly parallel over its batch dimension,
so the natural mesh is 1-D data-parallel over all chips — collectives only
appear at the final cross-chip reduction (sub-tree roots / pairing product).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


BATCH_AXIS = "batch"


def make_mesh(devices=None) -> Mesh:
    """1-D ``batch`` mesh over ``devices`` (default: all available)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices).reshape(-1), (BATCH_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 over the batch axis, replicate the rest."""
    return NamedSharding(mesh, P(BATCH_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (constants: zero-hash tables, generators)."""
    return NamedSharding(mesh, P())
