"""Multi-chip scaling: device mesh construction + sharded hot-path kernels.

The reference scales its per-slot crypto with rayon across CPU cores
(``/root/reference/consensus/state_processing/src/per_block_processing/block_signature_verifier.rs:392-405``,
``consensus/types/src/beacon_state/tree_hash_cache.rs:535``).  The TPU-native
equivalent is a single batched kernel sharded over an ICI mesh with
``shard_map``/``pjit``, with cross-chip reduction (sub-tree Merkle roots,
pairing partial products) riding XLA collectives.
"""

from .pipeline import ChunkStager, StagedExecutor  # noqa: F401
from .mesh import make_mesh  # noqa: F401
from .merkle_shard import sharded_merkle_root  # noqa: F401
from .bls_shard import (  # noqa: F401
    sharded_g1_sum, sharded_verify_signature_sets)
