"""One modeled slot through every mesh-resident subsystem.

The shared driver behind ``dryrun_multichip``, ``scripts/validate_mesh.py``
and the bench ``mesh_slot`` row.  A modeled slot exercises the per-slot
device pipeline end to end on whatever mesh the process knob resolves —
registry scatter + mirror rebuild (verify/transition stand-in), the
packed-column cache root, a fork-choice attestation round through the
fused (or mesh) kernel, and a slasher span ingest — with stage wall
times, the ledger's per-slot transfer deltas, and the per-shard byte
rows captured into one trace row per slot.

Every scenario here is deterministic (seeded, no wall-clock inputs), so
the SAME model run under ``LIGHTHOUSE_TPU_MESH_DEVICES=N`` and ``=1``
must produce bit-identical roots, heads and span planes — that is the
differential ``check_subsystem`` runs and the acceptance gate of PR 20.
"""

from __future__ import annotations

import hashlib
import time
from contextlib import contextmanager
from typing import Dict, Optional

import numpy as np

SUBSYSTEM_CHOICES = ("tree", "registry", "packed", "forkchoice",
                     "slasher", "all")

_SLOT_BASE = [1_000_000]  # distinct slot numbers per model run (the
#                           ledger ring is idempotent per slot value)


def _root(i: int) -> bytes:
    return int(i).to_bytes(4, "little") + b"\xcd" * 28


@contextmanager
def forced_devices(n: int):
    """Temporarily pin the mesh knob to ``n`` devices (and back)."""
    import os
    from . import mesh as pmesh
    # Prior value through the registry's raw accessor (knob-registry
    # invariant: env reads live in common/knobs.py; writes are ours).
    from ..common.knobs import _raw
    old = _raw("LIGHTHOUSE_TPU_MESH_DEVICES")
    os.environ["LIGHTHOUSE_TPU_MESH_DEVICES"] = str(int(n))
    pmesh.reset_mesh()
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("LIGHTHOUSE_TPU_MESH_DEVICES", None)
        else:
            os.environ["LIGHTHOUSE_TPU_MESH_DEVICES"] = old
        pmesh.reset_mesh()


def _make_registry(n: int, rng: np.random.Generator):
    from ..types.validators import ValidatorRegistry
    reg = ValidatorRegistry(n)
    reg._pubkey[:n] = rng.integers(0, 256, (n, 48), dtype=np.uint8)
    reg._withdrawal_credentials[:n] = rng.integers(
        0, 256, (n, 32), dtype=np.uint8)
    reg._effective_balance[:n] = (rng.integers(16, 33, n).astype(np.uint64)
                                  * np.uint64(10 ** 9))
    reg._activation_epoch[:n] = np.arange(n, dtype=np.uint64) % 7
    return reg


# ---------------------------------------------------------------------------
# Per-subsystem deterministic scenarios (each returns a digest of every
# observable device output; compared N-device vs 1-device bit-for-bit)
# ---------------------------------------------------------------------------

def _scenario_tree(seed: int = 0, w: int = 256) -> bytes:
    from ..ops.device_tree import DeviceTree
    rng = np.random.default_rng(seed)
    leaves = rng.integers(0, 2 ** 32, (w, 8), dtype=np.uint32)
    t = DeviceTree.from_host_leaves(leaves)
    h = hashlib.sha256(np.asarray(t.root_words()).tobytes())
    idx = np.asarray([1, 7, w // 2, w - 1], np.int64)
    rows = rng.integers(0, 2 ** 32, (idx.shape[0], 8), dtype=np.uint32)
    h.update(np.asarray(t.scatter(idx, rows)).tobytes())
    for lv in t.pull_levels():
        h.update(np.asarray(lv).tobytes())
    return h.digest()


def _scenario_registry(seed: int = 0, n: int = 200) -> bytes:
    from ..types.validators import DeviceRegistryMirror
    rng = np.random.default_rng(seed)
    reg = _make_registry(n, rng)
    mir = DeviceRegistryMirror.materialize(reg)
    h = hashlib.sha256(np.asarray(mir.tree.root_words()).tobytes())
    idx = np.asarray([3, n // 3, n - 1], np.int64)
    reg._effective_balance[idx] += np.uint64(1)
    h.update(np.asarray(mir.scatter_records(reg, idx)).tobytes())
    h.update(np.asarray(mir.rebuild(reg._n)).tobytes())
    return h.digest()


def _scenario_packed(seed: int = 0, n: int = 1024) -> bytes:
    from ..types.device_state import DevicePackedCache
    rng = np.random.default_rng(seed)
    col = rng.integers(0, 2 ** 62, n).astype(np.uint64)
    cache = DevicePackedCache(limit_chunks=1 << 12, mixin_length=True)
    h = hashlib.sha256(cache.root(col))
    col = col.copy()
    col[[0, n // 2, n - 1]] += np.uint64(7)  # warm scatter path
    h.update(cache.root(col))
    return h.digest()


def _scenario_forkchoice(seed: int = 0, nv: int = 64,
                         rounds: int = 3) -> bytes:
    from ..fork_choice.device_proto_array import DeviceProtoArrayForkChoice
    from ..fork_choice.proto_array import EXEC_OPTIMISTIC, ZERO_ROOT
    rng = np.random.default_rng(seed)
    fc = DeviceProtoArrayForkChoice(engine="jit")
    fc.on_block(slot=0, root=_root(0), parent_root=ZERO_ROOT,
                state_root=_root(0), justified_epoch=1,
                justified_root=_root(0), finalized_epoch=1,
                finalized_root=_root(0),
                execution_status=EXEC_OPTIMISTIC)
    h = hashlib.sha256()
    cp = (1, _root(0))
    for s in range(1, rounds + 1):
        # two competing children per round keeps best-child selection live
        for b in range(2):
            fc.on_block(slot=s, root=_root(2 * s + b),
                        parent_root=_root(max(2 * (s - 1), 0)),
                        state_root=_root(2 * s + b), justified_epoch=1,
                        justified_root=_root(0), finalized_epoch=1,
                        finalized_root=_root(0),
                        execution_status=EXEC_OPTIMISTIC)
        committee = rng.choice(nv, size=nv // 2, replace=False)
        fc.process_attestation_batch(
            [(committee.astype(np.int64), _root(2 * s), s)])
        bal = rng.integers(1, 100, nv).astype(np.uint64)
        deltas = fc.compute_deltas(bal)
        fc.apply_score_changes(deltas, cp, cp, ZERO_ROOT, 0, s)
        head = fc.find_head(_root(0), s)
        h.update(head)
        h.update(fc.cols.weight[:fc.cols.n].tobytes())
    return h.digest()


def _scenario_slasher(seed: int = 0, n: int = 256,
                      history: int = 64) -> bytes:
    from ..slasher.device_spans import DeviceSpanPlane
    rng = np.random.default_rng(seed)
    plane = DeviceSpanPlane(n, history=history)
    h = hashlib.sha256()
    for e in range(3, 6):
        idx = np.sort(rng.choice(n, size=n // 4, replace=False))
        pre = plane.ingest(plane.group([(e - 2, e, idx),
                                        (e - 1, e, idx[: n // 8])]))
        for key in sorted(pre):
            h.update(pre[key][0].tobytes())
            h.update(pre[key][1].tobytes())
    mn, mx = plane.to_host()
    h.update(mn.tobytes())
    h.update(mx.tobytes())
    return h.digest()


_SCENARIOS = {
    "tree": _scenario_tree,
    "registry": _scenario_registry,
    "packed": _scenario_packed,
    "forkchoice": _scenario_forkchoice,
    "slasher": _scenario_slasher,
}


def check_subsystem(name: str, seed: int = 0) -> dict:
    """Run one subsystem scenario on the current mesh AND forced to one
    device; returns ``{"subsystem", "devices", "match"}``.  Bit-identity
    is the contract — sharded programs reuse the 1-device fold order."""
    from . import mesh as pmesh
    fn = _SCENARIOS[name]
    ndev = pmesh.axis_size()
    mesh_digest = fn(seed)
    with forced_devices(1):
        ref_digest = fn(seed)
    return {"subsystem": name, "devices": ndev,
            "match": mesh_digest == ref_digest}


# ---------------------------------------------------------------------------
# The full modeled slot: verify/transition stand-in -> root -> fork
# choice -> slasher, per-slot ledger deltas + budget verdict
# ---------------------------------------------------------------------------

def run_slot_model(*, n_validators: int = 256, slots: int = 3,
                   history: int = 64, seed: int = 0) -> dict:
    """Drive ``slots`` modeled slots over every subsystem on the current
    mesh.  Returns ``{"devices", "digest", "rows", "budget",
    "shards"}`` — ``digest`` is the bit-exact observable-output hash
    (compare across device counts), ``rows`` one trace row per slot with
    per-stage wall ms, ``budget`` the warm-slot transfer verdict over the
    non-cold slots, ``shards`` the per-shard ledger byte rows."""
    from . import mesh as pmesh
    from ..common import device_ledger as DL
    from ..common.device_ledger import LEDGER
    from ..fork_choice.device_proto_array import DeviceProtoArrayForkChoice
    from ..fork_choice.proto_array import EXEC_OPTIMISTIC, ZERO_ROOT
    from ..slasher.device_spans import DeviceSpanPlane
    from ..types.device_state import DevicePackedCache
    from ..types.validators import DeviceRegistryMirror

    ndev = pmesh.axis_size()
    rng = np.random.default_rng(seed)
    base = _SLOT_BASE[0]
    _SLOT_BASE[0] += slots + 2
    digest = hashlib.sha256()

    # -- cold setup (the materialize slot; excluded from the budget) ----
    reg = _make_registry(n_validators, rng)
    mirror = DeviceRegistryMirror.materialize(reg)
    balances = reg._effective_balance[:n_validators].copy()
    cache = DevicePackedCache(limit_chunks=1 << 12, mixin_length=True)
    cache.root(balances)
    fc = DeviceProtoArrayForkChoice(engine="jit")
    fc.on_block(slot=0, root=_root(0), parent_root=ZERO_ROOT,
                state_root=_root(0), justified_epoch=1,
                justified_root=_root(0), finalized_epoch=1,
                finalized_root=_root(0),
                execution_status=EXEC_OPTIMISTIC)
    plane = DeviceSpanPlane(n_validators, history=history)
    cp = (1, _root(0))
    LEDGER.mark_slot(base)

    rows = []
    for s in range(1, slots + 1):
        row: Dict[str, object] = {"slot": s, "devices": ndev}

        # verify/transition stand-in: per-slot balance updates scatter
        # into the resident registry mirror, epoch-style full rebuild
        t0 = time.perf_counter()
        idx = np.sort(rng.choice(n_validators, size=max(n_validators // 8, 1),
                                 replace=False)).astype(np.int64)
        reg._effective_balance[idx] += np.uint64(s)
        digest.update(np.asarray(mirror.scatter_records(reg, idx)).tobytes())
        digest.update(np.asarray(mirror.rebuild(reg._n)).tobytes())
        row["registry_ms"] = round((time.perf_counter() - t0) * 1e3, 2)

        # state root: the packed balance column through the device cache
        t0 = time.perf_counter()
        balances[idx] += np.uint64(s)
        digest.update(cache.root(balances))
        row["packed_root_ms"] = round((time.perf_counter() - t0) * 1e3, 2)

        # fork choice: new block + one committee's attestations + head
        t0 = time.perf_counter()
        fc.on_block(slot=s, root=_root(s), parent_root=_root(s - 1),
                    state_root=_root(s), justified_epoch=1,
                    justified_root=_root(0), finalized_epoch=1,
                    finalized_root=_root(0),
                    execution_status=EXEC_OPTIMISTIC)
        committee = rng.choice(n_validators, size=n_validators // 3,
                               replace=False).astype(np.int64)
        fc.process_attestation_batch([(committee, _root(s), s)])
        deltas = fc.compute_deltas(balances)
        fc.apply_score_changes(deltas, cp, cp, ZERO_ROOT, 0, s)
        head = fc.find_head(_root(0), s)
        digest.update(head)
        row["fork_choice_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        row["head"] = head[:4].hex()

        # slasher: the slot's grouped attestations sweep the span planes
        t0 = time.perf_counter()
        pre = plane.ingest(plane.group(
            [(s + 1, s + 3, np.sort(committee).astype(np.int64))]))
        for key in sorted(pre):
            digest.update(pre[key][0].tobytes())
            digest.update(pre[key][1].tobytes())
        row["slasher_ms"] = round((time.perf_counter() - t0) * 1e3, 2)

        LEDGER.mark_slot(base + s)
        rows.append(row)

    mn, mx = plane.to_host()
    digest.update(mn.tobytes())
    digest.update(mx.tobytes())

    window = [d for d in LEDGER.slot_deltas()
              if base <= d["slot"] < base + slots]
    budget = DL.evaluate_budget(window, include_cold=False) \
        if window else {"ok": True, "rows": [], "attainment": 1.0}
    return {
        "devices": ndev,
        "digest": digest.hexdigest(),
        "rows": rows,
        "budget": budget,
        "shards": LEDGER.shard_totals(),
    }


def projected_slot_row(row_1dev: dict, n_chips: int,
                       sharded_fraction: float = 0.85) -> dict:
    """Project a measured 1-device slot trace row onto an ``n_chips``
    mesh: the validator-axis stages divide by the chip count while the
    replicated top folds / propagate / collectives do not (held at
    ``1 - sharded_fraction`` of each stage, the same split the mesh
    programs encode).  A projection, not a measurement — the hardware
    row stays a ROADMAP remainder."""
    stages = ("registry_ms", "packed_root_ms", "fork_choice_ms",
              "slasher_ms")
    out = {"slot": row_1dev.get("slot"), "devices": n_chips,
           "projected": True}
    total = 0.0
    for k in stages:
        ms = float(row_1dev[k])
        proj = ms * sharded_fraction / n_chips + ms * (1 - sharded_fraction)
        out[k] = round(proj, 2)
        total += proj
    out["slot_ms"] = round(total, 2)
    return out
