"""Staged device executor — overlapped host-prep / H2D staging / compute.

The three device hot paths (batched BLS verify, the cold Merkle build,
the registry cold build) all share one wall-clock pathology: the host
does ALL of its marshalling, then pushes ALL of the bytes, then the
device starts computing — so a 1024-set BLS batch spends ~70% of its
wall time with the device idle, and the cold state root spends 5+ s
blocked on one monolithic leaf push.  This module is the shared staging
layer that removes the serialization:

- :class:`StagedExecutor` — double-buffered ``prep → stage → dispatch``
  over a work list.  ``prep`` (host marshalling) of item *i+1* runs
  while the device computes item *i*: dispatches are issued without any
  ``block_until_ready`` between stages, so JAX's async dispatch keeps
  the device busy under the host loop.  A staging failure (the axon
  tunnel hiccuping mid-``device_put``) falls back to synchronous
  staging for that item — results are identical, only the overlap is
  lost.
- :class:`ChunkStager` — a background thread that pushes host chunks to
  the device IN ORDER while the consumer dispatches compute on earlier
  chunks: the existing background level-pull machinery
  (:func:`~lighthouse_tpu.ops.tree_cache.start_level_pull`) run in
  reverse.  The stager thread blocks on each transfer so the transfer
  time is paid OFF the critical path; the consumer only waits when it
  outruns the uploads.

Every stage boundary is instrumented through
:mod:`~lighthouse_tpu.common.metrics` (``pipeline_host_prep_seconds``,
``pipeline_h2d_seconds``, ``pipeline_h2d_wait_seconds``) and each
executor keeps a ``stats`` dict the benchmarks surface as
``stage_overlap_efficiency`` / ``push_overlap_ms``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from ..common.device_ledger import LEDGER
from ..common.metrics import observe


def _put_arrays(host):
    """``jax.device_put`` over the ndarray leaves of an array / dict /
    tuple; non-array leaves (static ints like a K bucket) pass through."""
    import jax
    import numpy as np

    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x) if isinstance(x, np.ndarray) else x,  # device-io: staging
        host)


def _tree_nbytes(host) -> int:
    """Total ndarray bytes in a staged item (the H2D accounting the
    executors report into the device ledger)."""
    import jax
    import numpy as np

    return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(host)
               if isinstance(leaf, np.ndarray))


def _default_stage(host):
    """Async H2D staging.  Returns immediately; the transfer completes
    in the background (callers must NOT block between stage and
    dispatch)."""
    return _put_arrays(host)


def _sync_stage(host):
    """Synchronous fallback staging: push and WAIT.  Used when the async
    path failed — correctness never depends on the overlap."""
    import jax
    out = _put_arrays(host)
    jax.block_until_ready(out)
    return out


class StagedExecutor:
    """Double-buffered ``prep → stage → dispatch`` pipeline.

    ``map(items, prep, dispatch)`` runs, for each item::

        host   = prep(item)       # host marshalling (numpy)
        staged = stage(host)      # async H2D (jax.device_put)
        out    = dispatch(staged) # async device dispatch

    and returns the list of ``dispatch`` results (device arrays /
    futures — the caller syncs once at the end).  Because ``dispatch``
    is asynchronous, ``prep`` of the NEXT item executes while the device
    is still computing the current one; that host/device overlap is the
    entire point.  References to ``host`` and ``staged`` are dropped as
    soon as the dispatch is issued, which is what makes buffer donation
    in the dispatched jit safe: nothing on the host can re-read a
    donated buffer.

    ``stage`` is pluggable for tests (inject transfer failures).  A
    failure raised by ``stage`` itself OR surfacing at dispatch time
    (async ``device_put`` defers transfer errors to consumption)
    re-stages that item synchronously and retries the dispatch once
    (``fallbacks`` counts both); errors that only surface at the
    caller's terminal host sync propagate — the caller owns that retry.
    """

    def __init__(self, name: str = "pipeline",
                 stage: Optional[Callable] = None,
                 subsystem: Optional[str] = "staging"):
        self.name = name
        self._stage = stage or _default_stage
        # Device-ledger attribution of the staged H2D bytes ("bls" for
        # the verify pipelines, "staging" for cold builds; None = the
        # caller accounts its own transfers).
        self.subsystem = subsystem
        self.stats = {
            "items": 0,
            "fallbacks": 0,
            "host_prep_s": 0.0,     # total host marshalling time
            "overlap_prep_s": 0.0,  # marshalling done while device busy
            "wall_s": 0.0,
        }

    def map(self, items: Sequence[Any], prep: Callable[[Any], Any],
            dispatch: Callable[[Any], Any]) -> List[Any]:
        t_wall = time.perf_counter()
        out: List[Any] = []
        in_flight = False  # a dispatch has been issued and not synced
        for item in items:
            t0 = time.perf_counter()
            host = prep(item)
            dt = time.perf_counter() - t0
            observe(f"{self.name}_host_prep_seconds", dt)
            self.stats["host_prep_s"] += dt
            if in_flight:
                # this marshalling ran under an outstanding device
                # dispatch — the overlap the double buffering buys
                self.stats["overlap_prep_s"] += dt
            if self.subsystem is not None:
                LEDGER.note_transfer("h2d", _tree_nbytes(host),
                                     subsystem=self.subsystem)
            t0 = time.perf_counter()
            try:
                staged = self._stage(host)
            except Exception:
                self.stats["fallbacks"] += 1
                staged = _sync_stage(host)
            observe(f"{self.name}_h2d_seconds",
                    time.perf_counter() - t0)
            try:
                out.append(dispatch(staged))
            except Exception:
                # An async device_put defers transfer errors to the
                # point of consumption — they surface HERE, not in the
                # staging call above.  Retry once on synchronously
                # staged (transfer-verified) buffers; a second failure
                # is a genuine dispatch error and propagates.
                self.stats["fallbacks"] += 1
                staged = _sync_stage(host)
                out.append(dispatch(staged))
            in_flight = True
            self.stats["items"] += 1
            del host, staged  # donated buffers must never be re-read
        self.stats["wall_s"] += time.perf_counter() - t_wall
        return out

    def overlap_efficiency(self) -> Optional[float]:
        """Fraction of host marshalling hidden behind device compute
        (1.0 = everything after the first dispatch overlapped; None
        until something ran)."""
        total = self.stats["host_prep_s"]
        if not self.stats["items"] or total <= 0:
            return None
        return self.stats["overlap_prep_s"] / total


class ChunkStager:
    """Background H2D staging of an ordered chunk list.

    A non-daemon thread pushes ``host_chunks[i]`` to the device (and
    BLOCKS on the transfer — off the critical path), depositing device
    chunks into a bounded queue; iterating the stager yields them in
    order while the consumer's earlier-chunk dispatches are still
    computing.  The queue depth (default 2) is the double buffer: at
    most one chunk transfers ahead of the one being consumed, bounding
    device memory for staged-but-unconsumed input.

    A failed transfer is retried synchronously by the CONSUMER (the
    host chunk is retained until consumed), so a tunnel hiccup degrades
    to the old serial push instead of failing the build.

    Stats: ``wait_s`` — time the consumer blocked waiting for a staged
    chunk (the only transfer time left on the critical path);
    ``transfer_s`` — total background transfer time (``transfer_s −
    wait_s`` is the push time the overlap hid).
    """

    def __init__(self, host_chunks: Sequence[Any],
                 stage: Optional[Callable] = None, depth: int = 2,
                 subsystem: Optional[str] = "staging"):
        self._chunks = list(host_chunks)
        self._stage = stage or _default_stage
        # Explicit attribution (the stager thread cannot see the
        # caller's thread-local ambient context); None = caller
        # accounted the push itself (the registry-mirror materialize).
        self.subsystem = subsystem
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._abort = threading.Event()
        self.wait_s = 0.0
        self.transfer_s = 0.0
        self.fallbacks = 0
        # Non-daemon like start_level_pull: a daemon thread inside a
        # jax transfer at interpreter shutdown aborts the process.
        self._thread = threading.Thread(target=self._run, daemon=False)
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer aborted (a
        consumer dying mid-iteration must not strand a non-daemon
        thread on a full queue)."""
        while not self._abort.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        import jax
        for i, chunk in enumerate(self._chunks):
            if self._abort.is_set():
                return
            if self.subsystem is not None:
                LEDGER.note_transfer("h2d", _tree_nbytes(chunk),
                                     subsystem=self.subsystem)
            t0 = time.perf_counter()
            try:
                dev = self._stage(chunk)
                jax.block_until_ready(dev)
            except Exception as e:  # consumer re-stages synchronously
                if not self._put((i, e)):
                    return
                continue
            self.transfer_s += time.perf_counter() - t0
            if not self._put((i, dev)):
                return

    def __iter__(self):
        try:
            for i in range(len(self._chunks)):
                t0 = time.perf_counter()
                j, got = self._q.get()
                dt = time.perf_counter() - t0
                self.wait_s += dt
                observe("pipeline_h2d_wait_seconds", dt)
                assert j == i, "chunk stager out of order"
                if isinstance(got, Exception):
                    self.fallbacks += 1
                    got = _sync_stage(self._chunks[i])
                self._chunks[i] = None  # release the host copy
                yield got
        finally:
            self._abort.set()
            self._thread.join()

    def join(self) -> None:
        self._abort.set()
        self._thread.join()
