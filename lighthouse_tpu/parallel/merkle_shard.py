"""Sharded Merkle-tree reduction over a device mesh.

The 1M-validator registry tree (depth 40+1,
``/root/reference/consensus/types/src/eth_spec.rs:267``) is the dominant
``hash_tree_root`` workload.  On a multi-chip mesh we split the leaf range
over the ``batch`` axis, reduce each contiguous sub-range to its sub-tree
root entirely on-chip with ``shard_map`` (zero communication — leaf ranges
are power-of-two aligned so each shard owns a whole sub-tree), all-gather
the per-chip roots over ICI, and fold the remaining ``log2(n_chips)`` +
zero-padding levels replicated.  The reference's equivalent is rayon over
4096-validator arenas (``tree_hash_cache.rs:25-33,535-556``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
# The experimental import with ``check_rep=False`` is the ONE spelling
# proven on both jax lineages this repo runs under (0.4.x here, newer on
# the multichip driver) — the same pattern as ``bls_shard``'s
# ``sharded_g1_sum``.  The 0.5+ top-level ``jax.shard_map`` renamed the
# kwarg to ``check_vma``, so feature-detecting the import and passing one
# kwarg name unconditionally breaks on whichever side wasn't tested.
from jax.experimental.shard_map import shard_map

from ..ops.merkle import merkleize
from .mesh import BATCH_AXIS, axis_size, batch_sharding, mesh_program


def _log2(n: int) -> int:
    assert n & (n - 1) == 0 and n > 0, f"{n} not a power of two"
    return n.bit_length() - 1


def _is_pow2(n: int) -> bool:
    return n > 0 and n & (n - 1) == 0


@partial(jax.jit, static_argnames=("depth", "mesh"))
def sharded_merkle_root(leaves: jnp.ndarray, mesh: Mesh, depth: int) -> jnp.ndarray:
    """Root of a depth-``depth`` padded tree over ``leaves`` ``(n, 8)`` u32.

    ``n`` must be a power of two divisible by the mesh size.  The input is
    (re)sharded contiguously over the ``batch`` axis; output is the
    replicated ``(8,)`` root.
    """
    n = leaves.shape[0]
    ndev = mesh.shape[BATCH_AXIS]
    assert n % ndev == 0, (n, ndev)
    local_n = n // ndev
    local_depth = _log2(local_n)
    assert depth >= local_depth + _log2(ndev)

    leaves = jax.lax.with_sharding_constraint(leaves, batch_sharding(mesh))

    def local_subtree(chunk):
        # chunk: (local_n, 8) — one whole aligned sub-tree per device.
        return merkleize(chunk, local_depth)[None]  # (1, 8)

    # check_rep=False: the SHA round scan seeds its carry with the constant
    # IV (unvarying) and folds in the sharded block, which trips the
    # replication/varying-axes check; semantics are still purely per-shard.
    roots = shard_map(
        local_subtree, mesh=mesh,
        in_specs=P(BATCH_AXIS), out_specs=P(BATCH_AXIS),
        check_rep=False,
    )(leaves)  # (ndev, 8), sharded — the following gather rides ICI.

    return merkleize(roots, depth, base_level=local_depth)


# ---------------------------------------------------------------------------
# Resident-tree levels (PR 20): the DeviceTree / registry-mirror level
# stack as a mesh program, not just a one-shot root
# ---------------------------------------------------------------------------
#
# A contiguous pow2 leaf range per shard means every interior node whose
# level is wider than the mesh has BOTH children on the same shard, so
# levels of width ≥ ndev shard cleanly over ``batch`` (each shard folds
# its own sub-tree, zero communication) and only the top ``log2(ndev)``
# levels cross the shard boundary — they are computed past one implicit
# all-gather of the (ndev, 8) sub-root level.  The fold order is exactly
# ``_levels_body``'s, so the level stack is bit-identical to the
# 1-device build.

_LEVELS_PROGRAMS = {}  # (mesh, local_depth, use_kernel) -> program
_TOP_FOLD_JIT = None


def _get_top_fold():
    global _TOP_FOLD_JIT
    if _TOP_FOLD_JIT is None:
        def top_fold(cur):
            from ..ops.sha256 import hash64
            levels = []
            while cur.shape[0] > 1:
                cur = hash64(cur[0::2], cur[1::2])
                levels.append(cur)
            return tuple(levels)
        _TOP_FOLD_JIT = jax.jit(top_fold)
    return _TOP_FOLD_JIT


def sharded_tree_levels(leaves, mesh: Mesh, *,
                        use_kernel: bool = False):
    """Every level of the padded tree over ``(w, 8)`` u32 leaves as a
    sharded level stack, or ``None`` when the shape doesn't divide the
    mesh (the caller falls back to the 1-device build).

    Returns the same tuple as ``merkle_kernel._levels_body`` — widths
    ``w, w/2, …, 1`` — with levels of width ≥ ndev sharded over
    ``batch`` and the top ``log2(ndev)`` levels replicated.
    """
    w = int(leaves.shape[0])
    ndev = axis_size(mesh)
    if ndev == 1 or not _is_pow2(ndev) or not _is_pow2(w) \
            or w % ndev or w // ndev < 2:
        return None
    local_depth = _log2(w // ndev)

    key = (mesh, local_depth, bool(use_kernel))
    prog = _LEVELS_PROGRAMS.get(key)
    if prog is None:
        from ..ops.merkle_kernel import _levels_body

        def local_levels(chunk):
            # chunk: (local_w, 8) — one whole aligned sub-tree per
            # shard; its full local level stack, sub-root included.
            return _levels_body(chunk, use_kernel=use_kernel)

        prog = mesh_program(
            local_levels, mesh=mesh, in_specs=P(BATCH_AXIS),
            out_specs=tuple(P(BATCH_AXIS)
                            for _ in range(local_depth + 1)))
        _LEVELS_PROGRAMS[key] = prog

    lower = prog(leaves)          # widths w .. ndev, sharded
    tops = _get_top_fold()(lower[-1])  # widths ndev/2 .. 1, replicated
    return tuple(lower) + tuple(tops)
