"""Sharded Merkle-tree reduction over a device mesh.

The 1M-validator registry tree (depth 40+1,
``/root/reference/consensus/types/src/eth_spec.rs:267``) is the dominant
``hash_tree_root`` workload.  On a multi-chip mesh we split the leaf range
over the ``batch`` axis, reduce each contiguous sub-range to its sub-tree
root entirely on-chip with ``shard_map`` (zero communication — leaf ranges
are power-of-two aligned so each shard owns a whole sub-tree), all-gather
the per-chip roots over ICI, and fold the remaining ``log2(n_chips)`` +
zero-padding levels replicated.  The reference's equivalent is rayon over
4096-validator arenas (``tree_hash_cache.rs:25-33,535-556``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
# The experimental import with ``check_rep=False`` is the ONE spelling
# proven on both jax lineages this repo runs under (0.4.x here, newer on
# the multichip driver) — the same pattern as ``bls_shard``'s
# ``sharded_g1_sum``.  The 0.5+ top-level ``jax.shard_map`` renamed the
# kwarg to ``check_vma``, so feature-detecting the import and passing one
# kwarg name unconditionally breaks on whichever side wasn't tested.
from jax.experimental.shard_map import shard_map

from ..ops.merkle import merkleize
from .mesh import BATCH_AXIS, batch_sharding


def _log2(n: int) -> int:
    assert n & (n - 1) == 0 and n > 0, f"{n} not a power of two"
    return n.bit_length() - 1


@partial(jax.jit, static_argnames=("depth", "mesh"))
def sharded_merkle_root(leaves: jnp.ndarray, mesh: Mesh, depth: int) -> jnp.ndarray:
    """Root of a depth-``depth`` padded tree over ``leaves`` ``(n, 8)`` u32.

    ``n`` must be a power of two divisible by the mesh size.  The input is
    (re)sharded contiguously over the ``batch`` axis; output is the
    replicated ``(8,)`` root.
    """
    n = leaves.shape[0]
    ndev = mesh.shape[BATCH_AXIS]
    assert n % ndev == 0, (n, ndev)
    local_n = n // ndev
    local_depth = _log2(local_n)
    assert depth >= local_depth + _log2(ndev)

    leaves = jax.lax.with_sharding_constraint(leaves, batch_sharding(mesh))

    def local_subtree(chunk):
        # chunk: (local_n, 8) — one whole aligned sub-tree per device.
        return merkleize(chunk, local_depth)[None]  # (1, 8)

    # check_rep=False: the SHA round scan seeds its carry with the constant
    # IV (unvarying) and folds in the sharded block, which trips the
    # replication/varying-axes check; semantics are still purely per-shard.
    roots = shard_map(
        local_subtree, mesh=mesh,
        in_specs=P(BATCH_AXIS), out_specs=P(BATCH_AXIS),
        check_rep=False,
    )(leaves)  # (ndev, 8), sharded — the following gather rides ICI.

    return merkleize(roots, depth, base_level=local_depth)
