"""Sharded BLS aggregation over a device mesh.

The crypto analogue of the sharded Merkle reduction
(:mod:`.merkle_shard`): a large pubkey/signature aggregation is
data-parallel over the mesh — each chip tree-sums its local shard of
points (the per-set pubkey aggregation of
``verify_multiple_aggregate_signatures``,
``/root/reference/crypto/bls/src/impls/blst.rs:36-119``, which the
reference rayon-parallelises across cores), then the per-chip partial sums
combine via an ICI all-gather + replicated log-depth fold.  Elliptic-curve
addition is not a ``psum``-able monoid for XLA, so the collective moves
the 3×26-limb partials (312 bytes/chip) and every chip folds the gathered
row — communication-minimal and deterministic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from jax.experimental.shard_map import shard_map

from ..crypto import limb_curve as LC


def sharded_g1_sum(points: jnp.ndarray, mesh) -> jnp.ndarray:
    """Sum ``(n, 3, 26)`` projective G1 points, ``n`` divisible by the mesh
    size and a power of two per shard.  Returns one ``(3, 26)`` point
    (replicated)."""
    n = points.shape[0]
    d = mesh.devices.size
    if n % d:
        raise ValueError("point count must divide the mesh")
    local = n // d
    if local & (local - 1):
        raise ValueError("per-device point count must be a power of two")

    def block(pts):  # (local, 3, 26) on each device
        partial = LC.tree_sum(LC.G1_OPS, pts, local)      # (3, 26)
        gathered = jax.lax.all_gather(partial, "batch")   # (d, 3, 26)
        # Fold the gathered row with a scan, NOT an unrolled loop: the
        # complete-addition formula is ~250 HLO ops per instance and
        # XLA-CPU compiles each instance in ~80 s — the r3 multichip dry
        # run timed out on a body with d-1 unrolled copies.  A scan keeps
        # exactly one instance in the program; d is small (chip count), so
        # the sequential fold costs nothing at run time.
        def step(acc, q):
            return LC.point_add(LC.G1_OPS, acc, q), None
        acc0 = jnp.asarray(LC.identity_like(LC.G1_OPS, ()))
        total, _ = jax.lax.scan(step, acc0, gathered)
        return total

    fn = shard_map(block, mesh=mesh, in_specs=P("batch"), out_specs=P(),
                   check_rep=False)  # the fold is replicated by construction
    return jax.jit(fn)(points)
