"""Sharded BLS workloads over a device mesh.

The crypto analogue of the sharded Merkle reduction
(:mod:`.merkle_shard`), in two tiers:

- :func:`sharded_g1_sum` — data-parallel pubkey aggregation (the G1
  fragment that shipped first);
- :func:`sharded_verify_signature_sets` — the FLAGSHIP workload,
  ``verify_signature_sets`` itself, sets-axis data-parallel over the
  mesh.  Each chip runs the full per-set pipeline on its shard (pubkey
  tree-aggregation → RLC scaling → Miller loops → local Fq12 lane fold),
  then exactly three small collectives close the batch: an all-gather of
  the per-chip Fq12 partial products (5 KB/chip), an all-gather of the
  per-chip Σ c_i·σ_i G2 partials (2.4 KB/chip), and an all-gather of the
  identity-aggregate bad flags.  Every chip folds the gathered rows and
  runs ONE replicated final exponentiation — the product-of-pairings
  trick stretched across the ICI, so the 2700-bit-exponent tail is paid
  once per batch, not once per chip.

Elliptic-curve addition / Fq12 multiplication are not ``psum``-able
monoids for XLA, so the collectives move the tiny partials and every
chip folds the gathered row with a ``lax.scan`` — communication-minimal,
deterministic, and one compiled fold instance regardless of mesh size
(an unrolled fold made the r3 dry run time out; see the comment in
:func:`sharded_g1_sum`).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from jax.experimental.shard_map import shard_map

from ..crypto import limb_curve as LC
from ..crypto import limb_field as LF
from ..crypto import limb_tower as T
from ..crypto import limb_pairing as XP
from ..ops.merkle import _next_pow2
from .mesh import BATCH_AXIS


def sharded_g1_sum(points: jnp.ndarray, mesh) -> jnp.ndarray:
    """Sum ``(n, 3, 26)`` projective G1 points, ``n`` divisible by the mesh
    size and a power of two per shard.  Returns one ``(3, 26)`` point
    (replicated)."""
    n = points.shape[0]
    d = mesh.devices.size
    if n % d:
        raise ValueError("point count must divide the mesh")
    local = n // d
    if local & (local - 1):
        raise ValueError("per-device point count must be a power of two")

    def block(pts):  # (local, 3, 26) on each device
        partial = LC.tree_sum(LC.G1_OPS, pts, local)      # (3, 26)
        gathered = jax.lax.all_gather(partial, "batch")   # (d, 3, 26)
        # Fold the gathered row with a scan, NOT an unrolled loop: the
        # complete-addition formula is ~250 HLO ops per instance and
        # XLA-CPU compiles each instance in ~80 s — the r3 multichip dry
        # run timed out on a body with d-1 unrolled copies.  A scan keeps
        # exactly one instance in the program; d is small (chip count), so
        # the sequential fold costs nothing at run time.
        def step(acc, q):
            return LC.point_add(LC.G1_OPS, acc, q), None
        acc0 = jnp.asarray(LC.identity_like(LC.G1_OPS, ()))
        total, _ = jax.lax.scan(step, acc0, gathered)
        return total

    fn = shard_map(block, mesh=mesh, in_specs=P("batch"), out_specs=P(),
                   check_rep=False)  # the fold is replicated by construction
    from ..common.device_ledger import LEDGER
    LEDGER.note_transfer("h2d", int(getattr(points, "nbytes", 0)),
                         subsystem="bls")
    return jax.jit(fn)(points)


# ---------------------------------------------------------------------------
# Mesh-sharded flagship: verify_signature_sets
# ---------------------------------------------------------------------------


@lru_cache(maxsize=8)
def _sharded_verify_fn(mesh):
    """Compiled sets-sharded batch verify for ``mesh`` (jit-cached per
    input shape bucket).  Inputs mirror
    :func:`..crypto.tpu_backend._verify_sets_kernel` — pk (S, K, 3, 26),
    kmask (S, K) bool, sig/h (S, 3, 2, 26) projective, scal (S, 2)
    uint32 lo/hi, smask (S,) bool — with S divisible by the mesh and the
    per-chip shard a power of two.  Returns a replicated scalar bool."""

    def block(pk, kmask, sig, h, scal, smask):
        S_loc, K = pk.shape[0], pk.shape[1]
        ident1 = jnp.asarray(LC.identity_like(LC.G1_OPS, ()))
        pkm = LC.point_select(kmask, pk, ident1, LC.G1_OPS)
        agg = LC.tree_sum(LC.G1_OPS, pkm, K)              # (S_loc, 3, 26)
        # Live sets with identity aggregate pubkeys are invalid (the
        # blst/PythonBackend aggregate-move rule).
        bad = jnp.any(smask & LF.is_zero(agg[..., 2, :]))
        aggc = LC.scalar_mul(LC.G1_OPS, agg, scal)        # c_i · aggpk_i
        sigc = LC.scalar_mul(LC.G2_OPS, sig, scal)        # c_i · σ_i
        sig_part = LC.tree_sum(LC.G2_OPS, sigc, S_loc)    # (3, 2, 26)
        f_part = XP.multi_pairing_partial(aggc, h, smask)  # (2, 3, 2, 26)
        gf = jax.lax.all_gather(f_part, BATCH_AXIS)       # (d, 2, 3, 2, 26)
        gs = jax.lax.all_gather(sig_part, BATCH_AXIS)     # (d, 3, 2, 26)
        gbad = jax.lax.all_gather(bad, BATCH_AXIS)        # (d,)

        # Replicated folds of the gathered rows — scans, not unrolled
        # loops (one compiled instance; d is tiny, run time is nothing).
        def fq12_step(acc, q):
            return T.fq12_mul(acc, q), None

        ftot, _ = jax.lax.scan(fq12_step, jnp.asarray(T.FQ12_ONE_LIMBS), gf)

        def g2_step(acc, q):
            return LC.point_add(LC.G2_OPS, acc, q), None

        acc0 = jnp.asarray(LC.identity_like(LC.G2_OPS, ()))
        sigsum, _ = jax.lax.scan(g2_step, acc0, gs)
        return ftot, sigsum, jnp.any(gbad)

    sharded = shard_map(
        block, mesh=mesh,
        in_specs=(P(BATCH_AXIS), P(BATCH_AXIS), P(BATCH_AXIS),
                  P(BATCH_AXIS), P(BATCH_AXIS), P(BATCH_AXIS)),
        out_specs=(P(), P(), P()),
        check_rep=False)  # folds of all-gathered rows: replicated by hand

    def verify(pk, kmask, sig, h, scal, smask):
        ftot, sigsum, bad = sharded(pk, kmask, sig, h, scal, smask)
        # σ lane — e(−G, Σ c_i·σ_i) — replicated, ONE Miller lane for the
        # whole batch; multi_pairing_partial's identity masking covers the
        # all-sets-missing-signature degenerate exactly like the
        # single-chip kernel.
        neg_g = jnp.asarray(LC.g1_to_limbs(_neg_g1_gen()))
        sig_f = XP.multi_pairing_partial(
            neg_g[None], sigsum[None], jnp.ones((1,), bool))
        total = T.fq12_mul(ftot, sig_f)
        ok = XP.fq12_is_one(XP.final_exponentiation_cubed(total))
        return ok & ~bad

    return jax.jit(verify)


def _neg_g1_gen():
    from ..crypto import curve as C
    return C.g1_neg(C.G1_GEN)


def _pad_rows(arr: np.ndarray, total: int, fill: np.ndarray) -> np.ndarray:
    """Grow dim 0 of ``arr`` to ``total`` rows, padding with ``fill``."""
    if arr.shape[0] == total:
        return arr
    pad = np.broadcast_to(fill, (total - arr.shape[0],) + arr.shape[1:])
    return np.concatenate([arr, pad], axis=0)


def sharded_verify_signature_sets(sets, mesh, rand_fn=None) -> bool:
    """``verify_signature_sets`` data-parallel over ``mesh`` — the
    flagship batch-verify workload, sets-axis sharded.

    ``sets``: SignatureSet sequence (host pre-checks identical to
    ``TpuBackend.verify_signature_sets``); uneven set counts pad with
    masked lanes so any batch size shards over any mesh.  One device
    dispatch, one host sync; the verdict equals the host oracle's.
    """
    import secrets

    from ..crypto import tpu_backend as TB

    if not sets:
        return False
    entries = []
    for s in sets:
        if s.signature is None or s.signature.point is None:
            return False
        if not s.signing_keys:
            return False
        entries.append((s.signature.point,
                        [k.point for k in s.signing_keys],
                        bytes(s.message)))

    if rand_fn is None:
        def rand_fn():
            c = 0
            while c == 0:
                c = secrets.randbits(64)
            return c

    pk, kmask, sig, h, scal, smask = TB._marshal_xla(entries, rand_fn)
    d = int(mesh.devices.size)
    S = pk.shape[0]
    loc = _next_pow2(-(-S // d))          # per-chip sets, power of two
    S_pad = d * loc
    if S_pad != S:
        pk = _pad_rows(pk, S_pad, TB._G1_IDENT[None])
        kmask = _pad_rows(kmask, S_pad, np.zeros((1, kmask.shape[1]), bool))
        sig = _pad_rows(sig, S_pad, TB._G2_IDENT)
        h = _pad_rows(h, S_pad, TB._G2_IDENT)
        scal = _pad_rows(scal, S_pad, np.zeros((1, 2), np.uint32))
        smask = _pad_rows(smask, S_pad, np.zeros(1, bool))
    # Transfer accounting (the BLS shard's first): the jit call stages
    # the marshalled planes implicitly — account them here, where their
    # sizes are known, plus the 1-byte replicated verdict pull.
    from ..common.device_ledger import LEDGER
    LEDGER.note_transfer(
        "h2d", pk.nbytes + kmask.nbytes + sig.nbytes + h.nbytes
        + scal.nbytes + smask.nbytes, subsystem="bls")
    import time
    t0 = time.perf_counter()
    ok = bool(_sharded_verify_fn(mesh)(pk, kmask, sig, h, scal, smask))
    LEDGER.note_dispatch("bls", (time.perf_counter() - t0) * 1e3)
    LEDGER.note_transfer("d2h", 1, subsystem="bls")
    return ok


def bucketed_verify_signature_sets(sets, mesh, rand_fn=None) -> bool:
    """Sharded batch verify with verification-service-style K-buckets —
    the block-batch entry point of the overlapped signature pipeline.

    :func:`sharded_verify_signature_sets` pads every set's key list to
    the batch-wide max K.  A block's batch mixes committee-width
    attestation sets with single-key proposer/randao/exit sets and a
    possible 512-key sync aggregate, so one monolithic pad wastes most
    of the pubkey-aggregation lanes; here sets group by padded signer
    count (next_pow2 — the same bucket key the verification service
    uses at ingress) and each bucket dispatches as its own sharded
    batch.  Buckets are independent RLC products, so the AND of bucket
    verdicts equals the monolithic verdict (a failing bucket
    short-circuits, exactly like a failing monolithic batch returns
    one False)."""
    if not sets:
        return False
    groups: dict = {}
    for s in sets:
        k = _next_pow2(max(1, len(getattr(s, "signing_keys", ()) or ())))
        groups.setdefault(k, []).append(s)
    for k in sorted(groups):
        if not sharded_verify_signature_sets(groups[k], mesh,
                                             rand_fn=rand_fn):
            return False
    return True
