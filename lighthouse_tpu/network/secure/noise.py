"""Noise-XX secure channel over a TCP socket.

The role of the reference's libp2p noise transport
(``lighthouse_network``'s connection upgrade): mutual static-key
authentication with identity hiding, bound to the node id the rest of
the stack keys scores and bans on (``node_id = sha256(static_pub)[:8]``
— forging a node id now requires forging an X25519 key, not editing a
Status frame).

Handshake (Noise XX message pattern over X25519/ChaChaPoly/SHA-256):

    prologue:  codec offer byte (mixed into h by both sides — a MitM
               stripping compression breaks the handshake instead)
    → msg1:    e
    ← msg2:    e, ee, s, es   + encrypted payload: chosen codec byte
    → msg3:    s, se          + encrypted payload: empty

Each message travels as ``u16 len | body``.  After msg3 the symmetric
state splits into one AEAD key per direction; records are

    u32 len | AEAD(k_dir, nonce=LE64(counter), codec(frame))

with independent per-direction nonce counters and REKEY-ON-OVERFLOW:
when a direction's counter reaches ``rekey_after`` the key ratchets
(``k = HMAC(k, "rekey")``) and the counter resets — a long-lived
connection can never reuse a (key, nonce) pair.  Handshake and
per-record costs land in ``common.metrics`` histograms so the crypto
overhead stays a measured quantity (cf. *Performance of EdDSA and BLS
Signatures in Committee-Based Consensus*).
"""

from __future__ import annotations

import hashlib
import hmac
import socket
import struct
import time
from typing import Optional, Tuple

from ...common import metrics
from . import chacha, codec as codec_mod, x25519

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256_lighthouse-tpu"

# 64-bit nonce space; rekey long before it can wrap.  Small enough to
# exercise in tests via the constructor override.
REKEY_AFTER_DEFAULT = 1 << 20

HANDSHAKE_TIMEOUT_S = 8.0


class HandshakeError(Exception):
    """Handshake failed: truncated, tampered, or identity mismatch."""


def node_id_of(static_pub: bytes) -> bytes:
    """The stable node id the peer manager keys on."""
    return hashlib.sha256(static_pub).digest()[:8]


def _hkdf2(ck: bytes, ikm: bytes) -> Tuple[bytes, bytes]:
    """Noise HKDF (RFC 5869 with the chaining key as salt), 2 outputs."""
    prk = hmac.new(ck, ikm, hashlib.sha256).digest()
    t1 = hmac.new(prk, b"\x01", hashlib.sha256).digest()
    t2 = hmac.new(prk, t1 + b"\x02", hashlib.sha256).digest()
    return t1, t2


class _SymmetricState:
    """Noise symmetric state: transcript hash h + chaining key ck + the
    current handshake cipher key/nonce."""

    def __init__(self):
        self.h = hashlib.sha256(PROTOCOL_NAME).digest()
        self.ck = self.h
        self.k: Optional[bytes] = None
        self.n = 0

    def mix_hash(self, data: bytes) -> None:
        self.h = hashlib.sha256(self.h + data).digest()

    def mix_key(self, ikm: bytes) -> None:
        self.ck, self.k = _hkdf2(self.ck, ikm)
        self.n = 0

    def _nonce(self) -> bytes:
        n = struct.pack("<4xQ", self.n)
        self.n += 1
        return n

    def encrypt_and_hash(self, pt: bytes) -> bytes:
        if self.k is None:
            self.mix_hash(pt)
            return pt
        ct = chacha.seal(self.k, self._nonce(), pt, aad=self.h)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ct: bytes) -> bytes:
        if self.k is None:
            self.mix_hash(ct)
            return ct
        try:
            pt = chacha.open_(self.k, self._nonce(), ct, aad=self.h)
        except chacha.AuthError as e:
            raise HandshakeError(f"handshake AEAD failed: {e}") from e
        self.mix_hash(ct)
        return pt

    def split(self) -> Tuple[bytes, bytes]:
        return _hkdf2(self.ck, b"")


def _dh(priv: bytes, pub: bytes) -> bytes:
    shared = x25519.x25519(priv, pub)
    if x25519.is_low_order(shared):
        raise HandshakeError("low-order DH point from peer")
    return shared


# -- socket message framing ---------------------------------------------------

def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise HandshakeError(
                f"peer closed mid-handshake ({len(buf)}/{n} bytes)")
        buf += chunk
    return buf


def _send_msg(sock: socket.socket, body: bytes) -> None:
    sock.sendall(struct.pack("<H", len(body)) + body)


def _recv_msg(sock: socket.socket) -> bytes:
    (ln,) = struct.unpack("<H", recv_exact(sock, 2))
    return recv_exact(sock, ln)


# -- the post-handshake record layer ------------------------------------------

class SecureChannel:
    """One direction-pair of AEAD cipherstates + the negotiated codec.

    ``encrypt``/``decrypt`` operate on whole transport frames and are
    each single-threaded by construction (transport writer thread /
    reader thread respectively), so the nonce counters need no locks.
    """

    def __init__(self, send_key: bytes, recv_key: bytes,
                 peer_static_pub: bytes, codec_id: int, initiator: bool,
                 rekey_after: int = REKEY_AFTER_DEFAULT):
        self._send_key = send_key
        self._recv_key = recv_key
        self._send_n = 0
        self._recv_n = 0
        self.rekey_after = max(1, int(rekey_after))
        self.rekeys = 0
        self.initiator = initiator
        self.peer_static_pub = peer_static_pub
        self.peer_id = node_id_of(peer_static_pub)
        self.codec = codec_mod.Codec(codec_id)
        self._enc_hist = metrics.histogram(
            "network_secure_encrypt_seconds",
            "per-record AEAD seal (incl. codec)")
        self._dec_hist = metrics.histogram(
            "network_secure_decrypt_seconds",
            "per-record AEAD open (incl. codec)")

    @staticmethod
    def _ratchet(key: bytes) -> bytes:
        return hmac.new(key, b"rekey", hashlib.sha256).digest()

    def encrypt(self, frame: bytes) -> bytes:
        """plaintext transport frame → wire record (u32 len | ct)."""
        t0 = time.perf_counter()
        pt = self.codec.encode(frame)
        ct = chacha.seal(self._send_key,
                         struct.pack("<4xQ", self._send_n), pt)
        self._send_n += 1
        if self._send_n >= self.rekey_after:
            self._send_key = self._ratchet(self._send_key)
            self._send_n = 0
            self.rekeys += 1
        self._enc_hist.observe(time.perf_counter() - t0)
        return struct.pack("<I", len(ct)) + ct

    def decrypt(self, ct: bytes) -> bytes:
        """wire record body → plaintext transport frame.  Raises
        :class:`chacha.AuthError` on tamper/truncation — the transport
        treats that like any malformed frame: disconnect."""
        t0 = time.perf_counter()
        pt = chacha.open_(self._recv_key,
                          struct.pack("<4xQ", self._recv_n), ct)
        self._recv_n += 1
        if self._recv_n >= self.rekey_after:
            self._recv_key = self._ratchet(self._recv_key)
            self._recv_n = 0
        frame = self.codec.decode(pt)
        self._dec_hist.observe(time.perf_counter() - t0)
        return frame


# -- the two handshake roles --------------------------------------------------

def initiate(sock: socket.socket, static_priv: bytes,
             expected_peer_id: Optional[bytes] = None,
             codec_offer: Optional[int] = None,
             rekey_after: int = REKEY_AFTER_DEFAULT,
             timeout: float = HANDSHAKE_TIMEOUT_S) -> SecureChannel:
    """Run the initiator side (the dialing node).

    ``expected_peer_id`` is the node id discovery advertised for this
    endpoint; a responder whose static key hashes elsewhere aborts the
    connection (id spoofing), BEFORE we reveal our own static key."""
    t0 = time.perf_counter()
    old_to = sock.gettimeout()
    sock.settimeout(timeout)
    try:
        ss = _SymmetricState()
        offer = codec_mod.supported_mask() if codec_offer is None \
            else codec_offer
        ss.mix_hash(bytes([offer & 0xFF]))  # prologue
        e_priv = _gen_key()
        e_pub = x25519.pubkey(e_priv)
        # → msg1: e  (offer byte travels in clear; integrity via prologue)
        ss.mix_hash(e_pub)
        _send_msg(sock, bytes([offer & 0xFF]) + e_pub)
        # ← msg2: e, ee, s, es + codec payload
        msg2 = _recv_msg(sock)
        if len(msg2) < 32 + 48 + 17:
            raise HandshakeError("short handshake message 2")
        re_pub = msg2[:32]
        ss.mix_hash(re_pub)
        ss.mix_key(_dh(e_priv, re_pub))
        rs_ct, payload_ct = msg2[32:32 + 48], msg2[32 + 48:]
        rs_pub = ss.decrypt_and_hash(rs_ct)
        if expected_peer_id is not None \
                and node_id_of(rs_pub) != bytes(expected_peer_id):
            raise HandshakeError(
                "responder static key does not match advertised node id")
        ss.mix_key(_dh(e_priv, rs_pub))
        chosen = ss.decrypt_and_hash(payload_ct)
        if len(chosen) != 1:
            raise HandshakeError("bad codec payload")
        codec_id = chosen[0]
        if not (offer >> codec_id) & 1:
            # A responder answering a codec we never offered is a
            # protocol violation — abort loudly.  (Quietly dropping to
            # identity on our side only would desync the codecs: the
            # responder would keep compressing and every frame would
            # die in decode().)  The graceful-degradation path is the
            # RESPONDER's: choose() picks from the offer∩local
            # intersection, falling back to identity.
            raise HandshakeError(f"responder chose un-offered codec "
                                 f"{codec_id}")
        # → msg3: s, se
        s_pub = x25519.pubkey(static_priv)
        body = ss.encrypt_and_hash(s_pub)
        ss.mix_key(_dh(static_priv, re_pub))
        body += ss.encrypt_and_hash(b"")
        _send_msg(sock, body)
        k_send, k_recv = ss.split()
        metrics.observe("network_secure_handshake_seconds",
                        time.perf_counter() - t0)
        return SecureChannel(k_send, k_recv, rs_pub, codec_id,
                             initiator=True, rekey_after=rekey_after)
    except (OSError, struct.error) as e:
        raise HandshakeError(f"handshake I/O failed: {e}") from e
    finally:
        try:
            sock.settimeout(old_to)
        except OSError:
            pass


def respond(sock: socket.socket, static_priv: bytes,
            rekey_after: int = REKEY_AFTER_DEFAULT,
            timeout: float = HANDSHAKE_TIMEOUT_S) -> SecureChannel:
    """Run the responder side (the accepting node)."""
    t0 = time.perf_counter()
    old_to = sock.gettimeout()
    sock.settimeout(timeout)
    try:
        ss = _SymmetricState()
        # ← msg1: offer + e
        msg1 = _recv_msg(sock)
        if len(msg1) != 33:
            raise HandshakeError("bad handshake message 1")
        offer, re_pub = msg1[0], msg1[1:]
        ss.mix_hash(bytes([offer]))  # prologue
        ss.mix_hash(re_pub)
        # → msg2: e, ee, s, es + chosen codec
        e_priv = _gen_key()
        e_pub = x25519.pubkey(e_priv)
        ss.mix_hash(e_pub)
        ss.mix_key(_dh(e_priv, re_pub))
        s_pub = x25519.pubkey(static_priv)
        body = e_pub + ss.encrypt_and_hash(s_pub)
        ss.mix_key(_dh(static_priv, re_pub))
        codec_id = codec_mod.choose(offer)
        body += ss.encrypt_and_hash(bytes([codec_id]))
        _send_msg(sock, body)
        # ← msg3: s, se
        msg3 = _recv_msg(sock)
        if len(msg3) < 48 + 16:
            raise HandshakeError("short handshake message 3")
        is_pub = ss.decrypt_and_hash(msg3[:48])
        ss.mix_key(_dh(e_priv, is_pub))
        ss.decrypt_and_hash(msg3[48:])
        k_recv, k_send = ss.split()
        metrics.observe("network_secure_handshake_seconds",
                        time.perf_counter() - t0)
        return SecureChannel(k_send, k_recv, is_pub, codec_id,
                             initiator=False, rekey_after=rekey_after)
    except (OSError, struct.error) as e:
        raise HandshakeError(f"handshake I/O failed: {e}") from e
    finally:
        try:
            sock.settimeout(old_to)
        except OSError:
            pass


def _gen_key() -> bytes:
    import secrets

    return secrets.token_bytes(32)
