"""X25519 Diffie-Hellman — RFC 7748 curve25519 scalar multiplication.

The container has no ``cryptography`` package (the hard constraint the
keystore's :mod:`~lighthouse_tpu.crypto.aes_fallback` already works
under), so the handshake's DH is pure python: the RFC 7748 §5 Montgomery
ladder with constant structure (branchless conditional swap on the swap
bit).  Handshakes are rare — two ladders per connection — so python-int
field arithmetic is plenty; correctness is pinned to the RFC 7748 §5.2
scalar-mult vectors and the §6.1 Diffie-Hellman vector in
``tests/test_secure_channel.py``.
"""

from __future__ import annotations

P = 2**255 - 19
_A24 = 121665  # (486662 - 2) / 4


def _decode_u(u: bytes) -> int:
    """Little-endian u-coordinate; the top bit is masked (RFC 7748 §5)."""
    if len(u) != 32:
        raise ValueError("X25519 u-coordinate must be 32 bytes")
    return int.from_bytes(u, "little") & ((1 << 255) - 1)


def _decode_scalar(k: bytes) -> int:
    """Scalar clamping (RFC 7748 §5): clear the 3 low bits, clear bit
    255, set bit 254."""
    if len(k) != 32:
        raise ValueError("X25519 scalar must be 32 bytes")
    v = int.from_bytes(k, "little")
    v &= ~7
    v &= (1 << 255) - 1
    v |= 1 << 254
    return v


def x25519(k: bytes, u: bytes) -> bytes:
    """Scalar multiplication k·u → 32-byte shared u-coordinate."""
    kn = _decode_scalar(k)
    x1 = _decode_u(u)
    x2, z2 = 1, 0
    x3, z3 = x1, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (kn >> t) & 1
        swap ^= kt
        # RFC 7748's cswap; python ints carry no constant-time guarantees
        # anyway, so the readable branch form is honest here.
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % P
        aa = (a * a) % P
        b = (x2 - z2) % P
        bb = (b * b) % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = (d * a) % P
        cb = (c * b) % P
        x3 = (da + cb) % P
        x3 = (x3 * x3) % P
        z3 = (da - cb) % P
        z3 = (z3 * z3 * x1) % P
        x2 = (aa * bb) % P
        z2 = (e * (aa + _A24 * e)) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = (x2 * pow(z2, P - 2, P)) % P
    return out.to_bytes(32, "little")


_BASE = (9).to_bytes(32, "little")


def pubkey(secret: bytes) -> bytes:
    """Public key = k·9 (the curve's base point u=9)."""
    return x25519(secret, _BASE)


def is_low_order(shared: bytes) -> bool:
    """An all-zero shared secret means the peer sent a low-order point —
    RFC 7748 §6.1 mandates aborting (the Noise spec's DH validity
    check)."""
    return shared == b"\x00" * 32
