"""Pluggable frame compression — the SSZ-snappy seam.

The reference's Req/Resp streams are SSZ-snappy (``rpc/codec/``); this
environment has no snappy library, so the seam ships with the identity
codec and auto-detects ``snappy``/``cramjam`` when importable.  The codec
is NEGOTIATED in the secure handshake (initiator offers a bitmask in the
prologue, responder answers its pick inside the first encrypted payload)
and applied per-frame UNDER the AEAD layer: compress → encrypt, so the
wire shows only ciphertext.

Every frame updates the process-global byte counters in
:mod:`~lighthouse_tpu.common.metrics` (``network_codec_raw_bytes_total``
vs ``network_codec_wire_bytes_total``), so the compression win — or the
identity codec's absence of one — stays measured, not assumed.
"""

from __future__ import annotations

from typing import Dict, Optional

from ...common import metrics

CODEC_IDENTITY = 0
CODEC_SNAPPY = 1

# Per-frame flag byte prepended to the plaintext: did THIS frame actually
# get compressed?  (A codec may decline — e.g. incompressible or tiny
# frames — without renegotiating.)
FLAG_RAW = 0
FLAG_COMPRESSED = 1


def _load_snappy():
    try:  # python-snappy
        import snappy  # type: ignore

        return snappy.compress, snappy.decompress
    except Exception:
        pass
    try:  # cramjam ships a snappy module too
        import cramjam  # type: ignore

        return (lambda b: bytes(cramjam.snappy.compress_raw(b)),
                lambda b: bytes(cramjam.snappy.decompress_raw(b)))
    except Exception:
        return None


_SNAPPY = _load_snappy()

# Frames below this never attempt compression (header + tiny SSZ bodies
# don't win back the codec flag byte, let alone the CPU).
MIN_COMPRESS_LEN = 64


class Codec:
    """One negotiated codec instance; wraps/unwraps a plaintext frame."""

    def __init__(self, codec_id: int):
        if codec_id == CODEC_SNAPPY and _SNAPPY is None:
            raise ValueError("snappy negotiated but not importable")
        self.codec_id = codec_id
        self._raw = metrics.counter(
            "network_codec_raw_bytes_total",
            "plaintext frame bytes before compression")
        self._wire = metrics.counter(
            "network_codec_wire_bytes_total",
            "frame bytes after the codec (pre-AEAD)")
        self._frames = metrics.counter(
            "network_codec_frames_total", "frames through the codec seam")

    def encode(self, frame: bytes) -> bytes:
        """frame → flag byte + (possibly compressed) body."""
        out = bytes([FLAG_RAW]) + frame
        if (self.codec_id == CODEC_SNAPPY
                and len(frame) >= MIN_COMPRESS_LEN):
            packed = _SNAPPY[0](frame)
            if len(packed) < len(frame):
                out = bytes([FLAG_COMPRESSED]) + packed
        self._frames.inc()
        self._raw.inc(len(frame))
        self._wire.inc(len(out) - 1)
        return out

    def decode(self, data: bytes) -> bytes:
        if not data:
            raise ValueError("empty codec frame")
        flag, body = data[0], data[1:]
        if flag == FLAG_RAW:
            return body
        if flag == FLAG_COMPRESSED:
            if self.codec_id != CODEC_SNAPPY:
                raise ValueError("compressed frame on identity codec")
            return _SNAPPY[1](body)
        raise ValueError(f"unknown codec flag {flag}")


def supported_mask() -> int:
    """Bitmask of codecs THIS process can run (the handshake offer)."""
    mask = 1 << CODEC_IDENTITY
    if _SNAPPY is not None:
        mask |= 1 << CODEC_SNAPPY
    return mask


def choose(offer_mask: int, local_mask: Optional[int] = None) -> int:
    """Responder's pick: best codec both sides support.  An offer with no
    overlap (a peer speaking only codecs we lack) falls back to identity
    — every implementation MUST support it, so the connection degrades
    instead of failing."""
    local = supported_mask() if local_mask is None else local_mask
    both = offer_mask & local
    if both & (1 << CODEC_SNAPPY):
        return CODEC_SNAPPY
    return CODEC_IDENTITY
