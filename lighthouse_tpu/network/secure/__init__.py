"""Secure p2p subsystem: Noise-role encrypted transport, pluggable
compression, and Kademlia routing (VERDICT r5 item 8).

- :mod:`.x25519` / :mod:`.chacha` — RFC 7748 / RFC 8439 primitives,
  dependency-free (vector-pinned in ``tests/test_secure_channel.py``).
- :mod:`.noise` — the Noise-XX handshake + AEAD record layer the wire
  transport (:mod:`..transport`) runs every TCP connection through.
- :mod:`.codec` — the negotiated per-frame compression seam (identity
  now, snappy auto-detected when importable).
- :mod:`.kademlia` — the k-bucket table + iterative-lookup state driving
  :class:`..discovery.KademliaDiscovery`.
"""

from .chacha import AuthError
from .codec import CODEC_IDENTITY, CODEC_SNAPPY, Codec
from .kademlia import (
    BUCKET_SIZE,
    Contact,
    KBucketTable,
    LookupState,
    xor_distance,
)
from .noise import (
    HandshakeError,
    SecureChannel,
    initiate,
    node_id_of,
    respond,
)

__all__ = [
    "AuthError",
    "BUCKET_SIZE",
    "CODEC_IDENTITY",
    "CODEC_SNAPPY",
    "Codec",
    "Contact",
    "HandshakeError",
    "KBucketTable",
    "LookupState",
    "SecureChannel",
    "initiate",
    "node_id_of",
    "respond",
    "xor_distance",
]
