"""ChaCha20-Poly1305 AEAD — RFC 8439, dependency-free.

The per-record cipher under the secure channel.  ChaCha20 keystream
generation is vectorized across blocks with numpy uint32 columns (the
same columnar idiom as the state transition: one quarter-round operates
on every block's word lane at once), so a 64 KiB frame costs ~10
double-rounds of array ops instead of 10k python-int rounds.  Poly1305
runs over python ints (130-bit accumulator; one mulmod per 16-byte
block).  Both primitives are pinned to the RFC 8439 §2.3.2/§2.4.2/
§2.5.2/§2.8.2 test vectors in ``tests/test_secure_channel.py``.
"""

from __future__ import annotations

import struct

import numpy as np

_SIGMA = np.frombuffer(b"expa" b"nd 3" b"2-by" b"te k", dtype="<u4").copy()

# Quarter-round index schedule: 4 column rounds then 4 diagonal rounds.
_QR = ((0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
       (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14))


def _rotl(x: np.ndarray, n: int) -> np.ndarray:
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _keystream(key: bytes, counter: int, nonce: bytes,
               nblocks: int) -> bytes:
    """``nblocks`` ChaCha20 blocks starting at ``counter`` — state is a
    (16, nblocks) uint32 plane; every round transforms all blocks."""
    k = np.frombuffer(key, dtype="<u4")
    n = np.frombuffer(nonce, dtype="<u4")
    state = np.empty((16, nblocks), dtype=np.uint32)
    state[0:4] = _SIGMA[:, None]
    state[4:12] = k[:, None]
    state[12] = (counter + np.arange(nblocks, dtype=np.uint64)).astype(
        np.uint32)
    state[13:16] = n[:, None]
    x = state.copy()
    with np.errstate(over="ignore"):
        for _ in range(10):  # 10 double rounds = 20 rounds
            for a, b, c, d in _QR:
                x[a] += x[b]
                x[d] = _rotl(x[d] ^ x[a], 16)
                x[c] += x[d]
                x[b] = _rotl(x[b] ^ x[c], 12)
                x[a] += x[b]
                x[d] = _rotl(x[d] ^ x[a], 8)
                x[c] += x[d]
                x[b] = _rotl(x[b] ^ x[c], 7)
        x += state
    # Serialize: per block, the 16 words little-endian → (nblocks, 64) bytes.
    return x.T.astype("<u4").tobytes()


def chacha20_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    """One 64-byte block (RFC 8439 §2.3)."""
    return _keystream(key, counter, nonce, 1)


def chacha20_xor(key: bytes, counter: int, nonce: bytes,
                 data: bytes) -> bytes:
    """Encrypt/decrypt (RFC 8439 §2.4): XOR with the keystream starting
    at ``counter``."""
    if len(key) != 32 or len(nonce) != 12:
        raise ValueError("ChaCha20 needs a 32-byte key and 12-byte nonce")
    if not data:
        return b""
    nblocks = (len(data) + 63) // 64
    ks = np.frombuffer(_keystream(key, counter, nonce, nblocks),
                       dtype=np.uint8)[: len(data)]
    buf = np.frombuffer(data, dtype=np.uint8)
    return (buf ^ ks).tobytes()


_P1305 = (1 << 130) - 5


def poly1305(key: bytes, msg: bytes) -> bytes:
    """Poly1305 MAC (RFC 8439 §2.5): r is clamped; the accumulator runs
    mod 2^130-5; s is added mod 2^128 at the end."""
    if len(key) != 32:
        raise ValueError("Poly1305 needs a 32-byte one-time key")
    r = int.from_bytes(key[:16], "little") \
        & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:], "little")
    acc = 0
    for off in range(0, len(msg), 16):
        block = msg[off:off + 16]
        n = int.from_bytes(block, "little") | (1 << (8 * len(block)))
        acc = ((acc + n) * r) % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    return b"\x00" * (-len(data) % 16)


def _mac_data(aad: bytes, ct: bytes) -> bytes:
    return (aad + _pad16(aad) + ct + _pad16(ct)
            + struct.pack("<QQ", len(aad), len(ct)))


def seal(key: bytes, nonce: bytes, plaintext: bytes,
         aad: bytes = b"") -> bytes:
    """AEAD encrypt (RFC 8439 §2.8) → ciphertext || 16-byte tag.  The
    one-time Poly1305 key is block 0's first half; data starts at
    counter 1."""
    otk = chacha20_block(key, 0, nonce)[:32]
    ct = chacha20_xor(key, 1, nonce, plaintext)
    return ct + poly1305(otk, _mac_data(aad, ct))


class AuthError(Exception):
    """Tag verification failed — tampered or truncated ciphertext."""


def open_(key: bytes, nonce: bytes, sealed: bytes,
          aad: bytes = b"") -> bytes:
    """AEAD decrypt; raises :class:`AuthError` on any tag mismatch
    (including a record too short to carry a tag)."""
    import hmac as _hmac

    if len(sealed) < 16:
        raise AuthError("record shorter than the AEAD tag")
    ct, tag = sealed[:-16], sealed[-16:]
    otk = chacha20_block(key, 0, nonce)[:32]
    want = poly1305(otk, _mac_data(aad, ct))
    if not _hmac.compare_digest(tag, want):
        raise AuthError("AEAD tag mismatch")
    return chacha20_xor(key, 1, nonce, ct)
