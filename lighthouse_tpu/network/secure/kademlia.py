"""Kademlia k-bucket routing table over 64-bit node ids.

The discv5 role (``/root/reference/beacon_node/lighthouse_network/src/
discovery/`` wraps sigp's discv5, itself a Kademlia DHT): node ids live
in an XOR metric space; bucket ``i`` holds contacts whose distance to us
has its highest set bit at position ``i``.  Buckets are LRU-ordered with
the classic liveness bias: a full bucket NEVER evicts a live node for a
fresh one — the caller pings the least-recently-seen member and only
replaces it if that ping times out (old nodes are the reliable ones;
this is also the Sybil resistance argument from the Kademlia paper).

Pure data structure + pure lookup bookkeeping (:class:`LookupState`) —
all sockets live in :mod:`..discovery`, so this whole module unit-tests
without I/O.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

ID_BITS = 64
BUCKET_SIZE = 16          # k
LOOKUP_CONCURRENCY = 3    # alpha
REFRESH_INTERVAL_S = 60.0  # a bucket untouched this long gets a lookup


def xor_distance(a: bytes, b: bytes) -> int:
    return int.from_bytes(a, "big") ^ int.from_bytes(b, "big")


@dataclass
class Contact:
    """ENR-lite record + liveness bookkeeping."""
    node_id: bytes
    host: str
    udp_port: int
    tcp_port: int
    last_seen: float = field(default_factory=time.monotonic)

    @property
    def udp_addr(self) -> Tuple[str, int]:
        return (self.host, self.udp_port)


class KBucketTable:
    """Thread-safe: the discovery service mutates the table from its
    receive loop, its drive loop, AND the per-candidate liveness-ping
    threads; every public method holds the table lock (the buckets are
    tiny, so the critical sections are microseconds)."""

    def __init__(self, self_id: bytes, k: int = BUCKET_SIZE):
        import threading

        self.self_id = bytes(self_id)
        self.k = k
        self.buckets: List[List[Contact]] = [[] for _ in range(ID_BITS)]
        self.last_lookup = [0.0] * ID_BITS  # per-bucket refresh clock
        self._lock = threading.Lock()

    def _bucket_index(self, node_id: bytes) -> Optional[int]:
        d = xor_distance(self.self_id, node_id)
        if d == 0:
            return None  # never track ourselves
        return d.bit_length() - 1

    def get(self, node_id: bytes) -> Optional[Contact]:
        i = self._bucket_index(node_id)
        if i is None:
            return None
        with self._lock:
            for c in self.buckets[i]:
                if c.node_id == node_id:
                    return c
        return None

    def update(self, contact: Contact) -> Optional[Contact]:
        """Insert/refresh a contact (most-recently-seen goes last).

        Returns ``None`` when the contact was stored, or the bucket's
        LEAST-recently-seen member when the bucket is full — the caller
        should liveness-ping that candidate and either ``evict`` it (and
        re-``update``) or drop the newcomer."""
        i = self._bucket_index(contact.node_id)
        if i is None:
            return None
        with self._lock:
            bucket = self.buckets[i]
            for pos, c in enumerate(bucket):
                if c.node_id == contact.node_id:
                    # refresh in place (endpoint may move), move to MRU
                    bucket.pop(pos)
                    contact.last_seen = time.monotonic()
                    bucket.append(contact)
                    return None
            if len(bucket) < self.k:
                contact.last_seen = time.monotonic()
                bucket.append(contact)
                return None
            return bucket[0]  # full: LRU member is the eviction candidate

    def evict(self, node_id: bytes) -> bool:
        i = self._bucket_index(node_id)
        if i is None:
            return False
        with self._lock:
            bucket = self.buckets[i]
            for pos, c in enumerate(bucket):
                if c.node_id == node_id:
                    bucket.pop(pos)
                    return True
        return False

    def closest(self, target: bytes, n: Optional[int] = None
                ) -> List[Contact]:
        """All known contacts ordered by XOR distance to ``target``."""
        with self._lock:
            out = [c for bucket in self.buckets for c in bucket]
        out.sort(key=lambda c: xor_distance(c.node_id, target))
        return out[: (self.k if n is None else n)]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(b) for b in self.buckets)

    def contacts(self) -> List[Contact]:
        with self._lock:
            return [c for bucket in self.buckets for c in bucket]

    # -- refresh bookkeeping --------------------------------------------------

    def mark_lookup(self, target: bytes) -> None:
        i = self._bucket_index(target)
        if i is not None:
            self.last_lookup[i] = time.monotonic()

    def stale_buckets(self, max_age: float = REFRESH_INTERVAL_S
                      ) -> List[int]:
        """Non-empty buckets with no lookup landing in them recently —
        each gets a random-target refresh lookup (Kademlia §2.3)."""
        now = time.monotonic()
        with self._lock:
            return [i for i in range(ID_BITS)
                    if self.buckets[i]
                    and now - self.last_lookup[i] > max_age]

    def random_id_in_bucket(self, i: int) -> bytes:
        """A target id whose distance from us lands in bucket ``i``."""
        import secrets

        d = (1 << i) | secrets.randbits(i)
        return (int.from_bytes(self.self_id, "big") ^ d).to_bytes(
            ID_BITS // 8, "big")


class LookupState:
    """Iterative FINDNODE bookkeeping (Kademlia's node lookup): track a
    shortlist of the closest-seen contacts, hand out the next α unqueried
    ones, absorb responses, and report convergence (no contact closer
    than anything already queried remains).  The I/O loop in
    ``discovery.KademliaDiscovery.lookup`` drives it."""

    def __init__(self, target: bytes, seeds: Iterable[Contact],
                 k: int = BUCKET_SIZE, alpha: int = LOOKUP_CONCURRENCY):
        self.target = bytes(target)
        self.k = k
        self.alpha = alpha
        self.queried: set[bytes] = set()
        self.seen: Dict[bytes, Contact] = {}
        for c in seeds:
            self.seen[c.node_id] = c

    def _shortlist(self) -> List[Contact]:
        out = sorted(self.seen.values(),
                     key=lambda c: xor_distance(c.node_id, self.target))
        return out[: self.k]

    def next_batch(self) -> List[Contact]:
        batch = [c for c in self._shortlist()
                 if c.node_id not in self.queried][: self.alpha]
        for c in batch:
            self.queried.add(c.node_id)
        return batch

    def absorb(self, contacts: Iterable[Contact]) -> List[Contact]:
        """Merge a response; returns the contacts that were new."""
        fresh = []
        for c in contacts:
            if c.node_id not in self.seen:
                self.seen[c.node_id] = c
                fresh.append(c)
        return fresh

    def done(self) -> bool:
        return not any(c.node_id not in self.queried
                       for c in self._shortlist())

    def result(self) -> List[Contact]:
        return self._shortlist()
