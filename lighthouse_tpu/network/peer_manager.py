"""Peer manager with scoring — the role of
``/root/reference/beacon_node/lighthouse_network/src/peer_manager/``
(``score.rs`` real-score arithmetic + ban thresholds, ``peerdb``'s
per-peer state).

Scores are a decaying real number clamped to [MIN_SCORE, MAX_SCORE]; bad
behavior (invalid blocks, Req/Resp timeouts, dead sockets) subtracts,
useful service adds.  Below ``BAN_THRESHOLD`` a peer is banned and every
sync/lookup path skips it; scores decay toward zero with a halflife, so a
ban earned from transient flakiness eventually lifts (the reference's
``score.rs:34-57`` decay model).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Iterable, List, Optional


MAX_SCORE = 100.0
MIN_SCORE = -100.0
BAN_THRESHOLD = -60.0
SCORE_HALFLIFE_S = 600.0


class PeerAction(Enum):
    """(`peer_manager/mod.rs` ReportSource × score deltas)."""
    VALID_MESSAGE = 0.3       # served a good block / fresh gossip
    SYNC_SERVED = 1.0         # completed a range/lookup request usefully
    TIMEOUT = -5.0            # Req/Resp deadline missed
    UNREACHABLE = -10.0       # dead socket / connect refused
    INVALID_MESSAGE = -25.0   # sent a block that failed verification
    FATAL = -100.0            # protocol violation — instant ban


@dataclass
class PeerInfo:
    score: float = 0.0
    last_update: float = field(default_factory=time.monotonic)

    def _decay(self, now: float) -> None:
        dt = now - self.last_update
        if dt > 0:
            self.score *= 0.5 ** (dt / SCORE_HALFLIFE_S)
            self.last_update = now

    def apply(self, delta: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._decay(now)
        self.score = max(MIN_SCORE, min(MAX_SCORE, self.score + delta))

    def current_score(self, now: Optional[float] = None) -> float:
        self._decay(time.monotonic() if now is None else now)
        return self.score


class PeerManager:
    """Keyed by the peer's stable node id when the transport learned one
    (the wire Status handshake carries it — `peerdb` keys by libp2p
    PeerId), falling back to handle identity for in-process peers.  A
    banned node that reconnects gets a NEW handle but the SAME node id, so
    the ban follows it."""

    def __init__(self, log=None):
        self._info: Dict[object, PeerInfo] = {}
        self.log = log

    @staticmethod
    def _key(peer):
        return getattr(peer, "peer_id", None) or id(peer)

    def _entry(self, peer) -> PeerInfo:
        key = self._key(peer)
        info = self._info.get(key)
        if info is None:
            info = self._info[key] = PeerInfo()
        return info

    def report(self, peer, action: PeerAction) -> None:
        info = self._entry(peer)
        before_banned = info.score <= BAN_THRESHOLD
        info.apply(action.value)
        if self.log is not None and not before_banned \
                and info.score <= BAN_THRESHOLD:
            self.log.warn("peer banned", score=round(info.score, 1),
                          action=action.name)

    def score(self, peer) -> float:
        return self._entry(peer).current_score()

    def is_banned(self, peer) -> bool:
        return self._entry(peer).current_score() <= BAN_THRESHOLD

    def best_peers(self, peers: Iterable) -> List:
        """Non-banned peers, best score first — the sync layer's peer
        selection order (`range_sync` peer rotation)."""
        live = [p for p in peers if not self.is_banned(p)]
        return sorted(live, key=lambda p: -self.score(p))

    def identify(self, peer, node_id: bytes) -> None:
        """Attach a stable node id to a peer, MIGRATING any score already
        accumulated under its handle identity — without this, a spammer
        banned pre-handshake could un-ban itself by sending one Status
        (the fresh id would key a fresh zero score).  When both entries
        exist the WORSE score wins: identities cannot launder scores."""
        old = self._info.pop(id(peer), None)
        peer.peer_id = node_id
        if old is None:
            return
        cur = self._info.get(node_id)
        if cur is None or old.current_score() < cur.current_score():
            self._info[node_id] = old

    def forget(self, peer) -> None:
        """Disconnect housekeeping: drop UNKEYED (handle-identity) entries
        so churn cannot leak; identified peers keep their score so a ban
        survives reconnection."""
        if getattr(peer, "peer_id", None) is None:
            self._info.pop(id(peer), None)
