"""Node networking: BeaconProcessor scheduler + in-process gossip/RPC
(counterparts of ``beacon_node/network`` and the node-side architecture of
``beacon_node/lighthouse_network``)."""

from .beacon_processor import (
    BeaconProcessor,
    QUEUE_SPECS,
    WorkEvent,
    WorkType,
)
from .service import (
    ATTESTATION_SUBNET_COUNT,
    BlocksByRangeRequest,
    GossipBus,
    NetworkNode,
    TOPIC_AGGREGATE,
    TOPIC_BLOCK,
)

__all__ = [
    "BeaconProcessor", "WorkEvent", "WorkType", "QUEUE_SPECS",
    "GossipBus", "NetworkNode", "BlocksByRangeRequest",
    "TOPIC_BLOCK", "TOPIC_AGGREGATE", "ATTESTATION_SUBNET_COUNT",
]
