"""Wire transport: SSZ-framed TCP gossip + Req/Resp between OS processes.

The reference's internet stack is libp2p — gossipsub meshes, SSZ-snappy
Req/Resp streams, discv5 discovery
(``/root/reference/beacon_node/lighthouse_network/src/rpc/protocol.rs:161-179``).
This module is the real wire behind this framework's in-process seams: a
:class:`WireNetwork` owns a TCP listener, speaks length-prefixed SSZ
frames, and serves/issues ``Status`` + ``BlocksByRange``/``ByRoot``
Req/Resp.

Every connection is ENCRYPTED by default (the libp2p-noise role,
:mod:`.secure.noise`): dial runs the Noise-XX initiator synchronously,
accept runs the responder at the top of the connection's reader thread,
and all frames — gossip, control, Req/Resp — then travel as AEAD records
(``u32 len | ciphertext``) through the negotiated compression codec
(:mod:`.secure.codec`).  The node id every score/ban keys on is
``sha256(static_x25519_pub)[:8]``, so the handshake itself authenticates
it — a Status frame can no longer claim someone else's identity.
``secure=False`` (the CLI's ``--insecure``) keeps the legacy plaintext
framing for debugging and wire-format tests.

Gossip is a degree-bounded mesh, not a flood (VERDICT r4 #6): a 1 s
heartbeat GRAFTs the best-scoring peers per topic toward D=4 and PRUNEs
negative-score members (``gossipsub_scoring_parameters.rs`` role);
messages decode BEFORE forwarding (validate-before-propagate) with
seen-hash dedup.  Each connection drains through a bounded send queue —
slow peers are evicted, not buffered without bound — and Req/Resp is
token-bucket rate-limited per (peer, method) (``rpc/rate_limiter.rs``);
spam walks the peer score below the ban threshold, and bans follow the
node id carried in the Status handshake across reconnects.

Frame layout (all integers little-endian):

    u8 kind | u32 len | payload
    kind 0 GOSSIP:  u8 topic_len | topic | body
    kind 1 REQUEST: u32 req_id | u8 method | body
    kind 2 RESPONSE:u32 req_id | body

Gossip bodies carry a fork-id byte before each SSZ container so the
receiver picks the right per-fork class (the role of the reference's
ForkDigest in topic names).
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
from typing import Callable, Dict, List, Optional

from ..types.chain_spec import ForkName
from .service import (
    BlocksByRangeRequest,
    GossipBus,
    NetworkNode,
    TOPIC_AGGREGATE,
    TOPIC_BLOCK,
    TOPIC_LC_FINALITY,
    TOPIC_LC_OPTIMISTIC,
    TOPIC_SYNC_COMMITTEE,
)

_FORK_IDS = {f: i for i, f in enumerate(ForkName)}
_FORK_BY_ID = {i: f for f, i in _FORK_IDS.items()}

KIND_GOSSIP = 0
KIND_REQUEST = 1
KIND_RESPONSE = 2
KIND_CONTROL = 3   # gossipsub control: u8 op | u8 topic_len | topic

CTRL_GRAFT = 0
CTRL_PRUNE = 1

METHOD_STATUS = 0
METHOD_BLOCKS_BY_RANGE = 1
METHOD_BLOCKS_BY_ROOT = 2

# Mesh degree targets (gossipsub D_lo/D/D_hi).
MESH_D_LO = 2
MESH_D = 4
MESH_D_HI = 6


def _enc_block(T, signed_block) -> bytes:
    fork = T.fork_of_block(signed_block.message)
    return bytes([_FORK_IDS[fork]]) + type(signed_block).serialize(
        signed_block)


def _dec_block(T, data: bytes):
    fork = _FORK_BY_ID[data[0]]
    return T.signed_block_cls(fork).deserialize(data[1:])


def _enc_block_list(T, blocks: List) -> bytes:
    out = [struct.pack("<I", len(blocks))]
    for b in blocks:
        enc = _enc_block(T, b)
        out.append(struct.pack("<I", len(enc)))
        out.append(enc)
    return b"".join(out)


def _dec_block_list(T, data: bytes) -> List:
    (n,) = struct.unpack_from("<I", data, 0)
    off = 4
    out = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        out.append(_dec_block(T, data[off:off + ln]))
        off += ln
    return out


def _enc_sync(msg) -> bytes:
    slot, root, votes = msg
    out = [struct.pack("<Q32sH", slot, root, len(votes))]
    for positions, sig in votes:
        out.append(struct.pack("<H", len(positions)))
        out.append(b"".join(struct.pack("<H", int(p)) for p in positions))
        out.append(bytes(sig))
    return b"".join(out)


def _dec_sync(data: bytes):
    slot, root, n = struct.unpack_from("<Q32sH", data, 0)
    off = 42
    votes = []
    for _ in range(n):
        (npos,) = struct.unpack_from("<H", data, off)
        off += 2
        positions = list(struct.unpack_from("<%dH" % npos, data, off))
        off += 2 * npos
        votes.append((positions, data[off:off + 96]))
        off += 96
    return (slot, root, votes)


def _enc_lc_optimistic(T, upd) -> bytes:
    hdr = T.BeaconBlockHeader.serialize(upd.attested_header)
    agg = T.SyncAggregate.serialize(upd.sync_aggregate)
    return struct.pack("<HH", len(hdr), len(agg)) + hdr + agg + \
        struct.pack("<Q", int(upd.signature_slot))


def _dec_lc_optimistic(T, data: bytes):
    from ..light_client import LightClientOptimisticUpdate
    hl, al = struct.unpack_from("<HH", data, 0)
    off = 4
    hdr = T.BeaconBlockHeader.deserialize(data[off:off + hl])
    off += hl
    agg = T.SyncAggregate.deserialize(data[off:off + al])
    off += al
    (slot,) = struct.unpack_from("<Q", data, off)
    return LightClientOptimisticUpdate(
        attested_header=hdr, sync_aggregate=agg, signature_slot=slot)


def _enc_lc_finality(T, upd) -> bytes:
    a = T.BeaconBlockHeader.serialize(upd.attested_header)
    f = T.BeaconBlockHeader.serialize(upd.finalized_header)
    g = T.SyncAggregate.serialize(upd.sync_aggregate)
    return (struct.pack("<HHHB", len(a), len(f), len(g),
                        len(upd.finality_branch))
            + a + f + g + b"".join(bytes(b) for b in upd.finality_branch)
            + struct.pack("<QQ", int(upd.signature_slot),
                          int(upd.finalized_checkpoint_epoch)))


def _dec_lc_finality(T, data: bytes):
    from ..light_client import LightClientFinalityUpdate
    al, fl, gl, nb = struct.unpack_from("<HHHB", data, 0)
    off = 7
    attested = T.BeaconBlockHeader.deserialize(data[off:off + al])
    off += al
    finalized = T.BeaconBlockHeader.deserialize(data[off:off + fl])
    off += fl
    agg = T.SyncAggregate.deserialize(data[off:off + gl])
    off += gl
    branch = [data[off + 32 * i:off + 32 * (i + 1)] for i in range(nb)]
    off += 32 * nb
    slot, cp_epoch = struct.unpack_from("<QQ", data, off)
    return LightClientFinalityUpdate(
        attested_header=attested, finalized_header=finalized,
        finality_branch=branch, sync_aggregate=agg, signature_slot=slot,
        finalized_checkpoint_epoch=cp_epoch)


def _enc_atts(T, atts: List) -> bytes:
    out = [struct.pack("<I", len(atts))]
    for a in atts:
        enc = T.Attestation.serialize(a)
        out.append(struct.pack("<I", len(enc)))
        out.append(enc)
    return b"".join(out)


def _dec_atts(T, data: bytes) -> List:
    (n,) = struct.unpack_from("<I", data, 0)
    off = 4
    atts = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        atts.append(T.Attestation.deserialize(data[off:off + ln]))
        off += ln
    return atts


class _Conn:
    """One framed TCP connection: a reader thread plus a writer thread
    draining a BOUNDED send queue (backpressure — VERDICT r4 weak #8).
    A peer that cannot keep up fills its queue and is disconnected
    instead of blocking the sender or buffering without bound.

    ``channel`` (a :class:`.secure.SecureChannel`) wraps frames into
    AEAD records.  Dialed conns arrive with the channel ready (the
    initiator handshake ran synchronously in ``dial``); accepted conns
    get a ``handshake`` callable the reader thread runs FIRST — the
    writer holds queued frames behind ``_ready`` until the channel
    exists, so nothing ever leaves in plaintext on a secure conn."""

    SEND_QUEUE_FRAMES = 256
    SEND_QUEUE_BYTES = 4 << 20
    MAX_RECORD_LEN = 16 << 20

    def __init__(self, sock: socket.socket, on_frame, on_close,
                 channel=None, handshake=None, on_secure=None):
        import queue

        self.sock = sock
        self._on_frame = on_frame
        self._on_close = on_close
        self.channel = channel
        self._handshake = handshake
        self._on_secure = on_secure
        self._ready = threading.Event()
        if handshake is None:
            self._ready.set()
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue(
            self.SEND_QUEUE_FRAMES)
        self._q_bytes = 0
        self._qlock = threading.Lock()
        self.slow_dropped = False  # set when evicted for backpressure
        self._t = threading.Thread(target=self._reader, daemon=True)
        self._wt = threading.Thread(target=self._writer, daemon=True)

    def start(self) -> None:
        """Begin reading AFTER the owner has registered this conn in its
        peer maps — frames processed before registration would look like
        they came from an unknown peer (penalties silently dropped)."""
        self._t.start()
        self._wt.start()

    def send(self, kind: int, payload: bytes) -> None:
        import queue

        frame = struct.pack("<BI", kind, len(payload)) + payload
        with self._qlock:
            # The byte bound is on queue OCCUPANCY: a single oversized
            # frame (e.g. a large BlocksByRange response) is always
            # admitted when the queue is empty — only a backlog evicts.
            if self._q_bytes == 0 or \
                    self._q_bytes + len(frame) <= self.SEND_QUEUE_BYTES:
                try:
                    self._q.put_nowait(frame)
                    self._q_bytes += len(frame)
                    return
                except queue.Full:
                    pass
            self.slow_dropped = True
        # Queue overflow: the peer is too slow — evict it.
        self.close()
        raise OSError("peer send queue overflow (slow peer evicted)")

    def _writer(self) -> None:
        self._ready.wait()  # responder handshake may still be running
        while True:
            frame = self._q.get()
            if frame is None:
                return
            with self._qlock:
                self._q_bytes -= len(frame)
            # Encrypt at drain time, on this thread only: the channel's
            # send nonce counter needs no lock and records hit the wire
            # in counter order.
            data = self.channel.encrypt(frame) if self.channel else frame
            try:
                self.sock.sendall(data)
            except OSError:
                self.close()
                return

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _reader(self) -> None:
        try:
            if self._handshake is not None:
                # Responder role: a dialer that never completes (or
                # fails) the handshake costs its timeout, then the
                # socket closes — a truncated handshake cannot hold a
                # connection slot open.  _ready is set only on SUCCESS;
                # on failure close() sets it after the socket is closed,
                # so queued frames can never drain out in plaintext.
                self.channel = self._handshake(self.sock)
                self._ready.set()
                if self._on_secure is not None:
                    self._on_secure(self)
            while True:
                if self.channel is not None:
                    hdr = self._recv_exact(4)
                    if hdr is None:
                        break
                    (rlen,) = struct.unpack("<I", hdr)
                    if rlen > self.MAX_RECORD_LEN:
                        break  # length bomb
                    record = self._recv_exact(rlen)
                    if record is None:
                        break
                    # AuthError (tamper/truncation) propagates to the
                    # except: disconnect, like any malformed frame.
                    frame = self.channel.decrypt(record)
                    kind, ln = struct.unpack_from("<BI", frame, 0)
                    payload = frame[5:]
                    if len(payload) != ln:
                        break  # inner framing inconsistent
                else:
                    hdr = self._recv_exact(5)
                    if hdr is None:
                        break
                    kind, ln = struct.unpack("<BI", hdr)
                    payload = self._recv_exact(ln)
                    if payload is None:
                        break
                self._on_frame(self, kind, payload)
        except Exception:
            # Malformed frames (bad fork id, truncated SSZ, unknown
            # method, failed handshake, AEAD tag mismatch) disconnect
            # the peer — a remote can always send garbage; it must never
            # wedge the reader silently with the socket left open.
            pass
        finally:
            self.close()
            self._on_close(self)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
        self._ready.set()  # a closing conn must not strand its writer
        try:
            self._q.put_nowait(None)  # wake the writer to exit
        except Exception:
            pass


class _TokenBucket:
    """Per-(peer, method) Req/Resp quota — the role of the reference's
    ``rpc/rate_limiter.rs`` leaky buckets."""

    def __init__(self, capacity: float, refill_per_s: float):
        import time as _time
        self.capacity = capacity
        self.refill = refill_per_s
        self.tokens = capacity
        self.last = _time.monotonic()

    def allow(self, cost: float = 1.0) -> bool:
        import time as _time
        now = _time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self.last) * self.refill)
        self.last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


# Served block counts clamp to this per response (`MAX_REQUEST_BLOCKS`
# role): the quota cost is the CLAMPED count, so an honest oversized
# request degrades to a partial response instead of an unpayable cost
# that would ban the requester.
MAX_REQUEST_BLOCKS = 256

# (capacity, refill/s, cost-fn) per method — shaped after the reference's
# RPC quotas (`rate_limiter.rs` Quota per protocol).
_RPC_QUOTAS = {
    METHOD_STATUS: (8.0, 1.0, lambda body: 1.0),
    METHOD_BLOCKS_BY_RANGE: (
        256.0, 51.2,  # ≈ 512 blocks / 10 s
        lambda body: float(
            min(MAX_REQUEST_BLOCKS, max(1, struct.unpack("<QQ", body)[1])))
        if len(body) == 16 else 1.0),
    METHOD_BLOCKS_BY_ROOT: (
        128.0, 12.8,
        lambda body: float(
            min(MAX_REQUEST_BLOCKS,
                max(1, struct.unpack_from("<I", body, 0)[0])))
        if len(body) >= 4 else 1.0),
}

# Gossip frames per peer per second (burst capacity, refill).
_GOSSIP_QUOTA = (200.0, 50.0)


class RemotePeer:
    """Peer handle over a connection — the NetworkNode sync protocol
    (``head_slot()`` + ``blocks_by_range()``) backed by Req/Resp."""

    def __init__(self, net: "WireNetwork", conn: _Conn):
        self._net = net
        self._conn = conn
        self.status_head_slot = 0
        self.peer_id = None  # learned from the first Status round-trip

    def head_slot(self) -> int:
        # Refresh via a Status round-trip (`rpc` Status; the reference
        # also re-STATUSes before sync decisions).  The request carries
        # OUR node id so the remote can enforce bans at the handshake.
        try:
            resp = self._net._request(self._conn, METHOD_STATUS,
                                      self._net.node_id)
            (self.status_head_slot,) = struct.unpack("<Q", resp[:8])
            # Stable node id: peer-manager scores/bans follow it across
            # reconnections (the libp2p-PeerId role); identify() migrates
            # any score accumulated under the handle identity.
            if len(resp) >= 48 and self.peer_id is None:
                self._net.node.peer_manager.identify(self, resp[40:48])
        except Exception:
            pass
        return self.status_head_slot

    def blocks_by_range(self, req: BlocksByRangeRequest) -> List:
        body = struct.pack("<QQ", req.start_slot, req.count)
        resp = self._net._request(self._conn, METHOD_BLOCKS_BY_RANGE, body)
        return _dec_block_list(self._net.T, resp)

    def blocks_by_root(self, roots: List[bytes]) -> List:
        body = struct.pack("<I", len(roots)) + b"".join(
            bytes(r) for r in roots)
        resp = self._net._request(self._conn, METHOD_BLOCKS_BY_ROOT, body)
        return _dec_block_list(self._net.T, resp)


class WireNetwork:
    """TCP gossip + Req/Resp endpoint wrapping a :class:`NetworkNode`.

    Construction starts a listener on ``port`` (0 = ephemeral); ``dial``
    connects out.  All connected peers receive published gossip; incoming
    gossip floods onward (seen-hash dedup) and feeds the local node's
    BeaconProcessor exactly like in-process gossip.
    """

    def __init__(self, chain, name: str = "node", port: int = 0,
                 log=None, secure: bool = True,
                 static_key: Optional[bytes] = None,
                 rekey_after: Optional[int] = None):
        import secrets as _secrets

        from .secure import noise as _noise
        from .secure import x25519 as _x25519

        self.T = chain.T
        # Identity: a static X25519 key (persisted by the CLI across
        # restarts); the node id everyone scores/bans under is its hash,
        # so under the secure transport identity == key possession.
        self.secure = secure
        self.static_priv = static_key or _secrets.token_bytes(32)
        self.static_pub = _x25519.pubkey(self.static_priv)
        self.node_id = _noise.node_id_of(self.static_pub)
        self._noise = _noise
        self._rekey_after = rekey_after or _noise.REKEY_AFTER_DEFAULT
        self.bus = GossipBus()
        self.node = NetworkNode(chain, self.bus, name=name, log=log)
        self._conns: List[_Conn] = []
        self._peers: Dict[_Conn, RemotePeer] = {}
        self._pending: Dict[int, threading.Event] = {}
        self._responses: Dict[int, bytes] = {}
        self._req_id = 0
        self._seen: set[bytes] = set()
        self._lock = threading.Lock()
        # Gossipsub-style state: per-topic mesh membership, per-conn rate
        # limiter buckets (VERDICT r4 #6).
        self._mesh: Dict[str, set] = {}
        self._rpc_buckets: Dict[_Conn, Dict[int, _TokenBucket]] = {}
        self._gossip_buckets: Dict[_Conn, _TokenBucket] = {}
        self._hb_stop = threading.Event()
        self._hb_t = threading.Thread(target=self._heartbeat_loop,
                                      daemon=True)
        self._hb_t.start()
        # Outbound gossip: re-publish local publishes onto the wire.
        self.bus.subscribe(TOPIC_BLOCK, self._wire_block_out)
        self.bus.subscribe(TOPIC_AGGREGATE, self._wire_atts_out)
        from .service import ATTESTATION_SUBNET_COUNT, \
            TOPIC_ATTESTATION_SUBNET
        self.bus.subscribe(
            TOPIC_SYNC_COMMITTEE,
            lambda msg: self._flood(TOPIC_SYNC_COMMITTEE, _enc_sync(msg)))
        self.bus.subscribe(
            TOPIC_LC_OPTIMISTIC,
            lambda upd: self._flood(TOPIC_LC_OPTIMISTIC,
                                    _enc_lc_optimistic(self.T, upd)))
        self.bus.subscribe(
            TOPIC_LC_FINALITY,
            lambda upd: self._flood(TOPIC_LC_FINALITY,
                                    _enc_lc_finality(self.T, upd)))
        for subnet in range(ATTESTATION_SUBNET_COUNT):
            topic = TOPIC_ATTESTATION_SUBNET.format(subnet)
            self.bus.subscribe(
                topic, lambda atts, _t=topic: self._flood(
                    _t, _enc_atts(self.T, atts)))
        self._listener = socket.create_server(("127.0.0.1", port))
        self.port = self._listener.getsockname()[1]
        # The API introspects the outermost network layer: node_id/port
        # live here, peers/peer_manager on .node (http_api handles both).
        chain.network = self
        self._accept_t = threading.Thread(target=self._accept_loop,
                                          daemon=True)
        self._accept_t.start()

    # -- connections ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            self._add_conn(sock, responder=True)

    def _add_conn(self, sock: socket.socket,
                  channel=None, responder: bool = False) -> RemotePeer:
        handshake = None
        on_secure = None
        if responder and self.secure:
            handshake = lambda s: self._noise.respond(
                s, self.static_priv, rekey_after=self._rekey_after)
            on_secure = self._on_secure
        conn = _Conn(sock, self._on_frame, self._on_close,
                     channel=channel, handshake=handshake,
                     on_secure=on_secure)
        peer = RemotePeer(self, conn)
        with self._lock:
            self._conns.append(conn)
            self._peers[conn] = peer
        self.node.peers.append(peer)
        if channel is not None:
            # Initiator: the handshake already authenticated the peer's
            # node id — bans apply before a single frame is exchanged.
            self.node.peer_manager.identify(peer, channel.peer_id)
        conn.start()  # only read once the peer maps know this conn
        if channel is not None and \
                self.node.peer_manager.is_banned(peer):
            conn.close()
            raise OSError("banned peer (handshake identity)")
        return peer

    def _on_secure(self, conn: _Conn) -> None:
        """Responder handshake completed: bind the cryptographic node id
        to the peer handle and enforce bans at the door (`peerdb` ban
        enforcement, now keyed on a key-derived id)."""
        peer = self._peers.get(conn)
        if peer is None:
            return
        self.node.peer_manager.identify(peer, conn.channel.peer_id)
        if self.node.peer_manager.is_banned(peer):
            conn.close()

    def dial(self, port: int, host: str = "127.0.0.1",
             expected_id: Optional[bytes] = None) -> RemotePeer:
        sock = socket.create_connection((host, port))
        channel = None
        if self.secure:
            try:
                channel = self._noise.initiate(
                    sock, self.static_priv, expected_peer_id=expected_id,
                    rekey_after=self._rekey_after)
            except self._noise.HandshakeError as e:
                try:
                    sock.close()
                except OSError:
                    pass
                # Callers (discovery, sync) already handle dial failures
                # as OSError; an id-spoofing endpoint is just a failed
                # dial to them.
                raise OSError(f"secure handshake failed: {e}") from e
        return self._add_conn(sock, channel=channel)

    def connect_unique(self, host: str, port: int,
                       expected_id: Optional[bytes] = None,
                       ) -> Optional[RemotePeer]:
        """Dial unless the target turns out to be this node or an
        already-connected peer: a Status round-trip identifies the remote
        before keeping the connection, so mutual discovery (A and B both
        seeing each other's record) converges on ~one connection per pair
        instead of flooding every frame twice.

        Duplicates resolve by node-id tie-break (libp2p's simultaneous-
        dial rule): the LOWER node id keeps its outbound dial, the higher
        id yields.  "Close my outbound whenever any conn already has this
        peer_id" let A and B each treat the other's inbound as the
        existing connection and close both sockets — a permanently
        partitioned pair, since discovery never re-dials a known node id
        (the boot-node mesh flake)."""
        peer = self.dial(port, host, expected_id=expected_id)
        peer.head_slot()  # Status round-trip (fills peer_id when insecure)
        pid = peer.peer_id
        if pid is not None:
            if pid == self.node_id:
                peer._conn.close()
                return None
            dups = [p for p in self.node.peers
                    if p is not peer and p.peer_id == pid]
            if dups:
                if self.node_id < pid:
                    # Canonical dialer: keep this outbound, retire the
                    # duplicate inbound conns (the remote closes the same
                    # sockets from its side of the tie-break).
                    for p in dups:
                        p._conn.close()
                else:
                    peer._conn.close()
                    return None
        return peer

    def discover(self, boot_host: str, boot_port: int,
                 interval: float = 2.0):
        """Join the network via any bootstrap UDP endpoint — a standalone
        :class:`.discovery.BootNode` or another node's own discovery
        service (`discovery/mod.rs` role).  Runs the Kademlia table +
        iterative FINDNODE lookups and dials every fresh record, pinning
        each dial to the record's node id (the secure handshake aborts on
        a mismatch)."""
        from .discovery import KademliaDiscovery
        return KademliaDiscovery(
            self.node_id, self.port, [(boot_host, boot_port)],
            dial=self.connect_unique, interval=interval,
            log=self.node.log)

    def close(self, persist: bool = True) -> None:
        """``persist=False`` is the crash shape: sockets drop, nothing
        is flushed to the store beyond already-committed batches."""
        self._hb_stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for c in list(self._conns):
            c.close()
        self.node.close(persist=persist)

    def _on_close(self, conn: _Conn) -> None:
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)
            peer = self._peers.pop(conn, None)
            for mesh in self._mesh.values():
                mesh.discard(conn)
            self._rpc_buckets.pop(conn, None)
            self._gossip_buckets.pop(conn, None)
        if peer is not None:
            if peer in self.node.peers:
                self.node.peers.remove(peer)
            self.node.peer_manager.forget(peer)

    # -- gossip --------------------------------------------------------------

    def _wire_block_out(self, signed_block) -> None:
        self._flood(TOPIC_BLOCK, _enc_block(self.T, signed_block))

    def _wire_atts_out(self, atts) -> None:
        self._flood(TOPIC_AGGREGATE, _enc_atts(self.T, atts))

    def _flood(self, topic: str, body: bytes,
               exclude: Optional[_Conn] = None) -> bool:
        """Forward to the topic MESH unless already seen; returns True iff
        the message was FRESH (callers gate local delivery on this —
        gossipsub delivers each message id once).

        Degree-bounded forwarding (VERDICT r4 #6): messages go to the
        topic's mesh members (grafted by the heartbeat from peer scores),
        not to every connection.  With no mesh yet (startup, tiny nets)
        it falls back to flooding all conns so the simulator converges
        before the first heartbeat."""
        digest = hashlib.sha256(body).digest()
        with self._lock:
            if digest in self._seen:
                return False
            self._seen.add(digest)
            if len(self._seen) > (1 << 16):
                self._seen.clear()
            mesh = self._mesh.get(topic)
            conns = list(mesh) if mesh else list(self._conns)
        t = topic.encode()
        payload = bytes([len(t)]) + t + body
        for c in conns:
            if c is exclude:
                continue
            try:
                c.send(KIND_GOSSIP, payload)
            except OSError:
                self._penalize(c)
        return True

    # -- gossipsub mesh maintenance ------------------------------------------

    def _mesh_topics(self) -> List[str]:
        topics = [TOPIC_BLOCK, TOPIC_AGGREGATE, TOPIC_SYNC_COMMITTEE,
                  TOPIC_LC_OPTIMISTIC, TOPIC_LC_FINALITY]
        from .service import TOPIC_ATTESTATION_SUBNET
        topics += [TOPIC_ATTESTATION_SUBNET.format(s)
                   for s in self.node.subnets]
        return topics

    def _send_control(self, conn: _Conn, op: int, topic: str) -> None:
        t = topic.encode()
        try:
            conn.send(KIND_CONTROL, bytes([op, len(t)]) + t)
        except OSError:
            pass

    def _heartbeat_loop(self, interval: float = 1.0) -> None:
        while not self._hb_stop.wait(interval):
            try:
                self._heartbeat()
            except Exception:
                pass

    def _heartbeat(self) -> None:
        """Score-driven graft/prune toward D per topic (`gossipsub
        heartbeat + gossipsub_scoring_parameters.rs` roles): prune
        negative-score members, graft best-scoring outsiders below D_lo,
        prune worst members above D_hi."""
        pm = self.node.peer_manager
        with self._lock:
            conns = list(self._conns)
            peers = dict(self._peers)
        # Banned peers are disconnected outright (`peerdb` ban handling).
        for c in conns:
            p = peers.get(c)
            if p is not None and pm.is_banned(p):
                c.close()
        for topic in self._mesh_topics():
            with self._lock:
                mesh = self._mesh.setdefault(topic, set())
                mesh &= set(conns)  # drop dead conns
                members = list(mesh)

            def score(c):
                p = peers.get(c)
                return pm.score(p) if p is not None else 0.0

            for c in members:  # prune misbehaving members immediately
                if score(c) < 0:
                    with self._lock:
                        mesh.discard(c)
                    self._send_control(c, CTRL_PRUNE, topic)
            with self._lock:
                size = len(mesh)
            if size < MESH_D_LO:
                outsiders = sorted(
                    (c for c in conns
                     if c not in mesh and score(c) >= 0
                     and not pm.is_banned(peers.get(c))),
                    key=score, reverse=True)
                for c in outsiders[:MESH_D - size]:
                    with self._lock:
                        mesh.add(c)
                    self._send_control(c, CTRL_GRAFT, topic)
            elif size > MESH_D_HI:
                worst = sorted(mesh, key=score)[:size - MESH_D]
                for c in worst:
                    with self._lock:
                        mesh.discard(c)
                    self._send_control(c, CTRL_PRUNE, topic)

    def _penalize(self, conn: _Conn, action=None) -> None:
        from .peer_manager import PeerAction
        peer = self._peers.get(conn)
        if peer is None:
            return
        if action is None:
            action = (PeerAction.UNREACHABLE
                      if getattr(conn, "slow_dropped", False)
                      else PeerAction.INVALID_MESSAGE)
        self.node.peer_manager.report(peer, action)

    # -- frames --------------------------------------------------------------

    def _gossip_allowed(self, conn: _Conn) -> bool:
        with self._lock:
            b = self._gossip_buckets.get(conn)
            if b is None:
                b = self._gossip_buckets[conn] = _TokenBucket(
                    *_GOSSIP_QUOTA)
        return b.allow()

    def _rpc_allowed(self, conn: _Conn, method: int, body: bytes) -> bool:
        quota = _RPC_QUOTAS.get(method)
        if quota is None:
            return False
        cap, refill, cost_fn = quota
        with self._lock:
            per = self._rpc_buckets.setdefault(conn, {})
            b = per.get(method)
            if b is None:
                b = per[method] = _TokenBucket(cap, refill)
        try:
            cost = cost_fn(body)
        except Exception:
            cost = cap  # malformed body: burn the bucket
        return b.allow(cost)

    def _on_frame(self, conn: _Conn, kind: int, payload: bytes) -> None:
        if kind == KIND_GOSSIP:
            peer = self._peers.get(conn)
            if peer is not None and self.node.peer_manager.is_banned(peer):
                return  # banned: drop silently (heartbeat disconnects)
            if not self._gossip_allowed(conn):
                # Spam: penalize and drop the frame.  Repeated floods walk
                # the score below the ban threshold; the heartbeat prunes
                # and sync paths skip banned peers.
                self._penalize(conn)
                return
            tlen = payload[0]
            topic = payload[1:1 + tlen].decode()
            body = payload[1 + tlen:]
            # Validate-before-propagate (gossipsub's default validation
            # mode): DECODE first, forward only what parses — otherwise an
            # honest relayer of junk looks like a spammer to its own mesh
            # and the network self-partitions.  (Deeper semantic checks
            # run async in the BeaconProcessor, as in the reference.)
            deliver = None
            try:
                if topic == TOPIC_BLOCK:
                    obj = _dec_block(self.T, body)
                    deliver = lambda: self.node._on_gossip_block(obj)
                elif topic == TOPIC_AGGREGATE:
                    obj = _dec_atts(self.T, body)
                    deliver = lambda: self.node._on_gossip_attestation(obj)
                elif topic == TOPIC_SYNC_COMMITTEE:
                    obj = _dec_sync(body)
                    deliver = lambda: self.node._on_gossip_sync_messages(
                        obj)
                elif topic == TOPIC_LC_OPTIMISTIC:
                    obj = _dec_lc_optimistic(self.T, body)
                    deliver = lambda: self.node._on_gossip_lc_optimistic(
                        obj)
                elif topic == TOPIC_LC_FINALITY:
                    obj = _dec_lc_finality(self.T, body)
                    deliver = lambda: self.node._on_gossip_lc_finality(
                        obj)
                elif topic.startswith("beacon_attestation_"):
                    # Forward decodable subnet traffic; deliver only
                    # subscribed subnets.
                    obj = _dec_atts(self.T, body)
                    subnet = int(topic.rsplit("_", 1)[-1])
                    if subnet in self.node.subnets:
                        deliver = lambda: \
                            self.node._on_gossip_subnet_attestation(obj)
                    else:
                        deliver = lambda: None
                else:
                    self._penalize(conn)  # unknown topic
                    return
            except Exception:
                # Undecodable gossip body: penalize, stay connected (the
                # score decides when it becomes a ban), do NOT forward.
                self._penalize(conn)
                return
            if not self._flood(topic, body, exclude=conn):
                return  # duplicate: neither re-forward nor re-deliver
            deliver()
        elif kind == KIND_CONTROL:
            # Control frames share the gossip token bucket, and only
            # KNOWN topics may create mesh state — a graft flood of
            # random topics must not grow memory nor dodge the limiter.
            if not self._gossip_allowed(conn):
                self._penalize(conn)
                return
            op = payload[0]
            tlen = payload[1]
            topic = payload[2:2 + tlen].decode()
            from .service import TOPIC_ATTESTATION_SUBNET, \
                ATTESTATION_SUBNET_COUNT
            known = (topic in (TOPIC_BLOCK, TOPIC_AGGREGATE,
                               TOPIC_SYNC_COMMITTEE, TOPIC_LC_OPTIMISTIC,
                               TOPIC_LC_FINALITY)
                     or topic in {TOPIC_ATTESTATION_SUBNET.format(s)
                                  for s in range(ATTESTATION_SUBNET_COUNT)})
            if not known:
                self._penalize(conn)
                return
            peer = self._peers.get(conn)
            with self._lock:
                mesh = self._mesh.setdefault(topic, set())
                if op == CTRL_PRUNE:
                    mesh.discard(conn)
                    return
                if op != CTRL_GRAFT:
                    return
                accept = (len(mesh) < MESH_D_HI and peer is not None
                          and self.node.peer_manager.score(peer) >= 0)
                if accept:
                    mesh.add(conn)
            if not accept:
                self._send_control(conn, CTRL_PRUNE, topic)
        elif kind == KIND_REQUEST:
            (req_id,) = struct.unpack_from("<I", payload, 0)
            method = payload[4]
            body = payload[5:]
            if not self._rpc_allowed(conn, method, body):
                # Over-quota (`rate_limiter.rs` role): penalize and answer
                # with an EMPTY response so the requester fails fast
                # instead of hanging out its 10 s timeout.
                self._penalize(conn)
                conn.send(KIND_RESPONSE, struct.pack("<I", req_id))
                return
            resp = self._serve(conn, method, body)
            conn.send(KIND_RESPONSE, struct.pack("<I", req_id) + resp)
        elif kind == KIND_RESPONSE:
            (req_id,) = struct.unpack_from("<I", payload, 0)
            with self._lock:
                ev = self._pending.get(req_id)
                if ev is None:
                    return  # requester timed out — drop, don't leak
                self._responses[req_id] = payload[4:]
            ev.set()

    def _serve(self, conn: _Conn, method: int, body: bytes) -> bytes:
        if method == METHOD_STATUS:
            # The request body carries the CALLER's node id, so bans
            # follow identities across reconnects and a banned node is
            # dropped at the handshake (`peerdb` ban enforcement).  On a
            # SECURE conn the noise handshake already proved an id — the
            # cryptographic identity always wins over the claimed one
            # (a Status body may not re-key a peer to someone else).
            if len(body) >= 8:
                peer = self._peers.get(conn)
                if peer is not None:
                    claimed = conn.channel.peer_id \
                        if conn.channel is not None else body[:8]
                    # identify() migrates any pre-handshake score to the
                    # stable id (worse score wins — no ban laundering).
                    self.node.peer_manager.identify(peer, claimed)
                    if self.node.peer_manager.is_banned(peer):
                        conn.close()
                        raise OSError("banned peer rejected at handshake")
            return struct.pack("<Q32s8s", self.node.chain.head.slot,
                               self.node.chain.head.root, self.node_id)
        if method == METHOD_BLOCKS_BY_RANGE:
            start, count = struct.unpack("<QQ", body)
            blocks = self.node.blocks_by_range(BlocksByRangeRequest(
                start_slot=start, count=min(count, MAX_REQUEST_BLOCKS)))
            return _enc_block_list(self.T, blocks)
        if method == METHOD_BLOCKS_BY_ROOT:
            (n,) = struct.unpack_from("<I", body, 0)
            n = min(n, MAX_REQUEST_BLOCKS)
            roots = [body[4 + i * 32:4 + (i + 1) * 32] for i in range(n)]
            return _enc_block_list(self.T, self.node.blocks_by_root(roots))
        raise ValueError(f"unknown method {method}")

    def _request(self, conn: _Conn, method: int, body: bytes,
                 timeout: float = 10.0) -> bytes:
        with self._lock:
            self._req_id += 1
            req_id = self._req_id
            ev = threading.Event()
            self._pending[req_id] = ev
        conn.send(KIND_REQUEST,
                  struct.pack("<I", req_id) + bytes([method]) + body)
        if not ev.wait(timeout):
            with self._lock:
                self._pending.pop(req_id, None)
                self._responses.pop(req_id, None)
            raise TimeoutError("req/resp timeout")
        with self._lock:
            self._pending.pop(req_id, None)
            return self._responses.pop(req_id)

    # -- convenience ---------------------------------------------------------

    def publish_block(self, signed_block) -> None:
        self.node.publish_block(signed_block)

    def publish_attestations(self, atts: List) -> None:
        self.node.publish_attestations(atts)
