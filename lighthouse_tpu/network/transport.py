"""Wire transport: SSZ-framed TCP gossip + Req/Resp between OS processes.

The reference's internet stack is libp2p — gossipsub meshes, SSZ-snappy
Req/Resp streams, discv5 discovery
(``/root/reference/beacon_node/lighthouse_network/src/rpc/protocol.rs:161-179``).
This module is the first real wire behind this framework's in-process
seams: a :class:`WireNetwork` owns a TCP listener, speaks length-prefixed
SSZ frames (snappy is not available in this environment; the framing layer
is a strict subset of SSZ-snappy minus compression), floods gossip to
every connected peer with seen-message dedup, and serves/issues
``Status`` + ``BlocksByRange`` Req/Resp — enough for two processes to find
each other's head and range-sync, the ``testing/simulator`` seed.

Frame layout (all integers little-endian):

    u8 kind | u32 len | payload
    kind 0 GOSSIP:  u8 topic_len | topic | body
    kind 1 REQUEST: u32 req_id | u8 method | body
    kind 2 RESPONSE:u32 req_id | body

Gossip bodies carry a fork-id byte before each SSZ container so the
receiver picks the right per-fork class (the role of the reference's
ForkDigest in topic names).
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
from typing import Callable, Dict, List, Optional

from ..types.chain_spec import ForkName
from .service import (
    BlocksByRangeRequest,
    GossipBus,
    NetworkNode,
    TOPIC_AGGREGATE,
    TOPIC_BLOCK,
    TOPIC_SYNC_COMMITTEE,
)

_FORK_IDS = {f: i for i, f in enumerate(ForkName)}
_FORK_BY_ID = {i: f for f, i in _FORK_IDS.items()}

KIND_GOSSIP = 0
KIND_REQUEST = 1
KIND_RESPONSE = 2

METHOD_STATUS = 0
METHOD_BLOCKS_BY_RANGE = 1
METHOD_BLOCKS_BY_ROOT = 2


def _enc_block(T, signed_block) -> bytes:
    fork = T.fork_of_block(signed_block.message)
    return bytes([_FORK_IDS[fork]]) + type(signed_block).serialize(
        signed_block)


def _dec_block(T, data: bytes):
    fork = _FORK_BY_ID[data[0]]
    return T.signed_block_cls(fork).deserialize(data[1:])


def _enc_block_list(T, blocks: List) -> bytes:
    out = [struct.pack("<I", len(blocks))]
    for b in blocks:
        enc = _enc_block(T, b)
        out.append(struct.pack("<I", len(enc)))
        out.append(enc)
    return b"".join(out)


def _dec_block_list(T, data: bytes) -> List:
    (n,) = struct.unpack_from("<I", data, 0)
    off = 4
    out = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        out.append(_dec_block(T, data[off:off + ln]))
        off += ln
    return out


def _enc_sync(msg) -> bytes:
    slot, root, votes = msg
    out = [struct.pack("<Q32sH", slot, root, len(votes))]
    for positions, sig in votes:
        out.append(struct.pack("<H", len(positions)))
        out.append(b"".join(struct.pack("<H", int(p)) for p in positions))
        out.append(bytes(sig))
    return b"".join(out)


def _dec_sync(data: bytes):
    slot, root, n = struct.unpack_from("<Q32sH", data, 0)
    off = 42
    votes = []
    for _ in range(n):
        (npos,) = struct.unpack_from("<H", data, off)
        off += 2
        positions = list(struct.unpack_from("<%dH" % npos, data, off))
        off += 2 * npos
        votes.append((positions, data[off:off + 96]))
        off += 96
    return (slot, root, votes)


def _enc_atts(T, atts: List) -> bytes:
    out = [struct.pack("<I", len(atts))]
    for a in atts:
        enc = T.Attestation.serialize(a)
        out.append(struct.pack("<I", len(enc)))
        out.append(enc)
    return b"".join(out)


def _dec_atts(T, data: bytes) -> List:
    (n,) = struct.unpack_from("<I", data, 0)
    off = 4
    atts = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        atts.append(T.Attestation.deserialize(data[off:off + ln]))
        off += ln
    return atts


class _Conn:
    """One framed TCP connection with a reader thread."""

    def __init__(self, sock: socket.socket, on_frame, on_close):
        self.sock = sock
        self._wlock = threading.Lock()
        self._on_frame = on_frame
        self._on_close = on_close
        self._t = threading.Thread(target=self._reader, daemon=True)
        self._t.start()

    def send(self, kind: int, payload: bytes) -> None:
        frame = struct.pack("<BI", kind, len(payload)) + payload
        with self._wlock:
            self.sock.sendall(frame)

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _reader(self) -> None:
        try:
            while True:
                hdr = self._recv_exact(5)
                if hdr is None:
                    break
                kind, ln = struct.unpack("<BI", hdr)
                payload = self._recv_exact(ln)
                if payload is None:
                    break
                self._on_frame(self, kind, payload)
        except Exception:
            # Malformed frames (bad fork id, truncated SSZ, unknown
            # method) disconnect the peer — a remote can always send
            # garbage; it must never wedge the reader silently with the
            # socket left open.
            pass
        finally:
            self.close()
            self._on_close(self)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class RemotePeer:
    """Peer handle over a connection — the NetworkNode sync protocol
    (``head_slot()`` + ``blocks_by_range()``) backed by Req/Resp."""

    def __init__(self, net: "WireNetwork", conn: _Conn):
        self._net = net
        self._conn = conn
        self.status_head_slot = 0
        self.peer_id = None  # learned from the first Status round-trip

    def head_slot(self) -> int:
        # Refresh via a Status round-trip (`rpc` Status; the reference
        # also re-STATUSes before sync decisions).
        try:
            resp = self._net._request(self._conn, METHOD_STATUS, b"")
            (self.status_head_slot,) = struct.unpack("<Q", resp[:8])
            # Stable node id: peer-manager scores/bans follow it across
            # reconnections (the libp2p-PeerId role).
            if len(resp) >= 48:
                self.peer_id = resp[40:48]
        except Exception:
            pass
        return self.status_head_slot

    def blocks_by_range(self, req: BlocksByRangeRequest) -> List:
        body = struct.pack("<QQ", req.start_slot, req.count)
        resp = self._net._request(self._conn, METHOD_BLOCKS_BY_RANGE, body)
        return _dec_block_list(self._net.T, resp)

    def blocks_by_root(self, roots: List[bytes]) -> List:
        body = struct.pack("<I", len(roots)) + b"".join(
            bytes(r) for r in roots)
        resp = self._net._request(self._conn, METHOD_BLOCKS_BY_ROOT, body)
        return _dec_block_list(self._net.T, resp)


class WireNetwork:
    """TCP gossip + Req/Resp endpoint wrapping a :class:`NetworkNode`.

    Construction starts a listener on ``port`` (0 = ephemeral); ``dial``
    connects out.  All connected peers receive published gossip; incoming
    gossip floods onward (seen-hash dedup) and feeds the local node's
    BeaconProcessor exactly like in-process gossip.
    """

    def __init__(self, chain, name: str = "node", port: int = 0,
                 log=None):
        import secrets as _secrets
        self.T = chain.T
        self.node_id = _secrets.token_bytes(8)
        self.bus = GossipBus()
        self.node = NetworkNode(chain, self.bus, name=name, log=log)
        self._conns: List[_Conn] = []
        self._peers: Dict[_Conn, RemotePeer] = {}
        self._pending: Dict[int, threading.Event] = {}
        self._responses: Dict[int, bytes] = {}
        self._req_id = 0
        self._seen: set[bytes] = set()
        self._lock = threading.Lock()
        # Outbound gossip: re-publish local publishes onto the wire.
        self.bus.subscribe(TOPIC_BLOCK, self._wire_block_out)
        self.bus.subscribe(TOPIC_AGGREGATE, self._wire_atts_out)
        from .service import ATTESTATION_SUBNET_COUNT, \
            TOPIC_ATTESTATION_SUBNET
        self.bus.subscribe(
            TOPIC_SYNC_COMMITTEE,
            lambda msg: self._flood(TOPIC_SYNC_COMMITTEE, _enc_sync(msg)))
        for subnet in range(ATTESTATION_SUBNET_COUNT):
            topic = TOPIC_ATTESTATION_SUBNET.format(subnet)
            self.bus.subscribe(
                topic, lambda atts, _t=topic: self._flood(
                    _t, _enc_atts(self.T, atts)))
        self._listener = socket.create_server(("127.0.0.1", port))
        self.port = self._listener.getsockname()[1]
        self._accept_t = threading.Thread(target=self._accept_loop,
                                          daemon=True)
        self._accept_t.start()

    # -- connections ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            self._add_conn(sock)

    def _add_conn(self, sock: socket.socket) -> RemotePeer:
        conn = _Conn(sock, self._on_frame, self._on_close)
        peer = RemotePeer(self, conn)
        with self._lock:
            self._conns.append(conn)
            self._peers[conn] = peer
        self.node.peers.append(peer)
        return peer

    def dial(self, port: int, host: str = "127.0.0.1") -> RemotePeer:
        sock = socket.create_connection((host, port))
        return self._add_conn(sock)

    def connect_unique(self, host: str, port: int) -> Optional[RemotePeer]:
        """Dial unless the target turns out to be this node or an
        already-connected peer: a Status round-trip identifies the remote
        before keeping the connection, so mutual discovery (A and B both
        seeing each other's record) converges on ~one connection per pair
        instead of flooding every frame twice.  A simultaneous-dial race
        can still leave a transient duplicate; gossip stays correct either
        way via the seen-hash dedup in ``_flood``."""
        peer = self.dial(port, host)
        peer.head_slot()  # Status: fills peer.peer_id
        pid = peer.peer_id
        if pid is not None:
            dup = pid == self.node_id or any(
                p is not peer and p.peer_id == pid
                for p in self.node.peers)
            if dup:
                peer._conn.close()
                return None
        return peer

    def discover(self, boot_host: str, boot_port: int,
                 interval: float = 2.0):
        """Join the network via a boot node (`discovery/mod.rs` role):
        registers this endpoint and dials every fresh record."""
        from .discovery import DiscoveryService
        return DiscoveryService(
            self.node_id, self.port, (boot_host, boot_port),
            dial=self.connect_unique, interval=interval,
            log=self.node.log)

    def close(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        for c in list(self._conns):
            c.close()

    def _on_close(self, conn: _Conn) -> None:
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)
            peer = self._peers.pop(conn, None)
        if peer is not None:
            if peer in self.node.peers:
                self.node.peers.remove(peer)
            self.node.peer_manager.forget(peer)

    # -- gossip --------------------------------------------------------------

    def _wire_block_out(self, signed_block) -> None:
        self._flood(TOPIC_BLOCK, _enc_block(self.T, signed_block))

    def _wire_atts_out(self, atts) -> None:
        self._flood(TOPIC_AGGREGATE, _enc_atts(self.T, atts))

    def _flood(self, topic: str, body: bytes,
               exclude: Optional[_Conn] = None) -> bool:
        """Forward to peers unless already seen; returns True iff the
        message was FRESH (callers gate local delivery on this — gossipsub
        delivers each message id once)."""
        digest = hashlib.sha256(body).digest()
        with self._lock:
            if digest in self._seen:
                return False
            self._seen.add(digest)
            if len(self._seen) > (1 << 16):
                self._seen.clear()
            conns = list(self._conns)
        t = topic.encode()
        payload = bytes([len(t)]) + t + body
        for c in conns:
            if c is exclude:
                continue
            try:
                c.send(KIND_GOSSIP, payload)
            except OSError:
                pass
        return True

    # -- frames --------------------------------------------------------------

    def _on_frame(self, conn: _Conn, kind: int, payload: bytes) -> None:
        if kind == KIND_GOSSIP:
            tlen = payload[0]
            topic = payload[1:1 + tlen].decode()
            body = payload[1 + tlen:]
            if not self._flood(topic, body, exclude=conn):
                return  # duplicate: neither re-forward nor re-deliver
            if topic == TOPIC_BLOCK:
                self.node._on_gossip_block(_dec_block(self.T, body))
            elif topic == TOPIC_AGGREGATE:
                self.node._on_gossip_attestation(_dec_atts(self.T, body))
            elif topic == TOPIC_SYNC_COMMITTEE:
                self.node._on_gossip_sync_messages(_dec_sync(body))
            elif topic.startswith("beacon_attestation_"):
                # Deliver only subscribed subnets (forwarding above keeps
                # the mesh connected; a real gossipsub would not even
                # forward unsubscribed topics).
                subnet = int(topic.rsplit("_", 1)[-1])
                if subnet in self.node.subnets:
                    self.node._on_gossip_attestation(_dec_atts(self.T, body))
        elif kind == KIND_REQUEST:
            (req_id,) = struct.unpack_from("<I", payload, 0)
            method = payload[4]
            body = payload[5:]
            resp = self._serve(method, body)
            conn.send(KIND_RESPONSE, struct.pack("<I", req_id) + resp)
        elif kind == KIND_RESPONSE:
            (req_id,) = struct.unpack_from("<I", payload, 0)
            with self._lock:
                ev = self._pending.get(req_id)
                if ev is None:
                    return  # requester timed out — drop, don't leak
                self._responses[req_id] = payload[4:]
            ev.set()

    def _serve(self, method: int, body: bytes) -> bytes:
        if method == METHOD_STATUS:
            return struct.pack("<Q32s8s", self.node.chain.head.slot,
                               self.node.chain.head.root, self.node_id)
        if method == METHOD_BLOCKS_BY_RANGE:
            start, count = struct.unpack("<QQ", body)
            blocks = self.node.blocks_by_range(
                BlocksByRangeRequest(start_slot=start, count=count))
            return _enc_block_list(self.T, blocks)
        if method == METHOD_BLOCKS_BY_ROOT:
            (n,) = struct.unpack_from("<I", body, 0)
            roots = [body[4 + i * 32:4 + (i + 1) * 32] for i in range(n)]
            return _enc_block_list(self.T, self.node.blocks_by_root(roots))
        raise ValueError(f"unknown method {method}")

    def _request(self, conn: _Conn, method: int, body: bytes,
                 timeout: float = 10.0) -> bytes:
        with self._lock:
            self._req_id += 1
            req_id = self._req_id
            ev = threading.Event()
            self._pending[req_id] = ev
        conn.send(KIND_REQUEST,
                  struct.pack("<I", req_id) + bytes([method]) + body)
        if not ev.wait(timeout):
            with self._lock:
                self._pending.pop(req_id, None)
                self._responses.pop(req_id, None)
            raise TimeoutError("req/resp timeout")
        with self._lock:
            self._pending.pop(req_id, None)
            return self._responses.pop(req_id)

    # -- convenience ---------------------------------------------------------

    def publish_block(self, signed_block) -> None:
        self.node.publish_block(signed_block)

    def publish_attestations(self, atts: List) -> None:
        self.node.publish_attestations(atts)
