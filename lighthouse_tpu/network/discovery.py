"""UDP peer discovery — the discv5 role
(``/root/reference/beacon_node/lighthouse_network/src/discovery/`` and the
standalone ``boot_node`` subcommand, ``boot_node/src/``).

Real discv5 is a Kademlia DHT over authenticated UDP; this environment's
stand-in keeps the deployment shape (a UDP boot node that never joins the
chain + per-node discovery services that register and query it) with an
ENR-lite record: ``node_id (8B) | tcp_port (u16) | head_slot (u64)``.

Frames (all little-endian):

    0 PING  node_id(8) tcp_port(2)      → registers the sender
    1 PONG
    2 FIND                              → asks for known records
    3 NODES count(u16) records(18B each: node_id, tcp_port, ipv4)
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..common.logging import Logger, test_logger

MSG_PING = 0
MSG_PONG = 1
MSG_FIND = 2
MSG_NODES = 3

RECORD = struct.Struct("<8sH4s")  # node_id, tcp_port, ipv4


class BootNode:
    """Standalone registry process (`boot_node/src/server.rs` role): keeps
    liveness-pruned records, answers FIND with everyone it knows."""

    LIVENESS_S = 60.0

    def __init__(self, port: int = 0, log: Optional[Logger] = None):
        self.log = (log or test_logger()).child("boot_node")
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", port))
        self.port = self.sock.getsockname()[1]
        self.records: Dict[bytes, Tuple[int, bytes, float]] = {}
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while True:
            try:
                data, addr = self.sock.recvfrom(4096)
            except OSError:
                return
            if not data:
                continue
            kind = data[0]
            if kind == MSG_PING and len(data) >= 11:
                node_id = data[1:9]
                (tcp_port,) = struct.unpack_from("<H", data, 9)
                ip = socket.inet_aton(addr[0])
                fresh = node_id not in self.records
                self.records[node_id] = (tcp_port, ip, time.monotonic())
                if fresh:
                    self.log.info("peer registered",
                                  node=node_id.hex(), port=tcp_port)
                self.sock.sendto(bytes([MSG_PONG]), addr)
            elif kind == MSG_FIND:
                now = time.monotonic()
                # Prune dead records in place — each node restart mints a
                # fresh node_id, so a long-lived boot node would otherwise
                # accumulate a record per restart forever.
                self.records = {
                    nid: rec for nid, rec in self.records.items()
                    if now - rec[2] < self.LIVENESS_S}
                live = [(nid, p, ip) for nid, (p, ip, seen)
                        in self.records.items()]
                out = [bytes([MSG_NODES]), struct.pack("<H", len(live))]
                for nid, p, ip in live:
                    out.append(RECORD.pack(nid, p, ip))
                self.sock.sendto(b"".join(out), addr)

    def close(self) -> None:
        self.sock.close()


class DiscoveryService:
    """Per-node client (`discovery/mod.rs` role): registers this node's
    wire endpoint with the boot node and dials newly discovered peers."""

    def __init__(self, node_id: bytes, tcp_port: int,
                 boot_addr: Tuple[str, int],
                 dial: Callable[[str, int], object],
                 interval: float = 2.0, log: Optional[Logger] = None):
        self.node_id = node_id
        self.tcp_port = tcp_port
        self.boot_addr = boot_addr
        self.dial = dial  # (host, port) → peer handle; dedup is dial's job
        self.interval = interval
        self.log = (log or test_logger()).child("discovery")
        self.known: set[bytes] = {node_id}
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.settimeout(3.0)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _recv_kind(self, kind: int) -> bytes | None:
        """Receive until a frame of ``kind`` arrives or the socket times
        out.  A PONG delayed past one round's timeout otherwise desyncs
        every later round (the stale PONG answers the next FIND, and the
        64-byte PONG read would truncate-and-drop a NODES datagram) —
        the cause of the discovery-mesh flake under full-suite load."""
        deadline = time.monotonic() + self.sock.gettimeout()
        while time.monotonic() < deadline:
            try:
                data, _ = self.sock.recvfrom(65536)
            except OSError:
                return None
            if data and data[0] == kind:
                return data
        return None

    def poll_once(self) -> List[Tuple[bytes, int, str]]:
        """One PING + FIND round; dials fresh records. Returns them."""
        try:
            self.sock.sendto(
                bytes([MSG_PING]) + self.node_id
                + struct.pack("<H", self.tcp_port), self.boot_addr)
            if self._recv_kind(MSG_PONG) is None:
                return []
            self.sock.sendto(bytes([MSG_FIND]), self.boot_addr)
            data = self._recv_kind(MSG_NODES)
        except OSError:
            return []
        if not data:
            return []
        (n,) = struct.unpack_from("<H", data, 1)
        fresh = []
        off = 3
        for _ in range(n):
            nid, port, ip = RECORD.unpack_from(data, off)
            off += RECORD.size
            if nid in self.known:
                continue
            self.known.add(nid)
            host = socket.inet_ntoa(ip)
            fresh.append((nid, port, host))
            try:
                self.dial(host, port)
                self.log.info("discovered peer", node=nid.hex(), port=port)
            except OSError:
                self.known.discard(nid)  # retry on the next round
        return fresh

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                pass
            self._stop.wait(self.interval)

    def close(self) -> None:
        self._stop.set()
        self.sock.close()
