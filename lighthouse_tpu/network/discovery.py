"""UDP peer discovery — the discv5 role
(``/root/reference/beacon_node/lighthouse_network/src/discovery/`` and the
standalone ``boot_node`` subcommand, ``boot_node/src/``).

Real discv5 is a Kademlia DHT over authenticated UDP; this module keeps
the deployment shape (a boot node that never joins the chain + per-node
discovery services) but the per-node service is now a real Kademlia
participant (:class:`KademliaDiscovery`): every node answers FINDNODE
from its own k-bucket table (:mod:`.secure.kademlia`), lookups are
iterative (query the α closest, absorb, repeat until no closer contact
remains), buckets refresh on staleness, and full buckets evict via
liveness ping — so a node bootstraps through a peer-of-a-peer it never
had in its config, instead of depending on one flat registry.

ENR-lite record: ``node_id (8B) | ipv4 | udp_port | tcp_port``; the
node id is ``sha256(static_x25519_pub)[:8]``, and the TCP dial pins it —
a record advertising someone else's id fails the Noise handshake.

Frames (all little-endian; one datagram each):

    0 PING      node_id(8) tcp_port(2)            → registers the sender
    1 PONG      node_id(8) tcp_port(2)            (1-byte legacy accepted)
    4 FINDNODE  token(4) node_id(8) tcp_port(2) target(8)
    5 NODES     token(4) count(u8) records(16B each:
                node_id(8) ipv4(4) udp(2) tcp(2))
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..common import metrics
from ..common.logging import Logger, test_logger
from .secure.kademlia import (
    BUCKET_SIZE,
    Contact,
    KBucketTable,
    LookupState,
    REFRESH_INTERVAL_S,
    xor_distance,
)

MSG_PING = 0
MSG_PONG = 1
MSG_FINDNODE = 4
MSG_NODES = 5

RECORD = struct.Struct("<8s4sHH")  # node_id, ipv4, udp_port, tcp_port


def _pack_nodes(token: bytes, contacts: List[Contact]) -> bytes:
    out = [bytes([MSG_NODES]), token, bytes([len(contacts)])]
    for c in contacts:
        out.append(RECORD.pack(c.node_id, socket.inet_aton(c.host),
                               c.udp_port, c.tcp_port))
    return b"".join(out)


def _unpack_nodes(data: bytes) -> List[Contact]:
    count = data[5]
    contacts = []
    off = 6
    for _ in range(count):
        nid, ip, udp, tcp = RECORD.unpack_from(data, off)
        off += RECORD.size
        contacts.append(Contact(nid, socket.inet_ntoa(ip), udp, tcp))
    return contacts


class BootNode:
    """Standalone bootstrap process (`boot_node/src/server.rs` role): a
    Kademlia responder with a liveness-pruned record store that never
    TCP-dials anyone (its records advertise ``tcp_port=0``)."""

    LIVENESS_S = 60.0

    def __init__(self, port: int = 0, log: Optional[Logger] = None):
        import secrets as _secrets

        self.log = (log or test_logger()).child("boot_node")
        self.node_id = _secrets.token_bytes(8)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", port))
        self.port = self.sock.getsockname()[1]
        # node_id → Contact (+ last-seen inside the contact)
        self.records: Dict[bytes, Contact] = {}
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _register(self, data: bytes, addr, off: int) -> None:
        node_id = data[off:off + 8]
        (tcp_port,) = struct.unpack_from("<H", data, off + 8)
        fresh = node_id not in self.records
        self.records[node_id] = Contact(node_id, addr[0], addr[1],
                                        tcp_port)
        if fresh:
            self.log.info("peer registered", node=node_id.hex(),
                          port=tcp_port)

    def _prune(self) -> None:
        # Each node restart mints a fresh node id, so a long-lived boot
        # node would otherwise accumulate a record per restart forever.
        now = time.monotonic()
        self.records = {nid: c for nid, c in self.records.items()
                        if now - c.last_seen < self.LIVENESS_S}

    def _serve(self) -> None:
        while True:
            try:
                data, addr = self.sock.recvfrom(4096)
            except OSError:
                return
            if not data:
                continue
            kind = data[0]
            if kind == MSG_PING and len(data) >= 11:
                self._register(data, addr, 1)
                self.sock.sendto(
                    bytes([MSG_PONG]) + self.node_id
                    + struct.pack("<H", 0), addr)
            elif kind == MSG_FINDNODE and len(data) >= 23:
                token = data[1:5]
                self._register(data, addr, 5)
                target = data[15:23]
                self._prune()
                close = sorted(
                    self.records.values(),
                    key=lambda c: xor_distance(c.node_id, target))
                self.sock.sendto(_pack_nodes(token, close[:BUCKET_SIZE]),
                                 addr)

    def close(self) -> None:
        self.sock.close()


class KademliaDiscovery:
    """Per-node discovery service: one UDP socket that both ANSWERS the
    DHT protocol (PING → PONG + table insert, FINDNODE → k closest) and
    DRIVES it (periodic self-lookup + stale-bucket refresh through
    :class:`~.secure.kademlia.LookupState`).  Fresh dialable records are
    handed to ``dial(host, tcp_port, expected_id=node_id)``."""

    FIND_TIMEOUT_S = 1.5
    PING_TIMEOUT_S = 1.0

    def __init__(self, node_id: bytes, tcp_port: int,
                 bootstrap: List[Tuple[str, int]],
                 dial: Callable[..., object],
                 interval: float = 2.0, log: Optional[Logger] = None,
                 refresh_interval: float = REFRESH_INTERVAL_S,
                 port: int = 0):
        self.node_id = bytes(node_id)
        self.tcp_port = tcp_port
        self.bootstrap = list(bootstrap)
        self.dial = dial
        self.interval = interval
        self.refresh_interval = refresh_interval
        self.log = (log or test_logger()).child("discovery")
        self.table = KBucketTable(self.node_id)
        self.known: set[bytes] = {self.node_id}  # node ids ever dialed
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", port))
        self.udp_port = self.sock.getsockname()[1]
        self._lock = threading.Lock()
        self._token = 0
        # token → [event, contacts-or-None]; addr → list of ping events
        self._pending: Dict[bytes, list] = {}
        self._ping_waiters: Dict[Tuple[str, int], List[threading.Event]]\
            = {}
        self._stop = threading.Event()
        self._rx = threading.Thread(target=self._recv_loop, daemon=True)
        self._rx.start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- the server side ------------------------------------------------------

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, addr = self.sock.recvfrom(65536)
            except OSError:
                return
            try:
                self._dispatch(data, addr)
            except Exception:
                pass  # malformed datagrams never kill the service

    def _dispatch(self, data: bytes, addr: Tuple[str, int]) -> None:
        if not data:
            return
        kind = data[0]
        if kind == MSG_PING and len(data) >= 11:
            nid = data[1:9]
            (tcp,) = struct.unpack_from("<H", data, 9)
            self._consider(Contact(nid, addr[0], addr[1], tcp))
            self.sock.sendto(
                bytes([MSG_PONG]) + self.node_id
                + struct.pack("<H", self.tcp_port), addr)
        elif kind == MSG_PONG:
            if len(data) >= 11:  # extended PONG carries the responder
                nid = data[1:9]
                (tcp,) = struct.unpack_from("<H", data, 9)
                self._consider(Contact(nid, addr[0], addr[1], tcp))
            with self._lock:
                events = self._ping_waiters.pop(addr, [])
            for ev in events:
                ev.set()
        elif kind == MSG_FINDNODE and len(data) >= 23:
            token = data[1:5]
            nid = data[5:13]
            (tcp,) = struct.unpack_from("<H", data, 13)
            target = data[15:23]
            self._consider(Contact(nid, addr[0], addr[1], tcp))
            close = [c for c in self.table.closest(target, BUCKET_SIZE)
                     if c.node_id != nid]
            self.sock.sendto(_pack_nodes(token, close), addr)
        elif kind == MSG_NODES and len(data) >= 6:
            token = data[1:5]
            with self._lock:
                entry = self._pending.get(token)
            if entry is None:
                return  # late response to a timed-out query
            entry[1] = _unpack_nodes(data)
            entry[0].set()

    # -- the client side ------------------------------------------------------

    def _ping(self, addr: Tuple[str, int],
              timeout: Optional[float] = None) -> bool:
        ev = threading.Event()
        with self._lock:
            self._ping_waiters.setdefault(addr, []).append(ev)
        try:
            self.sock.sendto(
                bytes([MSG_PING]) + self.node_id
                + struct.pack("<H", self.tcp_port), addr)
            return ev.wait(timeout or self.PING_TIMEOUT_S)
        except OSError:
            return False
        finally:
            with self._lock:
                waiters = self._ping_waiters.get(addr)
                if waiters and ev in waiters:
                    waiters.remove(ev)
                    if not waiters:
                        self._ping_waiters.pop(addr, None)

    def find_node(self, addr: Tuple[str, int], target: bytes,
                  timeout: Optional[float] = None) -> List[Contact]:
        """One FINDNODE round-trip to ``addr``; [] on timeout."""
        with self._lock:
            self._token = (self._token + 1) & 0xFFFFFFFF
            token = struct.pack("<I", self._token)
            entry = [threading.Event(), None]
            self._pending[token] = entry
        try:
            self.sock.sendto(
                bytes([MSG_FINDNODE]) + token + self.node_id
                + struct.pack("<H", self.tcp_port) + bytes(target), addr)
            if not entry[0].wait(timeout or self.FIND_TIMEOUT_S):
                return []
            return entry[1] or []
        except OSError:
            return []
        finally:
            with self._lock:
                self._pending.pop(token, None)

    def lookup(self, target: bytes) -> List[Contact]:
        """Iterative Kademlia node lookup: seed from our table (and the
        bootstrap endpoints when the table is empty), query the α
        closest unvisited contacts, absorb, repeat until converged.
        Every contact learned along the way feeds the table + dialer."""
        t0 = time.perf_counter()
        self.table.mark_lookup(target)
        state = LookupState(target, self.table.closest(target,
                                                       BUCKET_SIZE))
        for addr in self.bootstrap:
            # Bootstrap endpoints are addr-only (no id yet): query them
            # directly in round 0 — cheap, and it registers us there.
            for c in self.find_node(addr, target):
                self._consider(c)
                state.absorb([c])
        while True:
            batch = state.next_batch()
            if not batch:
                break
            # The α queries really do fly concurrently — a batch of dead
            # contacts costs ONE find timeout, not α of them stacked.
            results: List[List[Contact]] = [[] for _ in batch]

            def _query(i, c):
                results[i] = self.find_node(c.udp_addr, target)

            threads = [threading.Thread(target=_query, args=(i, c),
                                        daemon=True)
                       for i, c in enumerate(batch)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(self.FIND_TIMEOUT_S + 1.0)
            for found_list in results:
                for found in found_list:
                    self._consider(found)
                    state.absorb([found])
            if state.done():
                break
        metrics.observe("network_discovery_lookup_seconds",
                        time.perf_counter() - t0)
        return state.result()

    # -- table/dial plumbing --------------------------------------------------

    def _consider(self, contact: Contact) -> None:
        """A live record reached us: fold it into the k-bucket table
        (with the Kademlia liveness-eviction rule on full buckets) and
        dial it if it is fresh and dialable."""
        if contact.node_id == self.node_id:
            return
        candidate = self.table.update(contact)
        if candidate is not None:
            # Full bucket: ping the LRU member off-thread; only a dead
            # one is evicted for the newcomer (liveness bias).
            threading.Thread(
                target=self._evict_or_keep, args=(candidate, contact),
                daemon=True).start()
        if contact.tcp_port:
            with self._lock:  # one dial per node id, ever (until failed)
                if contact.node_id in self.known:
                    return
                self.known.add(contact.node_id)
            threading.Thread(
                target=self._dial, args=(contact,), daemon=True).start()

    def _evict_or_keep(self, candidate: Contact, newcomer: Contact
                       ) -> None:
        if self._ping(candidate.udp_addr):
            return  # old node is alive: the newcomer is dropped
        self.table.evict(candidate.node_id)
        self.table.update(newcomer)
        self.log.info("evicted dead contact",
                      node=candidate.node_id.hex())

    def _dial(self, contact: Contact) -> None:
        try:
            self.dial(contact.host, contact.tcp_port,
                      expected_id=contact.node_id)
            self.log.info("discovered peer", node=contact.node_id.hex(),
                          port=contact.tcp_port)
        except OSError:
            with self._lock:
                self.known.discard(contact.node_id)  # retry next round

    # -- the drive loop -------------------------------------------------------

    def poll_once(self) -> List[Contact]:
        """One discovery round: announce to the bootstrap endpoints,
        self-lookup (who is near us?), then refresh stale buckets with
        random-target lookups."""
        for addr in self.bootstrap:
            self._ping(addr)
        found = self.lookup(self.node_id)
        for i in self.table.stale_buckets(self.refresh_interval):
            self.lookup(self.table.random_id_in_bucket(i))
        return found

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                pass
            self._stop.wait(self.interval)

    def close(self) -> None:
        self._stop.set()
        self.sock.close()
