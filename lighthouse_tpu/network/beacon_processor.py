"""Prioritized bounded work-queue scheduler — the ``BeaconProcessor``
(``/root/reference/beacon_node/network/src/beacon_processor/mod.rs:86-228,
978-1130``).

One manager drains a fixed-priority array of bounded per-work-type queues
into a worker pool (≤ ``max_workers``).  Gossip attestation/aggregate
queues BATCH up to 64 items into one work event (``mod.rs:200-201``) — the
shape the TPU batch-verify path wants.  Early/unresolvable work goes to a
delay queue and re-enters later (``work_reprocessing_queue.rs:46-177``).

Queue discipline follows the reference: LIFO for latency-sensitive gossip
(newest first — old gossip decays in value), FIFO for sync/backfill
correctness.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..common.metrics import REGISTRY
from ..common.tracing import TRACER


class WorkType(str, Enum):
    """Priority order = declaration order (`mod.rs:978` match order)."""
    ChainSegment = "chain_segment"
    # Sidecars outrank blocks: their verification is cheap and a block's
    # import is gated on them, so draining sidecars first avoids a
    # needless unavailable→fetch round-trip for same-burst deliveries.
    GossipBlobSidecar = "gossip_blob_sidecar"
    GossipBlock = "gossip_block"
    GossipAggregateBatch = "gossip_aggregate_batch"
    GossipAttestationBatch = "gossip_attestation_batch"
    Rpc = "rpc"
    GossipVoluntaryExit = "gossip_voluntary_exit"
    GossipSlashing = "gossip_slashing"
    BackfillSync = "backfill_sync"


# (max queue length, lifo?, batch size) per work type — bounds from
# `mod.rs:86-228` (scaled), batching from `:200-201`.
QUEUE_SPECS: Dict[WorkType, Tuple[int, bool, int]] = {
    WorkType.ChainSegment: (64, False, 1),
    WorkType.GossipBlock: (1024, False, 1),
    WorkType.GossipBlobSidecar: (1024, False, 1),
    WorkType.GossipAggregateBatch: (4096, True, 64),
    WorkType.GossipAttestationBatch: (16384, True, 64),
    WorkType.Rpc: (1024, False, 1),
    WorkType.GossipVoluntaryExit: (4096, True, 1),
    WorkType.GossipSlashing: (4096, True, 1),
    WorkType.BackfillSync: (64, False, 1),
}


@dataclass
class WorkEvent:
    work_type: WorkType
    payload: object
    process_fn: Callable  # fn(payload) or fn([payloads]) for batched types


class BeaconProcessor:
    """Manager + bounded queues + worker pool."""

    def __init__(self, max_workers: int = 4):
        self.max_workers = max_workers
        # Streaming verification service (beacon_chain.verification_
        # service): when attached, the processor pumps it at every idle
        # point, so SLO-deadline dispatches fire even with empty queues,
        # and run_until_idle's drain contract extends to it.
        self.verification_service = None
        self.queues: Dict[WorkType, Deque[WorkEvent]] = {
            wt: deque() for wt in WorkType}
        self.dropped: Dict[WorkType, int] = {wt: 0 for wt in WorkType}
        self._lock = threading.Condition()
        self._reprocess: List[Tuple[float, int, WorkEvent]] = []
        self._seq = 0
        self._active = 0
        self._pumping = False
        self._shutdown = False
        self._workers: List[threading.Thread] = []
        self._manager: Optional[threading.Thread] = None
        self._m_processed = REGISTRY.counter(
            "beacon_processor_events_total", "work events processed")
        self._m_dropped = REGISTRY.counter(
            "beacon_processor_events_dropped_total", "work events dropped")

    # -- submission ----------------------------------------------------------

    def submit(self, event: WorkEvent) -> bool:
        """Enqueue; full queues drop (oldest for LIFO, newest for FIFO —
        `mod.rs` drop policies).  Returns False when dropped."""
        maxlen, lifo, _batch = QUEUE_SPECS[event.work_type]
        with self._lock:
            q = self.queues[event.work_type]
            if len(q) >= maxlen:
                self.dropped[event.work_type] += 1
                self._m_dropped.inc()
                if lifo:
                    q.popleft()  # drop the OLDEST, keep the fresh item
                else:
                    return False  # FIFO: reject the newcomer
            q.append(event)
            self._lock.notify_all()
        return True

    def defer(self, event: WorkEvent, delay_s: float) -> None:
        """Delay-queue entry (`work_reprocessing_queue.rs` DelayQueue):
        early blocks / unknown-parent attestations re-enter later."""
        with self._lock:
            self._seq += 1
            heapq.heappush(self._reprocess,
                           (time.monotonic() + delay_s, self._seq, event))
            self._lock.notify_all()

    # -- scheduling ----------------------------------------------------------

    def _pop_next(self) -> Optional[WorkEvent]:
        """Highest-priority nonempty queue; batched types coalesce up to
        their batch size into ONE event."""
        now = time.monotonic()
        while self._reprocess and self._reprocess[0][0] <= now:
            _, _, ev = heapq.heappop(self._reprocess)
            self.queues[ev.work_type].append(ev)
        for wt in WorkType:
            q = self.queues[wt]
            if not q:
                continue
            maxlen, lifo, batch = QUEUE_SPECS[wt]
            if batch <= 1:
                return q.pop() if lifo else q.popleft()
            events = []
            while q and len(events) < batch:
                events.append(q.pop() if lifo else q.popleft())
            fn = events[0].process_fn
            return WorkEvent(wt, [e.payload for e in events],
                             lambda batch_payloads, fn=fn:
                             fn(batch_payloads))
        return None

    def run_until_idle(self, timeout: float = 10.0) -> int:
        """Synchronous drain (tests, simulator): process everything
        currently queued (+ anything its processing enqueues), inline.
        Returns the number of work events processed."""
        processed = 0
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                ev = self._pop_next()
            if ev is None:
                svc = self.verification_service
                if svc is not None and svc.pending():
                    # Synchronous drain semantics: everything submitted
                    # to the streaming verifier completes before this
                    # returns.  flush() also waits out messages a
                    # concurrent pump thread holds in flight, so even a
                    # 0-dispatch flush is progress (their callbacks may
                    # enqueue follow-up work) — loop again regardless.
                    processed += svc.flush()
                    continue
                if self._reprocess:
                    t = self._reprocess[0][0] - time.monotonic()
                    if t > 0 and time.monotonic() + t < deadline:
                        time.sleep(min(t, 0.05))
                        continue
                break
            with TRACER.span("work_event", cat="processor",
                             work=ev.work_type.value):
                ev.process_fn(ev.payload)
            self._m_processed.inc()
            processed += 1
        return processed

    def quiescent(self) -> bool:
        """True when nothing is queued, nothing is running and the
        attached verification service owes no verdicts — the threaded
        mode's drain predicate (the sustained-load drill's slot-end
        settle; ``run_until_idle`` is the synchronous twin)."""
        with self._lock:
            if self._active or self._pumping \
                    or any(self.queues.values()):
                return False
            # DUE reprocess entries count as pending work; future-dated
            # ones don't (a deferred retry must not wedge the predicate).
            if self._reprocess and self._reprocess[0][0] <= \
                    time.monotonic():
                return False
        svc = self.verification_service
        return svc is None or svc.pending() == 0

    # -- threaded mode -------------------------------------------------------

    def start(self) -> None:
        """Spawn the manager + workers (production mode)."""
        if self._manager is not None:
            return
        self._shutdown = False
        self._manager = threading.Thread(target=self._manager_loop,
                                         daemon=True)
        self._manager.start()

    def _manager_loop(self) -> None:
        while True:
            with self._lock:
                if self._shutdown:
                    return
                ev = self._pop_next()
                if ev is None:
                    self._lock.wait(timeout=0.05)
                else:
                    while self._active >= self.max_workers:
                        self._lock.wait(timeout=0.05)
                        if self._shutdown:
                            return
                    self._active += 1
            if ev is None:
                # Idle tick: SLO-driven dispatch of the streaming
                # verifier's due buckets — on a worker thread, never the
                # manager: a pump rides the resilience envelope (deadline
                # waits, backoff sleeps, host-oracle fallback), and a
                # wedged device would stall dispatch of every queued
                # work event behind an inline pump.  One pump thread at
                # a time; only the manager sets the flag.
                # Gate on due-ness, not mere pending-ness: a message
                # sitting inside its SLO window would otherwise spawn a
                # no-op pump thread every 50 ms tick.
                svc = self.verification_service
                if svc is not None and not self._pumping \
                        and svc.has_due_work():
                    self._pumping = True
                    threading.Thread(target=self._pump_service,
                                     args=(svc,), daemon=True).start()
                continue
            t = threading.Thread(target=self._run_one, args=(ev,),
                                 daemon=True)
            t.start()

    def _pump_service(self, svc) -> None:
        try:
            svc.pump()
        except Exception:  # noqa: BLE001 — pump must not kill workers
            pass
        finally:
            self._pumping = False

    def _run_one(self, ev: WorkEvent) -> None:
        try:
            with TRACER.span("work_event", cat="processor",
                             work=ev.work_type.value):
                ev.process_fn(ev.payload)
            self._m_processed.inc()
        finally:
            with self._lock:
                self._active -= 1
                self._lock.notify_all()

    def stop(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()
        if self._manager is not None:
            self._manager.join(timeout=2)
            self._manager = None
