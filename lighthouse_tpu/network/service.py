"""In-process networking: gossip bus, Req/Resp, node router, sync.

The internet-facing stack of the reference is libp2p (gossipsub + SSZ-snappy
Req/Resp + discv5 — ``beacon_node/lighthouse_network``); this module is the
node-side architecture — topics, router, BeaconProcessor dispatch, range
sync — over an in-process message bus, the shape the reference itself uses
for multi-node testing (``testing/node_test_rig``, ``testing/simulator``).
A production wire transport plugs in at the :class:`GossipBus` /
:class:`ReqRespClient` seams.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..beacon_chain import (
    BeaconChain,
    BlobSidecarError,
    BlobsUnavailable,
    BlockError,
    ParentUnknown,
)
from ..common.logging import Logger, test_logger
from ..common.tracing import TRACER
from .beacon_processor import BeaconProcessor, WorkEvent, WorkType

# Gossip topic names (`lighthouse_network/src/types/topics.rs:11-26`).
TOPIC_BLOCK = "beacon_block"
TOPIC_BLOB_SIDECAR = "blob_sidecar_{}"
BLOB_SIDECAR_SUBNET_COUNT = 6
TOPIC_AGGREGATE = "beacon_aggregate_and_proof"
TOPIC_ATTESTATION_SUBNET = "beacon_attestation_{}"
TOPIC_EXIT = "voluntary_exit"
TOPIC_PROPOSER_SLASHING = "proposer_slashing"
TOPIC_ATTESTER_SLASHING = "attester_slashing"
TOPIC_SYNC_COMMITTEE = "sync_committee_message"
TOPIC_LC_OPTIMISTIC = "light_client_optimistic_update"
TOPIC_LC_FINALITY = "light_client_finality_update"
ATTESTATION_SUBNET_COUNT = 64


class GossipBus:
    """In-process gossipsub: publish floods every other subscriber."""

    def __init__(self):
        self._subs: Dict[str, List[Callable]] = {}
        self._lock = threading.Lock()

    def subscribe(self, topic: str, handler: Callable) -> None:
        with self._lock:
            self._subs.setdefault(topic, []).append(handler)

    def publish(self, topic: str, message, *, exclude=None) -> None:
        with self._lock:
            handlers = list(self._subs.get(topic, []))
        for h in handlers:
            if h is not exclude:
                h(message)


@dataclass
class BlocksByRangeRequest:
    """`BlocksByRange` (`rpc/protocol.rs:161-179`)."""
    start_slot: int
    count: int


@dataclass
class BlobSidecarsByRangeRequest:
    """`BlobSidecarsByRange` (deneb p2p `rpc` addition)."""
    start_slot: int
    count: int


class NetworkNode:
    """One node: chain + processor + router + sync
    (``beacon_node/network/src/router/`` + ``sync/``)."""

    def __init__(self, chain: BeaconChain, bus: GossipBus,
                 name: str = "node", log: Optional[Logger] = None):
        self.chain = chain
        chain.network = self  # the API's /node/peers + gossip introspection
        self.bus = bus
        self.name = name
        self.log = (log or test_logger()).child(name)
        self.processor = BeaconProcessor()
        # Streaming verification: gossip-path signature/KZG checks flow
        # through the chain's resilient service (adaptive micro-batching
        # + circuit breaker + host fallback); the processor pumps its
        # SLO-due buckets at every idle point.
        chain.ensure_verification_service()
        self.processor.verification_service = chain.verification_service
        self.peers: List["NetworkNode"] = []
        from .peer_manager import PeerManager
        self.peer_manager = PeerManager(log=self.log)
        self._block_handler = self._on_gossip_block
        bus.subscribe(TOPIC_BLOCK, self._block_handler)
        self._att_handler = self._on_gossip_attestation
        bus.subscribe(TOPIC_AGGREGATE, self._att_handler)
        self._last_lc_opt = None
        self._last_lc_fin = None
        self._lc_opt_handler = self._on_gossip_lc_optimistic
        bus.subscribe(TOPIC_LC_OPTIMISTIC, self._lc_opt_handler)
        self._lc_fin_handler = self._on_gossip_lc_finality
        bus.subscribe(TOPIC_LC_FINALITY, self._lc_fin_handler)
        # Attestation subnets this node processes (`attestation_service
        # .rs` subscriptions: aggregation duties + persistent subnets).
        self.subnets: set[int] = set()
        self._subnet_handlers: dict[int, Callable] = {}
        self._sync_handler = self._on_gossip_sync_messages
        bus.subscribe(TOPIC_SYNC_COMMITTEE, self._sync_handler)
        # Blob sidecar subnets: every node subscribes to all of them (the
        # deneb p2p spec makes the 6 blob subnets mandatory for full
        # nodes, unlike the sampled attestation subnets).
        self._blob_handler = self._on_gossip_blob_sidecar
        for subnet in range(BLOB_SIDECAR_SUBNET_COUNT):
            bus.subscribe(TOPIC_BLOB_SIDECAR.format(subnet),
                          self._blob_handler)

    def close(self, persist: bool = True) -> None:
        """Tear the node down: persist the chain's fork-choice/op-pool
        snapshot (a clean shutdown must not lose the votes accumulated
        since the last finalization — `persist_fork_choice` on drop in
        the reference), stop the processor and release the chain's
        streaming-verification hooks — including this node's refcount on
        the process-global BLS envelope, so a dead node's breaker state
        cannot route later module-level verifies through watchdogs/host
        fallback.  ``persist=False`` models a crash (the simulator's
        SIGKILL stand-in): nothing beyond the already-committed atomic
        batches reaches the store."""
        self.processor.stop()
        # Drain in-flight verification first (release flushes), so votes
        # registering from completion callbacks make the final snapshot.
        self.chain.release_verification_service()
        if persist:
            try:
                self.chain.persist()
            except Exception as e:
                # Teardown must complete even over a store that is
                # already closed/broken; the journal still bounds what a
                # restart has to replay.
                self.log.warn("persist-on-close failed",
                              err=f"{type(e).__name__}: {e}")

    # -- publishing ----------------------------------------------------------

    def publish_block(self, signed_block, blob_sidecars=()) -> None:
        """Broadcast-then-self-import (`http_api/publish_blocks.rs`).

        A Deneb proposer hands its blobs in here: sidecars gossip FIRST
        (and outrank blocks in the processor queues), so both this node's
        and every subscriber's availability cache is primed before the
        block hits the import gate."""
        for sc in blob_sidecars:
            self.publish_blob_sidecar(sc)
        self.bus.publish(TOPIC_BLOCK, signed_block,
                         exclude=self._block_handler)
        self._on_gossip_block(signed_block)

    def publish_attestations(self, atts: List) -> None:
        self.bus.publish(TOPIC_AGGREGATE, atts, exclude=self._att_handler)
        self._on_gossip_attestation(atts)

    def publish_blob_sidecar(self, sidecar) -> None:
        """Blob sidecar → its index's subnet topic + local availability
        cache (proposers publish sidecars alongside the block)."""
        topic = TOPIC_BLOB_SIDECAR.format(
            int(sidecar.index) % BLOB_SIDECAR_SUBNET_COUNT)
        self.bus.publish(topic, sidecar, exclude=self._blob_handler)
        self._on_gossip_blob_sidecar(sidecar)

    # -- sync-committee gossip ------------------------------------------------

    def publish_sync_messages(self, slot: int, block_root: bytes,
                              votes: List) -> None:
        """Sync-committee messages → gossip + local pool
        (`sync_committee_verification` topic flow).  ``votes`` is a list
        of (positions, signature_bytes)."""
        msg = (int(slot), bytes(block_root), list(votes))
        self.bus.publish(TOPIC_SYNC_COMMITTEE, msg,
                         exclude=self._sync_handler)
        self._on_gossip_sync_messages(msg)

    def _publish_lc_updates(self) -> None:
        """Gossip the LC updates the import just produced
        (`light_client_finality_update_verification.rs` topics)."""
        upd = getattr(self.chain, "lc_optimistic_update", None)
        if upd is not None and upd is not self._last_lc_opt:
            self._last_lc_opt = upd
            self.bus.publish(TOPIC_LC_OPTIMISTIC, upd,
                             exclude=self._lc_opt_handler)
        fin = getattr(self.chain, "lc_finality_update", None)
        if fin is not None and fin is not self._last_lc_fin:
            self._last_lc_fin = fin
            self.bus.publish(TOPIC_LC_FINALITY, fin,
                             exclude=self._lc_fin_handler)

    def _on_gossip_lc_optimistic(self, upd) -> None:
        """Adopt a gossiped optimistic update after verifying its sync
        aggregate against OUR head committee
        (`light_client_optimistic_update_verification.rs`)."""
        from ..light_client import verify_update_sync_aggregate
        cur = getattr(self.chain, "lc_optimistic_update", None)
        if cur is not None and int(upd.attested_header.slot) <= \
                int(cur.attested_header.slot):
            return
        if verify_update_sync_aggregate(
                self.chain, upd.attested_header, upd.sync_aggregate,
                int(upd.signature_slot)):
            self.chain.lc_optimistic_update = upd
            self._last_lc_opt = upd

    def _on_gossip_lc_finality(self, upd) -> None:
        from ..light_client import verify_update_sync_aggregate
        cur = getattr(self.chain, "lc_finality_update", None)
        if cur is not None and int(upd.attested_header.slot) <= \
                int(cur.attested_header.slot):
            return
        if verify_update_sync_aggregate(
                self.chain, upd.attested_header, upd.sync_aggregate,
                int(upd.signature_slot)):
            self.chain.lc_finality_update = upd
            self._last_lc_fin = upd

    def _on_gossip_sync_messages(self, msg) -> None:
        slot, block_root, votes = msg
        for positions, sig in votes:
            self.chain.sync_message_pool.insert(
                slot, block_root, positions, sig)

    # -- attestation subnets --------------------------------------------------

    def subscribe_subnet(self, subnet: int) -> None:
        """Join one of the 64 attestation subnets (`attestation_service.rs`
        subscribe_to_subnet): only subscribed subnets reach this node's
        processor — the bandwidth-isolation role of gossipsub meshes."""
        subnet = int(subnet) % ATTESTATION_SUBNET_COUNT
        if subnet in self.subnets:
            return
        self.subnets.add(subnet)
        handler = self._on_gossip_subnet_attestation
        self._subnet_handlers[subnet] = handler
        self.bus.subscribe(TOPIC_ATTESTATION_SUBNET.format(subnet), handler)

    def publish_attestation_to_subnet(self, att, subnet: int) -> None:
        """Unaggregated attestation → its subnet topic (the VC's
        `publish_attestations` route before aggregation)."""
        subnet = int(subnet) % ATTESTATION_SUBNET_COUNT
        topic = TOPIC_ATTESTATION_SUBNET.format(subnet)
        handler = self._subnet_handlers.get(subnet)
        self.bus.publish(topic, [att], exclude=handler)
        if subnet in self.subnets:
            self._on_gossip_subnet_attestation([att])

    # -- gossip handlers → processor queues ----------------------------------

    def _on_gossip_block(self, signed_block) -> None:
        if TRACER.enabled:  # arrival stamp: where the slot trace begins
            TRACER.instant("gossip_arrival", cat="gossip_arrival",
                           slot=int(signed_block.message.slot),
                           kind="block", node=self.name)
        self.processor.submit(WorkEvent(
            WorkType.GossipBlock, signed_block, self._process_block))

    def _on_gossip_attestation(self, atts: List) -> None:
        """Aggregate-topic traffic: never shed by the verify service."""
        if TRACER.enabled and atts:
            TRACER.instant("gossip_arrival", cat="gossip_arrival",
                           slot=int(atts[0].data.slot), kind="aggregate",
                           count=len(atts), node=self.name)
        now = time.monotonic()  # SLO clock starts at gossip arrival
        for att in atts:
            # Stamp-once: the in-process bus hands every subscriber the
            # SAME object, and mesh redundancy redelivers it — the
            # FIRST arrival is the honest gossip→verified clock start,
            # and a later node/duplicate must not re-wind a stamp a
            # pending verify is about to read.
            if getattr(att, "_gossip_arrival", None) is None:
                att._gossip_arrival = now
            self.processor.submit(WorkEvent(
                WorkType.GossipAggregateBatch, att,
                self._process_aggregate_batch))

    def _on_gossip_subnet_attestation(self, atts: List) -> None:
        """Subnet (unaggregated) traffic: the sheddable class — under
        overload these degrade FIRST, never aggregates or blocks."""
        if TRACER.enabled and atts:
            TRACER.instant("gossip_arrival", cat="gossip_arrival",
                           slot=int(atts[0].data.slot),
                           kind="attestation", count=len(atts),
                           node=self.name)
        now = time.monotonic()  # SLO clock starts at gossip arrival
        for att in atts:
            if getattr(att, "_gossip_arrival", None) is None:
                att._gossip_arrival = now  # stamp-once (see above)
            self.processor.submit(WorkEvent(
                WorkType.GossipAttestationBatch, att,
                self._process_attestation_batch))

    def _on_gossip_blob_sidecar(self, sidecar) -> None:
        if TRACER.enabled:
            TRACER.instant(
                "gossip_arrival", cat="gossip_arrival",
                slot=int(sidecar.signed_block_header.message.slot),
                kind="blob_sidecar", index=int(sidecar.index),
                node=self.name)
        self.processor.submit(WorkEvent(
            WorkType.GossipBlobSidecar, sidecar,
            self._process_blob_sidecar))

    def _process_blob_sidecar(self, sidecar) -> None:
        da = self.chain.data_availability
        try:
            block_root = da.put_sidecar(sidecar)
        except BlobSidecarError as e:
            self.log.warn("blob sidecar rejected",
                          index=int(sidecar.index), reason=str(e))
            return
        # A block already verified and parked on this sidecar resumes the
        # moment its last blob lands (the availability cache's
        # Availability::Available transition).
        parked = da.peek_executed_block(block_root)
        if parked is not None and not da.missing_indices(
                parked.signed_block, block_root):
            self.processor.defer(WorkEvent(
                WorkType.GossipBlock, parked.signed_block,
                self._process_block), 0.0)

    def _process_block(self, signed_block) -> None:
        slot = int(signed_block.message.slot)
        self.chain.per_slot_task(max(slot, self.chain.current_slot()))
        try:
            self.chain.process_block(signed_block, is_timely=True)
            self.log.debug("block imported", slot=slot)
        except ParentUnknown:
            # Parent lookup (`block_lookups/`): try a cheap single-chain
            # BlocksByRoot walk first, fall back to range sync, then
            # retry via the reprocess queue.
            self.log.debug("unknown parent; looking up", slot=slot)
            if self._parent_lookup(signed_block) or self._range_sync(slot):
                self.processor.defer(WorkEvent(
                    WorkType.GossipBlock, signed_block,
                    self._process_block), 0.0)
        except BlobsUnavailable:
            # The block is fully verified but its blobs haven't arrived:
            # fetch the missing sidecars by root from peers, then retry
            # (the `block_lookups` single-block blob request flow).
            self.log.debug("blobs unavailable; fetching", slot=slot)
            if self._fetch_blobs(signed_block):
                self.processor.defer(WorkEvent(
                    WorkType.GossipBlock, signed_block,
                    self._process_block), 0.0)
        except BlockError as e:
            self.log.warn("block rejected", slot=slot,
                          reason=type(e).__name__)
        finally:
            # Whatever path imported blocks (direct, parent lookup, range
            # sync), publish any LC updates the chain produced.
            self._publish_lc_updates()

    def _process_attestation_batch(self, atts: List) -> None:
        self.chain.stream_attestation_batch(atts, kind="attestation")

    def _process_aggregate_batch(self, atts: List) -> None:
        self.chain.stream_attestation_batch(atts, kind="aggregate")

    # -- Req/Resp ------------------------------------------------------------

    def blocks_by_range(self, req: BlocksByRangeRequest) -> List:
        """Serve `BlocksByRange` from the canonical chain."""
        out = []
        root = self.chain.head.root
        while root in self.chain.fork_choice.proto.indices:
            block = self.chain.store.get_block(root)
            if block is None:
                break
            slot = int(block.message.slot)
            if slot < req.start_slot:
                break
            if slot < req.start_slot + req.count:
                out.append(block)
            root = bytes(block.message.parent_root)
        out.reverse()
        return out

    def blocks_by_root(self, roots: List[bytes]) -> List:
        """Serve `BlocksByRoot` (`rpc` BlocksByRoot; `block_lookups/`
        server side) from the store."""
        out = []
        for root in roots:
            block = self.chain.store.get_block(bytes(root))
            if block is not None:
                out.append(block)
        return out

    # -- blob sidecar Req/Resp (deneb p2p) -----------------------------------

    def blob_sidecars_by_range(self, req: BlobSidecarsByRangeRequest) -> List:
        """Serve `BlobSidecarsByRange` along the canonical chain,
        ascending (slot, index) like the wire protocol requires."""
        out = []
        root = self.chain.head.root
        while root in self.chain.fork_choice.proto.indices:
            block = self.chain.store.get_block(root)
            if block is None:
                break
            slot = int(block.message.slot)
            if slot < req.start_slot:
                break
            if slot < req.start_slot + req.count:
                out.extend(self.chain.store.get_blob_sidecars(root))
            root = bytes(block.message.parent_root)
        out.sort(key=lambda sc: (
            int(sc.signed_block_header.message.slot), int(sc.index)))
        return out

    def blob_sidecars_by_root(self, ids: List) -> List:
        """Serve `BlobSidecarsByRoot`; ``ids`` is (block_root, index)
        pairs (the BlobIdentifier shape)."""
        out = []
        for block_root, index in ids:
            sc = self.chain.store.get_blob_sidecar(bytes(block_root),
                                                   int(index))
            if sc is not None:
                out.append(sc)
        return out

    def _fetch_blobs(self, signed_block) -> bool:
        """Pull the block's missing sidecars by root from the best peers;
        True once the availability cache can satisfy the block."""
        from .peer_manager import PeerAction
        chain = self.chain
        block_root = signed_block.message.tree_hash_root()
        for peer in self.peer_manager.best_peers(self.peers):
            if not hasattr(peer, "blob_sidecars_by_root"):
                continue
            missing = chain.data_availability.missing_indices(
                signed_block, block_root)
            if not missing:
                return True
            try:
                got = peer.blob_sidecars_by_root(
                    [(block_root, i) for i in missing])
            except Exception:
                self.peer_manager.report(peer, PeerAction.TIMEOUT)
                continue
            for sc in got:
                try:
                    chain.data_availability.put_sidecar(sc)
                except BlobSidecarError:
                    # Served a sidecar that fails verification — as
                    # malicious as a bad block.
                    self.peer_manager.report(peer,
                                             PeerAction.INVALID_MESSAGE)
                    break
            if not chain.data_availability.missing_indices(signed_block,
                                                           block_root):
                self.peer_manager.report(peer, PeerAction.SYNC_SERVED)
                return True
        return not chain.data_availability.missing_indices(signed_block,
                                                           block_root)

    def head_slot(self) -> int:
        """Peer-handle protocol (shared with the wire transport's
        :class:`~.transport.RemotePeer`)."""
        return self.chain.head.slot

    # Parent chains longer than this go to range sync instead
    # (`block_lookups/parent_lookup.rs` PARENT_DEPTH_TOLERANCE).
    PARENT_DEPTH_TOLERANCE = 16

    def _parent_lookup(self, signed_block) -> bool:
        """`block_lookups/parent_lookup.rs`: walk unknown parents back via
        BlocksByRoot until hitting a known block, then import the chain
        oldest-first.  Cheaper than range sync for short reorg gaps."""
        from .peer_manager import PeerAction
        for peer in self.peer_manager.best_peers(self.peers):
            if not hasattr(peer, "blocks_by_root"):
                continue
            chain_segment: List = []  # per-peer: never replay another
            want = bytes(signed_block.message.parent_root)  # peer's segment
            while (not self.chain.fork_choice.contains_block(want)
                   and len(chain_segment) < self.PARENT_DEPTH_TOLERANCE):
                try:
                    got = peer.blocks_by_root([want])
                except Exception:
                    self.peer_manager.report(peer, PeerAction.TIMEOUT)
                    break
                if not got:
                    break
                parent = got[0]
                if parent.message.tree_hash_root() != want:
                    # served a block that is not the one asked for
                    self.peer_manager.report(
                        peer, PeerAction.INVALID_MESSAGE)
                    break
                chain_segment.append(parent)
                want = bytes(parent.message.parent_root)
            if self.chain.fork_choice.contains_block(want) and chain_segment:
                # Oldest-first import through the shared segment seam
                # (epoch-batched replay when the window allows, serial
                # oracle otherwise — same path as range sync).
                from ..sync import Outcome, process_chain_segment
                segment = list(reversed(chain_segment))
                res = process_chain_segment(self.chain, segment)
                if res.needs_blobs is not None:
                    # The recovered segment carries blobs we never saw
                    # on gossip: fetch by root (the same peers that
                    # served the blocks), retry once.
                    if self._fetch_blobs(res.needs_blobs):
                        res = process_chain_segment(self.chain, segment)
                ok = res.outcome is Outcome.OK or res.imported > 0
                if ok:
                    self.peer_manager.report(peer, PeerAction.SYNC_SERVED)
                    return True
                # Root-consistent chain whose blocks all fail verification
                # is as malicious as garbage roots — penalize (mirrors
                # `_range_sync`).
                self.peer_manager.report(peer, PeerAction.INVALID_MESSAGE)
        return False

    def _range_sync(self, target_slot: int) -> bool:
        """`range_sync`: epoch-aligned batch state machine with per-batch
        peer rotation and retries (:mod:`.range_sync` — `SyncingChain` /
        `BatchInfo` / finalized-vs-head split)."""
        from .range_sync import RangeSync
        rs = getattr(self, "_rs", None)
        if rs is None:
            rs = self._rs = RangeSync(self)
        return rs.sync_to(target_slot)
