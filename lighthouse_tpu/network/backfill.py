"""Backfill sync: reverse historical download after checkpoint boot.

Counterpart of ``beacon_node/network/src/sync/backfill_sync/`` +
``beacon_chain/src/historical_blocks.rs``: a checkpoint-synced node holds
nothing below its anchor; batches of historical blocks download BACKWARD
from the anchor toward genesis, each batch verified by hash-chain linkage
(block root == the child's ``parent_root``) plus a batched proposer-
signature check against the anchor state's registry, then persisted.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..crypto import bls
from ..state_transition.helpers import compute_domain, compute_signing_root
from ..types.chain_spec import Domain
from .service import BlocksByRangeRequest


class BackfillError(ValueError):
    pass


@dataclass
class BackfillProgress:
    oldest_slot: int          # lowest slot imported so far
    expected_root: bytes      # required root of the next (older) block
    complete: bool = False


class BackfillSync:
    """Reverse historical import (`backfill_sync/mod.rs` state machine,
    synchronous flavour)."""

    def __init__(self, chain, batch_size: int = 32):
        self.chain = chain
        self.batch_size = batch_size
        anchor_root = chain.genesis_block_root
        anchor = chain.store.get_block(anchor_root)
        if anchor is None:
            # Genesis boot: nothing to backfill.
            self.progress = BackfillProgress(0, b"\x00" * 32, complete=True)
        else:
            # RESUME: an interrupted backfill committed whole batches
            # atomically below the anchor — walk the stored parent chain
            # down to the oldest contiguous block so a restart requests
            # nothing it already holds (the crash-drill "no re-import"
            # invariant; `backfill_sync/mod.rs` resumes from
            # oldest_block_parent the same way).
            oldest = anchor
            exp = bytes(anchor.message.parent_root)
            while exp != b"\x00" * 32:
                b = chain.store.get_block(exp)
                if b is None:
                    break
                oldest = b
                exp = bytes(b.message.parent_root)
            slot = int(oldest.message.slot)
            self.progress = BackfillProgress(
                oldest_slot=slot, expected_root=exp,
                complete=slot == 0)

    def fill_from(self, peer) -> bool:
        """One batch from ``peer``; returns True if progress was made.
        Raises :class:`BackfillError` on an invalid batch (bad linkage or
        signatures — the reference penalises the peer and retries)."""
        if self.progress.complete:
            return False
        end = self.progress.oldest_slot  # exclusive
        start = max(end - self.batch_size, 0)
        blocks = peer.blocks_by_range(BlocksByRangeRequest(
            start_slot=start, count=end - start))
        if not blocks:
            if start == 0:
                # Nothing below: the oldest known parent is the genesis
                # anchor (genesis itself has no block to download).
                self.progress.complete = True
            return False
        self._import(blocks)
        return True

    def _import(self, blocks: List) -> None:
        """Validate linkage newest→oldest against ``expected_root``, batch-
        verify proposer signatures, persist (`historical_blocks.rs`
        import_historical_block_batch)."""
        chain = self.chain
        preset, spec = chain.preset, chain.spec
        exp = self.progress.expected_root
        roots = []
        for b in reversed(blocks):  # newest first
            root = b.message.tree_hash_root()
            if root != exp:
                raise BackfillError(
                    f"backfill batch breaks the hash chain at slot "
                    f"{int(b.message.slot)}")
            roots.append(root)
            exp = bytes(b.message.parent_root)
        # Proposer signatures in ONE batched verify.  Like the reference's
        # historical import, the CLAIMED proposer index is used — the hash
        # chain to the trusted anchor is the authentication; the signature
        # check only needs the claimed proposer's key (valid because the
        # registry only grows) and the fork domain AT the block's epoch.
        state = chain.head.state
        gvr = bytes(state.genesis_validators_root)
        sets = []
        for b, root in zip(reversed(blocks), roots):
            epoch = int(b.message.slot) // preset.SLOTS_PER_EPOCH
            fork_version = spec.fork_version(spec.fork_name_at_epoch(epoch))
            domain = compute_domain(Domain.BEACON_PROPOSER, fork_version, gvr)
            proposer = int(b.message.proposer_index)
            if proposer >= len(state.validators):
                raise BackfillError("historical proposer beyond registry")
            sets.append(bls.SignatureSet(
                signature=bls.Signature.deserialize(b.signature),
                signing_keys=[chain.pubkey_cache.get(state.validators,
                                                     proposer)],
                message=compute_signing_root(root, domain)))
        if sets:
            # One dispatcher-routed batch: dedup + the mesh-sharded BLS
            # path on a device backend — the same route the batched
            # replay windows take.
            from ..state_transition.sig_dispatch import get_dispatcher
            try:
                ok = get_dispatcher().submit(
                    sets, slot=int(blocks[-1].message.slot)).join()
            except Exception as e:
                raise BackfillError(
                    f"backfill batch signature verification errored: "
                    f"{e}") from e
            if not ok:
                raise BackfillError("backfill batch signature "
                                    "verification failed")
        # ONE atomic commit per batch: a crash mid-batch leaves either
        # the whole batch or none of it, so the resume walk in
        # ``__init__`` always lands on a batch boundary.
        ops: List[tuple] = []
        for b, root in zip(reversed(blocks), roots):
            ops.extend(chain.store.block_put_ops(root, b))
        chain.store.do_atomically(ops)
        oldest = int(blocks[0].message.slot)
        self.progress = BackfillProgress(
            oldest_slot=oldest, expected_root=exp,
            complete=oldest == 0 or exp == b"\x00" * 32)
