"""Range sync state machine — ``beacon_node/network/src/sync/range_sync``
(``chain.rs:59`` SyncingChain, ``batch.rs:86`` BatchInfo states,
``sync_type.rs:10`` finalized-vs-head split).

The round-3/4 loop pulled one unbounded span from one peer; this is the
real machine:

- work divides into EPOCH-ALIGNED batches (``EPOCHS_PER_BATCH`` = 2, like
  the reference) with a per-batch state lifecycle
  (Pending → Downloading → AwaitingProcessing → Processed | Failed);
- each batch records which peers attempted it; a failed download or a
  batch that fails import is RETRIED ON A DIFFERENT PEER (up to
  ``MAX_BATCH_ATTEMPTS``), with the serving peer penalized — a single
  dropping/corrupting peer cannot wedge the sync;
- batches process strictly in order (imports must chain), while the
  NEXT batch may already be downloading from another peer;
- chains are keyed by target (root, slot) and classed Finalized vs Head:
  all finalized chains drain before head chains start
  (``sync_type.rs`` RangeSyncType priority).

Execution is synchronous (the caller drives ``tick()``; our runtime is a
thread-pool BeaconProcessor, not an async executor) but the state
machine, retry, and peer-rotation semantics match the reference's.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Tuple

from ..sync import Outcome
from .peer_manager import PeerAction
from .service import BlocksByRangeRequest

EPOCHS_PER_BATCH = 2
MAX_BATCH_ATTEMPTS = 5


class BatchState(Enum):
    PENDING = "pending"
    DOWNLOADING = "downloading"
    AWAITING_PROCESSING = "awaiting_processing"
    PROCESSING = "processing"
    PROCESSED = "processed"
    FAILED = "failed"


@dataclass
class BatchInfo:
    """One epoch-aligned download unit (`batch.rs:86`)."""
    start_slot: int
    count: int
    state: BatchState = BatchState.PENDING
    attempts: List[object] = field(default_factory=list)  # peers tried
    blocks: List = field(default_factory=list)

    def failed_enough(self) -> bool:
        return len(self.attempts) >= MAX_BATCH_ATTEMPTS


class ChainType(Enum):
    FINALIZED = "finalized"
    HEAD = "head"


class SyncingChain:
    """One target chain being synced (`chain.rs:59`)."""

    def __init__(self, target_root: bytes, target_slot: int,
                 start_slot: int, slots_per_epoch: int,
                 chain_type: ChainType):
        self.target_root = target_root
        self.target_slot = target_slot
        self.chain_type = chain_type
        self.spe = slots_per_epoch
        self.batches: List[BatchInfo] = []
        span = EPOCHS_PER_BATCH * slots_per_epoch
        # epoch-aligned batch boundaries from the current head forward
        slot = start_slot
        while slot <= target_slot:
            count = min(span - (slot % span) if slot % span else span,
                        target_slot - slot + 1)
            self.batches.append(BatchInfo(start_slot=slot, count=count))
            slot += count
        self.peers: List[object] = []

    def done(self) -> bool:
        return all(b.state == BatchState.PROCESSED for b in self.batches)

    def failed(self) -> bool:
        return any(b.state == BatchState.FAILED for b in self.batches)

    def _next_downloadable(self) -> Optional[BatchInfo]:
        for b in self.batches:
            if b.state == BatchState.PENDING:
                return b
            if b.state in (BatchState.DOWNLOADING, BatchState.PROCESSING):
                return None  # synchronous driver: one in flight
        return None

    def _peer_for(self, batch: BatchInfo, peer_manager) -> Optional[object]:
        """Best-scored peer that has NOT yet attempted this batch —
        retries rotate peers (`chain.rs` peer pool rotation)."""
        for peer in peer_manager.best_peers(self.peers):
            if peer not in batch.attempts:
                return peer
        return None

    def tick(self, node, peer_manager) -> bool:
        """Advance the machine one step; returns True if progress was
        made — a batch downloaded or processed, OR a download attempt
        consumed.  A failed download returns the batch to PENDING and
        still counts as progress: the next tick retries it on the next
        eligible peer, so one dead top-scored peer cannot abort a whole
        ``sync_to`` round (it previously did — the driver stopped at the
        first no-progress tick and peer rotation waited for a later
        ``_range_sync`` invocation)."""
        progressed = False
        # 1. download the next pending batch
        batch = self._next_downloadable()
        if batch is not None:
            peer = self._peer_for(batch, peer_manager)
            if peer is None:
                if batch.failed_enough():
                    batch.state = BatchState.FAILED
                return progressed
            batch.state = BatchState.DOWNLOADING
            batch.attempts.append(peer)
            try:
                blocks = peer.blocks_by_range(BlocksByRangeRequest(
                    start_slot=batch.start_slot, count=batch.count))
            except Exception:
                peer_manager.report(peer, PeerAction.TIMEOUT)
                batch.state = (BatchState.FAILED if batch.failed_enough()
                               else BatchState.PENDING)
                # An attempt was consumed: loop progress (retry rotates
                # to the next peer immediately, attempts stay bounded by
                # MAX_BATCH_ATTEMPTS so this cannot spin forever).
                return True
            batch.blocks = [
                b for b in blocks
                if batch.start_slot <= int(b.message.slot)
                < batch.start_slot + batch.count]
            batch.state = BatchState.AWAITING_PROCESSING
            progressed = True

        # 2. process in order: the earliest AWAITING batch whose
        # predecessors are all PROCESSED
        for b in self.batches:
            if b.state == BatchState.PROCESSED:
                continue
            if b.state != BatchState.AWAITING_PROCESSING:
                break
            b.state = BatchState.PROCESSING
            served_by = b.attempts[-1]
            out = self._process(node, b)
            if out is Outcome.OK:
                b.state = BatchState.PROCESSED
                peer_manager.report(served_by, PeerAction.SYNC_SERVED)
                progressed = True
            elif out is Outcome.FATAL:
                # Deterministic BAD BLOCK: every honest peer would serve
                # the same bytes, so rotating peers only burns
                # MAX_BATCH_ATTEMPTS on the same verdict — fail the
                # chain NOW (`chain.rs` on_batch_process_result
                # FaultyFailure w/ penalize, but a consensus-invalid
                # block removes the chain).
                peer_manager.report(served_by, PeerAction.INVALID_MESSAGE)
                b.blocks = []
                b.state = BatchState.FAILED
                progressed = True
            else:
                # bad batch: penalize the server, retry on another peer
                peer_manager.report(served_by, PeerAction.INVALID_MESSAGE)
                b.blocks = []
                b.state = (BatchState.FAILED if b.failed_enough()
                           else BatchState.PENDING)
            break
        return progressed

    def _process(self, node, batch: BatchInfo):
        """Import the batch as a chain segment through the shared seam
        (``lighthouse_tpu.sync.process_chain_segment``: epoch-batched
        replay when the knob/window allow, serial oracle otherwise).  An
        EMPTY batch is valid (skipped slots).

        Deneb: a blob-carrying block surfaces ``needs_blobs`` on first
        import — fetch its sidecars by root (the range-sync blob flow)
        and retry once; only a still-unavailable block fails the batch
        (its server withheld data it advertised)."""
        from ..sync import process_chain_segment

        res = process_chain_segment(node.chain, batch.blocks)
        if res.needs_blobs is not None:
            if node._fetch_blobs(res.needs_blobs):
                res = process_chain_segment(node.chain, batch.blocks)
            if res.needs_blobs is not None:
                return Outcome.RETRY
        return res.outcome


class RangeSync:
    """Chain collection + finalized-first scheduling (`range_sync/mod.rs`
    + ``sync_type.rs``)."""

    def __init__(self, node):
        self.node = node
        self.chains: Dict[Tuple[bytes, int], SyncingChain] = {}

    def add_peer(self, peer, target_root: bytes, target_slot: int,
                 chain_type: ChainType = ChainType.HEAD) -> None:
        key = (bytes(target_root), int(target_slot))
        chain = self.chains.get(key)
        if chain is None:
            start = self.node.chain.head.slot + 1
            if target_slot < start:
                return
            chain = SyncingChain(
                target_root=key[0], target_slot=key[1], start_slot=start,
                slots_per_epoch=self.node.chain.preset.SLOTS_PER_EPOCH,
                chain_type=chain_type)
            self.chains[key] = chain
        if peer not in chain.peers:
            chain.peers.append(peer)

    def _ordered(self) -> List[SyncingChain]:
        fin = [c for c in self.chains.values()
               if c.chain_type == ChainType.FINALIZED]
        head = [c for c in self.chains.values()
                if c.chain_type == ChainType.HEAD]
        # finalized chains first; most peers = most credible target
        fin.sort(key=lambda c: -len(c.peers))
        head.sort(key=lambda c: -len(c.peers))
        return fin + head

    def tick(self) -> bool:
        """Drive the highest-priority live chain one step; drop finished
        and dead chains.  Returns True on progress."""
        pm = self.node.peer_manager
        for chain in self._ordered():
            key = (chain.target_root, chain.target_slot)
            if chain.done() or chain.failed():
                self.chains.pop(key, None)
                continue
            if chain.tick(self.node, pm):
                if chain.done():
                    self.chains.pop(key, None)
                return True
        return False

    def sync_to(self, target_slot: int, max_ticks: int = 1000) -> bool:
        """Synchronous convenience driver: build chains from current
        peers' heads and tick until the local head reaches
        ``target_slot`` or nothing progresses."""
        node = self.node
        for peer in node.peer_manager.best_peers(node.peers):
            try:
                head = peer.head_slot()
            except Exception:
                continue
            if head > node.chain.head.slot:
                self.add_peer(peer, b"\x00" * 32, head, ChainType.HEAD)
        for _ in range(max_ticks):
            if node.chain.head.slot >= target_slot:
                return True
            if not self.tick():
                break
        return node.chain.head.slot >= target_slot
