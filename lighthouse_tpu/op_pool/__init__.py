"""Operation pool: pending operations for block production.

Counterpart of ``beacon_node/operation_pool``
(``/root/reference/beacon_node/operation_pool/src/lib.rs``): attestations
stored compactly per ``AttestationData`` with aggregation-bit merging (the
``attestation_storage.rs`` split/compact idea), block packing by greedy
weighted max-coverage (``max_cover.rs``, ``attestation.rs`` AttMaxCover),
plus slashings/exits/BLS-change pools with per-validator de-duplication
(``lib.rs:366`` ``get_slashings_and_exits``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Hashable, List, Optional, Tuple

import numpy as np

from .max_cover import greedy_pack, maximum_cover

__all__ = ["OperationPool", "AttMaxCover", "maximum_cover"]


class AttMaxCover:
    """Attestation candidate weighted by effective balances of the NEW
    attesters it would add (`attestation.rs` AttMaxCover; rewards are
    balance-proportional, so balance weight orders candidates the same
    way as the reference's base-reward weight).  Coverage lives in flat
    int64 arrays (``cover_elements`` — max_cover's packed fast path);
    ``covering_set``/``update_covering_set`` keep the dict protocol for
    external callers."""

    def __init__(self, att, fresh_indices: np.ndarray,
                 balances: np.ndarray):
        self.att = att
        self._elems = np.asarray(fresh_indices, dtype=np.int64)
        self._weights = balances[self._elems].astype(np.int64)

    def cover_elements(self):
        return self._elems, self._weights

    def covering_set(self) -> Dict[int, int]:
        return dict(zip(self._elems.tolist(), self._weights.tolist()))

    def update_covering_set(self, covered: Dict[int, int]) -> None:
        if not covered:
            return
        dead = np.fromiter(covered.keys(), np.int64, len(covered))
        keep = ~np.isin(self._elems, dead)
        self._elems = self._elems[keep]
        self._weights = self._weights[keep]


@dataclass
class _StoredAttestation:
    data: object              # AttestationData
    bits: np.ndarray          # bool aggregation bits (committee-sized)
    signature: bytes          # aggregate signature bytes
    committee: np.ndarray     # validator indices for (slot, index)


class OperationPool:
    """Pending ops, keyed for de-duplication, packed on demand."""

    def __init__(self, preset, spec):
        self.preset = preset
        self.spec = spec
        # (data_root, committee_key) → list of compatible aggregates.
        self.attestations: Dict[bytes, List[_StoredAttestation]] = {}
        self.proposer_slashings: Dict[int, object] = {}
        self.attester_slashings: List[object] = []
        self.voluntary_exits: Dict[int, object] = {}
        self.bls_changes: Dict[int, object] = {}
        self.sync_contributions: Dict[Tuple[int, bytes], object] = {}

    # -- attestations --------------------------------------------------------

    def insert_attestation(self, att, committee: np.ndarray) -> None:
        """Merge into an existing aggregate when disjoint, else keep both
        (`lib.rs:198` insert_attestation + naive aggregation)."""
        key = att.data.tree_hash_root()
        bits = np.asarray(att.aggregation_bits, dtype=bool)
        entry = self.attestations.setdefault(key, [])
        for stored in entry:
            if not (stored.bits & bits).any():
                stored.bits = stored.bits | bits
                from ..crypto import bls
                sig_a = bls.Signature.deserialize(stored.signature)
                sig_b = bls.Signature.deserialize(bytes(att.signature))
                stored.signature = bls.aggregate_signatures(
                    [sig_a, sig_b]).serialize()
                return
        entry.append(_StoredAttestation(
            data=att.data, bits=bits.copy(),
            signature=bytes(att.signature),
            committee=np.asarray(committee)))

    def num_attestations(self) -> int:
        return sum(len(v) for v in self.attestations.values())

    def get_attestations(self, state, T) -> List:
        """Pack ≤ MAX_ATTESTATIONS by greedy max-cover over fresh attester
        balances (`lib.rs:248` get_attestations)."""
        slot = int(state.slot)
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        balances = state.validators.col("effective_balance")
        # Freshness is per-epoch: an attestation for epoch E only rewards
        # validators not yet credited in E's participation flags
        # (current vs previous — mixing them mis-weights boundary packing).
        n_vals = balances.shape[0]
        seen_cur = np.zeros(n_vals, bool)
        seen_prev = np.zeros(n_vals, bool)
        if hasattr(state, "current_epoch_participation"):
            cur_part = np.asarray(state.current_epoch_participation)
            if cur_part.size:
                seen_cur[:cur_part.shape[0]] = cur_part != 0
            prev_part = np.asarray(state.previous_epoch_participation)
            if prev_part.size:
                seen_prev[:prev_part.shape[0]] = prev_part != 0
        # else: phase0 — no participation flags; credited attesters live in
        # state.{previous,current}_epoch_attestations whose bits→index
        # resolution needs the committee shuffle, so every attester counts
        # as fresh (the reference's base-fork packing resolves them via its
        # epoch cache; over-weighting only costs packing optimality, never
        # validity).
        # Candidates must also pass the reference's curr/prev-epoch validity
        # filters (`attestation.rs` validity_filter): an attestation whose
        # source disagrees with the proposal state's justified checkpoint
        # would fail process_attestation in the very block we pack it into.
        def _cp_key(cp):
            return (int(cp.epoch), bytes(cp.root))

        want_cur = _cp_key(state.current_justified_checkpoint)
        want_prev = _cp_key(state.previous_justified_checkpoint)
        candidates = []       # (stored, is_current_epoch)
        for entry in self.attestations.values():
            if not entry:
                continue
            # Every aggregate in a group shares the same AttestationData
            # (the dict key is its root) — filter once per group.
            data = entry[0].data
            att_slot = int(data.slot)
            att_epoch = att_slot // self.preset.SLOTS_PER_EPOCH
            if att_slot + self.preset.MIN_ATTESTATION_INCLUSION_DELAY > slot:
                continue
            if slot > att_slot + self.preset.SLOTS_PER_EPOCH:
                # Upper inclusion bound: process_attestation enforces
                # slot ≤ att_slot + SLOTS_PER_EPOCH, which is TIGHTER
                # than the epoch filter below near an epoch boundary —
                # packing such an attestation would invalidate the very
                # block it rides in.
                continue
            if att_epoch not in (epoch, epoch - 1):
                continue
            want = want_cur if att_epoch == epoch else want_prev
            if _cp_key(data.source) != want:
                continue
            candidates.extend((stored, att_epoch == epoch)
                              for stored in entry)
        if len(candidates) >= 2048:
            chosen = _pack_columnar(candidates, balances, seen_cur,
                                    seen_prev, self.preset.MAX_ATTESTATIONS)
        else:
            covers = []
            for stored, is_cur in candidates:
                seen = seen_cur if is_cur else seen_prev
                idx = np.asarray(
                    stored.committee[stored.bits[:len(stored.committee)]],
                    dtype=np.int64)
                fresh = idx[~seen[idx]]
                if fresh.size == 0:
                    continue
                covers.append(AttMaxCover(stored, fresh, balances))
            chosen = [c.att for c in
                      maximum_cover(covers, self.preset.MAX_ATTESTATIONS)]
        return [self._to_attestation(c, T) for c in chosen]

    def _to_attestation(self, stored: _StoredAttestation, T):
        return T.Attestation(
            aggregation_bits=stored.bits[:len(stored.committee)].tolist(),
            data=stored.data,
            signature=stored.signature)

    # -- slashings / exits / changes ----------------------------------------

    def insert_proposer_slashing(self, slashing) -> None:
        self.proposer_slashings[
            int(slashing.signed_header_1.message.proposer_index)] = slashing

    def insert_attester_slashing(self, slashing) -> None:
        self.attester_slashings.append(slashing)

    def insert_voluntary_exit(self, exit_) -> None:
        self.voluntary_exits[int(exit_.message.validator_index)] = exit_

    def insert_bls_to_execution_change(self, change) -> None:
        self.bls_changes[int(change.message.validator_index)] = change

    def get_slashings_and_exits(self, state) -> Tuple[List, List, List]:
        """Filter against the state: not-yet-slashed / still-exitable
        (`lib.rs:366`)."""
        reg = state.validators
        slashed = reg.col("slashed")
        exiting = reg.col("exit_epoch")
        from ..types.chain_spec import FAR_FUTURE_EPOCH

        proposer = [
            s for i, s in self.proposer_slashings.items()
            if i < len(reg) and not slashed[i]
        ][:self.preset.MAX_PROPOSER_SLASHINGS]

        attester, covered = [], set()
        for s in self.attester_slashings:
            a = set(int(i) for i in s.attestation_1.attesting_indices)
            b = set(int(i) for i in s.attestation_2.attesting_indices)
            both = {i for i in a & b
                    if i < len(reg) and not slashed[i] and i not in covered}
            if both:
                covered |= both
                attester.append(s)
            if len(attester) >= self.preset.MAX_ATTESTER_SLASHINGS:
                break

        exits = [
            e for i, e in self.voluntary_exits.items()
            if i < len(reg) and not slashed[i]
            and int(exiting[i]) == FAR_FUTURE_EPOCH
        ][:self.preset.MAX_VOLUNTARY_EXITS]
        return proposer, attester, exits

    def get_bls_to_execution_changes(self, state) -> List:
        creds = state.validators.col("withdrawal_credentials")
        out = []
        for i, change in self.bls_changes.items():
            if i < creds.shape[0] and creds[i][0] == 0x00:
                out.append(change)
            if len(out) >= self.preset.MAX_BLS_TO_EXECUTION_CHANGES:
                break
        return out

    # -- maintenance ---------------------------------------------------------

    def prune(self, state) -> None:
        """Drop everything no longer includable (`lib.rs` prune_all)."""
        epoch = int(state.slot) // self.preset.SLOTS_PER_EPOCH
        self.attestations = {
            k: [s for s in v
                if int(s.data.slot) // self.preset.SLOTS_PER_EPOCH
                >= epoch - 1]
            for k, v in self.attestations.items()}
        self.attestations = {k: v for k, v in self.attestations.items() if v}
        slashed = state.validators.col("slashed")
        self.proposer_slashings = {
            i: s for i, s in self.proposer_slashings.items()
            if i < slashed.shape[0] and not slashed[i]}
        self.voluntary_exits = {
            i: e for i, e in self.voluntary_exits.items()
            if i < slashed.shape[0] and not slashed[i]}


def _pack_columnar(candidates, balances, seen_cur, seen_prev,
                   limit: int) -> List:
    """Columnar greedy max-cover — same greedy (heaviest-first, earliest
    tie-break, winners' coverage struck from the rest) as
    :func:`max_cover.maximum_cover`, expressed over flat CSR arrays feeding
    the fixed-shape device rounds engine (:mod:`.device_pack`; the host
    CELF :func:`max_cover.greedy_pack` core stays as the oracle behind
    ``LIGHTHOUSE_TPU_DEVICE_PACK=0``) so a backlogged pool packs in
    device/numpy time, not Python-dict time (the 100k-candidate BASELINE
    row-5 shape; the earlier padded (N, W) matrix form spent half its time
    materialising ~100 MB gathers).  Freshness is resolved per candidate
    epoch against the packed participation state in one flat gather.
    Equivalence across all three paths is asserted in tests.
    CSR-build / coverage / select phase timings land in the ``op_pool``
    tracing stage source."""
    import time as _time
    from .device_pack import device_pack_enabled, greedy_pack_device

    t0 = _time.perf_counter()
    N = len(candidates)
    ws = np.fromiter((len(s.committee) for s, _ in candidates),
                     np.int64, N)
    bounds = np.zeros(N + 1, dtype=np.int64)
    np.cumsum(ws, out=bounds[1:])
    # int32 ids: the flat passes below are memory-bandwidth bound.
    flat_comm = np.concatenate(
        [np.asarray(s.committee) for s, _ in candidates],
        dtype=np.int32, casting="unsafe")
    flat_bit = np.concatenate(
        [np.asarray(s.bits[:w], bool)
         for (s, _), w in zip(candidates, ws)])
    # Mask by aggregation bits FIRST so the freshness gathers touch only
    # set members (~half the flat length); candidate segment bounds track
    # through the compactions via searchsorted/cumsum instead of a
    # full-length candidate-id column.
    attesting = np.flatnonzero(flat_bit)
    att_bounds = np.searchsorted(attesting, bounds)
    att_comm = flat_comm[attesting]
    csr_build_ms = (_time.perf_counter() - t0) * 1e3
    t1 = _time.perf_counter()
    is_cur = np.fromiter((cur for _, cur in candidates), bool, N)
    att_cur = np.repeat(is_cur, np.diff(att_bounds))
    seen_flat = np.empty(attesting.shape[0], dtype=bool)
    seen_flat[att_cur] = seen_cur[att_comm[att_cur]]
    not_cur = ~att_cur
    seen_flat[not_cur] = seen_prev[att_comm[not_cur]]
    fresh = ~seen_flat
    cfs = np.zeros(attesting.shape[0] + 1, dtype=np.int64)
    np.cumsum(fresh, out=cfs[1:])
    offsets = cfs[att_bounds]
    flat_e = att_comm[fresh]
    flat_w = balances[flat_e].astype(np.int64)
    coverage_ms = (_time.perf_counter() - t1) * 1e3
    if device_pack_enabled():
        chosen = greedy_pack_device(flat_e, flat_w, offsets,
                                    balances.shape[0], limit,
                                    csr_build_ms=csr_build_ms,
                                    coverage_ms=coverage_ms)
    else:
        chosen, _, _ = greedy_pack(flat_e, flat_w, offsets,
                                   balances.shape[0], limit)
    return [candidates[b][0] for b in chosen]


def bench_pack_attestations(n_atts: int, n_validators: int = 1 << 20,
                            seed: int = 0) -> Tuple[float, int]:
    """BASELINE row 5: time ``get_attestations`` max-cover packing over
    ``n_atts`` pooled aggregates (reference workload:
    ``operation_pool/src/lib.rs:248`` at a backlogged pool).

    Synthetic but structurally faithful: aggregates spread over the
    previous 32 slots × 64 committee indices (distinct ``AttestationData``
    per (slot, index)), 128-member committees drawn from a 2^20-validator
    registry, random half-full aggregation bits, empty participation (every
    attester fresh).  Returns (milliseconds, packed-count).
    """
    import time as _time
    from types import SimpleNamespace
    from ..types.presets import MAINNET
    from ..types.factory import spec_types

    preset = MAINNET
    T = spec_types(MAINNET)
    pool = OperationPool(preset, None)
    rng = np.random.default_rng(seed)
    slot = 100
    cur_src = T.Checkpoint(epoch=2, root=b"\x22" * 32)
    prev_src = T.Checkpoint(epoch=1, root=b"\x11" * 32)
    datas = []
    for s in range(slot - 32, slot):
        epoch = s // preset.SLOTS_PER_EPOCH
        src = cur_src if epoch == slot // preset.SLOTS_PER_EPOCH else prev_src
        for index in range(64):
            datas.append(T.AttestationData(
                slot=s, index=index,
                beacon_block_root=bytes(rng.integers(0, 256, 32, np.uint8)),
                source=src,
                target=T.Checkpoint(epoch=epoch, root=b"\x33" * 32)))
    per_data = max(1, n_atts // len(datas))
    total = 0
    for data in datas:
        if total >= n_atts:
            break
        key = data.tree_hash_root()
        committee = rng.choice(n_validators, 128, replace=False)
        entry = pool.attestations.setdefault(key, [])
        for _ in range(min(per_data, n_atts - total)):
            bits = rng.random(128) < 0.5
            entry.append(_StoredAttestation(
                data=data, bits=bits, signature=b"\x00" * 96,
                committee=committee))
            total += 1

    class _Reg:
        def __init__(self, bal):
            self._bal = bal

        def col(self, name):
            return self._bal

    balances = np.full(n_validators, 32 * 10**9, np.uint64)
    state = SimpleNamespace(
        slot=slot, validators=_Reg(balances),
        current_epoch_participation=np.zeros(n_validators, np.uint8),
        previous_epoch_participation=np.zeros(n_validators, np.uint8),
        current_justified_checkpoint=cur_src,
        previous_justified_checkpoint=prev_src)
    t0 = _time.perf_counter()
    packed = pool.get_attestations(state, T)
    ms = (_time.perf_counter() - t0) * 1e3
    return ms, len(packed)
