"""Greedy weighted maximum-coverage — the block-packing core
(``/root/reference/beacon_node/operation_pool/src/max_cover.rs:11-53``).

The classic (1 − 1/e) greedy: repeatedly take the candidate with the
highest remaining weight, then strike its covered elements out of every
other candidate.  Candidates expose their covering dict so the update is
one dict-difference per round, exactly the reference's
``update_covering_set`` contract.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, List, Protocol, TypeVar

T = TypeVar("T")


class MaxCoverItem(Protocol):
    """`MaxCover` trait: an object with a covering-set weight map."""

    def covering_set(self) -> Dict[Hashable, int]:
        ...

    def update_covering_set(self, covered: Dict[Hashable, int]) -> None:
        ...


def maximum_cover(items: List, limit: int) -> List:
    """Pick ≤ ``limit`` items maximising total covered weight
    (`max_cover.rs` ``maximum_cover()``)."""
    candidates = [it for it in items if sum(it.covering_set().values()) > 0]
    chosen: List = []
    while candidates and len(chosen) < limit:
        best = max(candidates,
                   key=lambda it: sum(it.covering_set().values()))
        if sum(best.covering_set().values()) == 0:
            break
        covered = dict(best.covering_set())
        chosen.append(best)
        candidates.remove(best)
        for it in candidates:
            it.update_covering_set(covered)
        candidates = [it for it in candidates
                      if sum(it.covering_set().values()) > 0]
    return chosen
