"""Greedy weighted maximum-coverage — the block-packing core
(``/root/reference/beacon_node/operation_pool/src/max_cover.rs:11-53``).

The classic (1 − 1/e) greedy: repeatedly take the candidate with the
highest remaining weight, then strike its covered elements out of every
other candidate.  Candidates expose their covering dict so the update is
one dict-difference per round, exactly the reference's
``update_covering_set`` contract.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, List, Protocol, TypeVar

T = TypeVar("T")


class MaxCoverItem(Protocol):
    """`MaxCover` trait: an object with a covering-set weight map."""

    def covering_set(self) -> Dict[Hashable, int]:
        ...

    def update_covering_set(self, covered: Dict[Hashable, int]) -> None:
        ...


def maximum_cover(items: List, limit: int) -> List:
    """Pick ≤ ``limit`` items maximising total covered weight
    (`max_cover.rs` ``maximum_cover()``).

    Weights are cached and only re-summed for candidates whose covering
    set intersects the round's winner (tracked via an element → candidates
    reverse index) — the naive re-sum-everything loop made 100k-candidate
    packing (BASELINE row 5) take seconds.  Ties break toward the earliest
    item, matching the original first-maximal scan.
    """
    import heapq

    weights = [sum(it.covering_set().values()) for it in items]
    by_elem: Dict[Hashable, List[int]] = {}
    for i, it in enumerate(items):
        for e in it.covering_set():
            by_elem.setdefault(e, []).append(i)
    alive = {i for i, w in enumerate(weights) if w > 0}
    # Lazy-deletion heap: stale entries (weight changed since push) are
    # skipped on pop.  (-w, i) ordering pops the heaviest candidate with
    # earliest-index tie-break, matching the original first-maximal scan.
    heap = [(-w, i) for i, w in enumerate(weights) if w > 0]
    heapq.heapify(heap)
    chosen: List = []
    while heap and len(chosen) < limit:
        neg_w, best = heapq.heappop(heap)
        if best not in alive or -neg_w != weights[best]:
            continue  # removed or stale
        covered = dict(items[best].covering_set())
        chosen.append(items[best])
        alive.remove(best)
        touched = set()
        for e in covered:
            for i in by_elem.get(e, ()):
                if i in alive:
                    touched.add(i)
        for i in touched:
            items[i].update_covering_set(covered)
            w = sum(items[i].covering_set().values())
            weights[i] = w
            if w == 0:
                alive.remove(i)
            else:
                heapq.heappush(heap, (-w, i))
    return chosen
