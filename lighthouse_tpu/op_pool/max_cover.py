"""Greedy weighted maximum-coverage — the block-packing core
(``/root/reference/beacon_node/operation_pool/src/max_cover.rs:11-53``).

The classic (1 − 1/e) greedy: repeatedly take the candidate with the
highest remaining weight, then strike its covered elements out of every
other candidate.  The core runs over flat CSR arrays with a packed-uint64
coverage bitset (one bit per element) — per-key Python dicts made the
backlogged-pool shapes (BASELINE row 5) pack in dict time, not numpy
time.  The public :func:`maximum_cover` still honours the reference's
``MaxCover`` dict protocol (``covering_set`` / ``update_covering_set``)
for arbitrary hashable keys; items may instead expose ``cover_elements()``
→ ``(int64 elements, int64 weights)`` to skip the dict round-trip.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Protocol, Tuple

import numpy as np


class MaxCoverItem(Protocol):
    """`MaxCover` trait: an object with a covering-set weight map."""

    def covering_set(self) -> Dict[Hashable, int]:
        ...

    def update_covering_set(self, covered: Dict[Hashable, int]) -> None:
        ...


def _covered_bits(covered: np.ndarray, elems: np.ndarray) -> np.ndarray:
    """Gather the coverage bit of each element from the packed bitset."""
    return ((covered[elems >> 6] >> (elems & 63).astype(np.uint64))
            & np.uint64(1)).astype(bool)


def greedy_pack(flat_e: np.ndarray, flat_w: np.ndarray, offsets: np.ndarray,
                n_elements: int, limit: int
                ) -> Tuple[List[int], List[np.ndarray], np.ndarray]:
    """Greedy max-cover over CSR candidate→element lists.

    ``flat_e``: int64 element ids in ``[0, n_elements)``, grouped by
    candidate; ``flat_w``: the element weights; ``offsets``: ``(N+1,)``
    segment bounds.  Returns ``(chosen candidate ids in selection order,
    per-choice array of elements still uncovered at selection, the final
    packed coverage bitset)``.  Ties break toward the earliest candidate,
    matching the reference's first-maximal scan.
    """
    import heapq

    N = offsets.shape[0] - 1
    cs = np.zeros(flat_w.shape[0] + 1, dtype=np.int64)
    np.cumsum(flat_w, out=cs[1:])
    weights = cs[offsets[1:]] - cs[offsets[:-1]]
    covered = np.zeros((n_elements + 63) // 64, dtype=np.uint64)
    # Lazy exact greedy (CELF): cached weights are upper bounds (coverage
    # only removes weight), so popping the heap top, re-evaluating it
    # against the CURRENT bitset, and accepting iff its weight did not
    # drop selects exactly the eager greedy's (max weight, earliest index)
    # winner each round — without maintaining an element→candidate
    # reverse index or re-scoring every touched candidate per round.
    heap = [(-int(w), i) for i, w in enumerate(weights) if w > 0]
    heapq.heapify(heap)
    chosen: List[int] = []
    live_at_sel: List[np.ndarray] = []
    while heap and len(chosen) < limit:
        neg_w, b = heapq.heappop(heap)
        elems = flat_e[offsets[b]:offsets[b + 1]]
        fresh = ~_covered_bits(covered, elems)
        w_now = int(flat_w[offsets[b]:offsets[b + 1]][fresh].sum())
        if w_now <= 0:
            continue
        if heap and w_now < -heap[0][0]:
            heapq.heappush(heap, (-w_now, b))
            continue
        if heap and w_now == -heap[0][0] and heap[0][1] < b:
            # An equal-weight upper bound with a smaller index must get
            # the first claim at this weight level.
            heapq.heappush(heap, (-w_now, b))
            continue
        new = elems[fresh]
        chosen.append(b)
        live_at_sel.append(new)
        np.bitwise_or.at(covered, new >> 6,
                         np.uint64(1) << (new & 63).astype(np.uint64))
    return chosen, live_at_sel, covered


def maximum_cover(items: List, limit: int) -> List:
    """Pick ≤ ``limit`` items maximising total covered weight
    (`max_cover.rs` ``maximum_cover()``).

    Items exposing ``cover_elements()`` feed the packed core directly;
    dict-protocol items are converted once (keys compacted to element
    ids) and receive ``update_covering_set`` calls afterwards so their
    external covering-set state matches the round-by-round contract:
    a chosen item loses the elements covered before its selection, a
    non-chosen item loses every covered element.
    """
    if not items:
        return []
    elem_arrays: List[np.ndarray] = []
    weight_arrays: List[np.ndarray] = []
    key_id: Dict[Hashable, int] = {}
    id_key: List[Hashable] = []
    any_dicts = False
    for it in items:
        fast = getattr(it, "cover_elements", None)
        if fast is not None:
            e, w = fast()
            elem_arrays.append(np.asarray(e, dtype=np.int64))
            weight_arrays.append(np.asarray(w, dtype=np.int64))
            continue
        any_dicts = True
        cs = it.covering_set()
        ids = np.empty(len(cs), dtype=np.int64)
        ws = np.empty(len(cs), dtype=np.int64)
        for j, (k, w) in enumerate(cs.items()):
            i = key_id.get(k)
            if i is None:
                i = key_id[k] = len(id_key)
                id_key.append(k)
            ids[j] = i
            ws[j] = w
        elem_arrays.append(ids)
        weight_arrays.append(ws)
    if any_dicts and any(getattr(it, "cover_elements", None) is not None
                         for it in items):
        raise TypeError("maximum_cover: cannot mix dict-protocol and "
                        "array-interface items (element id spaces differ)")
    flat_e = (np.concatenate(elem_arrays) if elem_arrays
              else np.zeros(0, np.int64))
    flat_w = (np.concatenate(weight_arrays) if weight_arrays
              else np.zeros(0, np.int64))
    counts = np.fromiter((a.shape[0] for a in elem_arrays), np.int64,
                         len(elem_arrays))
    offsets = np.zeros(len(items) + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    n_elements = int(flat_e.max()) + 1 if flat_e.size else 0
    chosen, live_at_sel, covered = greedy_pack(flat_e, flat_w, offsets,
                                               n_elements, limit)
    if any_dicts and chosen:
        covered_ids = np.flatnonzero(
            _covered_bits(covered, np.arange(n_elements, dtype=np.int64)))
        covered_all = {id_key[i]: 0 for i in covered_ids}
        chosen_set = dict(zip(chosen, live_at_sel))
        for i, it in enumerate(items):
            live = chosen_set.get(i)
            if live is not None:
                # Chosen: strike only what was covered BEFORE selection.
                seg = elem_arrays[i]
                dead = seg[~np.isin(seg, live)]
                removed = {id_key[e]: 0 for e in dead}
            else:
                removed = covered_all
            if removed:
                it.update_covering_set(removed)
    return [items[b] for b in chosen]
