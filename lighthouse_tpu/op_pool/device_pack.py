"""Device greedy-pack — fixed-shape max-cover for block production.

The host :func:`~.max_cover.greedy_pack` is a lazy-exact CELF loop:
pop the heaviest cached candidate, re-evaluate it against the current
coverage bitset, accept iff its weight held up.  That loop is exactly
the EAGER greedy — each round selects the ``(max marginal weight,
earliest index)`` candidate — so it reformulates as a fixed-shape
device program with no heap and no data-dependent control flow:

- the candidate pool is a CSR over flat entry columns (element id,
  weight, segment id) plus two precomputed coverage planes (``word =
  e >> 6``, ``bitmask = 1 << (e & 63)``) against a packed uint64
  coverage bitset;
- per round: one gather of the covered word per entry, a masked
  segment-sum of still-fresh weights per candidate (the marginal), one
  ``argmax`` (first occurrence == earliest-index tie-break, matching
  the CELF heap's ``(−w, i)`` ordering bit for bit), and a scatter-OR
  of the winner's fresh bits back into the bitset;
- the loop runs a fixed ``MAX_ATTESTATIONS`` rounds inside one
  ``fori_loop`` program; a round whose best marginal is ≤ 0 selects
  the ``−1`` sentinel, and coverage is then a fixed point, so trailing
  sentinel rounds are free and termination matches the host's.

Entry counts and candidate counts are bucket-padded to the next power
of two (the ``parallel/bls_shard`` / fork-choice mirror pattern), so
pool growth re-uses compiled programs instead of recompiling.  Like
the fork-choice jit engine, the kernel traces inside a scoped
``jax.experimental.enable_x64()`` (the bitset is uint64, marginals are
int64) and auto-selects: jitted XLA on a real TPU, an equivalent
vectorized numpy rounds engine elsewhere (CPU jit is correctness-equal
but compile-bound at test shapes).  ``LIGHTHOUSE_TPU_DEVICE_PACK=0``
routes packing back through the host CELF oracle; the differential
suite pins selection-order equality between all three.

Precondition (holds for every real candidate — committee members are
unique within a committee): element ids do not repeat WITHIN one
candidate's segment.  Both paths double-count a repeated element's
weight identically, but the device scatter-OR is an add over fresh
bits, which is only OR-exact when the winner's fresh bits are
distinct.

The staged CSR columns are an accounted device-ledger subsystem
(``op_pool``): pushes/pulls and dispatch wall time land in the warm-
slot budget like every other resident plane.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from ..ops.merkle import _next_pow2

__all__ = ["device_pack_enabled", "greedy_pack_device", "modeled_pack_ms",
           "note_adopt"]

# Modeled single-chip HBM stream rate for the rounds kernel (v5e-class
# ~819 GB/s; deliberately conservative).  The kernel is bandwidth-bound:
# per round each entry streams its covered-word gather + bitmask +
# weight + segment id and the scatter writes back — the bench's modeled
# row and scripts/validate_block_production.py share this one model.
PACK_MODELED_HBM_GBPS = 819.0
_BYTES_PER_ENTRY_ROUND = 40.0   # 4B word idx + 8B gather + 8B bitmask
#                                 + 8B weight + 4B seg + 8B scatter
_BYTES_PER_CAND_ROUND = 16.0    # marginal segment-sum + argmax stream

# Stage timings of the LAST pack + production adopt/discard decision —
# read via ``tracing.stage_split("op_pool")`` only (stage-source rule).
LAST_PACK_STATS: dict = {}


def device_pack_enabled() -> bool:
    """The oracle knob: ``LIGHTHOUSE_TPU_DEVICE_PACK=0`` routes
    packing through the host CELF :func:`~.max_cover.greedy_pack`."""
    from ..common.knobs import knob_bool
    return knob_bool("LIGHTHOUSE_TPU_DEVICE_PACK")


_ENGINE_AUTO: Optional[str] = None


def _resolve_engine(engine: Optional[str]) -> str:
    if engine in ("numpy", "jit"):
        return engine
    from ..common.knobs import knob_tribool
    forced = knob_tribool("LIGHTHOUSE_TPU_PACK_JIT")
    if forced is not None:
        return "jit" if forced else "numpy"
    global _ENGINE_AUTO
    if _ENGINE_AUTO is None:
        try:
            import jax
            _ENGINE_AUTO = ("jit" if jax.default_backend() == "tpu"
                            else "numpy")
        except Exception:
            _ENGINE_AUTO = "numpy"
    return _ENGINE_AUTO


def _bucket(k: int, floor: int = 8) -> int:
    return max(_next_pow2(max(int(k), 1)), floor)


def modeled_pack_ms(entries: int, candidates: int, rounds: int,
                    hbm_gbps: float = PACK_MODELED_HBM_GBPS) -> float:
    """Modeled device wall time of the rounds kernel at the PADDED
    shape — bytes streamed per round over the modeled HBM rate."""
    lb = _bucket(entries)
    b = _bucket(candidates)
    per_round = lb * _BYTES_PER_ENTRY_ROUND + b * _BYTES_PER_CAND_ROUND
    return rounds * per_round / (hbm_gbps * 1e9) * 1e3


def note_adopt(adopt_ms: float, adopted: bool) -> None:
    """Production-path hook: record the speculative-state adopt-vs-
    discard decision into this module's stage dict (the defining module
    owns all writes — callers never touch ``LAST_PACK_STATS``)."""
    LAST_PACK_STATS["adopt_ms"] = round(float(adopt_ms), 3)
    LAST_PACK_STATS["adopted"] = int(bool(adopted))
    LAST_PACK_STATS["discarded"] = int(not adopted)


def reset_stats() -> None:
    """Clear the stage dict (bench rows isolating one measurement from
    a previous row's pack; same ownership rule — writes stay here)."""
    LAST_PACK_STATS.clear()


# ---------------------------------------------------------------------------
# numpy rounds engine — the same per-round math as the jit kernel, on
# true (unpadded) shapes with early exit.  This is what the test fleet
# and CPU boxes run; selection order is pinned against both the jit
# kernel and the host CELF oracle.
# ---------------------------------------------------------------------------


def _pack_rounds_numpy(flat_e: np.ndarray, flat_w: np.ndarray,
                       offsets: np.ndarray, n_elements: int,
                       limit: int) -> List[int]:
    n = offsets.shape[0] - 1
    if n <= 0 or limit <= 0:
        return []
    e = flat_e.astype(np.int64, copy=False)
    seg = np.repeat(np.arange(n, dtype=np.int64),
                    np.diff(offsets.astype(np.int64)))
    word = e >> 6
    bit = np.uint64(1) << (e & 63).astype(np.uint64)
    w = flat_w.astype(np.int64, copy=False)
    covered = np.zeros((int(n_elements) + 63) // 64, np.uint64)
    live = np.ones(e.shape[0], bool)
    chosen: List[int] = []
    for _ in range(limit):
        marg = np.zeros(n, np.int64)
        # np.add.at: unbuffered integer accumulation — exact for int64
        # weights where a float64 bincount could round ties apart.
        np.add.at(marg, seg[live], w[live])
        winner = int(np.argmax(marg))   # first occurrence: earliest idx
        if marg[winner] <= 0:
            break
        m = live & (seg == winner)
        np.bitwise_or.at(covered, word[m], bit[m])
        live &= (covered[word] & bit) == 0
        chosen.append(winner)
    return chosen


# ---------------------------------------------------------------------------
# Jitted rounds kernel — one compiled program per
# (entry-bucket, candidate-bucket, word-bucket, rounds) shape.
# ---------------------------------------------------------------------------

_KERNELS: dict = {}


def _get_pack_kernel(lb: int, b: int, words: int, rounds: int):
    key = (lb, b, words, rounds)
    fn = _KERNELS.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    def pack(word, bitmask, wgt, seg, valid):
        covered0 = jnp.zeros(words, jnp.uint64)
        sel0 = jnp.full(rounds, -1, jnp.int32)

        def body(r, carry):
            covered, sel = carry
            fresh = (covered[word] & bitmask) == 0
            live = jnp.where(valid & fresh, wgt, jnp.int64(0))
            marg = jnp.zeros(b, jnp.int64).at[seg].add(live)
            win = jnp.argmax(marg).astype(jnp.int32)
            took = marg[win] > 0
            m = took & valid & fresh & (seg == win)
            covered = covered.at[word].add(
                jnp.where(m, bitmask, jnp.uint64(0)))
            sel = sel.at[r].set(jnp.where(took, win, jnp.int32(-1)))
            return covered, sel

        _, sel = jax.lax.fori_loop(0, rounds, body, (covered0, sel0))
        return sel

    jitted = jax.jit(pack)

    def call(*args):
        with enable_x64():
            return jitted(*args)

    _KERNELS[key] = call
    return call


def _pack_rounds_jit(flat_e: np.ndarray, flat_w: np.ndarray,
                     offsets: np.ndarray, n_elements: int,
                     limit: int) -> List[int]:
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64
    from ..common.device_ledger import LEDGER

    n = offsets.shape[0] - 1
    L = int(offsets[-1])
    lb = _bucket(L, floor=64)
    b = _bucket(n)
    words = _bucket((int(n_elements) + 63) // 64)
    e64 = flat_e.astype(np.int64, copy=False)
    word = np.zeros(lb, np.int32)
    word[:L] = (e64 >> 6).astype(np.int32)
    bitmask = np.zeros(lb, np.uint64)
    bitmask[:L] = np.uint64(1) << (e64 & 63).astype(np.uint64)
    wgt = np.zeros(lb, np.int64)
    wgt[:L] = flat_w
    seg = np.zeros(lb, np.int32)
    seg[:L] = np.repeat(np.arange(n, dtype=np.int32),
                        np.diff(offsets.astype(np.int64)))
    valid = np.zeros(lb, bool)
    valid[:L] = True
    t0 = time.perf_counter()
    with LEDGER.attribute("op_pool"):
        with enable_x64():
            d_word = jnp.asarray(word)        # device-io: op_pool
            d_bit = jnp.asarray(bitmask)      # device-io: op_pool
            d_wgt = jnp.asarray(wgt)          # device-io: op_pool
            d_seg = jnp.asarray(seg)          # device-io: op_pool
            d_valid = jnp.asarray(valid)      # device-io: op_pool
        LEDGER.note_transfer(
            "h2d", word.nbytes + bitmask.nbytes + wgt.nbytes
            + seg.nbytes + valid.nbytes, subsystem="op_pool")
        LAST_PACK_STATS["stage_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        t1 = time.perf_counter()
        sel_dev = _get_pack_kernel(lb, b, words, limit)(
            d_word, d_bit, d_wgt, d_seg, d_valid)
        sel = np.asarray(jax.device_get(sel_dev))  # device-io: op_pool
        wall = (time.perf_counter() - t1) * 1e3
        LEDGER.note_transfer("d2h", sel.nbytes, subsystem="op_pool")
        LEDGER.note_dispatch("op_pool", wall)
    return [int(s) for s in sel if 0 <= s < n]


def greedy_pack_device(flat_e: np.ndarray, flat_w: np.ndarray,
                       offsets: np.ndarray, n_elements: int, limit: int,
                       engine: Optional[str] = None,
                       csr_build_ms: Optional[float] = None,
                       coverage_ms: Optional[float] = None) -> List[int]:
    """Fixed-shape greedy max-cover over the CSR candidate columns.

    Same contract as the host :func:`~.max_cover.greedy_pack` (CSR in,
    chosen candidate ids in selection order out) minus the per-choice
    live-element lists the columnar caller never used.  ``csr_build_ms``
    / ``coverage_ms`` let the caller hand its upstream phase timings in
    for the ``op_pool`` stage split without writing this module's
    stage dict from outside.
    """
    eng = _resolve_engine(engine)
    t0 = time.perf_counter()
    if eng == "jit":
        chosen = _pack_rounds_jit(flat_e, flat_w, offsets, n_elements,
                                  limit)
    else:
        LAST_PACK_STATS.pop("stage_ms", None)
        chosen = _pack_rounds_numpy(flat_e, flat_w, offsets, n_elements,
                                    limit)
    LAST_PACK_STATS["select_rounds_ms"] = round(
        (time.perf_counter() - t0) * 1e3, 3)
    if csr_build_ms is not None:
        LAST_PACK_STATS["csr_build_ms"] = round(float(csr_build_ms), 3)
    if coverage_ms is not None:
        LAST_PACK_STATS["coverage_ms"] = round(float(coverage_ms), 3)
    LAST_PACK_STATS["engine"] = eng
    LAST_PACK_STATS["candidates"] = int(offsets.shape[0] - 1)
    LAST_PACK_STATS["entries"] = int(offsets[-1]) if offsets.size else 0
    LAST_PACK_STATS["rounds"] = int(limit)
    LAST_PACK_STATS["selected"] = len(chosen)
    return chosen
