"""Operation-pool persistence — `PersistedOperationPool`
(``/root/reference/beacon_node/operation_pool/src/persistence.rs``).

A restart must not lose pending operations: stored aggregates (data +
merged bits + signature + committee), slashings, exits and BLS changes
round-trip through one blob.  SSZ for the consensus containers, fixed
headers for the framing.
"""

from __future__ import annotations

import struct

import numpy as np

from . import OperationPool, _StoredAttestation

_MAGIC = b"LTOP\x01"


def _blob(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


def _unblob(buf: memoryview, off: int) -> tuple[bytes, int]:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return bytes(buf[off:off + n]), off + n


def encode_op_pool(pool: OperationPool, T) -> bytes:
    out = [_MAGIC]
    stored = [(k, s) for k, v in pool.attestations.items() for s in v]
    out.append(struct.pack("<I", len(stored)))
    for key, s in stored:
        out.append(_blob(key))
        out.append(_blob(T.AttestationData.serialize(s.data)))
        out.append(_blob(np.packbits(
            np.asarray(s.bits, bool), bitorder="little").tobytes()
            + struct.pack("<I", len(s.bits))))
        out.append(_blob(s.signature))
        out.append(_blob(np.asarray(s.committee, np.int64).tobytes()))
    for items, enc in (
            (list(pool.proposer_slashings.values()),
             T.ProposerSlashing.serialize),
            (pool.attester_slashings, T.AttesterSlashing.serialize),
            (list(pool.voluntary_exits.values()),
             T.SignedVoluntaryExit.serialize),
            (list(pool.bls_changes.values()),
             T.SignedBLSToExecutionChange.serialize)):
        out.append(struct.pack("<I", len(items)))
        out.extend(_blob(enc(it)) for it in items)
    return b"".join(out)


def decode_op_pool(data: bytes, preset, spec, T) -> OperationPool:
    buf = memoryview(data)
    if bytes(buf[:5]) != _MAGIC:
        raise ValueError("bad op-pool blob")
    off = 5
    pool = OperationPool(preset, spec)
    (n_att,) = struct.unpack_from("<I", buf, off)
    off += 4
    for _ in range(n_att):
        key, off = _unblob(buf, off)
        data_b, off = _unblob(buf, off)
        bits_b, off = _unblob(buf, off)
        sig, off = _unblob(buf, off)
        comm_b, off = _unblob(buf, off)
        (n_bits,) = struct.unpack("<I", bits_b[-4:])
        bits = np.unpackbits(
            np.frombuffer(bits_b[:-4], np.uint8),
            bitorder="little")[:n_bits].astype(bool)
        pool.attestations.setdefault(key, []).append(_StoredAttestation(
            data=T.AttestationData.deserialize(data_b),
            bits=bits, signature=sig,
            committee=np.frombuffer(comm_b, np.int64).copy()))
    for attr, dec, keyed in (
            ("proposer_slashings", T.ProposerSlashing.deserialize,
             lambda s: int(s.signed_header_1.message.proposer_index)),
            ("attester_slashings", T.AttesterSlashing.deserialize, None),
            ("voluntary_exits", T.SignedVoluntaryExit.deserialize,
             lambda e: int(e.message.validator_index)),
            ("bls_changes", T.SignedBLSToExecutionChange.deserialize,
             lambda c: int(c.message.validator_index))):
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        for _ in range(n):
            raw, off = _unblob(buf, off)
            item = dec(raw)
            if keyed is None:
                getattr(pool, attr).append(item)
            else:
                getattr(pool, attr)[keyed(item)] = item
    return pool
