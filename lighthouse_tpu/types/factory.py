"""Per-preset consensus containers — phase0 through capella.

The counterpart of the reference's generic types
(``/root/reference/consensus/types/src/*.rs``, monomorphized over
``EthSpec``): :func:`spec_types` builds the full set of container classes for
a :class:`~lighthouse_tpu.types.presets.Preset` and caches it.  Fork-versioned
types (``superstruct`` enums in the reference — ``beacon_state.rs:19``,
``beacon_block.rs``, ``execution_payload.rs``) become per-fork classes whose
common field prefix is shared via annotated base classes, so SSZ field order
matches the spec exactly.

Hot state columns use the columnar types from
:mod:`lighthouse_tpu.types.columns` and the SoA registry from
:mod:`lighthouse_tpu.types.validators` — wire-identical to SSZ, hashed as
batched device reductions.

NOTE: no ``from __future__ import annotations`` here — container field
annotations must evaluate eagerly so they can reference the other classes
built in this scope.
"""

from ..ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Bytes4,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    Vector,
    boolean,
    uint64,
    uint256,
)
from .chain_spec import ForkName
from .columns import (
    PackedU8List,
    PackedU64List,
    PackedU64Vector,
    RootsList,
    RootsVector,
)
from .device_state import DEVICE_COLUMN_FIELDS as _DEVICE_COLUMN_FIELDS_T
from .presets import Preset
from .validators import Validator, ValidatorRegistryList

_DEVICE_COLUMN_FIELDS = frozenset(_DEVICE_COLUMN_FIELDS_T)


class SpecTypes:
    """Namespace of container classes for one preset."""

    def __init__(self, preset: Preset):
        self.preset = preset
        p = preset
        ns = self.__dict__

        # -- fork-independent leaf containers (beacon-chain.md) --------------

        class Fork(Container):
            previous_version: Bytes4
            current_version: Bytes4
            epoch: uint64

        class ForkData(Container):
            current_version: Bytes4
            genesis_validators_root: Bytes32

        class SigningData(Container):
            object_root: Bytes32
            domain: Bytes32

        class Checkpoint(Container):
            epoch: uint64
            root: Bytes32

        class AttestationData(Container):
            slot: uint64
            index: uint64
            beacon_block_root: Bytes32
            source: Checkpoint
            target: Checkpoint

        class IndexedAttestation(Container):
            attesting_indices: List(uint64, p.MAX_VALIDATORS_PER_COMMITTEE)
            data: AttestationData
            signature: Bytes96

        class PendingAttestation(Container):
            aggregation_bits: Bitlist(p.MAX_VALIDATORS_PER_COMMITTEE)
            data: AttestationData
            inclusion_delay: uint64
            proposer_index: uint64

        class Eth1Data(Container):
            deposit_root: Bytes32
            deposit_count: uint64
            block_hash: Bytes32

        class HistoricalBatch(Container):
            block_roots: RootsVector(p.SLOTS_PER_HISTORICAL_ROOT)
            state_roots: RootsVector(p.SLOTS_PER_HISTORICAL_ROOT)

        class HistoricalSummary(Container):
            block_summary_root: Bytes32
            state_summary_root: Bytes32

        class DepositMessage(Container):
            pubkey: Bytes48
            withdrawal_credentials: Bytes32
            amount: uint64

        class DepositData(Container):
            pubkey: Bytes48
            withdrawal_credentials: Bytes32
            amount: uint64
            signature: Bytes96

        class Deposit(Container):
            proof: Vector(Bytes32, p.DEPOSIT_CONTRACT_TREE_DEPTH + 1)
            data: DepositData

        class BeaconBlockHeader(Container):
            slot: uint64
            proposer_index: uint64
            parent_root: Bytes32
            state_root: Bytes32
            body_root: Bytes32

        class SignedBeaconBlockHeader(Container):
            message: BeaconBlockHeader
            signature: Bytes96

        class ProposerSlashing(Container):
            signed_header_1: SignedBeaconBlockHeader
            signed_header_2: SignedBeaconBlockHeader

        class AttesterSlashing(Container):
            attestation_1: IndexedAttestation
            attestation_2: IndexedAttestation

        class Attestation(Container):
            aggregation_bits: Bitlist(p.MAX_VALIDATORS_PER_COMMITTEE)
            data: AttestationData
            signature: Bytes96

        class VoluntaryExit(Container):
            epoch: uint64
            validator_index: uint64

        class SignedVoluntaryExit(Container):
            message: VoluntaryExit
            signature: Bytes96

        class SyncAggregate(Container):
            sync_committee_bits: Bitvector(p.SYNC_COMMITTEE_SIZE)
            sync_committee_signature: Bytes96

        class SyncCommittee(Container):
            pubkeys: Vector(Bytes48, p.SYNC_COMMITTEE_SIZE)
            aggregate_pubkey: Bytes48

        class AggregateAndProof(Container):
            aggregator_index: uint64
            aggregate: Attestation
            selection_proof: Bytes96

        class SignedAggregateAndProof(Container):
            message: AggregateAndProof
            signature: Bytes96

        class SyncCommitteeMessage(Container):
            slot: uint64
            beacon_block_root: Bytes32
            validator_index: uint64
            signature: Bytes96

        class SyncCommitteeContribution(Container):
            slot: uint64
            beacon_block_root: Bytes32
            subcommittee_index: uint64
            aggregation_bits: Bitvector(p.sync_subcommittee_size)
            signature: Bytes96

        class ContributionAndProof(Container):
            aggregator_index: uint64
            contribution: SyncCommitteeContribution
            selection_proof: Bytes96

        class SignedContributionAndProof(Container):
            message: ContributionAndProof
            signature: Bytes96

        class SyncAggregatorSelectionData(Container):
            slot: uint64
            subcommittee_index: uint64

        class Withdrawal(Container):
            index: uint64
            validator_index: uint64
            address: Bytes20
            amount: uint64

        class BLSToExecutionChange(Container):
            validator_index: uint64
            from_bls_pubkey: Bytes48
            to_execution_address: Bytes20

        class SignedBLSToExecutionChange(Container):
            message: BLSToExecutionChange
            signature: Bytes96

        # -- execution payloads (bellatrix / capella) ------------------------

        Transaction = ByteList(p.MAX_BYTES_PER_TRANSACTION)
        LogsBloom = ByteVector(p.BYTES_PER_LOGS_BLOOM)
        ExtraData = ByteList(p.MAX_EXTRA_DATA_BYTES)

        class _PayloadCommon(Container):
            parent_hash: Bytes32
            fee_recipient: Bytes20
            state_root: Bytes32
            receipts_root: Bytes32
            logs_bloom: LogsBloom
            prev_randao: Bytes32
            block_number: uint64
            gas_limit: uint64
            gas_used: uint64
            timestamp: uint64
            extra_data: ExtraData
            base_fee_per_gas: uint256
            block_hash: Bytes32

        class ExecutionPayloadBellatrix(_PayloadCommon):
            transactions: List(Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD)

        class ExecutionPayloadCapella(_PayloadCommon):
            transactions: List(Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD)
            withdrawals: List(Withdrawal, p.MAX_WITHDRAWALS_PER_PAYLOAD)

        class ExecutionPayloadDeneb(_PayloadCommon):
            transactions: List(Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD)
            withdrawals: List(Withdrawal, p.MAX_WITHDRAWALS_PER_PAYLOAD)
            blob_gas_used: uint64
            excess_blob_gas: uint64

        class ExecutionPayloadHeaderBellatrix(_PayloadCommon):
            transactions_root: Bytes32

        class ExecutionPayloadHeaderCapella(_PayloadCommon):
            transactions_root: Bytes32
            withdrawals_root: Bytes32

        class ExecutionPayloadHeaderDeneb(_PayloadCommon):
            transactions_root: Bytes32
            withdrawals_root: Bytes32
            blob_gas_used: uint64
            excess_blob_gas: uint64

        # -- block bodies / blocks per fork ----------------------------------

        class _BodyCommon(Container):
            randao_reveal: Bytes96
            eth1_data: Eth1Data
            graffiti: Bytes32
            proposer_slashings: List(ProposerSlashing, p.MAX_PROPOSER_SLASHINGS)
            attester_slashings: List(AttesterSlashing, p.MAX_ATTESTER_SLASHINGS)
            attestations: List(Attestation, p.MAX_ATTESTATIONS)
            deposits: List(Deposit, p.MAX_DEPOSITS)
            voluntary_exits: List(SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS)

        class BeaconBlockBodyPhase0(_BodyCommon):
            pass

        class BeaconBlockBodyAltair(_BodyCommon):
            sync_aggregate: SyncAggregate

        class BeaconBlockBodyBellatrix(_BodyCommon):
            sync_aggregate: SyncAggregate
            execution_payload: ExecutionPayloadBellatrix

        class BeaconBlockBodyCapella(_BodyCommon):
            sync_aggregate: SyncAggregate
            execution_payload: ExecutionPayloadCapella
            bls_to_execution_changes: List(
                SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES)

        # Deneb (EIP-4844): the body carries the blob KZG commitments; the
        # blobs themselves travel as BlobSidecars outside the block.
        KZGCommitment = Bytes48
        KZGProof = Bytes48
        Blob = ByteVector(p.BYTES_PER_BLOB)

        class BeaconBlockBodyDeneb(_BodyCommon):
            sync_aggregate: SyncAggregate
            execution_payload: ExecutionPayloadDeneb
            bls_to_execution_changes: List(
                SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES)
            blob_kzg_commitments: List(
                KZGCommitment, p.MAX_BLOB_COMMITMENTS_PER_BLOCK)

        class BlobSidecar(Container):
            """`deneb/p2p-interface.md` BlobSidecar: blob + proof bound to
            a block via the header and the commitment inclusion branch."""
            index: uint64
            blob: Blob
            kzg_commitment: KZGCommitment
            kzg_proof: KZGProof
            signed_block_header: SignedBeaconBlockHeader
            kzg_commitment_inclusion_proof: Vector(
                Bytes32, p.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH)

        class BlobIdentifier(Container):
            """`BlobSidecarsByRoot` request element."""
            block_root: Bytes32
            index: uint64

        def _make_block(body_cls):
            class BeaconBlock(Container):
                slot: uint64
                proposer_index: uint64
                parent_root: Bytes32
                state_root: Bytes32
                body: body_cls

            class SignedBeaconBlock(Container):
                message: BeaconBlock
                signature: Bytes96

            return BeaconBlock, SignedBeaconBlock

        BeaconBlockPhase0, SignedBeaconBlockPhase0 = _make_block(BeaconBlockBodyPhase0)
        BeaconBlockAltair, SignedBeaconBlockAltair = _make_block(BeaconBlockBodyAltair)
        BeaconBlockBellatrix, SignedBeaconBlockBellatrix = _make_block(BeaconBlockBodyBellatrix)
        BeaconBlockCapella, SignedBeaconBlockCapella = _make_block(BeaconBlockBodyCapella)
        BeaconBlockDeneb, SignedBeaconBlockDeneb = _make_block(BeaconBlockBodyDeneb)

        # -- blinded blocks (builder flow) ------------------------------------
        # The payload is replaced by its header; because the header's
        # tree-hash equals the full payload's, a blinded block and its
        # unblinded twin share one root — the `AbstractExecPayload`
        # machinery of `consensus/types/src/payload.rs` collapses to two
        # parallel container families here.

        class BlindedBeaconBlockBodyBellatrix(_BodyCommon):
            sync_aggregate: SyncAggregate
            execution_payload_header: ExecutionPayloadHeaderBellatrix

        class BlindedBeaconBlockBodyCapella(_BodyCommon):
            sync_aggregate: SyncAggregate
            execution_payload_header: ExecutionPayloadHeaderCapella
            bls_to_execution_changes: List(
                SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES)

        class BlindedBeaconBlockBodyDeneb(_BodyCommon):
            sync_aggregate: SyncAggregate
            execution_payload_header: ExecutionPayloadHeaderDeneb
            bls_to_execution_changes: List(
                SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES)
            blob_kzg_commitments: List(
                KZGCommitment, p.MAX_BLOB_COMMITMENTS_PER_BLOCK)

        BlindedBeaconBlockBellatrix, SignedBlindedBeaconBlockBellatrix = \
            _make_block(BlindedBeaconBlockBodyBellatrix)
        BlindedBeaconBlockCapella, SignedBlindedBeaconBlockCapella = \
            _make_block(BlindedBeaconBlockBodyCapella)
        BlindedBeaconBlockDeneb, SignedBlindedBeaconBlockDeneb = \
            _make_block(BlindedBeaconBlockBodyDeneb)

        # -- states per fork -------------------------------------------------

        JustificationBits = Bitvector(4)
        Balances = PackedU64List(p.VALIDATOR_REGISTRY_LIMIT)
        Participation = PackedU8List(p.VALIDATOR_REGISTRY_LIMIT)
        InactivityScores = PackedU64List(p.VALIDATOR_REGISTRY_LIMIT)
        Slashings = PackedU64Vector(p.EPOCHS_PER_SLASHINGS_VECTOR)
        Registry = ValidatorRegistryList(p.VALIDATOR_REGISTRY_LIMIT)

        class _StateCommon(Container):
            """Shared state prefix + the incremental tree-hash cache hook
            (``BeaconTreeHashCache``,
            ``types/src/beacon_state/tree_hash_cache.rs:332``): instances
            carry a :class:`~lighthouse_tpu.types.state_cache.StateHashCache`
            that makes repeated ``tree_hash_root()`` calls O(changes·log n);
            ``copy()`` clones it like the reference's state clone.

            Once a state is device-resident
            (:func:`~lighthouse_tpu.types.device_state.materialize_state`),
            wholesale column assignment (``state.balances = new``) is routed
            INTO the existing :class:`~lighthouse_tpu.types.device_state.
            DeviceColumn` instead of replacing it, so residency and dirty
            tracking survive every legacy write path; a jax-array RHS is
            adopted without a pull (the jitted epoch sweep's outputs stay
            in HBM)."""

            def __setattr__(self, name, value):
                if name in _DEVICE_COLUMN_FIELDS:
                    from .device_state import DeviceColumn
                    cur = self.__dict__.get(name)
                    if isinstance(cur, DeviceColumn) and cur is not value:
                        if isinstance(value, DeviceColumn):
                            object.__setattr__(self, name, value)
                        else:
                            cur.assign(value)
                        return
                object.__setattr__(self, name, value)

            def tree_hash_root(self) -> bytes:
                from .state_cache import StateHashCache
                thc = self.__dict__.get("_thc")
                if thc is None:
                    thc = self.__dict__["_thc"] = StateHashCache()
                return thc.root(self)

            def copy(self):
                out = super().copy()
                thc = self.__dict__.get("_thc")
                if thc is not None:
                    out.__dict__["_thc"] = thc.copy()
                if self.__dict__.get("_device_resident"):
                    out.__dict__["_device_resident"] = True
                return out

            genesis_time: uint64
            genesis_validators_root: Bytes32
            slot: uint64
            fork: Fork
            latest_block_header: BeaconBlockHeader
            block_roots: RootsVector(p.SLOTS_PER_HISTORICAL_ROOT)
            state_roots: RootsVector(p.SLOTS_PER_HISTORICAL_ROOT)
            historical_roots: RootsList(p.HISTORICAL_ROOTS_LIMIT)
            eth1_data: Eth1Data
            eth1_data_votes: List(
                Eth1Data, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH)
            eth1_deposit_index: uint64
            validators: Registry
            balances: Balances
            randao_mixes: RootsVector(p.EPOCHS_PER_HISTORICAL_VECTOR)
            slashings: Slashings

        class BeaconStatePhase0(_StateCommon):
            previous_epoch_attestations: List(
                PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH)
            current_epoch_attestations: List(
                PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH)
            justification_bits: JustificationBits
            previous_justified_checkpoint: Checkpoint
            current_justified_checkpoint: Checkpoint
            finalized_checkpoint: Checkpoint

        class _StateAltairCommon(_StateCommon):
            previous_epoch_participation: Participation
            current_epoch_participation: Participation
            justification_bits: JustificationBits
            previous_justified_checkpoint: Checkpoint
            current_justified_checkpoint: Checkpoint
            finalized_checkpoint: Checkpoint
            inactivity_scores: InactivityScores
            current_sync_committee: SyncCommittee
            next_sync_committee: SyncCommittee

        class BeaconStateAltair(_StateAltairCommon):
            pass

        class BeaconStateBellatrix(_StateAltairCommon):
            latest_execution_payload_header: ExecutionPayloadHeaderBellatrix

        class BeaconStateCapella(_StateAltairCommon):
            latest_execution_payload_header: ExecutionPayloadHeaderCapella
            next_withdrawal_index: uint64
            next_withdrawal_validator_index: uint64
            historical_summaries: List(
                HistoricalSummary, p.HISTORICAL_ROOTS_LIMIT)

        class BeaconStateDeneb(_StateAltairCommon):
            latest_execution_payload_header: ExecutionPayloadHeaderDeneb
            next_withdrawal_index: uint64
            next_withdrawal_validator_index: uint64
            historical_summaries: List(
                HistoricalSummary, p.HISTORICAL_ROOTS_LIMIT)

        # -- publish ---------------------------------------------------------

        for k, v in list(locals().items()):
            if k not in ("self", "p", "ns", "preset") and not k.startswith("_"):
                ns[k] = v
        ns["Validator"] = Validator
        ns["Transaction"] = Transaction
        ns["JustificationBits"] = JustificationBits
        ns["Registry"] = Registry
        ns["Balances"] = Balances
        ns["Participation"] = Participation

        self._by_fork = {
            ForkName.PHASE0: (BeaconStatePhase0, BeaconBlockPhase0,
                              SignedBeaconBlockPhase0, BeaconBlockBodyPhase0),
            ForkName.ALTAIR: (BeaconStateAltair, BeaconBlockAltair,
                              SignedBeaconBlockAltair, BeaconBlockBodyAltair),
            ForkName.BELLATRIX: (BeaconStateBellatrix, BeaconBlockBellatrix,
                                 SignedBeaconBlockBellatrix,
                                 BeaconBlockBodyBellatrix),
            ForkName.CAPELLA: (BeaconStateCapella, BeaconBlockCapella,
                               SignedBeaconBlockCapella,
                               BeaconBlockBodyCapella),
            ForkName.DENEB: (BeaconStateDeneb, BeaconBlockDeneb,
                             SignedBeaconBlockDeneb, BeaconBlockBodyDeneb),
        }
        self._payload_by_fork = {
            ForkName.BELLATRIX: (ExecutionPayloadBellatrix,
                                 ExecutionPayloadHeaderBellatrix),
            ForkName.CAPELLA: (ExecutionPayloadCapella,
                               ExecutionPayloadHeaderCapella),
            ForkName.DENEB: (ExecutionPayloadDeneb,
                             ExecutionPayloadHeaderDeneb),
        }
        self._blinded_by_fork = {
            ForkName.BELLATRIX: (BlindedBeaconBlockBellatrix,
                                 SignedBlindedBeaconBlockBellatrix,
                                 BlindedBeaconBlockBodyBellatrix),
            ForkName.CAPELLA: (BlindedBeaconBlockCapella,
                               SignedBlindedBeaconBlockCapella,
                               BlindedBeaconBlockBodyCapella),
            ForkName.DENEB: (BlindedBeaconBlockDeneb,
                             SignedBlindedBeaconBlockDeneb,
                             BlindedBeaconBlockBodyDeneb),
        }

    # -- fork-indexed access (superstruct's common accessors) ---------------

    def state_cls(self, fork: ForkName) -> type:
        return self._by_fork[fork][0]

    def block_cls(self, fork: ForkName) -> type:
        return self._by_fork[fork][1]

    def signed_block_cls(self, fork: ForkName) -> type:
        return self._by_fork[fork][2]

    def body_cls(self, fork: ForkName) -> type:
        return self._by_fork[fork][3]

    def payload_cls(self, fork: ForkName) -> type:
        return self._payload_by_fork[fork][0]

    def payload_header_cls(self, fork: ForkName) -> type:
        return self._payload_by_fork[fork][1]

    def blinded_block_cls(self, fork: ForkName) -> type:
        return self._blinded_by_fork[fork][0]

    def signed_blinded_block_cls(self, fork: ForkName) -> type:
        return self._blinded_by_fork[fork][1]

    def blinded_body_cls(self, fork: ForkName) -> type:
        return self._blinded_by_fork[fork][2]

    def fork_of_state(self, state) -> ForkName:
        for fork, (scls, *_rest) in self._by_fork.items():
            if type(state) is scls:
                return fork
        raise TypeError(f"not a BeaconState: {type(state).__name__}")

    def fork_of_block(self, block) -> ForkName:
        for fork, (_s, bcls, sbcls, _body) in self._by_fork.items():
            if type(block) is bcls or type(block) is sbcls:
                return fork
        for fork, (bcls, sbcls, _body) in self._blinded_by_fork.items():
            if type(block) is bcls or type(block) is sbcls:
                return fork
        raise TypeError(f"not a BeaconBlock: {type(block).__name__}")


_spec_types_cache: dict[str, SpecTypes] = {}


def spec_types(preset: Preset) -> SpecTypes:
    st = _spec_types_cache.get(preset.name)
    if st is None:
        st = SpecTypes(preset)
        _spec_types_cache[preset.name] = st
    return st
