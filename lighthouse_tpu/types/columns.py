"""Columnar SSZ state fields with device-batched Merkleization.

The big ``BeaconState`` fields — roots vectors (8192–65536 entries),
balances / inactivity scores (~1M u64), participation flags (~1M u8) — are
stored as numpy columns and hashed as single batched Merkle reductions on
the device (``lighthouse_tpu.ops.merkle``), instead of the reference's
per-field incremental CPU caches (``/root/reference/consensus/cached_tree_hash``,
``types/src/beacon_state/tree_hash_cache.rs``).  Wire encoding stays
bit-identical to SSZ (these are just ``Vector[Bytes32, N]`` /
``List[uint64, N]`` etc. with a columnar value representation).
"""

from __future__ import annotations

import numpy as np

from ..ssz.core import SszError, SszType
from ..ops.merkle import (
    _next_pow2,
    merkleize_auto,
    mix_in_length_host,
)
from ..ops.sha256 import words_to_bytes


def bytes_to_chunk_words(data: bytes) -> np.ndarray:
    """Byte string → ``(k, 8)`` u32 big-endian chunk words (zero-padded)."""
    pad = (-len(data)) % 32
    if pad:
        data = data + b"\x00" * pad
    if not data:
        return np.zeros((0, 8), dtype=np.uint32)
    return np.frombuffer(data, dtype=">u4").astype(np.uint32).reshape(-1, 8)


def device_merkle_root(chunk_words: np.ndarray, limit_chunks: int,
                       length_mixin: int | None = None) -> bytes:
    """Padded Merkle root of ``(k, 8)`` chunk words over a ``limit_chunks``
    tree, as one device reduction; optional SSZ length mixin.

    Registry-scale widths route through the fused Pallas sub-tree kernel
    (:mod:`..ops.merkle_kernel`); smaller trees use the XLA scan reduction
    or host hashing (:func:`..ops.merkle.merkleize_auto`)."""
    from ..ops.merkle_kernel import CHUNK_LOG2, merkle_root_chunked, _use_pallas

    depth = max((limit_chunks - 1).bit_length(), 0)
    k = chunk_words.shape[0]
    width = _next_pow2(max(k, 1))
    if k != width:
        padded = np.zeros((width, 8), dtype=np.uint32)
        padded[:k] = chunk_words
        chunk_words = padded
    chunk_words = np.asarray(chunk_words, dtype=np.uint32)
    if width >= (1 << CHUNK_LOG2) and _use_pallas():
        root = words_to_bytes(np.asarray(merkle_root_chunked(chunk_words, depth)))
    else:
        root = words_to_bytes(merkleize_auto(chunk_words, depth))
    if length_mixin is not None:
        # SSZ mixes a 256-bit LE length; Python ints are exact here, so even
        # >2^32-entry lists (registry limit is 2^40) hash correctly.
        root = mix_in_length_host(root, int(length_mixin))
    return root


class Roots(np.ndarray):
    """``(n, 32) uint8`` array of 32-byte roots with bytes accessors."""

    @classmethod
    def zeros(cls, n: int) -> "Roots":
        return np.zeros((n, 32), dtype=np.uint8).view(cls)

    @classmethod
    def from_list(cls, items) -> "Roots":
        out = cls.zeros(len(items))
        for i, b in enumerate(items):
            out.set(i, b)
        return out

    def get(self, i: int) -> bytes:
        return self[i].tobytes()

    def set(self, i: int, root: bytes) -> None:
        if len(root) != 32:
            raise SszError("root must be 32 bytes")
        self[i] = np.frombuffer(root, dtype=np.uint8)

    def append_root(self, root: bytes) -> "Roots":
        """Functional append (lists are short-lived; vectors never grow)."""
        out = np.concatenate(
            [self, np.frombuffer(root, dtype=np.uint8)[None, :]], axis=0)
        return out.view(Roots)

    def words(self) -> np.ndarray:
        return np.ascontiguousarray(self).view(">u4").astype(np.uint32)


_cache: dict[tuple, type] = {}


def _cached(key, build):
    cls = _cache.get(key)
    if cls is None:
        cls = build()
        cls.__name__ = f"{key[0]}[{','.join(str(k) for k in key[1:])}]"
        _cache[key] = cls
    return cls


def RootsVector(length: int) -> type:
    """``Vector[Bytes32, N]`` with columnar value + device htr."""
    def build():
        class _RootsVector(SszType):
            LENGTH = length

            @classmethod
            def is_fixed_size(cls) -> bool:
                return True

            @classmethod
            def fixed_size(cls) -> int:
                return 32 * cls.LENGTH

            @classmethod
            def serialize(cls, value) -> bytes:
                value = _as_roots(value)
                if value.shape[0] != cls.LENGTH:
                    raise SszError("roots vector length mismatch")
                return value.tobytes()

            @classmethod
            def deserialize(cls, data: bytes) -> Roots:
                if len(data) != 32 * cls.LENGTH:
                    raise SszError("roots vector byte length mismatch")
                return np.frombuffer(data, dtype=np.uint8).reshape(
                    -1, 32).copy().view(Roots)

            @classmethod
            def hash_tree_root(cls, value) -> bytes:
                value = _as_roots(value)
                if value.shape[0] != cls.LENGTH:
                    raise SszError("roots vector length mismatch")
                return device_merkle_root(value.words(), cls.LENGTH)

            @classmethod
            def leaf_words(cls, value):
                """(chunk words, limit_chunks, length mixin) for the
                incremental hash cache."""
                value = _as_roots(value)
                return value.words(), cls.LENGTH, None

            @classmethod
            def default(cls) -> Roots:
                return Roots.zeros(cls.LENGTH)

        return _RootsVector
    return _cached(("RootsVector", length), build)


def RootsList(limit: int) -> type:
    """``List[Bytes32, N]`` with columnar value + device htr."""
    def build():
        class _RootsList(SszType):
            LIMIT = limit

            @classmethod
            def is_fixed_size(cls) -> bool:
                return False

            @classmethod
            def serialize(cls, value) -> bytes:
                value = _as_roots(value)
                if value.shape[0] > cls.LIMIT:
                    raise SszError("roots list exceeds limit")
                return value.tobytes()

            @classmethod
            def deserialize(cls, data: bytes) -> Roots:
                if len(data) % 32:
                    raise SszError("roots list byte length not 32-multiple")
                out = np.frombuffer(data, dtype=np.uint8).reshape(
                    -1, 32).copy().view(Roots)
                if out.shape[0] > cls.LIMIT:
                    raise SszError("roots list exceeds limit")
                return out

            @classmethod
            def hash_tree_root(cls, value) -> bytes:
                value = _as_roots(value)
                if value.shape[0] > cls.LIMIT:
                    raise SszError("roots list exceeds limit")
                return device_merkle_root(value.words(), cls.LIMIT,
                                          length_mixin=value.shape[0])

            @classmethod
            def leaf_words(cls, value):
                value = _as_roots(value)
                return value.words(), cls.LIMIT, value.shape[0]

            @classmethod
            def default(cls) -> Roots:
                return Roots.zeros(0)

        return _RootsList
    return _cached(("RootsList", limit), build)


def _as_roots(value) -> Roots:
    if isinstance(value, np.ndarray) and value.dtype == np.uint8 \
            and value.ndim == 2 and value.shape[1] == 32:
        return value.view(Roots)
    return Roots.from_list(list(value))


def _packed_uint(name: str, dtype, bits: int, bound: int, is_list: bool) -> type:
    per_chunk = 32 // (bits // 8)
    limit_chunks = max((bound + per_chunk - 1) // per_chunk, 1)

    class _Packed(SszType):
        BOUND = bound
        DTYPE = dtype

        @classmethod
        def is_fixed_size(cls) -> bool:
            return not is_list

        @classmethod
        def fixed_size(cls) -> int:
            if is_list:
                return SszType.fixed_size.__func__(cls)  # raises
            return bound * (bits // 8)

        @classmethod
        def _as_arr(cls, value) -> np.ndarray:
            arr = np.asarray(value)
            if arr.ndim != 1:
                raise SszError("packed column must be one-dimensional")
            if arr.size == 0:
                arr = np.zeros(0, dtype=dtype)
            if arr.dtype != dtype:
                if arr.dtype.kind not in "iu" and arr.dtype != bool:
                    raise SszError(f"cannot pack {arr.dtype} as uint{bits}")
                if arr.dtype.kind == "i" and int(arr.min()) < 0:
                    raise SszError("negative value in unsigned column")
                if (np.dtype(arr.dtype).itemsize * 8 > bits
                        and int(arr.max()) >= (1 << bits)):
                    raise SszError(f"value out of range for uint{bits}")
                arr = arr.astype(dtype)
            if is_list:
                if arr.shape[0] > bound:
                    raise SszError("list exceeds limit")
            elif arr.shape[0] != bound:
                raise SszError("vector length mismatch")
            return arr

        @classmethod
        def serialize(cls, value) -> bytes:
            arr = cls._as_arr(value)
            return arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes()

        @classmethod
        def deserialize(cls, data: bytes) -> np.ndarray:
            item = bits // 8
            if len(data) % item:
                raise SszError("byte length not a multiple of element size")
            arr = np.frombuffer(
                data, dtype=np.dtype(dtype).newbyteorder("<")).astype(dtype)
            return cls._as_arr(arr)

        @classmethod
        def hash_tree_root(cls, value) -> bytes:
            arr = cls._as_arr(value)
            words = bytes_to_chunk_words(
                arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes())
            return device_merkle_root(
                words, limit_chunks,
                length_mixin=arr.shape[0] if is_list else None)

        @classmethod
        def leaf_words(cls, value):
            arr = cls._as_arr(value)
            words = bytes_to_chunk_words(
                arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes())
            return words, limit_chunks, (arr.shape[0] if is_list else None)

        @classmethod
        def default(cls) -> np.ndarray:
            return np.zeros(0 if is_list else bound, dtype=dtype)

    return _Packed


def PackedU64List(limit: int) -> type:
    """``List[uint64, N]`` (balances, inactivity scores) — device htr."""
    return _cached(("PackedU64List", limit),
                   lambda: _packed_uint("u64l", np.uint64, 64, limit, True))


def PackedU64Vector(length: int) -> type:
    """``Vector[uint64, N]`` (slashings) — device htr."""
    return _cached(("PackedU64Vector", length),
                   lambda: _packed_uint("u64v", np.uint64, 64, length, False))


def PackedU8List(limit: int) -> type:
    """``List[uint8, N]`` (participation flags) — device htr."""
    return _cached(("PackedU8List", limit),
                   lambda: _packed_uint("u8l", np.uint8, 8, limit, True))
