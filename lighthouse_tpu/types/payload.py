"""Full ↔ blinded payload conversions — the working core of
``consensus/types/src/payload.rs`` (``AbstractExecPayload`` /
``BlindedPayload`` / ``FullPayload``).

The type-level machinery the reference needs (one generic block type
instantiated at two payload types) collapses in Python to two parallel
container families (:mod:`.factory`) plus these conversions.  The
load-bearing invariant: ``blind_block(b).tree_hash_root() ==
b.tree_hash_root()`` — the builder signs over the same root the proposer
committed to, because an SSZ header whose ``transactions_root`` is the
tree-hash of the transactions list merkleizes identically to the full
payload.
"""

from __future__ import annotations


def payload_to_header(payload, T, fork):
    """ExecutionPayload → ExecutionPayloadHeader (`payload.rs` From impl)."""
    header_cls = T.payload_header_cls(fork)
    payload_cls = T.payload_cls(fork)
    header = header_cls.default()
    for name, ftype in header_cls.FIELDS.items():
        if name == "transactions_root":
            setattr(header, name, payload_cls.FIELDS[
                "transactions"].hash_tree_root(payload.transactions))
        elif name == "withdrawals_root":
            setattr(header, name, payload_cls.FIELDS[
                "withdrawals"].hash_tree_root(payload.withdrawals))
        else:
            setattr(header, name, getattr(payload, name))
    return header


def blind_block(block, T):
    """BeaconBlock → BlindedBeaconBlock with the same tree-hash root."""
    fork = T.fork_of_block(block)
    blinded = T.blinded_block_cls(fork).default()
    for name in ("slot", "proposer_index", "parent_root", "state_root"):
        setattr(blinded, name, getattr(block, name))
    src, dst = block.body, T.blinded_body_cls(fork).default()
    for name in type(dst).FIELDS:
        if name == "execution_payload_header":
            dst.execution_payload_header = payload_to_header(
                src.execution_payload, T, fork)
        else:
            setattr(dst, name, getattr(src, name))
    blinded.body = dst
    return blinded


def unblind_block(blinded, payload, T):
    """BlindedBeaconBlock + the builder-revealed payload → full block.

    Refuses a payload that does not match the committed header
    (`validator/src/block_service.rs` unblinding check — accepting a
    substituted payload would let a builder make the proposer equivocate
    about execution content).
    """
    fork = T.fork_of_block(blinded)
    want = blinded.body.execution_payload_header.tree_hash_root()
    got = payload_to_header(payload, T, fork).tree_hash_root()
    if want != got:
        raise ValueError(
            f"builder payload root {got.hex()} does not match the blinded "
            f"block's committed header {want.hex()}")
    block = T.block_cls(fork).default()
    for name in ("slot", "proposer_index", "parent_root", "state_root"):
        setattr(block, name, getattr(blinded, name))
    src, dst = blinded.body, T.body_cls(fork).default()
    for name in type(dst).FIELDS:
        if name == "execution_payload":
            dst.execution_payload = payload
        else:
            setattr(dst, name, getattr(src, name))
    block.body = dst
    return block
