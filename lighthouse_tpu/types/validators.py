"""Validator registry as a structure-of-arrays (SoA) — the TPU-first redesign
of the reference's ``List<Validator, ValidatorRegistryLimit>``.

The reference stores validators as an array-of-structs and parallelises
hashing with rayon over 4096-record arenas
(``/root/reference/consensus/types/src/beacon_state/tree_hash_cache.rs:25-33,
535-556``).  On TPU the natural layout is columnar: each field is one numpy
array, so

- epoch processing (rewards, effective-balance updates, registry updates)
  is vectorized arithmetic over whole columns (no per-validator Python);
- the registry Merkle root is ONE batched device program: 8 chunk-leaves per
  validator, three ``hash64`` levels to per-validator roots, then the big
  padded reduction to the 2^40-leaf registry root
  (``consensus/types/src/validator.rs`` field order defines the leaves).

``Validator`` (the AoS container) remains the single-record interchange type;
the registry converts at the boundary.
"""

from __future__ import annotations

import numpy as np

from ..ssz.core import Bytes32, Bytes48, SszError
from ..ssz.composite import Container
from ..ssz import boolean, uint64
from ..ops.sha256 import hash64
from .chain_spec import FAR_FUTURE_EPOCH

# Packed wire layout: 121 bytes per record, field order per the spec
# container (``consensus/types/src/validator.rs``).
_VALIDATOR_DTYPE = np.dtype([
    ("pubkey", "u1", (48,)),
    ("withdrawal_credentials", "u1", (32,)),
    ("effective_balance", "<u8"),
    ("slashed", "u1"),
    ("activation_eligibility_epoch", "<u8"),
    ("activation_epoch", "<u8"),
    ("exit_epoch", "<u8"),
    ("withdrawable_epoch", "<u8"),
])
assert _VALIDATOR_DTYPE.itemsize == 121

_EPOCH_FIELDS = ("activation_eligibility_epoch", "activation_epoch",
                 "exit_epoch", "withdrawable_epoch")


class Validator(Container):
    """Single-record AoS form (interchange/SSZ boundary)."""
    pubkey: Bytes48
    withdrawal_credentials: Bytes32
    effective_balance: uint64
    slashed: boolean
    activation_eligibility_epoch: uint64
    activation_epoch: uint64
    exit_epoch: uint64
    withdrawable_epoch: uint64


def u64_to_chunk_words(v: np.ndarray) -> np.ndarray:
    """``(n,) uint64`` → ``(n, 8) uint32`` big-endian words of the 32-byte
    SSZ chunk (value little-endian, zero-padded)."""
    v = np.asarray(v, dtype=np.uint64)
    lo = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (v >> np.uint64(32)).astype(np.uint32)
    out = np.zeros(v.shape + (8,), dtype=np.uint32)
    out[..., 0] = lo.byteswap()
    out[..., 1] = hi.byteswap()
    return out


def bytes_col_to_words(col: np.ndarray) -> np.ndarray:
    """``(n, 4k) uint8`` → ``(n, k) uint32`` big-endian words."""
    col = np.ascontiguousarray(col)
    return col.view(">u4").astype(np.uint32)


class ValidatorRegistry:
    """SoA columns + list-like API.  Mutations go through the columns
    (vectorized) or :meth:`set`; ``append`` amortizes with capacity doubling
    like the reference's ``CacheArena`` grow path."""

    __ssz_mutable__ = True

    def __init__(self, n: int = 0, _cap: int | None = None):
        cap = max(_cap if _cap is not None else n, n, 8)
        self._n = n
        self._pubkey = np.zeros((cap, 48), dtype=np.uint8)
        self._withdrawal_credentials = np.zeros((cap, 32), dtype=np.uint8)
        self._effective_balance = np.zeros(cap, dtype=np.uint64)
        self._slashed = np.zeros(cap, dtype=bool)
        self._activation_eligibility_epoch = np.full(
            cap, FAR_FUTURE_EPOCH, dtype=np.uint64)
        self._activation_epoch = np.full(cap, FAR_FUTURE_EPOCH, dtype=np.uint64)
        self._exit_epoch = np.full(cap, FAR_FUTURE_EPOCH, dtype=np.uint64)
        self._withdrawable_epoch = np.full(cap, FAR_FUTURE_EPOCH,
                                           dtype=np.uint64)
        # Dirty tracking for the incremental tree-hash cache
        # (``cached_tree_hash``'s dirty leaves, at column/row granularity).
        # ``col()`` views are read-only so every write goes through ``wcol``/
        # ``set``/``append`` and is tracked — an unmarked write raises.
        # Marks are CONSUMED by the hash cache at root time: a ``wcol``
        # view is only valid for writing until the next ``hash_tree_root``
        # (every in-tree caller writes immediately; sticky marks meant
        # re-diffing 130 MB of columns on every root at 2^20 validators).
        self._dirty_cols: set = set(self._COLUMNS)
        self._dirty_rows: set = set()
        # Lazy pubkey → index map (the ``ValidatorPubkeyCache`` reverse
        # lookup).  Shared by reference across ``copy()`` (pubkeys are
        # append-only in practice); extension forks the dict first so a
        # sharer never sees rows it does not have, and ``set()`` — the only
        # in-place pubkey overwrite — invalidates.
        self._pk_index: dict | None = None
        self._pk_index_n = 0
        # Device mirror (HBM-resident raw columns + record-root tree),
        # attached by the device-resident hash cache; COW-shared across
        # copy().  None until materialized.
        self._dev_mirror = None

    _COLUMNS = ("pubkey", "withdrawal_credentials", "effective_balance",
                "slashed", "activation_eligibility_epoch", "activation_epoch",
                "exit_epoch", "withdrawable_epoch")

    # -- list-like API -------------------------------------------------------

    def __len__(self) -> int:
        return self._n

    def col(self, name: str) -> np.ndarray:
        """Read-only view of a column, truncated to the real length.
        Writes must go through :meth:`wcol` (which marks the column dirty
        for the incremental hash cache) — writing this view raises, and the
        public column attributes are themselves read-only views so no write
        can bypass the tracking."""
        v = getattr(self, "_" + name)[:self._n]
        v.flags.writeable = False
        return v

    def wcol(self, name: str) -> np.ndarray:
        """Writable column view; marks the whole column dirty (the hash
        cache diffs it against its stored copy at root time, so the cost of
        a column-wide mark is one vectorized compare, not a rehash).

        The view must not be written after the next ``hash_tree_root`` —
        the cache consumes the mark there; re-call ``wcol`` for later
        writes."""
        self._dirty_cols.add(name)
        return getattr(self, "_" + name)[:self._n]

    def __getitem__(self, i: int) -> Validator:
        if not -self._n <= i < self._n:
            raise IndexError(i)
        i %= max(self._n, 1)
        return Validator(
            pubkey=self._pubkey[i].tobytes(),
            withdrawal_credentials=self._withdrawal_credentials[i].tobytes(),
            effective_balance=int(self._effective_balance[i]),
            slashed=bool(self._slashed[i]),
            activation_eligibility_epoch=int(
                self._activation_eligibility_epoch[i]),
            activation_epoch=int(self._activation_epoch[i]),
            exit_epoch=int(self._exit_epoch[i]),
            withdrawable_epoch=int(self._withdrawable_epoch[i]),
        )

    def __iter__(self):
        for i in range(self._n):
            yield self[i]

    def init_columns(self, **arrays) -> None:
        """Bulk-initialise columns on a FRESH registry (genesis fast path).
        All columns start dirty, so no extra marking is needed; using this
        instead of ``wcol`` avoids sticky-marking bulk-written columns."""
        for name, arr in arrays.items():
            if name not in self._COLUMNS:
                raise KeyError(name)
            getattr(self, "_" + name)[:self._n] = arr
        self._pk_index = None

    def pubkey_index(self, pubkey: bytes) -> int | None:
        """Index of ``pubkey`` in the registry (first occurrence), or None.
        One lazy dict build per registry lineage; copies share it and
        appended rows extend it incrementally."""
        d = self._pk_index
        if d is None:
            d = {}
            self._pk_index_n = 0
        if self._pk_index_n < self._n:
            if d:
                d = dict(d)  # fork: never extend a possibly-shared dict
            pks = self._pubkey
            for i in range(self._pk_index_n, self._n):
                d.setdefault(pks[i].tobytes(), i)
            self._pk_index, self._pk_index_n = d, self._n
        idx = d.get(pubkey)
        if idx is None:
            return None
        if idx < self._n and self._pubkey[idx].tobytes() == pubkey:
            return idx
        # Stale entry (row overwritten out from under a shared dict):
        # rebuild this registry's own map once.
        d = {}
        pks = self._pubkey
        for i in range(self._n):
            d.setdefault(pks[i].tobytes(), i)
        self._pk_index, self._pk_index_n = d, self._n
        return d.get(pubkey)

    def set(self, i: int, v: Validator) -> None:
        if not 0 <= i < self._n:
            raise IndexError(i)
        self._pk_index = None  # row overwrite may change a pubkey
        self._dirty_rows.add(i)
        self._pubkey[i] = np.frombuffer(v.pubkey, dtype=np.uint8)
        self._withdrawal_credentials[i] = np.frombuffer(
            v.withdrawal_credentials, dtype=np.uint8)
        self._effective_balance[i] = v.effective_balance
        self._slashed[i] = v.slashed
        self._activation_eligibility_epoch[i] = v.activation_eligibility_epoch
        self._activation_epoch[i] = v.activation_epoch
        self._exit_epoch[i] = v.exit_epoch
        self._withdrawable_epoch[i] = v.withdrawable_epoch

    def _grow(self, need: int) -> None:
        cap = self._effective_balance.shape[0]
        if need <= cap:
            return
        new_cap = max(need, cap * 2)
        for name in self._COLUMNS:
            old = getattr(self, "_" + name)
            new = np.empty((new_cap,) + old.shape[1:], dtype=old.dtype)
            new[:self._n] = old[:self._n]
            if old.dtype == np.uint64 and name in _EPOCH_FIELDS:
                new[self._n:] = FAR_FUTURE_EPOCH
            else:
                new[self._n:] = 0
            setattr(self, "_" + name, new)

    def append(self, v: Validator) -> None:
        self._grow(self._n + 1)
        self._n += 1
        self.set(self._n - 1, v)

    def copy(self) -> "ValidatorRegistry":
        out = ValidatorRegistry.__new__(type(self))
        out._n = self._n
        for name in self._COLUMNS:
            setattr(out, "_" + name, getattr(self, "_" + name)[:self._n].copy())
        out._dirty_cols = set(self._dirty_cols)
        out._dirty_rows = set(self._dirty_rows)
        out._pk_index = self._pk_index  # shared; forked on extension
        out._pk_index_n = self._pk_index_n
        # COW: the clone shares every device buffer; the first mutation of
        # either lineage lands in fresh buffers (undonated update program),
        # so cloning duplicates no HBM and forces no pull.
        out._dev_mirror = (None if self._dev_mirror is None
                           else self._dev_mirror.share())
        return out

    def __eq__(self, other):
        if not isinstance(other, ValidatorRegistry):
            return NotImplemented
        if self._n != other._n:
            return False
        return all(
            np.array_equal(self.col(name), other.col(name))
            for name in self._COLUMNS)

    def __repr__(self):
        return f"ValidatorRegistry(n={self._n})"

    # -- bulk conversion -----------------------------------------------------

    @classmethod
    def from_validators(cls, validators) -> "ValidatorRegistry":
        out = cls(len(validators))
        out._n = len(validators)
        for i, v in enumerate(validators):
            out.set(i, v)
        return out

    def to_packed(self) -> bytes:
        arr = np.empty(self._n, dtype=_VALIDATOR_DTYPE)
        arr["pubkey"] = self._pubkey[:self._n]
        arr["withdrawal_credentials"] = self._withdrawal_credentials[:self._n]
        arr["effective_balance"] = self._effective_balance[:self._n]
        arr["slashed"] = self._slashed[:self._n].astype(np.uint8)
        for f in _EPOCH_FIELDS:
            arr[f] = getattr(self, "_" + f)[:self._n]
        return arr.tobytes()

    @classmethod
    def from_packed(cls, data: bytes) -> "ValidatorRegistry":
        if len(data) % _VALIDATOR_DTYPE.itemsize:
            raise SszError("validator registry bytes not a multiple of 121")
        arr = np.frombuffer(data, dtype=_VALIDATOR_DTYPE)
        n = arr.shape[0]
        out = cls(n)
        out._n = n
        out._pubkey[:n] = arr["pubkey"]
        out._withdrawal_credentials[:n] = arr["withdrawal_credentials"]
        out._effective_balance[:n] = arr["effective_balance"]
        if arr["slashed"].size and (arr["slashed"] > 1).any():
            raise SszError("invalid boolean byte in validator record")
        out._slashed[:n] = arr["slashed"].astype(bool)
        for f in _EPOCH_FIELDS:
            getattr(out, "_" + f)[:n] = arr[f]
        return out

    # -- Merkleization (the hot path) ---------------------------------------

    def record_roots_words(self, indices=None) -> np.ndarray:
        """Per-validator hash_tree_roots as ``(k, 8)`` u32 words — one
        batched device program (vs rayon-per-arena in the reference,
        ``tree_hash_cache.rs:535-556``).  ``indices`` restricts to a subset
        (the incremental cache recomputes only dirty records)."""
        from ..ops.merkle import HOST_DISPATCH_THRESHOLD, hash64_host_words
        from ..ops.tree_cache import HASH_COUNT
        n = self._n
        if indices is None:
            sel = slice(None, n)  # zero-copy column views for full builds
            k = n
        else:
            sel = np.asarray(indices)
            k = sel.shape[0]
        if k == 0:
            return np.zeros((0, 8), dtype=np.uint32)
        inner = (hash64_host_words if k <= HOST_DISPATCH_THRESHOLD
                 else lambda a, b: np.asarray(hash64(a, b)))

        def h64(a, b):
            HASH_COUNT[0] += int(np.prod(a.shape[:-1], dtype=np.int64))
            return inner(a, b)
        pk = self._pubkey[sel]
        pk_hi = np.zeros((k, 32), dtype=np.uint8)
        pk_hi[:, :16] = pk[:, 32:]
        pubkey_root = h64(bytes_col_to_words(pk[:, :32]),
                          bytes_col_to_words(pk_hi))
        leaves = np.stack([
            np.asarray(pubkey_root),
            bytes_col_to_words(self._withdrawal_credentials[sel]),
            u64_to_chunk_words(self._effective_balance[sel]),
            u64_to_chunk_words(self._slashed[sel].astype(np.uint64)),
            u64_to_chunk_words(self._activation_eligibility_epoch[sel]),
            u64_to_chunk_words(self._activation_epoch[sel]),
            u64_to_chunk_words(self._exit_epoch[sel]),
            u64_to_chunk_words(self._withdrawable_epoch[sel]),
        ], axis=1)  # (k, 8, 8)
        l1 = h64(leaves[:, 0::2], leaves[:, 1::2])   # (k, 4, 8)
        l2 = h64(l1[:, 0::2], l1[:, 1::2])           # (k, 2, 8)
        l3 = h64(l2[:, 0], l2[:, 1])                 # (k, 8)
        return np.asarray(l3)

    def hash_tree_root(self, limit: int) -> bytes:
        """Registry root: batched record roots → padded device reduction to
        the ``limit``-leaf tree → length mixin."""
        from .columns import device_merkle_root
        return device_merkle_root(self.record_roots_words(), limit,
                                  length_mixin=self._n)


def _column_property(name: str) -> property:
    def get(self):
        v = getattr(self, "_" + name).view()
        v.flags.writeable = False
        return v
    get.__doc__ = (f"Read-only view of the {name} column storage (full "
                   "capacity); mutate via wcol()/set()/append() so the "
                   "incremental hash cache sees the change.")
    return property(get)


for _cname in ValidatorRegistry._COLUMNS:
    setattr(ValidatorRegistry, _cname, _column_property(_cname))
del _cname


# ---------------------------------------------------------------------------
# Device cold build: every registry tree level in ONE dispatch
# ---------------------------------------------------------------------------
#
# The incremental state cache needs the record roots AND the interior levels
# of the registry tree (to propagate dirty paths on the host).  Computing
# them eagerly level-by-level bounces hundreds of MB through the axon tunnel
# (the r3 cold path cost 559 s); host hashlib needs ~8 hashes/record ≈ 10+ s
# at 2^20.  Instead one jitted program computes the per-record mini-trees and
# every registry level on-device (Pallas hash64 for the wide levels), the
# 32-byte root is pulled immediately, and the levels are pulled lazily (the
# tunnel pulls ~11 MB/s — a background thread hides the ~6 s for 2^20).

def _bswap32(x):
    import jax.numpy as jnp
    return (((x & np.uint32(0xFF)) << np.uint32(24))
            | (((x >> np.uint32(8)) & np.uint32(0xFF)) << np.uint32(16))
            | (((x >> np.uint32(16)) & np.uint32(0xFF)) << np.uint32(8))
            | (x >> np.uint32(24)))


def _u64_lohi_words(lohi):
    """(n, 2) u32 little-endian (lo, hi) → (n, 8) big-endian chunk words."""
    import jax.numpy as jnp
    z = jnp.zeros_like(lohi[:, 0])
    return jnp.stack([_bswap32(lohi[:, 0]), _bswap32(lohi[:, 1]),
                      z, z, z, z, z, z], axis=-1)


def _registry_raw_columns(reg: "ValidatorRegistry", m: int) -> dict:
    """Host marshalling for the cold build: byte columns as words, u64
    columns as raw (n, 2) u32 views (device expands them — 4× less tunnel
    traffic than pushing chunk words), padded to ``m`` rows."""
    n = reg._n

    def pad(a):
        if a.shape[0] == m:
            return a
        out = np.zeros((m,) + a.shape[1:], dtype=a.dtype)
        out[:n] = a
        return out

    def lohi(col):
        return np.ascontiguousarray(col[:n]).view(np.uint32).reshape(n, 2)

    cols = {
        "pubkey": pad(bytes_col_to_words(reg._pubkey[:n])),
        "withdrawal_credentials": pad(
            bytes_col_to_words(reg._withdrawal_credentials[:n])),
        # u8 on the wire (the tunnel pushes ~43 MB/s — every byte counts);
        # widened on-device.
        "slashed": pad(reg._slashed[:n].astype(np.uint8)),
    }
    for f in ("effective_balance",) + _EPOCH_FIELDS:
        cols[f] = pad(lohi(getattr(reg, "_" + f)))
    return cols


def _h64_device(use_kernel: bool):
    """The shared ``hash64`` selector of the device bodies: Pallas for
    lane counts the kernel can take, XLA scan otherwise."""
    from ..ops.merkle_kernel import hash64_pallas

    PB = 1 << 15  # hash64_pallas lane-count granularity

    def h64(a, b):
        flat_ok = a.shape[0] % PB == 0 and a.shape[0] >= PB and a.ndim == 2
        if use_kernel and flat_ok:
            return hash64_pallas(a, b)
        return hash64(a, b)

    return h64


def _record_roots_body(cols: dict, *, use_kernel: bool):
    """Device body: raw columns (m rows) → (m, 8) record mini-tree roots.
    Jitted per chunk shape so the chunked cold build reduces each staged
    column chunk while later chunks are still in transfer."""
    import jax.numpy as jnp

    h64 = _h64_device(use_kernel)
    pk = cols["pubkey"]                       # (m, 12) words
    m = pk.shape[0]
    pk_lo = pk[:, :8]
    pk_hi = jnp.pad(pk[:, 8:], ((0, 0), (0, 4)))
    pubkey_root = h64(pk_lo, pk_hi)
    sl = cols["slashed"].astype(jnp.uint32)
    z = jnp.zeros_like(sl)
    slashed_words = jnp.stack([_bswap32(sl), z, z, z, z, z, z, z], axis=-1)
    leaves = jnp.stack([
        pubkey_root,
        cols["withdrawal_credentials"],
        _u64_lohi_words(cols["effective_balance"]),
        slashed_words,
        _u64_lohi_words(cols["activation_eligibility_epoch"]),
        _u64_lohi_words(cols["activation_epoch"]),
        _u64_lohi_words(cols["exit_epoch"]),
        _u64_lohi_words(cols["withdrawable_epoch"]),
    ], axis=1)                                # (m, 8, 8)
    l1 = h64(leaves[:, 0::2].reshape(4 * m, 8),
             leaves[:, 1::2].reshape(4 * m, 8)).reshape(m, 4, 8)
    l2 = h64(l1[:, 0::2].reshape(2 * m, 8),
             l1[:, 1::2].reshape(2 * m, 8)).reshape(m, 2, 8)
    return h64(l2[:, 0], l2[:, 1])            # (m, 8) record roots


def _registry_levels_body(cols: dict, *, n: int, w: int, use_kernel: bool):
    """Device body: raw columns (m rows) → tuple of registry tree levels.

    ``levels[0]`` = (w, 8) record roots of the first ``n ≤ m`` records,
    padded with zero CHUNKS (SSZ list semantics) to the power-of-two width
    ``w``; ``levels[-1]`` = (1, 8) root of the w-subtree.  Rows n..m are
    marshalling pad (Pallas needs 2^15-multiples) — their garbage mini-tree
    roots are sliced off before the zero-chunk padding.
    """
    rec = _record_roots_body(cols, use_kernel=use_kernel)
    return _levels_from_records(rec, n, w, _h64_device(use_kernel))


def _levels_combine_body(rec, *, n: int, w: int, use_kernel: bool):
    """Concatenated per-chunk record roots → the registry tree levels
    (the tail of :func:`_registry_levels_body`, as its own jit for the
    chunked cold build)."""
    return _levels_from_records(rec, n, w, _h64_device(use_kernel))


def _levels_from_records(rec, n: int, w: int, h64):
    """Registry levels over ``rec``: keep the first ``n`` REAL record roots
    (rows beyond ``n`` are marshalling-pad garbage — zero-RECORD roots, not
    zero chunks), zero-chunk pad to the power-of-two width ``w``."""
    import jax.numpy as jnp
    rec = rec[:n]
    if n < w:
        rec = jnp.concatenate(
            [rec, jnp.zeros((w - n, 8), jnp.uint32)], axis=0)
    levels = [rec]
    cur = rec
    while cur.shape[0] > 1:
        cur = h64(cur[0::2], cur[1::2])
        levels.append(cur)
    return tuple(levels)


_PALLAS_PAD = 1 << 15
_levels_jit = None
_record_roots_jit = None
_levels_combine_jit = None

# H2D streaming granularity of the chunked cold build: 2^17 records
# ≈ 15 MiB of raw columns per chunk (a multiple of the Pallas pad).
REG_PUSH_CHUNK_ROWS = 1 << 17

# Stage timings of the most recent cold build (ms), for bench reporting:
# the column push through the axon tunnel (~43 MB/s measured) dominates the
# on-device compute; ``push_ms`` is the transfer time left on the critical
# path and ``push_overlap_ms`` the transfer time the chunked pipeline hid
# behind the earlier chunks' on-device reduction.
LAST_COLD_TIMINGS: dict = {}


def _reg_chunk_rows() -> int:
    """The shared env knob (ROWS, i.e. records — the registry's ~120 B
    rows make a chunk ~2× the byte size of a same-rows leaf chunk),
    clamped to a usable multiple of the Pallas pad so a small-but-
    positive value still chunks instead of silently going monolithic.
    ≤ 0 disables."""
    from ..common.knobs import knob_int
    rows = knob_int("LIGHTHOUSE_TPU_PUSH_CHUNK_ROWS",
                    default=REG_PUSH_CHUNK_ROWS)
    if rows <= 0:
        return 0
    return max((rows // _PALLAS_PAD) * _PALLAS_PAD, _PALLAS_PAD)


def registry_cold_device(reg: "ValidatorRegistry",
                         chunk_rows: int | None = None):
    """Cold build on the attached TPU with a streamed column push.

    Returns ``(root_words, levels)``: ``root_words`` is the (8,) u32 root of
    the occupied power-of-two subtree (host numpy, pulled immediately);
    ``levels`` are the device-resident tree levels for the caller to pull
    lazily into the host incremental cache.

    Registries wider than one push chunk stream their raw columns up in
    row chunks via a background :class:`~lighthouse_tpu.parallel.
    pipeline.ChunkStager`: chunk i+1 transfers while chunk i's record
    mini-trees already reduce on-device, and a final combine program
    builds the registry levels over the concatenated record roots —
    the monolithic blocking push (5+ s of the cold state root at 2^20)
    leaves the critical path.  Small registries keep the one-dispatch
    monolithic body."""
    global _levels_jit, _record_roots_jit, _levels_combine_jit
    import time
    import jax
    import jax.numpy as jnp
    from ..ops.merkle import _next_pow2
    from ..ops.merkle_kernel import _use_pallas

    n = reg._n
    w = _next_pow2(max(n, 1))
    # Pad rows to the Pallas granularity; slice the pad off on-device.
    m = max(-(-n // _PALLAS_PAD) * _PALLAS_PAD, _PALLAS_PAD)
    use_kernel = _use_pallas()
    chunk = _reg_chunk_rows() if chunk_rows is None else chunk_rows
    if chunk <= 0 or m <= chunk or chunk % _PALLAS_PAD:
        from ..parallel.mesh import mesh_put
        t0 = time.perf_counter()
        host_cols = _registry_raw_columns(reg, m)
        cols = {k: mesh_put("registry_cols", v, subsystem="staging")
                for k, v in host_cols.items()}
        jax.block_until_ready(cols)
        t1 = time.perf_counter()
        if _levels_jit is None:
            _levels_jit = jax.jit(_registry_levels_body,
                                  static_argnames=("n", "w", "use_kernel"))
        levels = _levels_jit(cols, n=n, w=w, use_kernel=use_kernel)
        root_words = np.asarray(levels[-1])[0]  # device-io: staging
        t2 = time.perf_counter()
        LAST_COLD_TIMINGS.update(
            push_ms=round((t1 - t0) * 1e3, 1),
            compute_ms=round((t2 - t1) * 1e3, 1),
            push_overlap_ms=0.0, push_chunks=1)
        return root_words, levels

    from ..parallel.pipeline import ChunkStager

    t0 = time.perf_counter()
    host = _registry_raw_columns(reg, m)
    chunks = [{k: v[b:b + chunk] for k, v in host.items()}
              for b in range(0, m, chunk)]
    stager = ChunkStager(chunks, subsystem="staging")
    if _record_roots_jit is None:
        _record_roots_jit = jax.jit(_record_roots_body,
                                    static_argnames=("use_kernel",))
        _levels_combine_jit = jax.jit(
            _levels_combine_body, static_argnames=("n", "w", "use_kernel"))
    recs = [_record_roots_jit(dev, use_kernel=use_kernel)
            for dev in stager]
    rec = recs[0] if len(recs) == 1 else jnp.concatenate(recs, axis=0)
    levels = _levels_combine_jit(rec, n=n, w=w, use_kernel=use_kernel)
    root_words = np.asarray(levels[-1])[0]  # device-io: staging
    wall = time.perf_counter() - t0
    LAST_COLD_TIMINGS.update(
        push_ms=round(stager.wait_s * 1e3, 1),
        compute_ms=round(max(wall - stager.wait_s, 0.0) * 1e3, 1),
        push_overlap_ms=round(
            max(stager.transfer_s - stager.wait_s, 0.0) * 1e3, 1),
        push_chunks=len(chunks),
        push_fallbacks=stager.fallbacks)
    return root_words, levels


# ---------------------------------------------------------------------------
# Device-resident registry Merkleization (one fused dispatch)
# ---------------------------------------------------------------------------
#
# The per-level eager pipeline bounces (n, 8) arrays host↔device between
# launches — harmless locally, ruinous through a tunneled TPU (hundreds of
# MB per root).  Production shape: the registry columns live in HBM
# (SURVEY §7 hard-part 3) and ONE jitted program computes record roots,
# the fused chunk reduction and the zero-cap fold, returning 32 bytes.

def registry_device_columns(reg: "ValidatorRegistry") -> dict:
    """Push the registry columns to the device once (HBM residency)."""
    from ..parallel.mesh import mesh_put
    n = reg._n
    host = {
        "pubkey": bytes_col_to_words(reg._pubkey[:n]),
        "withdrawal_credentials":
            bytes_col_to_words(reg._withdrawal_credentials[:n]),
        "effective_balance": u64_to_chunk_words(reg._effective_balance[:n]),
        "slashed": u64_to_chunk_words(reg._slashed[:n].astype(np.uint64)),
        "activation_eligibility_epoch":
            u64_to_chunk_words(reg._activation_eligibility_epoch[:n]),
        "activation_epoch": u64_to_chunk_words(reg._activation_epoch[:n]),
        "exit_epoch": u64_to_chunk_words(reg._exit_epoch[:n]),
        "withdrawable_epoch":
            u64_to_chunk_words(reg._withdrawable_epoch[:n]),
    }
    return {k: mesh_put("registry_cols", v, subsystem="staging")
            for k, v in host.items()}


def _registry_root_fused(cols: dict, *, depth: int, chunk_log2: int,
                         use_kernel: bool):
    """Device body, expansion-tree form: the registry tree over record
    roots is exactly the tree over ``8n`` leaves
    ``[pubkey_root, wc, eff, slashed, 4 epochs] × n`` (a zero record's
    root equals the zero-subtree hash, so padding semantics coincide).
    The Pallas chunk kernel therefore swallows the per-record mini-trees
    and the registry levels in one pass; only the 48-byte pubkey pre-hash
    runs as its own (also Pallas) level."""
    import jax.numpy as jnp
    from ..ops.merkle import ZERO_HASHES
    from ..ops.merkle_kernel import _chunk_roots_natural_impl, hash64_pallas

    pk = cols["pubkey"]                       # (n, 12) words
    n = pk.shape[0]
    pk_lo = pk[:, :8]
    pk_hi = jnp.pad(pk[:, 8:], ((0, 0), (0, 4)))
    if use_kernel and n >= (1 << 15):
        pubkey_root = hash64_pallas(pk_lo, pk_hi)
    else:
        pubkey_root = hash64(pk_lo, pk_hi)
    leaves = jnp.stack([
        pubkey_root,
        cols["withdrawal_credentials"],
        cols["effective_balance"],
        cols["slashed"],
        cols["activation_eligibility_epoch"],
        cols["activation_epoch"],
        cols["exit_epoch"],
        cols["withdrawable_epoch"],
    ], axis=1).reshape(8 * n, 8)              # 8n-leaf expansion tree
    g = _chunk_roots_natural_impl(leaves, chunk_log2, use_kernel)
    lvl = chunk_log2
    while g.shape[0] > 1:
        g = hash64(g[0::2], g[1::2])
        lvl += 1
    root = g[0]
    # Zero caps: the registry list pads with zero CHUNKS at the
    # record-root level, so cap siblings are record-level zero hashes —
    # expansion level ℓ pairs with ZERO_HASHES[ℓ − 3].
    while lvl < depth + 3:
        root = hash64(root, jnp.asarray(ZERO_HASHES[lvl - 3]))  # device-io: registry_mirror
        lvl += 1
    return root


_registry_root_jit = None


def registry_root_device(cols: dict, count: int, limit: int) -> bytes:
    """Registry ``hash_tree_root`` from device-resident columns — one
    dispatch, 32 bytes pulled back.  ``count`` must be a power of two
    ≥ the Pallas chunk size (pad rows to reach it)."""
    import jax
    from functools import partial
    from ..ops.merkle import mix_in_length_host
    from ..ops.merkle_kernel import CHUNK_LOG2, _use_pallas
    from ..ops.sha256 import words_to_bytes

    depth = max((int(limit) - 1).bit_length(), 0)
    if _use_pallas():
        global _registry_root_jit
        if _registry_root_jit is None:
            _registry_root_jit = jax.jit(
                partial(_registry_root_fused),
                static_argnames=("depth", "chunk_log2", "use_kernel"))
        root = _registry_root_jit(cols, depth=depth, chunk_log2=CHUNK_LOG2,
                                  use_kernel=True)
    else:
        # Off-TPU (tests): run eagerly — XLA-CPU takes minutes to compile
        # the jitted unrolled compression chain the Mosaic kernel replaces.
        root = _registry_root_fused(cols, depth=depth,
                                    chunk_log2=CHUNK_LOG2, use_kernel=False)
    return mix_in_length_host(words_to_bytes(np.asarray(root)), count)


# ---------------------------------------------------------------------------
# Device-resident registry mirror: HBM columns + record-root tree as the
# hashing source of truth
# ---------------------------------------------------------------------------
#
# ``registry_cold_device`` (above) pushes the raw columns for EVERY cold
# root and pulls the interior levels back to host — the 5.1 s
# ``state_root_cold_push_ms`` of BENCH_LATEST.  The mirror makes that push a
# ONE-TIME materialization: the raw columns and every tree level stay in
# HBM, ``wcol``/``set``/``append`` dirty marks become per-root record
# scatters (k raw rows up, 32 bytes down), and the rebuild crossover
# (dirty > width/8) re-reduces from the HBM-resident columns with zero
# push.  ``share()`` gives copy-on-write clones for the fork-choice state
# cache: buffers are shared until either lineage mutates (the update
# program runs undonated and lands in fresh buffers).

def _registry_raw_rows(reg: "ValidatorRegistry", idx: np.ndarray) -> dict:
    """Raw-form marshalling of ``idx`` records (same column encodings as
    :func:`_registry_raw_columns`, k rows instead of the full width)."""
    rows = {
        "pubkey": bytes_col_to_words(reg._pubkey[idx]),
        "withdrawal_credentials": bytes_col_to_words(
            reg._withdrawal_credentials[idx]),
        "slashed": reg._slashed[idx].astype(np.uint8),
    }
    for f in ("effective_balance",) + _EPOCH_FIELDS:
        rows[f] = np.ascontiguousarray(
            getattr(reg, "_" + f)[idx]).view(np.uint32).reshape(-1, 2)
    return rows


def _pad_rows_bucket(idx: np.ndarray, rows: dict) -> tuple:
    """Bucket-pad a record scatter — :func:`..ops.device_tree.pad_bucket`
    applied per raw column (duplicating the first (index, raw row) pair is
    idempotent: it scatters the same record and re-hashes the same path)."""
    from ..ops.device_tree import pad_bucket
    pidx = idx.astype(np.int32, copy=False)
    out = {}
    for name, arr in rows.items():
        pidx, out[name] = pad_bucket(idx, arr)
    return pidx, out


def _mirror_scatter_body(levels, cols, idx, rows):
    """The fused warm-root program: scatter the raw rows into the HBM
    columns, re-hash exactly those records' 8-leaf mini-trees, and
    propagate their ancestor paths through the record-root tree — leaf
    re-hash → level propagation as ONE jitted dispatch."""
    from ..ops.device_tree import scatter_propagate_body
    new_cols = {k: cols[k].at[idx].set(rows[k]) for k in cols}
    rec = _record_roots_body(rows, use_kernel=False)  # k records: XLA h64
    return new_cols, scatter_propagate_body(levels, idx, rec)


def _mirror_rebuild_body(cols, n_arr, *, use_kernel: bool):
    """Full re-reduction from the HBM-resident columns (dirty fraction
    past the walk/rebuild crossover, or width growth) — zero push.  Rows
    at or beyond the dynamic record count ``n_arr`` are masked to zero
    CHUNKS (SSZ list padding), so one compiled artifact per width serves
    every count."""
    import jax.numpy as jnp
    rec = _record_roots_body(cols, use_kernel=use_kernel)
    w = rec.shape[0]
    keep = (jnp.arange(w, dtype=jnp.uint32) < n_arr)[:, None]
    rec = jnp.where(keep, rec, jnp.zeros_like(rec))
    h64 = _h64_device(use_kernel)
    levels = [rec]
    cur = rec
    while cur.shape[0] > 1:
        cur = h64(cur[0::2], cur[1::2])
        levels.append(cur)
    return tuple(levels)


_mirror_scatter_jits: dict = {}
_mirror_rebuild_jit = None


def _get_mirror_scatter_jit(donate: bool):
    import jax
    jit = _mirror_scatter_jits.get(donate)
    if jit is None:
        jit = (jax.jit(_mirror_scatter_body, donate_argnums=(0, 1))
               if donate else jax.jit(_mirror_scatter_body))
        _mirror_scatter_jits[donate] = jit
    return jit


def _get_mirror_rebuild_jit():
    global _mirror_rebuild_jit
    import jax
    if _mirror_rebuild_jit is None:
        _mirror_rebuild_jit = jax.jit(_mirror_rebuild_body,
                                      static_argnames=("use_kernel",))
    return _mirror_rebuild_jit


_mirror_rebuild_mesh_programs: dict = {}


def _get_mirror_rebuild_mesh(mesh, local_w: int):
    """The rebuild as a mesh program: record mini-trees + the level fold
    are per-shard over a contiguous record range (the SSZ count mask
    needs GLOBAL row indices, so the shard offsets its ``arange`` by
    ``axis_index * local_w``); the top ``log2(ndev)`` levels fold past
    the shard boundary.  Bit-identical to ``_mirror_rebuild_body``
    (same fold order; XLA hash64 — the Pallas lane floor exceeds a
    shard's rows at differential widths)."""
    key = (mesh, local_w)
    prog = _mirror_rebuild_mesh_programs.get(key)
    if prog is not None:
        return prog
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import BATCH_AXIS, mesh_program
    from ..parallel.merkle_shard import _get_top_fold
    from ..ops.sha256 import hash64

    def local_levels(cols, n_arr):
        rec = _record_roots_body(cols, use_kernel=False)
        base = jax.lax.axis_index(BATCH_AXIS).astype(jnp.uint32) \
            * jnp.uint32(local_w)
        keep = (base + jnp.arange(local_w, dtype=jnp.uint32)
                < n_arr)[:, None]
        rec = jnp.where(keep, rec, jnp.zeros_like(rec))
        levels = [rec]
        cur = rec
        while cur.shape[0] > 1:
            cur = hash64(cur[0::2], cur[1::2])
            levels.append(cur)
        return tuple(levels)

    n_local = local_w.bit_length()  # log2(local_w) + 1 local levels
    lower = mesh_program(
        local_levels, mesh=mesh,
        in_specs=(P(BATCH_AXIS), P()),
        out_specs=tuple(P(BATCH_AXIS) for _ in range(n_local)))

    def run(cols, n_arr):
        low = lower(cols, n_arr)
        return tuple(low) + tuple(_get_top_fold()(low[-1]))

    _mirror_rebuild_mesh_programs[key] = run
    return run


def _mirror_levels(cols: dict, n: int):
    """Every record-tree level from the HBM columns: the sharded mesh
    program when the process mesh has >1 shard and the width divides it,
    else the 1-device fused body."""
    from ..ops.merkle_kernel import _use_pallas
    from ..parallel import mesh as pmesh
    w = cols["slashed"].shape[0]
    ndev = pmesh.axis_size()
    if ndev > 1 and ndev & (ndev - 1) == 0 and w % ndev == 0 \
            and w // ndev >= 2:
        return _get_mirror_rebuild_mesh(pmesh.get_mesh(), w // ndev)(
            cols, np.uint32(n))
    return _get_mirror_rebuild_jit()(cols, np.uint32(n),
                                     use_kernel=_use_pallas())


class DeviceRegistryMirror:
    """HBM-resident raw columns + record-root tree for one registry
    lineage (COW across :meth:`ValidatorRegistry.copy`)."""

    __slots__ = ("cols", "tree", "shared", "_res", "__weakref__")

    def __init__(self, cols: dict, tree, shared: bool = False):
        self.cols = cols
        self.tree = tree
        self.shared = shared
        self._res = None

    @property
    def width(self) -> int:
        return self.cols["slashed"].shape[0]

    def note_residency(self) -> None:
        """Ledger watermark seam: this mirror's HBM columns + record
        tree (a share() clone counts nothing until it diverges — the
        parent owns the shared buffers)."""
        from ..common.device_ledger import LEDGER
        total = sum(int(v.nbytes) for v in self.cols.values()) \
            + sum(int(lv.nbytes) for lv in self.tree.levels)
        if self._res is None:
            self._res = LEDGER.track(self, "registry_mirror", total)
        else:
            self._res.set(total)

    @classmethod
    def materialize(cls, reg: "ValidatorRegistry") -> "DeviceRegistryMirror":
        """One-time column push (chunk-staged for big registries, like the
        cold build) + in-HBM level reduction.  This is the LAST full-width
        push this lineage ever makes."""
        import jax.numpy as jnp
        from ..common.device_ledger import LEDGER
        from ..ops.device_tree import DeviceTree
        from ..ops.merkle import _next_pow2
        from ..parallel.mesh import mesh_place, mesh_put

        n = reg._n
        w = _next_pow2(max(n, 1))
        with LEDGER.attribute("registry_mirror"):
            host = _registry_raw_columns(reg, w)
            LEDGER.note_event("materializes")
            chunk = _reg_chunk_rows()
            if chunk > 0 and w > chunk and w % chunk == 0:
                from ..parallel.pipeline import ChunkStager
                chunks = [{k: v[b:b + chunk] for k, v in host.items()}
                          for b in range(0, w, chunk)]
                # subsystem=None: the streamed push settles its wire
                # total + per-shard split at the mesh_place seam below —
                # the stager must not double-count it.
                parts = list(ChunkStager(chunks, subsystem=None))
                cols = {k: mesh_place(
                            "registry_cols",
                            jnp.concatenate([p[k] for p in parts],
                                            axis=0),
                            h2d_bytes=host[k].nbytes)
                        for k in host}
            else:
                cols = {k: mesh_put("registry_cols", v)
                        for k, v in host.items()}
            levels = _mirror_levels(cols, n)
            from ..ops.tree_cache import HASH_COUNT
            HASH_COUNT[0] += 8 * w + (w - 1)
            mirror = cls(cols, DeviceTree(levels), False)
            mirror.note_residency()
            return mirror

    def scatter_records(self, reg: "ValidatorRegistry",
                        idx: np.ndarray) -> np.ndarray:
        """Land ``idx`` dirty records as one fused device dispatch; returns
        the new subtree root words.  H2D = the bucket-padded raw rows
        (the replicated ``registry_dirty`` mesh family)."""
        from ..common.device_ledger import LEDGER
        from ..ops.device_tree import _donation_works
        from ..ops.tree_cache import HASH_COUNT
        from ..parallel.mesh import mesh_put

        with LEDGER.attribute("registry_mirror"):
            pidx, rows = _pad_rows_bucket(np.asarray(idx),
                                          _registry_raw_rows(reg, idx))
            LEDGER.note_event("scatters")
            HASH_COUNT[0] += pidx.shape[0] * (8 + len(self.tree.levels) - 1)
            jit = _get_mirror_scatter_jit(
                _donation_works() and not self.shared
                and not self.tree.shared)
            self.cols, self.tree.levels = jit(
                self.tree.levels, self.cols,
                mesh_put("registry_dirty", pidx),
                {k: mesh_put("registry_dirty", v)
                 for k, v in rows.items()})
            self.shared = False
            self.tree.shared = False
            self.note_residency()
            return self.tree.root_words()

    def scatter_cols(self, reg: "ValidatorRegistry",
                     idx: np.ndarray) -> None:
        """Update only the HBM columns at ``idx`` (no tree propagation) —
        the prelude to :meth:`rebuild` when the dirty fraction or a width
        change makes path-walking the wrong tool."""
        from ..common.device_ledger import LEDGER
        from ..parallel.mesh import mesh_put

        with LEDGER.attribute("registry_mirror"):
            pidx, rows = _pad_rows_bucket(np.asarray(idx),
                                          _registry_raw_rows(reg, idx))
            idx_dev = mesh_put("registry_dirty", pidx)
            for k in self.cols:
                self.cols[k] = self.cols[k].at[idx_dev].set(
                    mesh_put("registry_dirty", rows[k]))
            self.shared = False

    def rebuild(self, n: int) -> np.ndarray:
        """Re-reduce every level from the HBM columns — zero push (a
        sharded mesh program when the process mesh has >1 shard)."""
        from ..common.device_ledger import LEDGER
        from ..ops.tree_cache import HASH_COUNT

        LEDGER.note_event("rebuilds", subsystem="registry_mirror")
        w = self.width
        HASH_COUNT[0] += 8 * w + (w - 1)
        self.tree.levels = _mirror_levels(self.cols, n)
        self.tree.shared = False
        self.note_residency()
        return self.tree.root_words()

    def ensure_width(self, new_w: int) -> bool:
        """Grow the HBM columns to ``new_w`` rows (device-side zero pad —
        pad rows are masked at rebuild, their values never hashed).
        Returns True when the width changed (caller must rebuild)."""
        import jax.numpy as jnp
        from ..parallel.mesh import mesh_place
        w = self.width
        if new_w <= w:
            return False
        for k, v in self.cols.items():
            pad = jnp.zeros((new_w - w,) + v.shape[1:], dtype=v.dtype)
            self.cols[k] = mesh_place(
                "registry_cols", jnp.concatenate([v, pad], axis=0))
        self.shared = False  # concat produced buffers only we hold
        self.note_residency()
        return True

    def share(self) -> "DeviceRegistryMirror":
        self.shared = True
        return DeviceRegistryMirror(dict(self.cols), self.tree.share(),
                                    shared=True)


_registry_type_cache: dict[int, type] = {}


def ValidatorRegistryList(limit: int) -> type:
    """SSZ type for ``List[Validator, limit]`` backed by the SoA registry."""
    cls = _registry_type_cache.get(limit)
    if cls is not None:
        return cls

    from ..ssz.core import SszType

    class _RegistryList(SszType):
        ELEM = Validator
        LIMIT = limit

        @classmethod
        def is_fixed_size(cls) -> bool:
            return False

        @classmethod
        def serialize(cls, value) -> bytes:
            if isinstance(value, ValidatorRegistry):
                if len(value) > cls.LIMIT:
                    raise SszError("validator registry exceeds limit")
                return value.to_packed()
            return ValidatorRegistry.from_validators(value).to_packed()

        @classmethod
        def deserialize(cls, data: bytes) -> ValidatorRegistry:
            out = ValidatorRegistry.from_packed(data)
            if len(out) > cls.LIMIT:
                raise SszError("validator registry exceeds limit")
            return out

        @classmethod
        def hash_tree_root(cls, value) -> bytes:
            if not isinstance(value, ValidatorRegistry):
                value = ValidatorRegistry.from_validators(value)
            return value.hash_tree_root(cls.LIMIT)

        @classmethod
        def default(cls) -> ValidatorRegistry:
            return ValidatorRegistry()

    _RegistryList.__name__ = f"ValidatorRegistryList[{limit}]"
    _registry_type_cache[limit] = _RegistryList
    return _RegistryList
