"""Consensus type system.

Counterpart of ``/root/reference/consensus/types`` (16.2k LoC of Rust):
compile-time size presets (``EthSpec`` typenum presets,
``types/src/eth_spec.rs:51,254,298``) become :class:`Preset` instances;
runtime parameters (``types/src/chain_spec.rs``) become :class:`ChainSpec`;
the per-fork ``superstruct`` enums become per-fork container classes sharing
annotated bases (field order = base-first, so the common prefix matches).

All SSZ bounds come from the preset, so the full set of container classes is
built per preset by :func:`spec_types` and cached — mirroring how the
reference monomorphizes ``BeaconState<E: EthSpec>`` per preset.
"""

from .presets import Preset, MAINNET, MINIMAL
from .chain_spec import ChainSpec, Domain, ForkName
from .factory import spec_types, SpecTypes

__all__ = [
    "Preset", "MAINNET", "MINIMAL", "ChainSpec", "Domain", "ForkName",
    "spec_types", "SpecTypes",
]
