"""BeaconState incremental tree-hash cache.

Counterpart of ``BeaconTreeHashCache``
(``/root/reference/consensus/types/src/beacon_state/tree_hash_cache.rs:332``)
and its per-field ``cached_tree_hash`` arenas: the state root becomes
O(changes·log n) instead of O(state).

Three tiers, by field shape:

- **Validator registry** (the 2^40-limit list of 8-field records,
  the reference's rayon-parallel arena — ``tree_hash_cache.rs:535-556``):
  dirty records come from the registry's column/row dirty marks (writes go
  through ``wcol``/``set``); only marked columns are diffed (one vectorized
  compare), only changed records re-hash their 8-leaf mini-trees (batched),
  and the big record-root tree updates incrementally.
- **Columnar packed fields** (balances, participation flags, inactivity
  scores, roots vectors, slashings): leaf-diff + dirty-path propagation via
  :class:`~lighthouse_tpu.ops.tree_cache.IncrementalMerkleCache`.
- **Small fields** (headers, checkpoints, sync committees, vote lists):
  re-hashed only when their SSZ encoding changes (memoised; the encode-and
  -compare costs µs, and a SyncCommittee rehash alone is ~1k hashes).

The cache travels with ``BeaconState.copy()`` (levels are copied, like the
reference's ``BeaconState`` clone-with-cache) and rebuilds transparently if
absent, so correctness never depends on it.
"""

from __future__ import annotations

import numpy as np

from ..ops.merkle import merkleize_host
from ..ops.tree_cache import HASH_COUNT, IncrementalMerkleCache


class RegistryCache:
    """Record-root cache for the SoA validator registry."""

    def __init__(self):
        self.stored: dict[str, np.ndarray] | None = None  # column copies
        self.record_roots: np.ndarray | None = None       # (n, 8) u32
        self.tree: IncrementalMerkleCache | None = None

    def root(self, reg, limit: int) -> bytes:
        n = len(reg)
        if self.tree is None:
            self.tree = IncrementalMerkleCache(limit, mixin_length=True)
        if self.stored is None or self.record_roots is None \
                or self.record_roots.shape[0] > n:
            # Cold start (or shrink, which consensus never does): full
            # build.  np.array: the device path hands back read-only views.
            self.record_roots = np.array(reg.record_roots_words())
            self.stored = {c: np.array(getattr(reg, c)[:n])
                           for c in reg._COLUMNS}
        else:
            old_n = self.record_roots.shape[0]
            dirty = np.zeros(n, dtype=bool)
            dirty[old_n:] = True
            for cname in reg._dirty_cols:
                col = getattr(reg, cname)[:old_n]
                st = self.stored[cname][:old_n]
                if col.ndim == 1:
                    np.logical_or(dirty[:old_n], col != st, out=dirty[:old_n])
                else:
                    np.logical_or(dirty[:old_n], (col != st).any(axis=1),
                                  out=dirty[:old_n])
            for r in reg._dirty_rows:
                if r < n:
                    dirty[r] = True
            idx = np.nonzero(dirty)[0]
            if idx.size:
                roots = reg.record_roots_words(idx)
                if n != old_n:
                    grown = np.zeros((n, 8), dtype=np.uint32)
                    grown[:old_n] = self.record_roots
                    self.record_roots = grown
                self.record_roots[idx] = roots
                for cname in reg._COLUMNS:
                    col = getattr(reg, cname)[:n]
                    st = self.stored[cname]
                    if st.shape[0] != n:
                        st = np.array(col)
                        self.stored[cname] = st
                    else:
                        st[idx] = col[idx]
        # Row marks are consumed; column marks are sticky (a wcol view may
        # be held and written later — the column is re-diffed every root).
        reg._dirty_rows.clear()
        return self.tree.root_words(self.record_roots, length=n)

    def copy(self) -> "RegistryCache":
        out = RegistryCache.__new__(RegistryCache)
        out.stored = (None if self.stored is None
                      else {k: v.copy() for k, v in self.stored.items()})
        out.record_roots = (None if self.record_roots is None
                            else self.record_roots.copy())
        out.tree = None if self.tree is None else self.tree.copy()
        return out


_PACKED_PER_CHUNK = {8: 4, 1: 32}  # u64 → 4/chunk, u8 → 32/chunk


class _PackedSourceCache:
    """Source-level diff for packed uint columns (balances, participation,
    inactivity): compare the raw column against the stored copy (one
    vectorized pass over the source values — 4-32× less traffic than
    leaf-word diffing and no full reconversion), pack ONLY the changed
    chunks, and hand the sparse update to the interior-node cache."""

    def __init__(self, limit_chunks: int, mixin_length: bool):
        self.tree = IncrementalMerkleCache(limit_chunks,
                                           mixin_length=mixin_length)
        self.src: np.ndarray | None = None

    @staticmethod
    def _pack_chunks(vals: np.ndarray) -> np.ndarray:
        """(k, per) source values → (k, 8) big-endian chunk words (SSZ
        little-endian packing inside each 32-byte chunk)."""
        le = np.ascontiguousarray(
            vals.astype(vals.dtype.newbyteorder("<"), copy=False))
        return np.frombuffer(le.tobytes(), dtype=">u4").astype(
            np.uint32).reshape(vals.shape[0], 8)

    def root(self, arr: np.ndarray) -> bytes:
        per = _PACKED_PER_CHUNK[arr.dtype.itemsize]
        n = arr.shape[0]
        n_chunks = (n + per - 1) // per
        pad = n_chunks * per - n
        if self.src is None or self.src.shape[0] != n:
            self.src = arr.copy()
            padded = np.concatenate([arr, np.zeros(pad, arr.dtype)])                 if pad else arr
            return self.tree.root_words(
                self._pack_chunks(padded.reshape(n_chunks, per)), length=n)
        changed = np.nonzero(self.src != arr)[0]
        if changed.size == 0:
            return self.tree.update_rows(
                np.empty(0, np.int64), np.empty((0, 8), np.uint32),
                n_chunks, length=n)
        chunk_idx = np.unique(changed // per)
        self.src[changed] = arr[changed]
        flat = (chunk_idx[:, None] * per
                + np.arange(per)[None, :]).reshape(-1)
        vals = np.where(flat < n, arr[np.minimum(flat, n - 1)],
                        np.zeros(1, arr.dtype))
        rows = self._pack_chunks(vals.reshape(chunk_idx.shape[0], per))
        return self.tree.update_rows(chunk_idx, rows, n_chunks, length=n)

    def copy(self) -> "_PackedSourceCache":
        out = _PackedSourceCache.__new__(_PackedSourceCache)
        out.tree = self.tree.copy()
        out.src = None if self.src is None else self.src.copy()
        return out


class StateHashCache:
    """Per-state-instance cache over all fields + the container fold."""

    def __init__(self):
        self.fields: dict[str, IncrementalMerkleCache] = {}
        self.packed: dict[str, _PackedSourceCache] = {}
        self.registry = RegistryCache()
        self.small: dict[str, tuple[bytes, bytes]] = {}  # fname → (enc, root)

    def root(self, state) -> bytes:
        leaves = []
        for fname, ftype in type(state).FIELDS.items():
            v = getattr(state, fname)
            if fname == "validators":
                leaves.append(self.registry.root(v, ftype.LIMIT))
            elif getattr(ftype, "DTYPE", None) is not None                     and isinstance(v, np.ndarray) and v.ndim == 1                     and v.dtype.itemsize in _PACKED_PER_CHUNK:
                cache = self.packed.get(fname)
                if cache is None:
                    _w, limit_chunks, length = ftype.leaf_words(v)
                    cache = _PackedSourceCache(limit_chunks,
                                               length is not None)
                    self.packed[fname] = cache
                leaves.append(cache.root(np.asarray(v)))
            elif hasattr(ftype, "leaf_words"):
                words, limit_chunks, length = ftype.leaf_words(v)
                cache = self.fields.get(fname)
                if cache is None:
                    cache = IncrementalMerkleCache(
                        limit_chunks, mixin_length=length is not None)
                    self.fields[fname] = cache
                leaves.append(cache.root_words(words, length))
            else:
                enc = ftype.serialize(v)
                memo = self.small.get(fname)
                if memo is not None and memo[0] == enc:
                    leaves.append(memo[1])
                else:
                    r = ftype.hash_tree_root(v)
                    self.small[fname] = (enc, r)
                    leaves.append(r)
        HASH_COUNT[0] += len(leaves)  # container fold, ~2 per leaf
        return merkleize_host(leaves)

    def copy(self) -> "StateHashCache":
        out = StateHashCache.__new__(StateHashCache)
        out.fields = {k: c.copy() for k, c in self.fields.items()}
        out.packed = {k: c.copy() for k, c in self.packed.items()}
        out.registry = self.registry.copy()
        out.small = dict(self.small)
        return out
