"""BeaconState incremental tree-hash cache.

Counterpart of ``BeaconTreeHashCache``
(``/root/reference/consensus/types/src/beacon_state/tree_hash_cache.rs:332``)
and its per-field ``cached_tree_hash`` arenas: the state root becomes
O(changes·log n) instead of O(state).

Three tiers, by field shape:

- **Validator registry** (the 2^40-limit list of 8-field records,
  the reference's rayon-parallel arena — ``tree_hash_cache.rs:535-556``):
  dirty records come from the registry's column/row dirty marks (writes go
  through ``wcol``/``set``); only marked columns are diffed (one vectorized
  compare), only changed records re-hash their 8-leaf mini-trees (batched),
  and the big record-root tree updates incrementally.
- **Columnar packed fields** (balances, participation flags, inactivity
  scores, roots vectors, slashings): leaf-diff + dirty-path propagation via
  :class:`~lighthouse_tpu.ops.tree_cache.IncrementalMerkleCache`.
- **Small fields** (headers, checkpoints, sync committees, vote lists):
  re-hashed only when their SSZ encoding changes (memoised; the encode-and
  -compare costs µs, and a SyncCommittee rehash alone is ~1k hashes).

The cache travels with ``BeaconState.copy()`` (levels are copied, like the
reference's ``BeaconState`` clone-with-cache) and rebuilds transparently if
absent, so correctness never depends on it.
"""

from __future__ import annotations

import numpy as np

from ..ops.merkle import merkleize_host
from ..ops.tree_cache import (HASH_COUNT, IncrementalMerkleCache,
                              REBUILD_FRACTION)


# Cold builds at/above this many records run on the attached TPU in one
# dispatch (below it the host path costs ms anyway, and Pallas wants 2^15
# lanes).
DEVICE_COLD_MIN = 1 << 16

from ..ops.tree_cache import (_tpu_attached, join_level_pull,  # noqa: E402
                              start_level_pull)


class RegistryCache:
    """Record-root cache for the SoA validator registry.

    Incremental roots are PURE HOST work — diff the columns written since
    the last root (marks are consumed, not sticky), re-hash only the dirty
    records, walk their ancestor paths (`tree_hash_cache.rs:535-556` role).
    The attached-TPU dispatch round trip alone costs ~90 ms through the
    axon tunnel, so the per-slot path never touches the device; only the
    registry-scale cold build does (one fused dispatch), with the interior
    levels pulled into the host tree by a background thread (tunnel pulls
    run ~11 MB/s — ~6 s at 2^20 that the caller shouldn't block on).
    """

    def __init__(self):
        self.stored: dict[str, np.ndarray] | None = None  # column copies
        self.count = 0                                    # records at last root
        self.tree: IncrementalMerkleCache | None = None
        self._pending = None                              # (thread, [levels])

    # -- cold builds ---------------------------------------------------------

    def _cold_host(self, reg, n: int) -> bytes:
        self._snapshot(reg, n)
        record_roots = np.array(reg.record_roots_words())
        return self.tree.root_words(record_roots, length=n)

    def _cold_device(self, reg, n: int) -> bytes:
        """Fused device build: root now, host levels in the background."""
        from .validators import registry_cold_device

        self._snapshot(reg, n)
        root_words, levels = registry_cold_device(reg)
        self._pending = start_level_pull(levels)
        return self._fold(root_words, len(levels) - 1, n)

    def _fold(self, root_words: np.ndarray, lvl: int, n: int) -> bytes:
        from ..ops.tree_cache import fold_zero_cap
        return fold_zero_cap(root_words, lvl, self.tree.depth, True, n)

    def _snapshot(self, reg, n: int) -> None:
        self.stored = {c: np.array(getattr(reg, c)[:n])
                       for c in reg._COLUMNS}
        self.count = n
        reg._dirty_cols.clear()
        reg._dirty_rows.clear()

    def _finish_pending(self) -> None:
        """Join the background level pull into the host tree."""
        got = join_level_pull(self._pending)
        self._pending = None
        if got is not None:
            self.tree.levels = got
        # On pull failure leave tree.levels unset: the next root() sees a
        # cold tree and rebuilds (correctness never depends on the cache).

    # -- device-resident mode ------------------------------------------------

    def _diff_dirty(self, reg, n: int) -> np.ndarray:
        """Consume the registry's dirty marks into exact record indices
        (the shared walk of the host and device-resident warm paths):
        marked columns diff against the stored copies with one vectorized
        compare, grown rows are dirty by construction."""
        old_n = self.count
        dirty = np.zeros(n, dtype=bool)
        dirty[old_n:] = True
        for cname in reg._dirty_cols:
            col = getattr(reg, cname)[:old_n]
            st = self.stored[cname][:old_n]
            if col.ndim == 1:
                np.logical_or(dirty[:old_n], col != st, out=dirty[:old_n])
            else:
                np.logical_or(dirty[:old_n], (col != st).any(axis=1),
                              out=dirty[:old_n])
        for r in reg._dirty_rows:
            if r < n:
                dirty[r] = True
        reg._dirty_cols.clear()
        reg._dirty_rows.clear()
        return np.nonzero(dirty)[0]

    def _update_stored(self, reg, idx: np.ndarray, n: int) -> None:
        for cname in reg._COLUMNS:
            col = getattr(reg, cname)
            st = self.stored[cname]
            if st.shape[0] != n:  # grew (any padded width)
                grown = np.zeros((n,) + st.shape[1:], dtype=st.dtype)
                grown[:min(self.count, n)] = st[:min(self.count, n)]
                st = grown
                self.stored[cname] = st
            if idx.size:
                st[idx] = col[idx]

    def _root_device(self, reg, n: int) -> bytes:
        """Device-resident root: the mirror's HBM columns + record-root
        tree are the hashing source of truth.  Cold = the ONE-TIME
        materialization; warm = dirty records land as one fused scatter
        dispatch (k raw rows up, 32 bytes down); past the rebuild
        crossover the whole tree re-reduces from HBM with zero push."""
        from ..ops.merkle import _next_pow2
        from .validators import DeviceRegistryMirror

        mirror = getattr(reg, "_dev_mirror", None)
        w = _next_pow2(max(n, 1))
        if self.stored is None or self.count > n or mirror is None:
            self._snapshot(reg, n)
            mirror = DeviceRegistryMirror.materialize(reg)
            reg._dev_mirror = mirror
            return self._fold(mirror.tree.root_words(),
                              len(mirror.tree.levels) - 1, n)
        idx = self._diff_dirty(reg, n)
        self._update_stored(reg, idx, n)
        self.count = n
        grew = mirror.ensure_width(w)
        if idx.size == 0 and not grew:
            root = mirror.tree.root_words()
        elif grew or idx.size > w // REBUILD_FRACTION:
            if idx.size:
                mirror.scatter_cols(reg, idx)
            root = mirror.rebuild(n)
        else:
            root = mirror.scatter_records(reg, idx)
        return self._fold(root, len(mirror.tree.levels) - 1, n)

    # -- the per-root entry point -------------------------------------------

    def root(self, reg, limit: int, device: bool = False) -> bytes:
        n = len(reg)
        if self.tree is None:
            self.tree = IncrementalMerkleCache(limit, mixin_length=True)
        if self._pending is not None:
            self._finish_pending()
        if device:
            return self._root_device(reg, n)
        if getattr(reg, "_dev_mirror", None) is not None:
            # Knob flipped off mid-life: this host root consumes the dirty
            # marks the mirror would need, so residency ends HERE — a later
            # device root re-materializes instead of serving a stale tree.
            # Any host levels predate the device era (device roots update
            # only stored + HBM), so they must be rebuilt, not patched.
            reg._dev_mirror = None
            self.tree.levels = None
        from ..ops.merkle import _next_pow2
        cold = (self.stored is None or self.count > n
                or self.tree.levels is None
                or self.tree.levels[0].shape[0] != _next_pow2(max(n, 1)))
        if cold:
            if n >= DEVICE_COLD_MIN and _tpu_attached():
                return self._cold_device(reg, n)
            return self._cold_host(reg, n)

        # Marks are consumed: wcol views are only valid until the next
        # root (every in-tree caller writes immediately; the sticky
        # alternative re-diffed 130 MB of columns every slot at 2^20).
        idx = self._diff_dirty(reg, n)
        if idx.size:
            roots = reg.record_roots_words(idx)
            self._update_stored(reg, idx, n)
            self.count = n
            return self.tree.update_rows(idx, roots, n, length=n)
        self.count = n
        return self.tree.update_rows(
            np.empty(0, np.int64), np.empty((0, 8), np.uint32), n, length=n)

    def copy(self) -> "RegistryCache":
        if self._pending is not None:
            self._finish_pending()
        out = RegistryCache.__new__(RegistryCache)
        out.stored = (None if self.stored is None
                      else {k: v.copy() for k, v in self.stored.items()})
        out.count = self.count
        out.tree = None if self.tree is None else self.tree.copy()
        out._pending = None
        return out


# Shared with the device-resident twin (device_state.DevicePackedCache):
# ONE packing implementation keeps the host-oracle and device roots
# bit-identical by construction.
from .device_state import _PER_CHUNK as _PACKED_PER_CHUNK  # noqa: E402
from .device_state import pack_chunk_rows  # noqa: E402


class _PackedSourceCache:
    """Source-level diff for packed uint columns (balances, participation,
    inactivity): compare the raw column against the stored copy (one
    vectorized pass over the source values — 4-32× less traffic than
    leaf-word diffing and no full reconversion), pack ONLY the changed
    chunks, and hand the sparse update to the interior-node cache."""

    def __init__(self, limit_chunks: int, mixin_length: bool):
        self.tree = IncrementalMerkleCache(limit_chunks,
                                           mixin_length=mixin_length)
        self.src: np.ndarray | None = None

    _pack_chunks = staticmethod(pack_chunk_rows)

    def root(self, arr: np.ndarray) -> bytes:
        per = _PACKED_PER_CHUNK[arr.dtype.itemsize]
        n = arr.shape[0]
        n_chunks = (n + per - 1) // per
        pad = n_chunks * per - n
        if self.src is None or self.src.shape[0] != n:
            self.src = arr.copy()
            padded = np.concatenate([arr, np.zeros(pad, arr.dtype)])                 if pad else arr
            return self.tree.root_words(
                self._pack_chunks(padded.reshape(n_chunks, per)), length=n)
        changed = np.nonzero(self.src != arr)[0]
        if changed.size == 0:
            return self.tree.update_rows(
                np.empty(0, np.int64), np.empty((0, 8), np.uint32),
                n_chunks, length=n)
        chunk_idx = np.unique(changed // per)
        self.src[changed] = arr[changed]
        flat = (chunk_idx[:, None] * per
                + np.arange(per)[None, :]).reshape(-1)
        vals = np.where(flat < n, arr[np.minimum(flat, n - 1)],
                        np.zeros(1, arr.dtype))
        rows = self._pack_chunks(vals.reshape(chunk_idx.shape[0], per))
        return self.tree.update_rows(chunk_idx, rows, n_chunks, length=n)

    def copy(self) -> "_PackedSourceCache":
        out = _PackedSourceCache.__new__(_PackedSourceCache)
        out.tree = self.tree.copy()
        out.src = None if self.src is None else self.src.copy()
        return out


class StateHashCache:
    """Per-state-instance cache over all fields + the container fold."""

    def __init__(self):
        self.fields: dict[str, IncrementalMerkleCache] = {}
        self.packed: dict[str, _PackedSourceCache] = {}
        self.device_packed: dict = {}  # fname → DevicePackedCache
        self.registry = RegistryCache()
        self.small: dict[str, tuple[bytes, bytes]] = {}  # fname → (enc, root)
        # Per-field roots of the LAST root() fold — the proof plane
        # (light_client / proof_engine) reads this instead of re-hashing
        # every field per request.  Valid only for the root just
        # computed; root() refreshes it, copy() drops it.
        self.field_layer: list | None = None

    @staticmethod
    def _packed_limits(ftype) -> tuple[int, bool]:
        """(limit_chunks, mixin_length) of a packed uint field without
        needing a value (the DeviceColumn path never round-trips one)."""
        per = 32 // np.dtype(ftype.DTYPE).itemsize
        return (max((ftype.BOUND + per - 1) // per, 1),
                not ftype.is_fixed_size())

    def root(self, state) -> bytes:
        from .device_state import (DeviceColumn, DevicePackedCache,
                                   wants_device_state, wrap_state_column)
        use_dev = wants_device_state(state)
        leaves = []
        for fname, ftype in type(state).FIELDS.items():
            v = getattr(state, fname)
            is_packed = (getattr(ftype, "DTYPE", None) is not None
                         and np.dtype(ftype.DTYPE).itemsize
                         in _PACKED_PER_CHUNK
                         and (isinstance(v, DeviceColumn)
                              or (isinstance(v, np.ndarray)
                                  and v.ndim == 1)))
            if fname == "validators":
                leaves.append(self.registry.root(v, ftype.LIMIT,
                                                 device=use_dev))
            elif is_packed and use_dev:
                col = wrap_state_column(state, fname)
                cache = self.device_packed.get(fname)
                if cache is None:
                    limit_chunks, mixin = self._packed_limits(ftype)
                    cache = DevicePackedCache(limit_chunks, mixin)
                    self.device_packed[fname] = cache
                leaves.append(cache.root(col))
            elif is_packed:
                if isinstance(v, DeviceColumn):  # knob flipped off mid-life
                    v = v.host()
                cache = self.packed.get(fname)
                if cache is None:
                    _w, limit_chunks, length = ftype.leaf_words(v)
                    cache = _PackedSourceCache(limit_chunks,
                                               length is not None)
                    self.packed[fname] = cache
                leaves.append(cache.root(np.asarray(v)))
            elif hasattr(ftype, "leaf_words"):
                words, limit_chunks, length = ftype.leaf_words(v)
                cache = self.fields.get(fname)
                if cache is None:
                    cache = IncrementalMerkleCache(
                        limit_chunks, mixin_length=length is not None)
                    self.fields[fname] = cache
                leaves.append(cache.root_words(words, length))
            else:
                enc = ftype.serialize(v)
                memo = self.small.get(fname)
                if memo is not None and memo[0] == enc:
                    leaves.append(memo[1])
                else:
                    r = ftype.hash_tree_root(v)
                    self.small[fname] = (enc, r)
                    leaves.append(r)
        HASH_COUNT[0] += len(leaves)  # container fold, ~2 per leaf
        self.field_layer = leaves
        return merkleize_host(leaves)

    def copy(self) -> "StateHashCache":
        out = StateHashCache.__new__(StateHashCache)
        out.fields = {k: c.copy() for k, c in self.fields.items()}
        out.packed = {k: c.copy() for k, c in self.packed.items()}
        out.device_packed = {k: c.copy()
                             for k, c in self.device_packed.items()}
        out.registry = self.registry.copy()
        out.small = dict(self.small)
        out.field_layer = None
        return out
