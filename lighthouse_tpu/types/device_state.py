"""Device-resident BeaconState columns — HBM as the source of truth.

Once a state is *materialized* (:func:`materialize_state` — explicit, or
automatic at registry scale on an attached TPU), the hot columns stop being
re-staged for every device pass:

- the big packed columns (``balances``, the two participation flag columns,
  ``inactivity_scores``, ``slashings``) are wrapped in :class:`DeviceColumn`
  — an ndarray-shaped handle whose Merkle leaves and interior tree levels
  live on the device (:class:`~lighthouse_tpu.ops.device_tree.DeviceTree`)
  and whose host numpy buffer is a lazily-pulled *view* of device results;
- every mutation is tracked: ``col[idx] = v`` and the transition passes'
  :func:`store_column` record exact dirty indices, wholesale host
  assignments fall back to a vectorized diff, and a device-computed result
  (the jitted epoch sweep) is *adopted* — the jax array becomes the column,
  nothing is pulled, and the next root repacks + re-reduces entirely in HBM;
- a warm ``hash_tree_root`` therefore pushes only the dirty chunk rows and
  pulls 32 bytes — the full-state H2D re-stage (5.1 s of the 9.2 s cold
  root at 2^20, ``state_root_cold_push_ms``) is eliminated, not overlapped.

``BeaconState.copy()`` clones are copy-on-write on the device side: the
clone shares every device buffer (jax arrays are immutable) and the first
mutation of either lineage lands in fresh buffers via an undonated update
program — no HBM duplication, no forced pull
(:meth:`~lighthouse_tpu.ops.device_tree.DeviceTree.share`).

The host scalar/incremental path remains the differential oracle:
``LIGHTHOUSE_TPU_DEVICE_STATE=0`` disables materialization entirely (the
PR 3 oracle-knob pattern), and `tests/test_device_state.py` asserts the
device-resident root is byte-identical to the host spec root under
randomized mutation interleavings.
"""

from __future__ import annotations


import numpy as np

from ..common.device_ledger import LEDGER
from ..ops.device_tree import DeviceTree, residency_snapshot
from ..ops.merkle import _next_pow2
from ..ops.tree_cache import fold_zero_cap

# Columns that get a device mirror on materialization (plus the validator
# registry, handled by the registry's own mirror in types/validators.py).
DEVICE_COLUMN_FIELDS = (
    "balances",
    "previous_epoch_participation",
    "current_epoch_participation",
    "inactivity_scores",
    "slashings",
)
_DEVICE_COLUMN_SET = frozenset(DEVICE_COLUMN_FIELDS)

# Timings/bytes of the most recent materialize_state call (bench surface).
LAST_MATERIALIZE_STATS: dict = {}


def device_state_enabled() -> bool:
    """Master knob: device-resident state unless
    ``LIGHTHOUSE_TPU_DEVICE_STATE=0`` (the host incremental path is the
    differential oracle — README "Device-resident state")."""
    from ..common.knobs import knob_bool
    return knob_bool("LIGHTHOUSE_TPU_DEVICE_STATE")


def is_materialized(state) -> bool:
    return bool(state.__dict__.get("_device_resident"))


def _is_jax_array(x) -> bool:
    if isinstance(x, np.ndarray):
        return False
    try:
        import jax
        return isinstance(x, jax.Array)
    except Exception:  # pragma: no cover - jax always importable in-tree
        return False


def pack_chunk_rows(vals: np.ndarray) -> np.ndarray:
    """``(k, per)`` source values → ``(k, 8)`` big-endian u32 chunk words
    (SSZ little-endian packing inside each 32-byte chunk)."""
    le = np.ascontiguousarray(
        vals.astype(vals.dtype.newbyteorder("<"), copy=False))
    return np.frombuffer(le.tobytes(), dtype=">u4").astype(
        np.uint32).reshape(vals.shape[0], 8)


class DeviceColumn:
    """Ndarray-shaped handle for one packed state column.

    Reads see the host view (pulled lazily after a device-side update);
    writes are tracked so the per-root device work is bounded by the dirty
    fraction.  Unknown attributes delegate to the read-only host view, so
    ``col.sum()`` / ``col.astype(...)`` keep working — while an attempted
    *in-place* write through such a view raises instead of silently
    desynchronizing the device tree (the registry ``col()``/``wcol()``
    discipline, applied to the flat columns).
    """

    __ssz_mutable__ = True
    __slots__ = ("_host", "_dev", "_stale", "_idx", "_all", "_adopted")

    def __init__(self, arr: np.ndarray):
        arr = np.asarray(arr)
        if not arr.flags.writeable:
            arr = arr.copy()
        object.__setattr__(self, "_host", arr)
        object.__setattr__(self, "_dev", None)
        object.__setattr__(self, "_stale", False)
        object.__setattr__(self, "_idx", [])
        object.__setattr__(self, "_all", True)  # fresh wrap: diff on 1st root
        object.__setattr__(self, "_adopted", False)

    # -- host/device plumbing ------------------------------------------------

    def _pull(self) -> None:
        from ..parallel.mesh import mesh_gather
        host = mesh_gather(self._dev, subsystem="packed_cache")
        object.__setattr__(self, "_host", host.copy()
                           if not host.flags.writeable else host)
        object.__setattr__(self, "_stale", False)

    def _master(self) -> np.ndarray:
        """Writable host master (pulls first if the device is ahead)."""
        if self._stale:
            self._pull()
        return self._host

    def _leave_adopted(self) -> None:
        """A host write is landing: the host master becomes authoritative
        again (the cache recovers its diff baseline from the last adopted
        buffer it recorded)."""
        if self._adopted:
            self._master()  # ensure the host view is current first
            object.__setattr__(self, "_adopted", False)
            object.__setattr__(self, "_dev", None)
            # If no root ran since the adoption, the cache's host baseline
            # predates it — index tracking can't name the adoption-era
            # delta, only a full diff recovers it.
            object.__setattr__(self, "_all", True)

    def host(self) -> np.ndarray:
        """Read-only view of the current column values."""
        v = self._master().view()
        v.flags.writeable = False
        return v

    # -- ndarray protocol ----------------------------------------------------

    def __array__(self, dtype=None, copy=None):
        v = self.host()
        if dtype is not None and dtype != v.dtype:
            return v.astype(dtype)
        if copy:
            return v.copy()
        return v

    @property
    def shape(self):
        return self._dev.shape if self._stale else self._host.shape

    @property
    def dtype(self):
        return np.dtype(self._dev.dtype) if self._stale else self._host.dtype

    @property
    def ndim(self) -> int:
        return 1

    @property
    def size(self) -> int:
        return int(self.shape[0])

    def __len__(self) -> int:
        return int(self.shape[0])

    def __iter__(self):
        return iter(self.host())

    def __getitem__(self, key):
        v = self.host()[key]
        # Fancy/bool indexing already copied; basic slices stay read-only
        # views so bypass writes raise loudly.
        return v

    def __setitem__(self, key, value) -> None:
        self._leave_adopted()
        h = self._master()
        h[key] = value
        if self._all:
            return
        if isinstance(key, (int, np.integer)):
            self._idx.append(np.asarray([int(key) % h.shape[0]],
                                        dtype=np.int64))
        elif isinstance(key, np.ndarray) and key.dtype == bool:
            self._idx.append(np.flatnonzero(key))
        elif isinstance(key, np.ndarray) and key.dtype.kind in "iu":
            idx = key.astype(np.int64).ravel() % max(h.shape[0], 1)
            self._idx.append(idx)
        else:  # slices / anything exotic: fall back to the full diff
            object.__setattr__(self, "_all", True)

    def __eq__(self, other):
        if isinstance(other, DeviceColumn):
            other = other.host()
        if isinstance(other, np.ndarray):
            return bool(np.array_equal(self.host(), other))
        return NotImplemented

    __hash__ = None

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.host(), name)

    def __repr__(self):
        where = "device" if self._adopted else "host"
        return (f"DeviceColumn(n={self.shape[0]}, dtype={self.dtype}, "
                f"authority={where})")

    # -- tracked mutation API ------------------------------------------------

    def assign(self, arr, touched: np.ndarray | None = None) -> None:
        """Wholesale replacement.  A jax array is *adopted* (device stays
        authoritative, zero pull); a numpy array replaces the host master
        with ``touched`` as the precise dirty set (full diff when None or
        on a length change)."""
        if _is_jax_array(arr):
            object.__setattr__(self, "_dev", arr)
            object.__setattr__(self, "_stale", True)
            object.__setattr__(self, "_adopted", True)
            self._idx.clear()
            object.__setattr__(self, "_all", False)
            return
        arr = np.asarray(arr)
        if not arr.flags.writeable:
            arr = arr.copy()
        length_changed = arr.shape != self.shape
        was_adopted = self._adopted  # un-rooted adoption ⇒ full diff
        object.__setattr__(self, "_adopted", False)
        object.__setattr__(self, "_dev", None)
        object.__setattr__(self, "_stale", False)
        object.__setattr__(self, "_host", arr)
        if touched is None or length_changed or self._all or was_adopted:
            object.__setattr__(self, "_all", True)
        else:
            self._idx.append(np.asarray(touched, dtype=np.int64).ravel())

    def consume(self):
        """Hand the accumulated dirty state to the hash cache and reset.
        Returns ``("device", jax_array)`` (adopted — rebuild in HBM),
        ``("all", None)`` (diff against the cache's baseline), or
        ``("idx", indices)`` (exact dirty value indices)."""
        if self._adopted:
            return "device", self._dev
        if self._all:
            object.__setattr__(self, "_all", False)
            self._idx.clear()
            return "all", None
        if not self._idx:
            return "idx", np.empty(0, dtype=np.int64)
        idx = np.unique(np.concatenate(self._idx))
        self._idx.clear()
        return "idx", idx

    def copy(self) -> "DeviceColumn":
        """COW clone: device buffers are shared (immutable), the host
        master is copied, dirty tracking travels."""
        out = DeviceColumn.__new__(DeviceColumn)
        object.__setattr__(out, "_host",
                           None if self._host is None else self._host.copy())
        object.__setattr__(out, "_dev", self._dev)
        object.__setattr__(out, "_stale", self._stale)
        object.__setattr__(out, "_idx", list(self._idx))
        object.__setattr__(out, "_all", self._all)
        object.__setattr__(out, "_adopted", self._adopted)
        return out


# ---------------------------------------------------------------------------
# Device-resident packed-column hash cache
# ---------------------------------------------------------------------------

_PER_CHUNK = {8: 4, 1: 32}  # u64 → 4 values/chunk, u8 → 32


def _repack_leaves_body(col, *, w: int):
    """Device body: a packed source column → its zero-padded ``(w, 8)``
    big-endian chunk-word leaf plane, entirely in HBM (the device twin of
    :func:`pack_chunk_rows`)."""
    import jax
    import jax.numpy as jnp

    def bswap32(x):
        return (((x & np.uint32(0xFF)) << np.uint32(24))
                | (((x >> np.uint32(8)) & np.uint32(0xFF)) << np.uint32(16))
                | (((x >> np.uint32(16)) & np.uint32(0xFF)) << np.uint32(8))
                | (x >> np.uint32(24)))

    n = col.shape[0]
    if col.dtype == jnp.uint8:
        flat = jnp.zeros(32 * w, dtype=jnp.uint32)
        flat = flat.at[:n].set(col.astype(jnp.uint32))
        b = flat.reshape(8 * w, 4)
        words = ((b[:, 0] << np.uint32(24)) | (b[:, 1] << np.uint32(16))
                 | (b[:, 2] << np.uint32(8)) | b[:, 3])
        return words.reshape(w, 8)
    # u64: little-endian value = (lo, hi) u32 pair; big-endian chunk word
    # of 4 LE bytes is just bswap32 of the LE u32.
    lohi = jax.lax.bitcast_convert_type(col, jnp.uint32)  # (n, 2)
    words = bswap32(lohi.reshape(-1))                     # (2n,)
    flat = jnp.zeros(8 * w, dtype=jnp.uint32)
    flat = flat.at[:words.shape[0]].set(words)
    return flat.reshape(w, 8)


_repack_levels_jit = None


def _repack_rebuild(col_dev, w: int):
    """Fused repack + full-level reduction over a device-resident source
    column — the zero-push rebuild used when a column was adopted from a
    device computation (the jitted epoch sweep).  Runs inside
    ``enable_x64`` because the adopted columns are u64 (the sweep's own
    convention, `per_epoch_device`)."""
    global _repack_levels_jit
    import jax
    from jax.experimental import enable_x64
    from ..ops.merkle_kernel import _levels_body, _use_pallas

    if _repack_levels_jit is None:
        def body(col, *, w, use_kernel):
            return _levels_body(_repack_leaves_body(col, w=w),
                                use_kernel=use_kernel)
        _repack_levels_jit = jax.jit(body,
                                     static_argnames=("w", "use_kernel"))
    with enable_x64():
        return _repack_levels_jit(col_dev, w=w, use_kernel=_use_pallas())


class DevicePackedCache:
    """Device-resident twin of ``state_cache._PackedSourceCache``: the
    interior tree lives in HBM and a warm root pushes only the changed
    chunk rows (or nothing at all, when the column itself was computed on
    the device)."""

    def __init__(self, limit_chunks: int, mixin_length: bool):
        self.depth = max((int(limit_chunks) - 1).bit_length(), 0)
        self.mixin = mixin_length
        self.tree: DeviceTree | None = None
        self.src: np.ndarray | None = None   # host baseline at last root
        self.src_dev = None                  # adopted-era baseline buffer

    # -- internals -----------------------------------------------------------

    def _fold(self, root_words: np.ndarray, w: int, length: int) -> bytes:
        return fold_zero_cap(root_words, (w - 1).bit_length(), self.depth,
                             self.mixin, length)

    def _ensure_src(self) -> None:
        """Recover the host diff baseline after an adopted era (one pull,
        paid only when host-side mutation resumes — which implies the host
        needed the values anyway)."""
        if self.src is None and self.src_dev is not None:
            from ..parallel.mesh import mesh_gather
            self.src = mesh_gather(
                self.src_dev, subsystem="packed_cache").copy()
            self.src_dev = None

    def _host_rebuild(self, host: np.ndarray, w: int) -> np.ndarray:
        per = _PER_CHUNK[host.dtype.itemsize]
        padded = np.zeros(w * per, dtype=host.dtype)
        padded[:host.shape[0]] = host
        leaves = pack_chunk_rows(padded.reshape(w, per))
        if self.tree is None:
            self.tree = DeviceTree.from_host_leaves(leaves)
        else:
            from ..parallel.mesh import mesh_put
            self.tree.rebuild_device(
                mesh_put("packed_leaves", leaves,
                         subsystem="packed_cache"))
        self.src = host.copy()
        self.src_dev = None
        return self.tree.root_words()

    # -- the per-root entry point -------------------------------------------

    def root(self, col) -> bytes:
        # Every transfer/compile under this root — including the nested
        # DeviceTree pushes — attributes to the packed-column cache.
        with LEDGER.attribute("packed_cache"):
            return self._root_inner(col)

    def _root_inner(self, col) -> bytes:
        if isinstance(col, DeviceColumn):
            state, payload = col.consume()
        else:  # untracked plain column (a path the interception missed)
            col = DeviceColumn(np.asarray(col))
            state, payload = "all", None
        n = int(col.shape[0])
        per = _PER_CHUNK[np.dtype(col.dtype).itemsize]
        n_chunks = max((n + per - 1) // per, 1)
        w = _next_pow2(n_chunks)

        if state == "device":
            if (payload is self.src_dev and self.tree is not None
                    and self.tree.width == w):
                return self._fold(self.tree.root_words(), w, n)
            levels = _repack_rebuild(payload, w)
            LEDGER.note_event("rebuilds")
            if self.tree is None:
                self.tree = DeviceTree(levels)
            else:
                self.tree.levels = levels
                self.tree.shared = False
            self.tree.note_residency()
            self.src = None
            self.src_dev = payload
            return self._fold(self.tree.root_words(), w, n)

        host = col._master()
        if self.tree is None or self.tree.width != w:
            return self._fold(self._host_rebuild(host, w), w, n)

        self._ensure_src()
        if self.src is None:  # first root ever went through device adopt
            return self._fold(self._host_rebuild(host, w), w, n)
        old_n = self.src.shape[0]
        if state == "idx":
            changed = payload[payload < min(old_n, n)] \
                if old_n != n else payload
        else:
            m = min(old_n, n)
            changed = np.nonzero(self.src[:m] != host[:m])[0]
        chunk_idx = np.unique(changed // per)
        if old_n != n:
            lo = min(old_n, n) // per
            hi = (max(old_n, n) + per - 1) // per
            tail = np.arange(lo, min(hi, w), dtype=np.int64)
            chunk_idx = np.union1d(chunk_idx, tail)
            self.src = host.copy()
        elif changed.size:
            self.src[changed] = host[changed]
        if chunk_idx.size == 0:
            return self._fold(self.tree.root_words(), w, n)
        flat = (chunk_idx[:, None] * per
                + np.arange(per)[None, :]).reshape(-1)
        vals = np.where(flat < n,
                        host[np.minimum(flat, max(n - 1, 0))]
                        if n else np.zeros(1, host.dtype),
                        np.zeros(1, host.dtype))
        rows = pack_chunk_rows(vals.reshape(chunk_idx.shape[0], per))
        root = self.tree.scatter(chunk_idx, rows)
        return self._fold(root, w, n)

    def copy(self) -> "DevicePackedCache":
        out = DevicePackedCache.__new__(DevicePackedCache)
        out.depth = self.depth
        out.mixin = self.mixin
        out.tree = None if self.tree is None else self.tree.share()
        out.src = None if self.src is None else self.src.copy()
        out.src_dev = self.src_dev
        return out


# ---------------------------------------------------------------------------
# Materialization + the transition-pass store seam
# ---------------------------------------------------------------------------

def _auto_materialize(state) -> bool:
    """Automatic residency: registry scale on an attached TPU (the old
    cold-device threshold) — explicit :func:`materialize_state` covers any
    backend (tests force it on the CPU mesh)."""
    from ..ops.tree_cache import _tpu_attached
    from .state_cache import DEVICE_COLD_MIN
    try:
        n = len(state.validators)
    except Exception:
        return False
    return n >= DEVICE_COLD_MIN and _tpu_attached()


def wants_device_state(state) -> bool:
    if not device_state_enabled():
        return False
    if is_materialized(state):
        return True
    if _auto_materialize(state):
        state.__dict__["_device_resident"] = True
        return True
    return False


def materialize_state(state, force: bool = True) -> bool:
    """Make device buffers the source of truth for this state's hot
    columns.  The one root computed here IS the materialization: the
    registry columns stream to HBM once (chunk-staged), every big field's
    tree levels are built in place, and from then on warm roots are
    bounded by compute + dirty fraction — never by a full re-stage.

    Returns False (no-op) when ``LIGHTHOUSE_TPU_DEVICE_STATE=0`` or, with
    ``force=False``, below the auto threshold off-TPU."""
    import time

    if not device_state_enabled():
        return False
    if is_materialized(state):
        return True
    if not force and not _auto_materialize(state):
        return False
    before = residency_snapshot()
    t0 = time.perf_counter()
    state.__dict__["_device_resident"] = True
    state.tree_hash_root()
    after = residency_snapshot()
    LAST_MATERIALIZE_STATS.clear()
    LAST_MATERIALIZE_STATS.update(
        materialize_ms=round((time.perf_counter() - t0) * 1e3, 1),
        bytes_pushed=after["bytes_pushed"] - before["bytes_pushed"])
    return True


def wrap_state_column(state, fname: str):
    """Ensure ``state.<fname>`` is a tracked :class:`DeviceColumn`
    (idempotent; used by the hash cache to recover from any assignment
    path the attribute interception did not see)."""
    v = state.__dict__.get(fname)
    if isinstance(v, DeviceColumn):
        return v
    col = DeviceColumn(np.asarray(v))
    object.__setattr__(state, fname, col)
    return col


def store_column(state, fname: str, arr, touched=None) -> None:
    """The transition passes' column store seam: lands ``arr`` in
    ``state.<fname>`` as a device scatter when the state is materialized
    (``touched`` = exact dirty indices; a jax array is adopted without a
    pull), and as a plain attribute assignment otherwise."""
    cur = state.__dict__.get(fname)
    if isinstance(cur, DeviceColumn):
        cur.assign(arr, touched=touched)
        return
    if _is_jax_array(arr):
        arr = np.asarray(arr)
    setattr(state, fname, arr)
