"""Runtime chain configuration — the ``ChainSpec`` analogue.

Mirrors ``/root/reference/consensus/types/src/chain_spec.rs`` (~115 params;
the subset the state transition, fork choice, and networking layers consume).
Fork scheduling follows the same model: each fork has a version and an
activation epoch (``None``/``FAR_FUTURE_EPOCH`` = never).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, replace

FAR_FUTURE_EPOCH = 2**64 - 1
GENESIS_EPOCH = 0
GENESIS_SLOT = 0

# Participation flag indices / weights (altair constants,
# consensus-specs `specs/altair/beacon-chain.md`).
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64
PARTICIPATION_FLAG_WEIGHTS = (
    TIMELY_SOURCE_WEIGHT, TIMELY_TARGET_WEIGHT, TIMELY_HEAD_WEIGHT)

BLS_WITHDRAWAL_PREFIX = b"\x00"
ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"


class Domain(bytes, enum.Enum):
    """Signature domain types (``chain_spec.rs`` domain constants)."""
    BEACON_PROPOSER = bytes([0, 0, 0, 0])
    BEACON_ATTESTER = bytes([1, 0, 0, 0])
    RANDAO = bytes([2, 0, 0, 0])
    DEPOSIT = bytes([3, 0, 0, 0])
    VOLUNTARY_EXIT = bytes([4, 0, 0, 0])
    SELECTION_PROOF = bytes([5, 0, 0, 0])
    AGGREGATE_AND_PROOF = bytes([6, 0, 0, 0])
    SYNC_COMMITTEE = bytes([7, 0, 0, 0])
    SYNC_COMMITTEE_SELECTION_PROOF = bytes([8, 0, 0, 0])
    CONTRIBUTION_AND_PROOF = bytes([9, 0, 0, 0])
    BLS_TO_EXECUTION_CHANGE = bytes([10, 0, 0, 0])


class ForkName(str, enum.Enum):
    """Fork schedule order (``types/src/fork_name.rs``)."""
    PHASE0 = "phase0"
    ALTAIR = "altair"
    BELLATRIX = "bellatrix"
    CAPELLA = "capella"
    DENEB = "deneb"

    @property
    def order(self) -> int:
        return _FORK_ORDER[self]

    def __ge__(self, other):  # type: ignore[override]
        if isinstance(other, ForkName):
            return self.order >= other.order
        return NotImplemented

    def __gt__(self, other):  # type: ignore[override]
        if isinstance(other, ForkName):
            return self.order > other.order
        return NotImplemented

    def __le__(self, other):  # type: ignore[override]
        if isinstance(other, ForkName):
            return self.order <= other.order
        return NotImplemented

    def __lt__(self, other):  # type: ignore[override]
        if isinstance(other, ForkName):
            return self.order < other.order
        return NotImplemented


_FORK_ORDER = {ForkName.PHASE0: 0, ForkName.ALTAIR: 1,
               ForkName.BELLATRIX: 2, ForkName.CAPELLA: 3,
               ForkName.DENEB: 4}


@dataclass
class ChainSpec:
    config_name: str = "mainnet"
    preset_base: str = "mainnet"

    # Genesis
    min_genesis_active_validator_count: int = 16384
    min_genesis_time: int = 1606824000
    genesis_fork_version: bytes = bytes(4)
    genesis_delay: int = 604800

    # Forking
    altair_fork_version: bytes = bytes([1, 0, 0, 0])
    altair_fork_epoch: int | None = 74240
    bellatrix_fork_version: bytes = bytes([2, 0, 0, 0])
    bellatrix_fork_epoch: int | None = 144896
    capella_fork_version: bytes = bytes([3, 0, 0, 0])
    capella_fork_epoch: int | None = 194048
    deneb_fork_version: bytes = bytes([4, 0, 0, 0])
    deneb_fork_epoch: int | None = 269568

    # Time parameters
    seconds_per_slot: int = 12
    seconds_per_eth1_block: int = 14
    min_validator_withdrawability_delay: int = 256
    shard_committee_period: int = 256
    eth1_follow_distance: int = 2048

    # Validator cycle
    inactivity_score_bias: int = 4
    inactivity_score_recovery_rate: int = 16
    ejection_balance: int = 16_000_000_000
    min_per_epoch_churn_limit: int = 4
    churn_limit_quotient: int = 65536

    # Fork choice
    proposer_score_boost: int = 40
    safe_slots_to_update_justified: int = 8

    # Deposit contract
    deposit_chain_id: int = 1
    deposit_network_id: int = 1
    deposit_contract_address: bytes = bytes(20)

    # Networking / validator
    target_aggregators_per_committee: int = 16
    attestation_subnet_count: int = 64
    epochs_per_subnet_subscription: int = 256
    attestation_propagation_slot_range: int = 32
    maximum_gossip_clock_disparity_ms: int = 500

    # Terminal-difficulty merge params (bellatrix); mainnet TTD per
    # `chain_spec.rs` / mainnet config.yaml.
    terminal_total_difficulty: int = 58750000000000000000000
    terminal_block_hash: bytes = bytes(32)
    terminal_block_hash_activation_epoch: int = FAR_FUTURE_EPOCH

    # -- fork schedule -------------------------------------------------------

    def fork_version(self, fork: ForkName) -> bytes:
        return {
            ForkName.PHASE0: self.genesis_fork_version,
            ForkName.ALTAIR: self.altair_fork_version,
            ForkName.BELLATRIX: self.bellatrix_fork_version,
            ForkName.CAPELLA: self.capella_fork_version,
            ForkName.DENEB: self.deneb_fork_version,
        }[fork]

    def fork_epoch(self, fork: ForkName) -> int | None:
        return {
            ForkName.PHASE0: 0,
            ForkName.ALTAIR: self.altair_fork_epoch,
            ForkName.BELLATRIX: self.bellatrix_fork_epoch,
            ForkName.CAPELLA: self.capella_fork_epoch,
            ForkName.DENEB: self.deneb_fork_epoch,
        }[fork]

    def fork_name_at_epoch(self, epoch: int) -> ForkName:
        """``ChainSpec::fork_name_at_epoch`` (``chain_spec.rs``)."""
        current = ForkName.PHASE0
        for fork in (ForkName.ALTAIR, ForkName.BELLATRIX, ForkName.CAPELLA,
                     ForkName.DENEB):
            fe = self.fork_epoch(fork)
            if fe is not None and fe != FAR_FUTURE_EPOCH and epoch >= fe:
                current = fork
        return current

    def next_fork(self, fork: ForkName) -> ForkName | None:
        order = [ForkName.PHASE0, ForkName.ALTAIR, ForkName.BELLATRIX,
                 ForkName.CAPELLA, ForkName.DENEB]
        i = order.index(fork)
        return order[i + 1] if i + 1 < len(order) else None

    # -- constructors --------------------------------------------------------

    # -- YAML config (`config.yaml`, `chain_spec.rs` from_config) ------------

    def to_yaml(self) -> str:
        """Spec ``config.yaml`` conventions: UPPER_SNAKE keys, fork
        versions as 0x-hex, epochs as ints (None → far-future)."""
        import dataclasses
        import yaml
        out = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            key = f.name.upper()
            if isinstance(v, bytes):
                v = "0x" + v.hex()
            elif v is None:
                v = FAR_FUTURE_EPOCH
            out[key] = v
        return yaml.safe_dump(out, sort_keys=False)

    @classmethod
    def from_yaml(cls, text: str) -> "ChainSpec":
        """Load a spec `config.yaml`; unknown keys are ignored (forward
        compatibility, like the reference's serde defaults)."""
        import dataclasses
        import yaml
        raw = yaml.safe_load(text) or {}
        fields = {f.name: f for f in dataclasses.fields(cls)}
        kwargs = {}
        for key, v in raw.items():
            name = key.lower()
            f = fields.get(name)
            if f is None:
                continue
            if isinstance(f.default, bytes):
                # Bytes fields (fork versions): published configs write
                # them as UNQUOTED 0x-hex, which PyYAML resolves to int —
                # convert either form to the field's byte width.
                if isinstance(v, str):
                    v = bytes.fromhex(v.removeprefix("0x"))
                elif isinstance(v, int):
                    v = v.to_bytes(len(f.default), "big")
            elif isinstance(v, str) and v.startswith("0x"):
                v = bytes.fromhex(v[2:])
            kwargs[name] = v
        return cls(**kwargs)

    @classmethod
    def mainnet(cls) -> "ChainSpec":
        return cls()

    @classmethod
    def minimal(cls) -> "ChainSpec":
        return cls(
            config_name="minimal",
            preset_base="minimal",
            min_genesis_active_validator_count=64,
            genesis_fork_version=bytes([0, 0, 0, 1]),
            genesis_delay=300,
            altair_fork_version=bytes([1, 0, 0, 1]),
            altair_fork_epoch=FAR_FUTURE_EPOCH,
            bellatrix_fork_version=bytes([2, 0, 0, 1]),
            bellatrix_fork_epoch=FAR_FUTURE_EPOCH,
            capella_fork_version=bytes([3, 0, 0, 1]),
            capella_fork_epoch=FAR_FUTURE_EPOCH,
            deneb_fork_version=bytes([4, 0, 0, 1]),
            deneb_fork_epoch=FAR_FUTURE_EPOCH,
            seconds_per_slot=6,
            shard_committee_period=64,
            eth1_follow_distance=16,
            min_per_epoch_churn_limit=2,
            churn_limit_quotient=32,
        )

    def with_forks_at_genesis(self, fork: ForkName) -> "ChainSpec":
        """All forks up to ``fork`` active from epoch 0 — the pattern the
        reference's harness uses for fork-parameterized tests
        (``beacon_chain/src/test_utils.rs``, ``fork_from_env``)."""
        updates = {}
        for f, attr in ((ForkName.ALTAIR, "altair_fork_epoch"),
                        (ForkName.BELLATRIX, "bellatrix_fork_epoch"),
                        (ForkName.CAPELLA, "capella_fork_epoch"),
                        (ForkName.DENEB, "deneb_fork_epoch")):
            if fork >= f:
                updates[attr] = 0
        return replace(self, **updates)
