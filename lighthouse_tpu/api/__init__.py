"""HTTP APIs: Beacon-API server + Prometheus metrics endpoint
(counterparts of ``beacon_node/http_api`` and ``beacon_node/http_metrics``)."""

from .http_api import HttpApiServer

__all__ = ["HttpApiServer"]
