"""Beacon-API HTTP server — ``beacon_node/http_api``
(``/root/reference/beacon_node/http_api/src/lib.rs``) plus the Prometheus
scrape endpoint of ``beacon_node/http_metrics``.

A threaded stdlib HTTP server exposing the standard ``/eth/v1`` surface
over an in-process :class:`~lighthouse_tpu.beacon_chain.BeaconChain` (the
reference uses warp; the route table and JSON conventions are the spec's).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..common.metrics import REGISTRY
from ..ssz.json import to_json


class HttpApiServer:
    def __init__(self, chain, host: str = "127.0.0.1", port: int = 0):
        self.chain = chain
        self.requests_total = REGISTRY.counter(
            "http_api_requests_total", "Beacon-API requests served")
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _text(self, text, code=200):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                api.requests_total.inc()
                try:
                    api._route_get(self)
                except Exception as e:  # noqa: BLE001
                    self._json({"code": 500, "message": str(e)}, 500)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length) if length else b""
                    api._route_post(self, body)
                except Exception as e:  # noqa: BLE001
                    self._json({"code": 500, "message": str(e)}, 500)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    # -- state resolution ----------------------------------------------------

    def _state(self, state_id: str):
        chain = self.chain
        if state_id in ("head", "justified", "finalized"):
            return chain.head.state
        if state_id.startswith("0x"):
            return chain.store.get_state(bytes.fromhex(state_id[2:]))
        raise ValueError(f"unsupported state id {state_id}")

    def _block(self, block_id: str):
        chain = self.chain
        if block_id == "head":
            return chain.store.get_block(chain.head.root), chain.head.root
        if block_id.startswith("0x"):
            root = bytes.fromhex(block_id[2:])
            return chain.store.get_block(root), root
        raise ValueError(f"unsupported block id {block_id}")

    # -- routes --------------------------------------------------------------

    def _route_get(self, h) -> None:
        path = urlparse(h.path).path.rstrip("/")
        chain = self.chain
        if path == "/eth/v1/node/version":
            h._json({"data": {"version": "lighthouse-tpu/0.3.0"}})
        elif path == "/eth/v1/node/health":
            h.send_response(200)
            h.end_headers()
        elif path == "/eth/v1/node/syncing":
            h._json({"data": {
                "head_slot": str(chain.head.slot),
                "sync_distance": str(max(
                    chain.current_slot() - chain.head.slot, 0)),
                "is_syncing": chain.current_slot() - chain.head.slot > 1,
                "is_optimistic": False, "el_offline": False}})
        elif path == "/eth/v1/beacon/genesis":
            st = chain.head.state
            h._json({"data": {
                "genesis_time": str(int(st.genesis_time)),
                "genesis_validators_root":
                    "0x" + bytes(st.genesis_validators_root).hex(),
                "genesis_fork_version":
                    "0x" + bytes(st.fork.previous_version).hex()}})
        elif path.startswith("/eth/v1/beacon/states/"):
            parts = path.split("/")
            state = self._state(parts[5])
            if state is None:
                h._json({"code": 404, "message": "state not found"}, 404)
            elif parts[6] == "root":
                h._json({"data": {
                    "root": "0x" + state.tree_hash_root().hex()}})
            elif parts[6] == "finality_checkpoints":
                h._json({"data": {
                    "previous_justified": to_json(
                        state.previous_justified_checkpoint),
                    "current_justified": to_json(
                        state.current_justified_checkpoint),
                    "finalized": to_json(state.finalized_checkpoint)}})
            elif parts[6] == "validators":
                # Supports ?id=0,5,12 filtering and offset/limit
                # pagination (`http_api` validators route; the reference
                # pages via the id filter — a full registry dump at 1M
                # validators is a DoS on itself).
                qs = parse_qs(urlparse(h.path).query)
                reg = state.validators
                n = len(reg)
                if "id" in qs:
                    try:
                        indices = [int(x) for part in qs["id"]
                                   for x in part.split(",")]
                    except ValueError:
                        h._json({"code": 400,
                                 "message": "bad id filter"}, 400)
                        return
                    indices = [i for i in indices if 0 <= i < n]
                else:
                    try:
                        offset = int(qs.get("offset", ["0"])[0])
                        limit = min(int(qs.get("limit", ["1000"])[0]),
                                    10_000)
                        if offset < 0 or limit < 0:
                            raise ValueError("negative pagination")
                    except ValueError:
                        # same contract as the id-filter branch: malformed
                        # pagination is a 400, not an unhandled 500 (a
                        # negative offset would wrap the registry arrays)
                        h._json({"code": 400,
                                 "message": "bad offset/limit"}, 400)
                        return
                    indices = range(offset, min(offset + limit, n))
                epoch = chain.head.slot // chain.preset.SLOTS_PER_EPOCH
                act = reg.col("activation_epoch")
                exi = reg.col("exit_epoch")
                slashed = reg.col("slashed")
                out = []
                for i in indices:
                    if int(act[i]) > epoch:
                        status = "pending_queued"
                    elif int(exi[i]) <= epoch:
                        status = ("exited_slashed" if bool(slashed[i])
                                  else "exited_unslashed")
                    elif bool(slashed[i]):
                        status = "active_slashed"
                    elif int(exi[i]) != 2**64 - 1:
                        status = "active_exiting"
                    else:
                        status = "active_ongoing"
                    out.append({
                        "index": str(i),
                        "balance": str(int(state.balances[i])),
                        "status": status,
                        "validator": to_json(reg[i])})
                h._json({"data": out,
                         "execution_optimistic": False,
                         "finalized": False})
            elif parts[6] == "proof":
                # Generalized-index proofs off the device proof engine
                # (?gindex=3&gindex=10,11; ?format=multiproof for the
                # deduplicated helper set).  Malformed gindices are the
                # client's fault: 400, never a 500.
                qs = parse_qs(urlparse(h.path).query)
                if "gindex" not in qs:
                    h._json({"code": 400,
                             "message": "missing gindex"}, 400)
                    return
                try:
                    gindices = [int(x) for part in qs["gindex"]
                                for x in part.split(",")]
                except ValueError:
                    h._json({"code": 400, "message": "bad gindex"}, 400)
                    return
                fmt = qs.get("format", ["single"])[0]
                try:
                    srv = chain.proof_server
                    if fmt == "multiproof":
                        leaves, helpers, hgs = srv.state_multiproof(
                            state, gindices)
                        body = {
                            "leaves": ["0x" + b.hex() for b in leaves],
                            "proof": ["0x" + b.hex() for b in helpers],
                            "helper_gindices": [str(g) for g in hgs],
                            "gindices": [str(g) for g in gindices]}
                    else:
                        branches = srv.state_proof(state, gindices)
                        body = {"proofs": [
                            {"gindex": str(g),
                             "branch": ["0x" + b.hex()
                                        for b in branches[g]]}
                            for g in gindices]}
                except ValueError as e:
                    h._json({"code": 400, "message": str(e)}, 400)
                    return
                body["state_root"] = \
                    "0x" + bytes(state.tree_hash_root()).hex()
                h._json({"data": body})
            else:
                h._json({"code": 404, "message": "unknown route"}, 404)
        elif path.startswith("/eth/v2/beacon/blocks/") \
                or path.startswith("/eth/v1/beacon/headers/"):
            block_id = path.split("/")[-1]
            block, root = self._block(block_id)
            if block is None:
                h._json({"code": 404, "message": "block not found"}, 404)
            elif "/headers/" in path:
                msg = block.message
                h._json({"data": {
                    "root": "0x" + root.hex(), "canonical": True,
                    "header": {"message": {
                        "slot": str(int(msg.slot)),
                        "proposer_index": str(int(msg.proposer_index)),
                        "parent_root": "0x" + bytes(msg.parent_root).hex(),
                        "state_root": "0x" + bytes(msg.state_root).hex(),
                        "body_root":
                            "0x" + msg.body.tree_hash_root().hex()},
                        "signature": "0x" + bytes(block.signature).hex()}}})
            else:
                h._json({"version": "capella", "data": to_json(block)})
        elif path.startswith("/eth/v1/beacon/blob_sidecars/"):
            # Deneb blob sidecars for a block (`http_api` blob route,
            # standard beacon-API `getBlobSidecars`), with the optional
            # ?indices=0,1 filter.
            block_id = path.split("/")[-1]
            try:
                block, root = self._block(block_id)
            except ValueError as e:
                h._json({"code": 400, "message": str(e)}, 400)
                return
            if block is None:
                h._json({"code": 404, "message": "block not found"}, 404)
                return
            qs = parse_qs(urlparse(h.path).query)
            want = None
            if "indices" in qs:
                try:
                    want = {int(x) for part in qs["indices"]
                            for x in part.split(",")}
                    if any(i < 0 for i in want):
                        raise ValueError("negative index")
                except ValueError:
                    h._json({"code": 400, "message": "bad indices"}, 400)
                    return
            sidecars = chain.store.get_blob_sidecars(root)
            if want is not None:
                sidecars = [sc for sc in sidecars if int(sc.index) in want]
            h._json({"data": [to_json(sc) for sc in sidecars],
                     "execution_optimistic": False, "finalized": False})
        elif path == "/eth/v1/beacon/pool/attestations":
            atts = []
            for entry in chain.op_pool.attestations.values():
                for stored in entry:
                    atts.append(to_json(
                        chain.op_pool._to_attestation(stored, chain.T)))
            h._json({"data": atts})
        elif path.startswith("/eth/v1/validator/duties/proposer/"):
            try:
                duties = self._proposer_duties(int(path.split("/")[-1]))
            except ValueError as e:
                h._json({"code": 400, "message": str(e)}, 400)
            else:
                h._json({"data": duties})
        elif path == "/eth/v1/validator/attestation_data":
            from ..validator_client.beacon_node import InProcessBeaconNode
            qs = parse_qs(urlparse(h.path).query)
            try:
                slot = int(qs["slot"][0])
                # Attestations are produced for the current slot; a huge
                # slot would otherwise advance a full state copy
                # unboundedly on the API thread.
                now = max(chain.current_slot(), chain.head.slot)
                if slot > now + 1:
                    raise ValueError(
                        f"attestation data only up to slot {now + 1}")
                data = InProcessBeaconNode(chain).attestation_data(
                    slot, int(qs["committee_index"][0]))
            except (KeyError, ValueError) as e:
                h._json({"code": 400, "message": str(e)}, 400)
            else:
                h._json({"data": to_json(data)})
        elif path.startswith("/eth/v1/beacon/rewards/blocks/"):
            # Block rewards (`http_api` rewards route): the proposer's
            # exact balance delta across the block — computed from the
            # stored pre/post states, so it includes attestation
            # inclusion, sync-aggregate, and slashing whistleblower
            # rewards without replaying.
            block_id = path.split("/")[-1]
            try:
                block, root = self._block(block_id)
            except ValueError as e:
                h._json({"code": 400, "message": str(e)}, 400)
                return
            if block is None:
                h._json({"code": 404, "message": "block not found"}, 404)
                return
            pre = chain.store.get_block(bytes(block.message.parent_root))
            post_state = chain.store.get_state(
                bytes(block.message.state_root))
            pre_state = None if pre is None else chain.store.get_state(
                bytes(pre.message.state_root))
            if post_state is None or pre_state is None:
                h._json({"code": 404, "message": "states unavailable"},
                        404)
                return
            p = int(block.message.proposer_index)
            from ..state_transition.per_slot import process_slots
            adv = process_slots(pre_state.copy(),
                                int(block.message.slot), chain.preset,
                                chain.spec, chain.T)
            total = int(post_state.balances[p]) - int(adv.balances[p])
            h._json({"data": {
                "proposer_index": str(p),
                "total": str(total),
                "attestations": str(max(total, 0)),
                "sync_aggregate": "0", "proposer_slashings": "0",
                "attester_slashings": "0"}})
        elif path == "/eth/v1/config/spec":
            import dataclasses
            out = {}
            for f in dataclasses.fields(chain.spec):
                v = getattr(chain.spec, f.name)
                out[f.name.upper()] = ("0x" + v.hex()
                                       if isinstance(v, bytes) else str(v))
            h._json({"data": out})
        elif path.startswith("/eth/v1/beacon/light_client/bootstrap/"):
            from ..light_client import LightClientServer
            root_hex = path.split("/")[-1]
            try:
                root = bytes.fromhex(root_hex[2:] if root_hex.startswith(
                    "0x") else root_hex)
                bs = LightClientServer(chain).bootstrap(root)
            except (ValueError, KeyError) as e:
                h._json({"code": 404, "message": str(e)}, 404)
            else:
                h._json({"data": {
                    "header": {"beacon": to_json(bs.header)},
                    "current_sync_committee": to_json(
                        bs.current_sync_committee),
                    "current_sync_committee_branch":
                        ["0x" + b.hex()
                         for b in bs.current_sync_committee_branch]}})
        elif path == "/eth/v1/beacon/light_client/updates":
            # Period-advancing updates (`light_client/updates` route):
            # serves the CURRENT period's update (this build keeps one
            # live period; a start_period beyond it 404s).  The update is
            # the full LightClientUpdate cached at block import —
            # attested_header = the parent header the aggregate actually
            # signed, branches from the parent state.  (It was once
            # rebuilt here from the live head, which paired the cached
            # aggregate with a header it never signed: every
            # spec-conformant client rejected the signature.)
            qs = parse_qs(urlparse(h.path).query)
            spe = chain.preset.SLOTS_PER_EPOCH
            period_slots = spe * chain.preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
            cur_period = chain.head.slot // period_slots
            try:
                start = int(qs.get("start_period", [str(cur_period)])[0])
            except ValueError:
                h._json({"code": 400, "message": "bad start_period"}, 400)
                return
            if start != cur_period:
                h._json({"code": 404,
                         "message": f"only period {cur_period} is live"},
                        404)
                return
            upd = chain.lc_period_update  # snapshot: import thread swaps
            if upd is None:
                h._json({"code": 404, "message": "no sync aggregate yet"},
                        404)
                return
            # an update's period is its ATTESTED header's (the spec keys
            # committee data off compute_sync_committee_period_at_slot of
            # the attested slot, not the signature slot)
            if int(upd.attested_header.slot) // period_slots != start:
                # head crossed into a new period but no update has been
                # produced for it yet — don't serve a stale period's
                # update under the new period's label
                h._json({"code": 404,
                         "message": f"no update for period {start} yet"},
                        404)
                return
            h._json({"data": [{
                "attested_header": {"beacon": to_json(upd.attested_header)},
                "next_sync_committee": to_json(upd.next_sync_committee),
                "next_sync_committee_branch":
                    ["0x" + b.hex()
                     for b in upd.next_sync_committee_branch],
                "finalized_header": (
                    {"beacon": to_json(upd.finalized_header)}
                    if upd.finalized_header is not None else None),
                "finality_branch": ["0x" + b.hex()
                                    for b in upd.finality_branch],
                "sync_aggregate": to_json(upd.sync_aggregate),
                "signature_slot": str(int(upd.signature_slot))}]})
        elif path == "/eth/v1/node/identity":
            net = getattr(chain, "network", None)
            node_id = getattr(net, "node_id", b"") if net else b""
            port = getattr(net, "port", 0) if net else 0
            inner = getattr(net, "node", net)  # WireNetwork wraps the node
            subnets = getattr(inner, "subnets", set()) or set()
            attnets = 0
            for sn in subnets:
                attnets |= 1 << int(sn)
            h._json({"data": {
                "peer_id": node_id.hex() if node_id else "",
                "enr": "",
                "p2p_addresses": ([f"/ip4/127.0.0.1/tcp/{port}"]
                                  if port else []),
                "discovery_addresses": [],
                "metadata": {"seq_number": "0",
                             "attnets": "0x" + attnets.to_bytes(
                                 8, "little").hex(),
                             "syncnets": "0x00"}}})
        elif path == "/eth/v1/node/peers":
            net = getattr(chain, "network", None)
            node = getattr(net, "node", net)  # WireNetwork wraps the node
            peers = []
            if node is not None:
                pm = node.peer_manager
                for p in list(node.peers):
                    pid = getattr(p, "peer_id", None)
                    peers.append({
                        "peer_id": (pid.hex() if pid else str(id(p))),
                        "state": ("disconnected"
                                  if pm.is_banned(p) else "connected"),
                        "score": round(pm.score(p), 2),
                        "direction": "outbound"})
            h._json({"data": peers,
                     "meta": {"count": len(peers)}})
        elif path == "/eth/v1/beacon/light_client/optimistic_update":
            upd = chain.lc_optimistic_update
            if upd is None:
                h._json({"code": 404, "message": "no update yet"}, 404)
            else:
                h._json({"data": {
                    "attested_header": {
                        "beacon": to_json(upd.attested_header)},
                    "sync_aggregate": to_json(upd.sync_aggregate),
                    "signature_slot": str(int(upd.signature_slot))}})
        elif path == "/eth/v1/beacon/light_client/finality_update":
            upd = chain.lc_finality_update
            if upd is None:
                h._json({"code": 404, "message": "no update yet"}, 404)
            else:
                h._json({"data": {
                    "attested_header": {
                        "beacon": to_json(upd.attested_header)},
                    "finalized_header": {
                        "beacon": to_json(upd.finalized_header)},
                    "finality_branch": ["0x" + b.hex()
                                        for b in upd.finality_branch],
                    "sync_aggregate": to_json(upd.sync_aggregate),
                    "signature_slot": str(int(upd.signature_slot)),
                    "finalized_checkpoint_epoch":
                        str(int(upd.finalized_checkpoint_epoch))}})
        elif path == "/eth/v1/events":
            self._serve_events(h)
        elif path == "/metrics":
            h._text(REGISTRY.encode())
        elif path == "/lighthouse/tracing/slots":
            # Assembled slot-trace ring: one summary row per slot still
            # held (slot, span count, wall ms, pipeline stages present).
            from ..common.tracing import TRACER
            h._json({"data": {"enabled": TRACER.enabled,
                              "ring": TRACER.max_slots,
                              "evicted": TRACER.evicted_slots,
                              "dropped_stale": TRACER.dropped_stale,
                              "slots": TRACER.slot_summaries()}})
        elif path.startswith("/lighthouse/tracing/slot/"):
            from ..common.tracing import TRACER
            try:
                slot = int(path.split("/")[-1])
            except ValueError:
                h._json({"code": 400, "message": "bad slot"}, 400)
                return
            qs = parse_qs(urlparse(h.path).query)
            fmt = qs.get("format", ["json"])[0]
            if fmt == "chrome_trace":
                trace = TRACER.chrome_trace(slot)
            elif fmt == "json":
                trace = TRACER.slot_trace(slot)
            else:
                h._json({"code": 400,
                         "message": f"unknown format {fmt}"}, 400)
                return
            if trace is None:
                h._json({"code": 404,
                         "message": f"no trace for slot {slot} "
                                    "(evicted or never traced)"}, 404)
            else:
                h._json(trace)
        elif path == "/lighthouse/validator_monitor":
            mon = chain.validator_monitor
            h._json({"data": [] if mon is None else mon.summaries()})
        elif path == "/lighthouse/slo":
            # Full per-objective scoreboard: windowed attainment /
            # error-budget burn, p50/p99, worst offending slots with
            # their trace links, health-transition log.  tick(), not
            # an unthrottled evaluate: a fast scraper must not churn
            # window snapshots or step the hysteresis counter faster
            # than the configured evaluation cadence (staleness is
            # bounded by min_eval_interval_s).
            engine = getattr(chain, "slo_engine", None)
            if engine is None:
                h._json({"code": 404, "message": "no SLO engine"}, 404)
            else:
                if engine.enabled:
                    engine.tick()
                h._json({"data": engine.report()})
        elif path == "/lighthouse/device":
            # Device-ledger scoreboard: per-subsystem transfer bytes/
            # ops, HBM residency watermarks, dispatch + compile counts,
            # the per-slot transfer-delta ring (keyed to the same slot
            # numbers as the trace ring), and the warm-slot budget
            # evaluated over the held slots.
            from ..common.device_ledger import (LEDGER, WARM_SLOT_BUDGET,
                                                evaluate_budget)
            snap = LEDGER.snapshot()
            deltas = LEDGER.slot_deltas()
            snap["slots"] = deltas
            snap["current_slot_delta"] = {
                s: row
                for s, row in LEDGER.current_slot_delta().items()
                if any(row.values())}
            # include_cold=False: a fresh node's materialize/cold-build
            # slots must not read as warm-path violations here (skipped
            # slots are listed; the sustained drill enforces ALL of its
            # measured slots).
            snap["budget"] = {
                "bytes_per_slot": WARM_SLOT_BUDGET,
                "evaluation": evaluate_budget(deltas,
                                              include_cold=False),
            }
            # Proof-serving panel: coalescing efficiency + the per-slot
            # D2H branch-pull bytes (the budget-relevant direction of
            # the serving plane).  Raw attribute — a scrape must never
            # construct the proof server.
            srv = getattr(chain, "_proof_server", None)
            snap["proof"] = {
                "active": srv is not None,
                "server": None if srv is None else srv.stats(),
                "d2h_branch_bytes_per_slot": {
                    row["slot"]:
                        row["subsystems"]["proof_engine"]["d2h_bytes"]
                    for row in deltas
                    if row["subsystems"].get("proof_engine", {})
                                        .get("d2h_bytes")},
            }
            h._json({"data": snap})
        elif path.startswith("/lighthouse/health"):
            # Node health: 200 when healthy/degraded (the node serves),
            # 503 when unhealthy (load balancers drain it).  An empty
            # trace ring / fresh node answers 200 healthy.
            engine = getattr(chain, "slo_engine", None)
            if engine is None:
                h._json({"data": {"state": "healthy", "reasons": [],
                                  "enabled": False}})
                return
            if engine.enabled:
                engine.tick()
            body = engine.health()
            h._json({"data": body},
                    503 if body["state"] == "unhealthy" else 200)
        else:
            h._json({"code": 404, "message": "unknown route"}, 404)

    def _proposer_duties(self, epoch: int) -> list:
        """`/eth/v1/validator/duties/proposer/{epoch}` (`validator/mod.rs`).

        Restricted to the current/next WALL-CLOCK epoch like the reference:
        past epochs computed from the head state would name wrong
        proposers, and a far-future epoch would be an unauthenticated way
        to make the handler advance billions of slots.  Gating on the head
        epoch instead would deadlock a quiet chain — a VC asking for the
        current epoch would get 400, never learn it proposes, and the head
        would never advance.

        Served from the chain's pre-materialized :class:`DutyCache`
        (primed by the idle-tail lookahead, so the steady-state request
        is a list read; a cold miss builds the cache ONCE through the
        chain's advanced-state memo instead of shuffling per request).
        """
        chain = self.chain
        spe = chain.preset.SLOTS_PER_EPOCH
        now_epoch = max(chain.current_slot(), chain.head.slot) // spe
        if not now_epoch <= epoch <= now_epoch + 1:
            raise ValueError(
                f"proposer duties only for epochs {now_epoch}.."
                f"{now_epoch + 1}")
        cache = chain.duty_cache(epoch)
        reg = chain.head.state.validators
        out = []
        for k, idx in enumerate(cache.proposers):
            out.append({
                "pubkey": "0x" + reg.pubkey[idx].tobytes().hex(),
                "validator_index": str(idx),
                "slot": str(cache.first_slot + k)})
        return out

    def _serve_events(self, h) -> None:
        """`/eth/v1/events?topics=head,block,...` — SSE stream
        (`http_api` `events.rs`).  Holds the connection; one thread per
        subscriber (ThreadingHTTPServer)."""
        import queue as _queue
        from urllib.parse import parse_qs
        from ..beacon_chain.events import TOPICS
        qs = parse_qs(urlparse(h.path).query)
        # Accept both ?topics=head,block and the query-array form
        # ?topics=head&topics=block (the beacon-API spec serialization).
        topics = [t
                  for part in qs.get("topics", [",".join(TOPICS)])
                  for t in part.split(",") if t in TOPICS]
        if not topics:
            h._json({"code": 400, "message": "no valid topics"}, 400)
            return
        sub = self.chain.event_bus.subscribe(topics)
        try:
            h.send_response(200)
            h.send_header("Content-Type", "text/event-stream")
            h.send_header("Cache-Control", "no-cache")
            h.end_headers()
            while True:
                try:
                    topic, data = sub.get(timeout=1.0)
                except _queue.Empty:
                    h.wfile.write(b":keepalive\n\n")
                    h.wfile.flush()
                    continue
                h.wfile.write(
                    f"event: {topic}\ndata: {json.dumps(data)}\n\n".encode())
                h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.chain.event_bus.unsubscribe(sub)

    def _route_post(self, h, body: bytes) -> None:
        path = urlparse(h.path).path.rstrip("/")
        chain = self.chain
        if path == "/eth/v1/beacon/blocks":
            # SSZ-encoded signed block publish (broadcast-then-import,
            # `publish_blocks.rs`).
            fork = chain.spec.fork_name_at_epoch(
                chain.current_slot() // chain.preset.SLOTS_PER_EPOCH)
            signed = chain.T.signed_block_cls(fork).deserialize(body)
            chain.per_slot_task(int(signed.message.slot))
            chain.process_block(signed, is_timely=True)
            h._json({})
        elif path.startswith("/eth/v1/validator/duties/attester/"):
            from ..validator_client.beacon_node import InProcessBeaconNode
            try:
                epoch = int(path.split("/")[-1])
                # Same unauthenticated-amplification gate as proposer
                # duties: only the current/next wall-clock epoch, else a
                # far-future epoch drives process_slots for billions of
                # slots on the API thread.
                spe = chain.preset.SLOTS_PER_EPOCH
                now_epoch = max(chain.current_slot(),
                                chain.head.slot) // spe
                if not now_epoch <= epoch <= now_epoch + 1:
                    raise ValueError(
                        f"attester duties only for epochs {now_epoch}.."
                        f"{now_epoch + 1}")
                indices = [int(i) for i in json.loads(body)]
                duties = InProcessBeaconNode(chain).attester_duties(
                    epoch, indices)
            except (ValueError, KeyError) as e:
                h._json({"code": 400, "message": str(e)}, 400)
                return
            reg = chain.head.state.validators
            h._json({"data": [{
                "pubkey": "0x" + reg.pubkey[d.validator_index]
                .tobytes().hex(),
                "validator_index": str(d.validator_index),
                "committee_index": str(d.committee_index),
                "committee_length": str(d.committee_length),
                "validator_committee_index": str(d.committee_position),
                "slot": str(d.slot)} for d in duties]})
        elif path.startswith("/eth/v1/validator/duties/sync/"):
            from ..validator_client.beacon_node import InProcessBeaconNode
            try:
                indices = [int(i) for i in json.loads(body)]
                positions = InProcessBeaconNode(
                    chain).sync_committee_positions(indices)
            except (ValueError, KeyError) as e:
                h._json({"code": 400, "message": str(e)}, 400)
                return
            reg = chain.head.state.validators
            h._json({"data": [{
                "pubkey": "0x" + reg.pubkey[vi].tobytes().hex(),
                "validator_index": str(vi),
                "validator_sync_committee_indices":
                    [str(p) for p in pos]}
                for vi, pos in positions.items() if pos]})
        elif path == "/eth/v1/beacon/pool/attestations":
            from ..ssz.json import from_json
            try:
                atts = [from_json(chain.T.Attestation, a)
                        for a in json.loads(body)]
            except (ValueError, KeyError, TypeError) as e:
                h._json({"code": 400, "message": str(e)}, 400)
                return
            chain.process_attestation_batch(atts)
            h._json({})
        elif path.startswith("/eth/v1/beacon/pool/"):
            self._pool_submit(h, path, body)
        elif path.startswith("/eth/v1/beacon/rewards/attestations/"):
            # Per-validator attestation rewards for an epoch (`http_api`
            # attestation-rewards route): the same per-component deltas
            # the EF rewards runner pins, filtered to the requested
            # validator indices (empty body = all).
            try:
                epoch = int(path.split("/")[-1])
                want = json.loads(body) if body else []
                want = [int(x) for x in want]
            except (ValueError, TypeError) as e:
                h._json({"code": 400, "message": str(e)}, 400)
                return
            state = chain.head.state
            spe = chain.preset.SLOTS_PER_EPOCH
            head_epoch = int(state.slot) // spe
            # Deltas read the PREVIOUS epoch's participation: the state
            # must sit in epoch + 1.
            if epoch != head_epoch - 1:
                h._json({"code": 400, "message":
                         f"rewards available for epoch {head_epoch - 1} "
                         "only (head participation window)"}, 400)
                return
            from ..types.chain_spec import ForkName
            fork = chain.spec.fork_name_at_epoch(head_epoch)
            if fork == ForkName.PHASE0:
                from ..state_transition.per_epoch_phase0 import (
                    attestation_deltas_phase0)
                deltas = attestation_deltas_phase0(state, chain.preset,
                                                   chain.spec)
            else:
                from ..state_transition.per_epoch import flag_deltas
                deltas = flag_deltas(state, fork, chain.preset,
                                     chain.spec)
            n_vals = len(state.validators)
            bad = [i for i in want if not 0 <= int(i) < n_vals]
            if bad:
                h._json({"code": 400,
                         "message": f"unknown validator ids {bad}"}, 400)
                return
            indices = want or range(n_vals)
            out = []
            for i in indices:
                i = int(i)
                row = {"validator_index": str(i)}
                for name in ("source", "target", "head"):
                    r, p = deltas[name]
                    row[name] = str(int(r[i]) - int(p[i]))
                if "inclusion_delay" in deltas:  # phase0 only
                    ir, ip = deltas["inclusion_delay"]
                    row["inclusion_delay"] = str(int(ir[i]) - int(ip[i]))
                ir, ip = deltas["inactivity_penalty"]
                row["inactivity"] = str(int(ir[i]) - int(ip[i]))
                out.append(row)
            h._json({"data": {"total_rewards": out}})
        elif path == "/eth/v1/validator/register_validator":
            # Builder registrations (`http_api` register_validator):
            # recorded on the chain (keyed by pubkey, newest timestamp
            # wins) and forwarded to the connected builder when one is
            # configured (`validator_registration.rs` flow).
            try:
                regs = json.loads(body)
                if not isinstance(regs, list):
                    raise ValueError("expected a list of registrations")
                store = getattr(chain, "validator_registrations", None)
                if store is None:
                    store = chain.validator_registrations = {}
                for reg in regs:
                    msg = reg["message"]
                    key = msg["pubkey"]
                    cur = store.get(key)
                    if cur is None or int(msg["timestamp"]) >= int(
                            cur["message"]["timestamp"]):
                        store[key] = reg
            except (ValueError, KeyError, TypeError) as e:
                h._json({"code": 400, "message": str(e)}, 400)
                return
            builder = getattr(chain, "builder", None)
            if builder is not None:
                try:
                    builder.register_validators(regs)
                except Exception as e:
                    h._json({"code": 502, "message": str(e)}, 502)
                    return
            h._json({})
        else:
            h._json({"code": 404, "message": "unknown route"}, 404)

    # One table drives every SigVerifiedOp pool route: the verified
    # wrapper's payload attribute differs per op, hence the getter.
    def _pool_submit(self, h, path: str, body: bytes) -> None:
        from ..beacon_chain import verify_operation as VO
        from ..ssz.json import from_json

        chain = self.chain
        T = chain.T
        table = {
            "/eth/v1/beacon/pool/voluntary_exits": (
                T.SignedVoluntaryExit, VO.verify_voluntary_exit,
                lambda v: chain.op_pool.insert_voluntary_exit(
                    v.signed_exit)),
            "/eth/v1/beacon/pool/proposer_slashings": (
                T.ProposerSlashing, VO.verify_proposer_slashing,
                lambda v: chain.op_pool.insert_proposer_slashing(
                    v.slashing)),
            "/eth/v1/beacon/pool/attester_slashings": (
                T.AttesterSlashing, VO.verify_attester_slashing,
                lambda v: chain.op_pool.insert_attester_slashing(
                    v.slashing)),
            "/eth/v1/beacon/pool/bls_to_execution_changes": (
                T.SignedBLSToExecutionChange,
                VO.verify_bls_to_execution_change,
                lambda v: chain.op_pool.insert_bls_to_execution_change(
                    v.change)),
        }
        entry = table.get(path)
        if entry is None:
            h._json({"code": 404, "message": "unknown route"}, 404)
            return
        cls, verify, insert = entry
        try:
            op = from_json(cls, json.loads(body))
            verified = verify(chain, op)
        except (VO.OpVerificationError, ValueError, KeyError,
                TypeError) as e:
            h._json({"code": 400, "message": str(e)}, 400)
            return
        insert(verified)
        h._json({})
