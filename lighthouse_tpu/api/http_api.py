"""Beacon-API HTTP server — ``beacon_node/http_api``
(``/root/reference/beacon_node/http_api/src/lib.rs``) plus the Prometheus
scrape endpoint of ``beacon_node/http_metrics``.

A threaded stdlib HTTP server exposing the standard ``/eth/v1`` surface
over an in-process :class:`~lighthouse_tpu.beacon_chain.BeaconChain` (the
reference uses warp; the route table and JSON conventions are the spec's).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

from ..common.metrics import REGISTRY
from ..ssz.json import to_json


class HttpApiServer:
    def __init__(self, chain, host: str = "127.0.0.1", port: int = 0):
        self.chain = chain
        self.requests_total = REGISTRY.counter(
            "http_api_requests_total", "Beacon-API requests served")
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _text(self, text, code=200):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                api.requests_total.inc()
                try:
                    api._route_get(self)
                except Exception as e:  # noqa: BLE001
                    self._json({"code": 500, "message": str(e)}, 500)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length) if length else b""
                    api._route_post(self, body)
                except Exception as e:  # noqa: BLE001
                    self._json({"code": 500, "message": str(e)}, 500)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    # -- state resolution ----------------------------------------------------

    def _state(self, state_id: str):
        chain = self.chain
        if state_id in ("head", "justified", "finalized"):
            return chain.head.state
        if state_id.startswith("0x"):
            return chain.store.get_state(bytes.fromhex(state_id[2:]))
        raise ValueError(f"unsupported state id {state_id}")

    def _block(self, block_id: str):
        chain = self.chain
        if block_id == "head":
            return chain.store.get_block(chain.head.root), chain.head.root
        if block_id.startswith("0x"):
            root = bytes.fromhex(block_id[2:])
            return chain.store.get_block(root), root
        raise ValueError(f"unsupported block id {block_id}")

    # -- routes --------------------------------------------------------------

    def _route_get(self, h) -> None:
        path = urlparse(h.path).path.rstrip("/")
        chain = self.chain
        if path == "/eth/v1/node/version":
            h._json({"data": {"version": "lighthouse-tpu/0.3.0"}})
        elif path == "/eth/v1/node/health":
            h.send_response(200)
            h.end_headers()
        elif path == "/eth/v1/node/syncing":
            h._json({"data": {
                "head_slot": str(chain.head.slot),
                "sync_distance": str(max(
                    chain.current_slot() - chain.head.slot, 0)),
                "is_syncing": chain.current_slot() - chain.head.slot > 1,
                "is_optimistic": False, "el_offline": False}})
        elif path == "/eth/v1/beacon/genesis":
            st = chain.head.state
            h._json({"data": {
                "genesis_time": str(int(st.genesis_time)),
                "genesis_validators_root":
                    "0x" + bytes(st.genesis_validators_root).hex(),
                "genesis_fork_version":
                    "0x" + bytes(st.fork.previous_version).hex()}})
        elif path.startswith("/eth/v1/beacon/states/"):
            parts = path.split("/")
            state = self._state(parts[5])
            if state is None:
                h._json({"code": 404, "message": "state not found"}, 404)
            elif parts[6] == "root":
                h._json({"data": {
                    "root": "0x" + state.tree_hash_root().hex()}})
            elif parts[6] == "finality_checkpoints":
                h._json({"data": {
                    "previous_justified": to_json(
                        state.previous_justified_checkpoint),
                    "current_justified": to_json(
                        state.current_justified_checkpoint),
                    "finalized": to_json(state.finalized_checkpoint)}})
            elif parts[6] == "validators":
                reg = state.validators
                out = []
                for i in range(len(reg)):
                    out.append({
                        "index": str(i), "balance": str(int(state.balances[i])),
                        "status": "active_ongoing",
                        "validator": to_json(reg[i])})
                h._json({"data": out})
            else:
                h._json({"code": 404, "message": "unknown route"}, 404)
        elif path.startswith("/eth/v2/beacon/blocks/") \
                or path.startswith("/eth/v1/beacon/headers/"):
            block_id = path.split("/")[-1]
            block, root = self._block(block_id)
            if block is None:
                h._json({"code": 404, "message": "block not found"}, 404)
            elif "/headers/" in path:
                msg = block.message
                h._json({"data": {
                    "root": "0x" + root.hex(), "canonical": True,
                    "header": {"message": {
                        "slot": str(int(msg.slot)),
                        "proposer_index": str(int(msg.proposer_index)),
                        "parent_root": "0x" + bytes(msg.parent_root).hex(),
                        "state_root": "0x" + bytes(msg.state_root).hex(),
                        "body_root":
                            "0x" + msg.body.tree_hash_root().hex()},
                        "signature": "0x" + bytes(block.signature).hex()}}})
            else:
                h._json({"version": "capella", "data": to_json(block)})
        elif path == "/eth/v1/beacon/pool/attestations":
            atts = []
            for entry in chain.op_pool.attestations.values():
                for stored in entry:
                    atts.append(to_json(
                        chain.op_pool._to_attestation(stored, chain.T)))
            h._json({"data": atts})
        elif path == "/metrics":
            h._text(REGISTRY.encode())
        elif path.startswith("/lighthouse/health"):
            h._json({"data": {"observed_attesters": "ok"}})
        else:
            h._json({"code": 404, "message": "unknown route"}, 404)

    def _route_post(self, h, body: bytes) -> None:
        path = urlparse(h.path).path.rstrip("/")
        chain = self.chain
        if path == "/eth/v1/beacon/blocks":
            # SSZ-encoded signed block publish (broadcast-then-import,
            # `publish_blocks.rs`).
            fork = chain.spec.fork_name_at_epoch(
                chain.current_slot() // chain.preset.SLOTS_PER_EPOCH)
            signed = chain.T.signed_block_cls(fork).deserialize(body)
            chain.per_slot_task(int(signed.message.slot))
            chain.process_block(signed, is_timely=True)
            h._json({})
        else:
            h._json({"code": 404, "message": "unknown route"}, 404)
