"""Chain runtime: staged verification pipelines + the BeaconChain
orchestrator (counterpart of ``beacon_node/beacon_chain``,
``/root/reference/beacon_node/beacon_chain/src/``)."""

from .chain import BeaconChain, CanonicalHead
from .block_verification import (
    ExecutedBlock,
    GossipVerifiedBlock,
    SignatureVerifiedBlock,
)
from .attestation_verification import (
    VerifiedAttestation,
    batch_verify_attestations,
)
from .data_availability import DataAvailabilityChecker, build_blob_sidecars
from .verification_service import (
    CircuitBreaker,
    ResilienceEnvelope,
    VerificationService,
)
from .errors import (
    AttestationError,
    BlobSidecarError,
    BlobsUnavailable,
    BlockError,
    BlockIsAlreadyKnown,
    FutureSlot,
    IncorrectProposer,
    InvalidSignatures,
    ParentUnknown,
    ProposalSignatureInvalid,
    RepeatProposal,
    StateRootMismatch,
)

__all__ = [
    "BeaconChain", "CanonicalHead", "GossipVerifiedBlock",
    "SignatureVerifiedBlock", "ExecutedBlock", "VerifiedAttestation",
    "batch_verify_attestations", "BlockError", "AttestationError",
    "BlockIsAlreadyKnown", "FutureSlot", "ParentUnknown",
    "IncorrectProposer", "ProposalSignatureInvalid", "InvalidSignatures",
    "StateRootMismatch", "RepeatProposal", "BlobsUnavailable",
    "BlobSidecarError", "DataAvailabilityChecker", "build_blob_sidecars",
    "VerificationService", "ResilienceEnvelope", "CircuitBreaker",
]
