"""Batched gossip-attestation verification —
``/root/reference/beacon_node/beacon_chain/src/attestation_verification.rs``
and its batch module (``attestation_verification/batch.rs:31-120``).

The batching window (≤64 per worker batch,
``beacon_processor/mod.rs:200``) is the natural device batch: every
attestation passes the cheap checks individually (slot window, known head,
committee resolution, first-seen dedup), then ALL signatures verify in ONE
``verify_signature_sets`` call — on TPU one fused kernel pipeline.  If the
batch fails, each attestation re-verifies individually so one bad item
cannot censor the rest (``batch.rs:203``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..crypto import bls
from ..state_transition import signature_sets as sigs
from ..state_transition.committees import get_beacon_committee
from .errors import (
    AttestationError,
    AttestationSlotOutOfWindow,
    AttestationSignatureInvalid,
    PriorAttestationKnown,
    UnknownHeadBlock,
)

ATTESTATION_PROPAGATION_SLOT_RANGE = 32


@dataclass
class VerifiedAttestation:
    """Attestation + resolved committee/indices, post-verification."""
    attestation: object
    indexed_indices: np.ndarray
    committee: np.ndarray


def attesting_indices(state, att, preset) -> Tuple[np.ndarray, np.ndarray]:
    """(attesting indices, committee) for an attestation — the committee
    lookup + aggregation-bit select shared by gossip verification and
    block-import fork-choice feeding."""
    committee = np.asarray(get_beacon_committee(
        state, int(att.data.slot), int(att.data.index), preset))
    bits = np.asarray(att.aggregation_bits, dtype=bool)[:len(committee)]
    return committee[bits], committee


def _cheap_checks(chain, att) -> Tuple[np.ndarray, np.ndarray, object]:
    """Slot window, known head, committee resolution, first-seen PEEK.
    Attesters are only RECORDED after the batch signature verifies —
    otherwise junk signatures naming honest validators would censor their
    real attestations (same two-phase as observed_block_producers).
    Returns (attesting indices, committee, resolved state)."""
    slot = int(att.data.slot)
    cur = chain.current_slot()
    if not (slot <= cur <= slot + ATTESTATION_PROPAGATION_SLOT_RANGE):
        raise AttestationSlotOutOfWindow(f"slot {slot}, current {cur}")
    head_root = bytes(att.data.beacon_block_root)
    if not chain.fork_choice.contains_block(head_root):
        raise UnknownHeadBlock(head_root.hex())
    try:
        state = chain.state_for_attestation(att)
    except AttestationError:
        raise
    except Exception as e:
        # Fork-choice may know the block while its state is already pruned
        # (hot→cold migration); that is a per-attestation failure, not a
        # batch abort — BlockError escaping here would drop the whole
        # 64-item gossip batch on one unverified message.
        raise UnknownHeadBlock(f"state unavailable: {e}") from e
    indices, committee = attesting_indices(state, att, chain.preset)
    epoch = int(att.data.target.epoch)
    fresh = [i for i in indices
             if not chain.observed_attesters.has_attested(epoch, int(i))]
    if not fresh:
        raise PriorAttestationKnown(
            f"all {len(indices)} attesters already seen for epoch {epoch}")
    return indices, committee, state


def _signature_set(chain, att, indices, state) -> bls.SignatureSet:
    return sigs.indexed_attestation_signature_set(
        state, [int(i) for i in indices], bytes(att.signature), att.data,
        chain.pubkey_cache, chain.preset)


def _accept(chain, att, idx, committee) -> VerifiedAttestation:
    """Record attesters (two-phase: only AFTER the signature verified)
    and build the verified wrapper — the synchronous batch path.  The
    streaming completion callback does NOT use this: it needs the
    atomic observe-if-fresh form (register only when some attester is
    new) to dedup concurrent duplicate copies."""
    epoch = int(att.data.target.epoch)
    for v in idx:
        chain.observed_attesters.observe(epoch, int(v))
    return VerifiedAttestation(att, idx, committee)


def batch_verify_attestations(chain, attestations: List
                              ) -> List[Tuple[object, Optional[Exception]]]:
    """One batched signature verify for the window; individual fallback on
    batch failure.  Returns per-attestation (VerifiedAttestation | None,
    error | None) preserving order."""
    staged = []
    results: List = [None] * len(attestations)
    for i, att in enumerate(attestations):
        try:
            indices, committee, state = _cheap_checks(chain, att)
            staged.append((i, att, indices, committee, state))
        except AttestationError as e:
            results[i] = (None, e)

    def accept(i, att, idx, committee):
        results[i] = (_accept(chain, att, idx, committee), None)

    if staged:
        sets = [_signature_set(chain, att, idx, state)
                for (_, att, idx, _, state) in staged]
        if bls.verify_signature_sets(sets):
            for (i, att, idx, committee, _state) in staged:
                accept(i, att, idx, committee)
        else:
            # Fallback: verify one-by-one (`batch.rs:203`).
            for (i, att, idx, committee, _state), sset in zip(staged, sets):
                if bls.verify_signature_sets([sset]):
                    accept(i, att, idx, committee)
                else:
                    results[i] = (None, AttestationSignatureInvalid(
                        f"attestation {i} signature invalid"))
    return results


def stream_verify_attestations(chain, service, attestations: List,
                               kind: str = "attestation") -> int:
    """Gossip-path streaming verification: cheap checks run NOW (slot
    window, known head, committee resolution, first-seen peek), the
    signature set streams through the service's adaptive device buckets,
    and an accepted attestation registers with the chain (fork choice +
    op pool) from the completion callback.  A batch-verdict failure
    splits per message inside the service, so the isolation guarantee of
    :func:`batch_verify_attestations` is preserved.  Returns the number
    of messages submitted (cheap-check rejects are dropped here, exactly
    like the synchronous path drops them with an error)."""
    submitted = 0
    for att in attestations:
        try:
            indices, committee, state = _cheap_checks(chain, att)
        except AttestationError:
            continue
        sset = _signature_set(chain, att, indices, state)

        def on_result(ok: bool, path: str, att=att, idx=indices,
                      committee=committee) -> None:
            if not ok:
                return
            # First-seen dedup at COMPLETION, via the ATOMIC
            # observe-if-fresh primitive: the streaming window is wider
            # than one batch (mesh redundancy delivers duplicate copies
            # within the SLO window, all passing the submit-time peek),
            # and concurrent pump threads can finish two copies at once
            # — a peek-then-observe pair here would let both register,
            # inflating the op pool and re-firing fork choice.
            # Attesters are still only recorded post-verify, so junk
            # can't censor; the copy that loses the observe race finds
            # no fresh attesters and drops, exactly like the
            # synchronous path's PriorAttestationKnown.
            epoch = int(att.data.target.epoch)
            fresh = [v for v in idx
                     if chain.observed_attesters.observe(epoch, int(v))]
            if not fresh:
                return
            chain.register_verified_attestation(
                VerifiedAttestation(att, idx, committee))

        # The network layer stamps gossip arrival on the message; the
        # service backdates its SLO clock to it, so the accounted
        # latency is gossip→verified (processor queue wait included),
        # not merely submit→verdict.
        if service.submit(kind, [sset], on_result, meta=att,
                          arrival=getattr(att, "_gossip_arrival", None)):
            submitted += 1
    return submitted
